"""Training callbacks: print/record/reset_parameter/early_stopping.

reference: python-package/lightgbm/callback.py (print_evaluation :60,
record_evaluation :85, reset_parameter :115, early_stopping :150).
"""

from __future__ import annotations

import collections
from typing import Callable, List

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list and \
                (env.iteration + 1) % period == 0:
            result = "\t".join(
                _format_eval_result(x, show_stdv) for x in env.evaluation_result_list)
            print(f"[{env.iteration + 1}]\t{result}")
    _callback.order = 10
    # no-op on iterations without evaluation results: the engine's fused
    # chunk scheduler may skip invoking it for mid-chunk iterations
    _callback._chunk_safe = True
    return _callback


def record_evaluation(eval_result: dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dict")
    eval_result.clear()

    def _callback(env: CallbackEnv) -> None:
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(item[2])

    # resume seam (resilience/checkpoint.py): the recorded history is part
    # of the training state a resumed run must replay
    def _get_state():
        import copy
        return copy.deepcopy(eval_result)

    def _set_state(state) -> None:
        import copy
        eval_result.clear()
        eval_result.update(copy.deepcopy(state))

    _callback.order = 20
    _callback._resume_token = "record_evaluation"
    _callback._chunk_safe = True   # no-op on empty evaluation lists
    _callback.get_state = _get_state
    _callback.set_state = _set_state
    return _callback


def reset_parameter(**kwargs) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"Length of list {key!r} has to equal to "
                                     "'num_boost_round'")
                new_param = value[env.iteration - env.begin_iteration]
            else:
                new_param = value(env.iteration - env.begin_iteration)
            new_parameters[key] = new_param
        if new_parameters:
            env.model.reset_parameter(new_parameters)
            env.params.update(new_parameters)
    _callback.before_iteration = True
    _callback.order = 10
    # a pure learning-rate schedule can ride INSIDE a fused chunk as a
    # [c] array (engine.py precomputes the per-iteration values); any
    # other reset forces the per-iteration path
    _callback._lr_schedule = (kwargs["learning_rate"]
                              if set(kwargs) == {"learning_rate"} else None)
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """reference: callback.py:150."""
    best_score: List = []
    best_iter: List = []
    best_score_list: List = []
    cmp_op: List = []
    higher_better: List[bool] = []
    enabled: List[bool] = [True]
    first_metric: List[str] = [""]

    def _init(env: CallbackEnv) -> None:
        enabled[0] = not any(
            env.params.get(alias, "") == "dart"
            for alias in ("boosting", "boosting_type", "boost"))
        if not enabled[0]:
            import warnings
            warnings.warn("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric is "
                "required for evaluation")
        if verbose:
            print(f"Training until validation scores don't improve for "
                  f"{stopping_rounds} rounds")
        first_metric[0] = env.evaluation_result_list[0][1].split(" ")[-1]
        for eval_ret in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)
            higher_better.append(bool(eval_ret[3]))
            if eval_ret[3]:  # higher better
                best_score.append(float("-inf"))
                cmp_op.append(lambda x, y: x > y)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda x, y: x < y)

    def _callback(env: CallbackEnv) -> None:
        if not cmp_op:
            if not env.evaluation_result_list:
                # metric_freq gating / fused chunks: iterations without an
                # evaluation carry no signal — defer init to the first
                # evaluated iteration (engine.py raises up front when no
                # eval will ever happen)
                return
            _init(env)
        if not enabled[0]:
            return
        for i in range(len(env.evaluation_result_list)):
            score = env.evaluation_result_list[i][2]
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            eval_name_splitted = env.evaluation_result_list[i][1].split(" ")
            if first_metric_only and first_metric[0] != eval_name_splitted[-1]:
                continue
            if env.evaluation_result_list[i][0] == "cv_agg" and \
                    eval_name_splitted[0] == "train":
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    print(f"Early stopping, best iteration is:\n"
                          f"[{best_iter[i] + 1}]\t"
                          + "\t".join(_format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if env.iteration == env.end_iteration - 1:
                if verbose:
                    print(f"Did not meet early stopping. Best iteration is:\n"
                          f"[{best_iter[i] + 1}]\t"
                          + "\t".join(_format_eval_result(x) for x in best_score_list[i]))
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if first_metric_only:
                break

    # resume seam (resilience/checkpoint.py): the closure's best-so-far
    # tracking IS training state — without it a resumed run would restart
    # the patience window and could stop at a different iteration than
    # the uninterrupted run.  cmp_op holds lambdas (unpicklable), so the
    # state carries higher_better flags and set_state rebuilds them.
    def _get_state():
        return {
            "best_score": list(best_score),
            "best_iter": list(best_iter),
            "best_score_list": list(best_score_list),
            "higher_better": list(higher_better),
            "enabled": enabled[0],
            "first_metric": first_metric[0],
        }

    def _set_state(state) -> None:
        for lst in (best_score, best_iter, best_score_list, cmp_op,
                    higher_better):
            del lst[:]
        best_score.extend(state["best_score"])
        best_iter.extend(state["best_iter"])
        best_score_list.extend(state["best_score_list"])
        higher_better.extend(state["higher_better"])
        cmp_op.extend((lambda x, y: x > y) if hib else (lambda x, y: x < y)
                      for hib in state["higher_better"])
        enabled[0] = state["enabled"]
        first_metric[0] = state["first_metric"]

    _callback.order = 30
    _callback._chunk_safe = True   # no-op on empty evaluation lists
    _callback._resume_token = (f"early_stopping({stopping_rounds},"
                               f"{first_metric_only})")
    _callback.get_state = _get_state
    _callback.set_state = _set_state
    return _callback
