"""Objective functions: gradients/hessians as pure JAX functions.

reference: src/objective/ — ObjectiveFunction interface
(include/LightGBM/objective_function.h:19) and the factory
(src/objective/objective_function.cpp:17-47).  Formulas match the reference
implementations cited per class.  Scores/gradients for multiclass use
[K, n] layout (class-major, like the reference's flattened num_data*k+i).

Each objective provides:
- ``get_gradients(score) -> (grad, hess)`` — jittable, shapes [n] or [K, n]
- ``boost_from_score(class_id)`` — host-side init score
- ``convert_output(score)`` — raw score -> prediction space (jittable)
- ``renew_percentile`` — not None for objectives that re-fit leaf outputs
  as residual percentiles (RenewTreeOutput, regression_objective.hpp:250)
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import Metadata


class ObjectiveFunction:
    name = "none"
    num_model_per_iteration = 1
    is_constant_hessian = False
    renew_percentile: Optional[float] = None
    need_group = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weight = (jnp.asarray(metadata.weight, jnp.float32)
                       if metadata.weight is not None else None)
        self.metadata = metadata

    def _w(self, g, h):
        if self.weight is not None:
            return g * self.weight, h * self.weight
        return g, h

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def convert_output(self, score: jax.Array) -> jax.Array:
        return score

    def _weighted_mean_label(self) -> float:
        lbl = np.asarray(self.label, np.float64)
        if self.weight is not None:
            w = np.asarray(self.weight, np.float64)
            return float((lbl * w).sum() / w.sum())
        return float(lbl.mean())


# ---------------------------------------------------------------------------
# Regression family (reference: src/objective/regression_objective.hpp)
# ---------------------------------------------------------------------------

class RegressionL2(ObjectiveFunction):
    """reference: RegressionL2loss (regression_objective.hpp:93)."""

    name = "regression"
    is_constant_hessian = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.config.reg_sqrt:
            lbl = np.asarray(metadata.label, np.float64)
            self.label = jnp.asarray(np.sign(lbl) * np.sqrt(np.abs(lbl)), jnp.float32)

    def get_gradients(self, score):
        return self._w(score - self.label, jnp.ones_like(score))

    def boost_from_score(self, class_id=0):
        return self._weighted_mean_label()

    def convert_output(self, score):
        if self.config.reg_sqrt:
            return jnp.sign(score) * score * score
        return score


class RegressionL1(RegressionL2):
    """reference: RegressionL1loss (regression_objective.hpp:204)."""

    name = "regression_l1"
    renew_percentile = 0.5

    def get_gradients(self, score):
        return self._w(jnp.sign(score - self.label), jnp.ones_like(score))

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self.label, np.float64)
        w = None if self.weight is None else np.asarray(self.weight, np.float64)
        return _percentile(lbl, w, 0.5)


class RegressionHuber(RegressionL2):
    """reference: RegressionHuberLoss (regression_objective.hpp:290)."""

    name = "huber"
    renew_percentile = 0.5

    def get_gradients(self, score):
        diff = score - self.label
        a = self.config.alpha
        g = jnp.where(jnp.abs(diff) <= a, diff, jnp.sign(diff) * a)
        return self._w(g, jnp.ones_like(score))


class RegressionFair(ObjectiveFunction):
    """reference: RegressionFairLoss (regression_objective.hpp:352)."""

    name = "fair"

    def get_gradients(self, score):
        c = self.config.fair_c
        x = score - self.label
        g = c * x / (jnp.abs(x) + c)
        h = c * c / (jnp.abs(x) + c) ** 2
        return self._w(g, h)


class RegressionPoisson(ObjectiveFunction):
    """reference: RegressionPoissonLoss (regression_objective.hpp:399)."""

    name = "poisson"

    def get_gradients(self, score):
        g = jnp.exp(score) - self.label
        h = jnp.exp(score + self.config.poisson_max_delta_step)
        return self._w(g, h)

    def boost_from_score(self, class_id=0):
        return math.log(max(self._weighted_mean_label(), 1e-20))

    def convert_output(self, score):
        return jnp.exp(score)


class RegressionQuantile(ObjectiveFunction):
    """reference: RegressionQuantileloss (regression_objective.hpp:480)."""

    name = "quantile"
    is_constant_hessian = True

    @property
    def renew_percentile(self):
        return self.config.alpha

    def get_gradients(self, score):
        a = self.config.alpha
        g = jnp.where(score > self.label, 1.0 - a, -a)
        return self._w(g, jnp.ones_like(score))

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self.label, np.float64)
        w = None if self.weight is None else np.asarray(self.weight, np.float64)
        return _percentile(lbl, w, self.config.alpha)


class RegressionMAPE(ObjectiveFunction):
    """reference: RegressionMAPELOSS (regression_objective.hpp:579)."""

    name = "mape"
    renew_percentile = 0.5

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lw = 1.0 / np.maximum(1.0, np.abs(np.asarray(metadata.label, np.float64)))
        self.label_weight = jnp.asarray(lw, jnp.float32)

    def get_gradients(self, score):
        diff = score - self.label
        g = jnp.sign(diff) * self.label_weight
        h = jnp.ones_like(score) if self.weight is None else self.weight
        if self.weight is not None:
            g = g * self.weight
        return g, h

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self.label, np.float64)
        w = np.asarray(self.label_weight, np.float64)
        if self.weight is not None:
            w = w * np.asarray(self.weight, np.float64)
        return _percentile(lbl, w, 0.5)


class RegressionGamma(RegressionPoisson):
    """reference: RegressionGammaLoss (regression_objective.hpp:674)."""

    name = "gamma"

    def get_gradients(self, score):
        g = 1.0 - self.label * jnp.exp(-score)
        h = self.label * jnp.exp(-score)
        return self._w(g, h)


class RegressionTweedie(RegressionPoisson):
    """reference: RegressionTweedieLoss (regression_objective.hpp:711)."""

    name = "tweedie"

    def get_gradients(self, score):
        rho = self.config.tweedie_variance_power
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        g = -self.label * e1 + e2
        h = -self.label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return self._w(g, h)


# ---------------------------------------------------------------------------
# Binary (reference: src/objective/binary_objective.hpp:21)
# ---------------------------------------------------------------------------

class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label, np.float64)
        # reference: is_pos = label > 0 (binary_objective.hpp:35) — any
        # positive value counts as the positive class, no {0,1} check
        self.label_sign = jnp.asarray(np.where(lbl > 0, 1.0, -1.0), jnp.float32)
        cnt_pos = float((lbl > 0).sum())
        cnt_neg = float(len(lbl) - cnt_pos)
        c = self.config
        if c.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                self.label_weight_pos, self.label_weight_neg = 1.0, cnt_pos / cnt_neg
            else:
                self.label_weight_pos, self.label_weight_neg = cnt_neg / cnt_pos, 1.0
        else:
            self.label_weight_pos, self.label_weight_neg = c.scale_pos_weight, 1.0
        self._pavg = None
        if cnt_pos + cnt_neg > 0:
            if self.weight is not None:
                w = np.asarray(self.weight, np.float64)
                spos = float((w * (lbl > 0)).sum())
                self._pavg = spos / w.sum()
            else:
                self._pavg = cnt_pos / (cnt_pos + cnt_neg)

    def get_gradients(self, score):
        sig = self.config.sigmoid
        lb = self.label_sign
        lw = jnp.where(lb > 0, self.label_weight_pos, self.label_weight_neg)
        response = -lb * sig / (1.0 + jnp.exp(lb * sig * score))
        abs_resp = jnp.abs(response)
        g = response * lw
        h = abs_resp * (sig - abs_resp) * lw
        return self._w(g, h)

    def boost_from_score(self, class_id=0):
        if not self.config.boost_from_average or self._pavg is None:
            return 0.0
        pavg = min(max(self._pavg, 1e-15), 1.0 - 1e-15)
        return math.log(pavg / (1.0 - pavg)) / self.config.sigmoid

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * score))


# ---------------------------------------------------------------------------
# Multiclass (reference: src/objective/multiclass_objective.hpp:24,180)
# ---------------------------------------------------------------------------

class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label, np.int32)
        if lbl.min() < 0 or lbl.max() >= self.num_class:
            raise ValueError("multiclass labels must be in [0, num_class)")
        self.label_int = jnp.asarray(lbl)
        onehot = np.zeros((self.num_class, len(lbl)), np.float32)
        onehot[lbl, np.arange(len(lbl))] = 1.0
        self.label_onehot = jnp.asarray(onehot)
        w = np.asarray(metadata.weight, np.float64) if metadata.weight is not None else np.ones(len(lbl))
        probs = np.array([(w * (lbl == k)).sum() for k in range(self.num_class)])
        self.class_init_probs = probs / w.sum()

    def get_gradients(self, score):
        # score: [K, n]
        p = jax.nn.softmax(score, axis=0)
        g = p - self.label_onehot
        # reference uses a flat 2.0 factor (multiclass_objective.hpp:100),
        # not the K/(K-1) Newton factor some other GBDTs use
        h = 2.0 * p * (1.0 - p)
        if self.weight is not None:
            g = g * self.weight[None, :]
            h = h * self.weight[None, :]
        return g, h

    def boost_from_score(self, class_id=0):
        if not self.config.boost_from_average:
            return 0.0
        return math.log(max(float(self.class_init_probs[class_id]), 1e-15))

    def convert_output(self, score):
        return jax.nn.softmax(score, axis=0)


class MulticlassOVA(ObjectiveFunction):
    """One-vs-all: K independent binary objectives
    (reference: multiclass_objective.hpp:180)."""

    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class
        self.num_model_per_iteration = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label, np.int32)
        onehot = np.zeros((self.num_class, len(lbl)), np.float32)
        onehot[lbl, np.arange(len(lbl))] = 1.0
        self.label_onehot = jnp.asarray(onehot)
        self.binary_objs = []
        for k in range(self.num_class):
            sub = BinaryLogloss(self.config)
            md = Metadata(label=(np.asarray(lbl) == k).astype(np.float32),
                          weight=metadata.weight)
            sub.init(md, num_data)
            self.binary_objs.append(sub)

    def get_gradients(self, score):
        gs, hs = [], []
        for k in range(self.num_class):
            g, h = self.binary_objs[k].get_gradients(score[k])
            gs.append(g)
            hs.append(h)
        return jnp.stack(gs), jnp.stack(hs)

    def boost_from_score(self, class_id=0):
        return self.binary_objs[class_id].boost_from_score()

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * score))


# ---------------------------------------------------------------------------
# Cross-entropy (reference: src/objective/xentropy_objective.hpp:44,148)
# ---------------------------------------------------------------------------

class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = np.asarray(metadata.label, np.float64)
        if lbl.min() < 0 or lbl.max() > 1:
            raise ValueError("cross_entropy labels must be in [0, 1]")

    def get_gradients(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score))
        return self._w(z - self.label, z * (1.0 - z))

    def boost_from_score(self, class_id=0):
        pavg = min(max(self._weighted_mean_label(), 1e-15), 1 - 1e-15)
        return math.log(pavg / (1.0 - pavg))

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-score))


class CrossEntropyLambda(ObjectiveFunction):
    """reference: CrossEntropyLambda (xentropy_objective.hpp:148)."""

    name = "cross_entropy_lambda"

    def get_gradients(self, score):
        # reference: xentropy_objective.hpp:185-212 (weighted branch; the
        # unweighted branch degenerates to plain sigmoid cross-entropy)
        if self.weight is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            return z - self.label, z * (1.0 - z)
        w = self.weight
        y = self.label
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = jnp.maximum(1.0 - jnp.exp(-w * hhat), 1e-15)
        enf = 1.0 / epf
        g = (1.0 - y / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        a = w * epf / ((1.0 + epf) * (1.0 + epf))
        d = c - 1.0
        b = (c / (d * d)) * (1.0 + w * epf - c)
        h = a * (1.0 + y * b)
        return g, h

    def boost_from_score(self, class_id=0):
        lbl = np.asarray(self.label, np.float64)
        if self.weight is not None:
            w = np.asarray(self.weight, np.float64)
            havg = float((lbl * w).sum() / w.sum())
        else:
            havg = float(lbl.mean())
        return math.log(max(math.expm1(max(havg, 1e-15)), 1e-15))

    def convert_output(self, score):
        return jnp.log1p(jnp.exp(score))


def _percentile(values: np.ndarray, weights: Optional[np.ndarray], alpha: float) -> float:
    """Weighted percentile matching reference Common::*Percentile
    (regression_objective.hpp:23-82)."""
    if len(values) == 0:
        return 0.0
    order = np.argsort(values, kind="stable")
    v = values[order]
    if weights is None:
        pos = alpha * (len(v) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(v) - 1)
        frac = pos - lo
        return float(v[lo] * (1 - frac) + v[hi] * frac)
    w = weights[order]
    cw = np.cumsum(w) - w / 2.0
    tot = w.sum()
    p = cw / tot
    idx = np.searchsorted(p, alpha)
    if idx <= 0:
        return float(v[0])
    if idx >= len(v):
        return float(v[-1])
    p0, p1 = p[idx - 1], p[idx]
    frac = 0.0 if p1 == p0 else (alpha - p0) / (p1 - p0)
    return float(v[idx - 1] * (1 - frac) + v[idx] * frac)


_REGISTRY = {}
for _cls in (RegressionL2, RegressionL1, RegressionHuber, RegressionFair,
             RegressionPoisson, RegressionQuantile, RegressionMAPE,
             RegressionGamma, RegressionTweedie, BinaryLogloss,
             MulticlassSoftmax, MulticlassOVA, CrossEntropy, CrossEntropyLambda):
    _REGISTRY[_cls.name] = _cls


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """reference: ObjectiveFunction::CreateObjectiveFunction
    (src/objective/objective_function.cpp:17-47)."""
    name = config.objective
    if name == "none":
        return None
    if name in ("lambdarank", "rank_xendcg"):
        from .objective_rank import LambdarankNDCG, RankXENDCG
        return LambdarankNDCG(config) if name == "lambdarank" else RankXENDCG(config)
    if name not in _REGISTRY:
        raise ValueError(f"unknown objective {name!r}")
    return _REGISTRY[name](config)
