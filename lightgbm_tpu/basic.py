"""Booster: the user-facing model handle.

reference: python-package/lightgbm/basic.py:1704 (class Booster) — but where
the reference Booster is a ctypes shim over the C API
(src/c_api.cpp:100 Booster wrapper), this one directly owns the boosting
object; there is no process boundary to cross.  Method surface mirrors the
reference Python package.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Union

import numpy as np

from .binning import BinType, MissingType
from .boosting import create_boosting
from .config import Config
from .dataset import Dataset
from .metrics import create_metric
from .model_text import load_model_from_string, save_model_to_string
from .objectives import create_objective
from .tree import HostTree
from .utils.log import log_info, set_verbosity


from .config import LightGBMError  # noqa: F401  (public at lgb.basic.*)


class Booster:
    def __init__(self, params: Optional[dict] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None,
                 silent: bool = False):
        self.params = dict(params or {})
        self.config = Config.from_params(self.params)
        set_verbosity(-1 if silent else self.config.verbosity)
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._loaded: Optional[dict] = None
        self.boosting = None
        self.train_set: Optional[Dataset] = None
        self.name_valid_sets: List[str] = []
        self._attr: Dict[str, str] = {}
        self._train_data_name = "training"

        if train_set is not None:
            self._init_train(train_set)
        elif model_file is not None:
            from .utils.file_io import open_file
            with open_file(model_file) as fh:
                self._init_from_string(fh.read())
        elif model_str is not None:
            self._init_from_string(model_str)
        else:
            raise ValueError("need train_set, model_file, or model_str")

    # -------------------------------------------------------------- training

    def _check_dataset_param_changes(self, train_set: Dataset,
                                     ds_params: dict,
                                     can_rebuild: bool) -> None:
        """reference: LGBM_DatasetUpdateParamChecking — dataset-level
        parameters cannot change once the dataset is constructed UNLESS
        the raw data is still around to rebuild from (can_rebuild);
        min_data_in_leaf may grow, or shrink when feature_pre_filter was
        off (the pre-filter dropped features using the old value).  One
        rule set for both the pre-constructed and binary-cache paths."""
        old = Config.from_params(train_set.params).to_dataset_params()
        explicit = {Config.canonical_key(k) for k in self.params}
        _ck = {"categorical_feature": "categorical_column"}
        diff = {k for k, v in ds_params.items()
                if _ck.get(k, k) in explicit and old.get(k) != v}
        if not diff:
            return
        if can_rebuild and train_set.raw_data is not None:
            # rebuild the dataset under the new parameters (the
            # reference re-creates the handle when raw data is kept)
            train_set.params.update({k: ds_params[k] for k in diff})
            train_set.constructed = False
            train_set.binned = None
            # a stale out-of-core spill store holds the OLD binning —
            # drop it so the next streaming election re-spills
            store = getattr(train_set, "_block_store", None)
            if store is not None:
                if getattr(train_set, "_block_store_owned", False):
                    store.cleanup()
                train_set._block_store = None
            return
        for k in sorted(diff):
            if k == "min_data_in_leaf":
                nv, ov = ds_params[k], old.get(k, 0)
                if nv > ov or not old.get("feature_pre_filter", True):
                    train_set.params[k] = nv
                    continue
                raise LightGBMError(
                    "Reducing `min_data_in_leaf` with "
                    "`feature_pre_filter=true` may cause unexpected "
                    "behaviour for features that were pre-filtered by "
                    "the larger `min_data_in_leaf`.")
            disp = {"is_sparse": "is_enable_sparse",
                    "forcedbins_filename": "forced bins"}.get(k, k)
            raise LightGBMError(
                f"Cannot change {disp} after constructed Dataset "
                "handle.")

    def _init_train(self, train_set: Dataset) -> None:
        ds_params = self.config.to_dataset_params()
        if train_set.constructed:
            self._check_dataset_param_changes(train_set, ds_params,
                                              can_rebuild=True)
        merged = dict(ds_params)
        merged.update(train_set.params)
        train_set.params = merged
        was_constructed = train_set.constructed
        train_set.construct()
        if (not was_constructed
                and getattr(train_set, "_from_binary_cache", False)):
            # the construct call resolved to a binary cache whose stored
            # params replaced train_set.params: explicit caller params
            # that contradict them cannot be honored (no raw data to
            # rebuild from)
            self._check_dataset_param_changes(train_set, ds_params,
                                              can_rebuild=False)
        self.train_set = train_set
        self.pandas_categorical = getattr(train_set, "pandas_categorical",
                                          None)
        self.objective = create_objective(self.config)
        self.boosting = create_boosting(self.config, train_set, self.objective)
        self._resolve_metrics()

    def _resolve_metrics(self) -> None:
        """(Re)build train/valid metric objects from the current config
        (reference: Booster::CreateObjectiveAndMetrics, c_api.cpp — also
        re-run on ResetConfig when the metric list changes)."""
        train_set = self.train_set
        names = self.config.metric or self.config.default_metric()
        self._metric_names = [m for m in names
                              if m.lower() not in ("none", "na", "null", "custom")]
        # objective/metric/num_class conflicts (reference:
        # Config::CheckParamConflict + metric factory fatals)
        from .config import _METRIC_ALIASES, _OBJECTIVE_ALIASES
        obj = _OBJECTIVE_ALIASES.get(self.config.objective,
                                     self.config.objective)
        # objective "none" (custom fobj) with num_class>1 counts as a
        # multiclass objective for conflict checking (reference
        # config.cpp:246 CheckParamConflict "custom" handling)
        is_multi_obj = (obj in ("multiclass", "multiclassova")
                        or (obj == "none" and self.config.num_class > 1))
        if is_multi_obj and self.config.num_class <= 1:
            raise LightGBMError(
                "Number of classes should be specified and greater than 1 "
                "for multiclass training")
        if not is_multi_obj and obj != "none" and self.config.num_class > 1:
            raise LightGBMError(
                "Number of classes must be 1 for non-multiclass training")
        multi_metrics = {"multi_logloss", "multi_error", "auc_mu"}
        binary_metrics = {"binary_logloss", "binary_error"}
        for m in self._metric_names:
            canon = _METRIC_ALIASES.get(m, m)
            if canon in multi_metrics and self.config.num_class <= 1:
                raise LightGBMError(
                    "Number of classes should be specified and greater "
                    "than 1 for multiclass training")
            if canon in binary_metrics and is_multi_obj:
                raise LightGBMError(
                    "Multiclass objective and metrics don't match")
        train_metrics = self._build_metrics(train_set.metadata,
                                            train_set.num_data)
        valid_metrics = [self._build_metrics(ds.metadata, ds.num_data)
                         for ds in self.boosting.valid_sets]
        self.boosting.set_metrics(train_metrics, valid_metrics)

    def _build_metrics(self, metadata, num_data):
        ms = []
        for m in self._metric_names:
            mt = create_metric(m, self.config)
            if mt is not None:
                mt.init(metadata, num_data)
                ms.append(mt)
        return ms

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if data.reference is None:
            data.reference = self.train_set
        data.construct()
        self.boosting.add_valid(data, name)
        self.name_valid_sets.append(name)
        self.boosting.valid_metrics.append(
            self._build_metrics(data.metadata, data.num_data))
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration. Returns True if stopped (no more splits).
        reference: basic.py:2089 Booster.update."""
        if fobj is not None:
            K = self.boosting.num_tree_per_iteration
            score = np.asarray(self.boosting.train_score)
            s = score if K > 1 else score[0]
            grad, hess = fobj(s if K > 1 else s, self.train_set)
            return self.boosting.train_one_iter(np.asarray(grad), np.asarray(hess))
        return self.boosting.train_one_iter()

    def update_chunk(self, chunk: int, learning_rates=None) -> bool:
        """Train ``chunk`` boosting iterations in ONE fused device program
        (lax.scan macro-step, boosting/macro.py) — bit-identical to calling
        ``update()`` ``chunk`` times for the supported modes
        (``self.boosting.chunk_supported()``).  ``learning_rates``: optional
        per-iteration shrinkage schedule of length ``chunk``.  Returns True
        if training stopped (no more splittable leaves)."""
        return self.boosting.train_chunk(chunk, learning_rates)

    def rollback_one_iter(self) -> "Booster":
        self.boosting.rollback_one_iter()
        return self

    def refit(self, data, label, decay_rate: float = 0.9,
              **kwargs) -> "Booster":
        """Refit the existing trees' leaf values to new data (structures
        unchanged).  reference: basic.py:2521 Booster.refit ->
        LGBM_BoosterRefit -> GBDT::RefitTree (gbdt.cpp:267)."""
        leaf_pred = self.predict(data, pred_leaf=True)
        if self.boosting is not None:
            params = dict(self.params)
        else:   # loaded from model text: rebuild params from the header
            params = {"objective": (self._loaded["objective_name"] or
                                    "regression").split(" ")[0],
                      "num_class": self._loaded["num_class"]}
        params.update(kwargs)
        params["refit_decay_rate"] = decay_rate
        new_booster = Booster(params=params,
                              train_set=Dataset(data, label=label))
        new_booster.boosting.models = [copy.deepcopy(m) for m in self.models]
        new_booster.boosting.iter = (
            len(new_booster.boosting.models)
            // max(new_booster.boosting.num_tree_per_iteration, 1))
        new_booster.boosting.refit_leaf_values(leaf_pred, decay_rate)
        return new_booster

    def current_iteration(self) -> int:
        return self.boosting.current_iteration() if self.boosting else \
            len(self._loaded["models"]) // self._loaded["num_tree_per_iteration"]

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """reference: LGBM_BoosterGetLeafValue (src/c_api.cpp)."""
        return float(self.models[tree_id].leaf_value[leaf_id])

    def upper_bound(self) -> float:
        """Sum over trees of each tree's max leaf value (reference:
        GBDT::GetUpperBoundValue, src/boosting/gbdt.cpp:632)."""
        return float(sum(np.max(m.leaf_value[:m.num_leaves])
                         for m in self.models))

    def lower_bound(self) -> float:
        """reference: GBDT::GetLowerBoundValue (src/boosting/gbdt.cpp:640)."""
        return float(sum(np.min(m.leaf_value[:m.num_leaves])
                         for m in self.models))

    def model_from_string(self, model_str: str, verbose: bool = True) -> "Booster":
        """Reset this Booster from a model string (reference:
        Booster.model_from_string, basic.py:2438)."""
        self.boosting = None
        self.train_set = None
        self._init_from_string(model_str)
        return self

    def num_feature(self) -> int:
        return self.num_features()

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        """Shuffle the order of the models between two iterations, using
        the reference's exact LCG draw sequence (reference:
        GBDT::ShuffleModels, src/boosting/gbdt.h:80 — Fisher-Yates with
        Random(17).NextShort).  Note: like the reference, this only
        permutes the stored trees; mid-training state (scores, rollback
        history) is not re-derived.
        """
        models = self.models
        K = self.num_tree_per_iteration
        total_iter = len(models) // K
        start = max(0, start_iteration)
        end = total_iter if end_iteration <= 0 else min(total_iter,
                                                        end_iteration)
        indices = list(range(total_iter))
        x = 17                                   # Random(seed=17)
        for i in range(start, end - 1):
            x = (214013 * x + 2531011) & 0xFFFFFFFF
            r = (x >> 16) & 0x7FFF               # NextShort(i+1, end)
            j = r % (end - (i + 1)) + (i + 1)
            indices[i], indices[j] = indices[j], indices[i]
        shuffled = [models[i * K + k] for i in indices for k in range(K)]
        models[:] = shuffled
        if self.boosting is not None:
            self.boosting.models_version += 1
        return self

    def num_trees(self) -> int:
        return len(self.models)

    def num_model_per_iteration(self) -> int:
        return self.num_tree_per_iteration

    def reset_parameter(self, params: dict) -> "Booster":
        if not set(params) - {"learning_rate"}:
            # hot path: per-iteration lr schedules (callback.py) reset only
            # learning_rate every iteration — a traced scalar, so no
            # rebuild, no rollback snapshot, no recompile
            self.params.update(params)
            self.config.update(params)
            if self.boosting is not None:
                self.boosting.shrinkage_rate = self.config.learning_rate
            return self
        old_params = dict(self.params)
        old_cfg_state = copy.deepcopy(self.config.__dict__)
        old_metric_names = list(getattr(self, "_metric_names", []))
        try:
            self.params.update(params)
            self.config.update(params)
            if self.boosting is not None:
                self.boosting.shrinkage_rate = self.config.learning_rate
                self.boosting._build_jit_fns()
                # a changed metric list (or @k knobs) must be reflected in
                # eval output and LGBM_BoosterGetEvalNames (reference
                # ResetConfig re-creates the metrics)
                if any(Config.canonical_key(k) in
                       ("metric", "eval_at", "multi_error_top_k")
                       for k in params):
                    self._resolve_metrics()
        except Exception:
            # a rejected reset must not poison the booster: restore the
            # previous params/config IN PLACE (boosting shares the config
            # object) and rebuild dependent state
            self.params = old_params
            self.config.__dict__.clear()
            self.config.__dict__.update(old_cfg_state)
            self._metric_names = old_metric_names
            if self.boosting is not None:
                self.boosting.shrinkage_rate = self.config.learning_rate
                self.boosting._build_jit_fns()
            raise
        return self

    # ------------------------------------------------------------------ eval

    def eval_train(self, feval=None):
        name = self._train_data_name
        out = [(name, n, v, h) for (d, n, v, h) in self.boosting.eval_train()]
        return out + self._custom_eval(feval, name, self.boosting.train_score,
                                       self.train_set)

    def eval(self, data: Dataset, name: str, feval=None):
        """Evaluate on ``data``, which must be the training set or an added
        validation set (reference: Booster.eval, basic.py:2274; results
        carry the CALLER's name)."""
        if data is self.train_set:
            out = [(name, n, v, h)
                   for (_, n, v, h) in self.boosting.eval_train()]
            return out + self._custom_eval(feval, name,
                                           self.boosting.train_score,
                                           self.train_set)
        for i, vs in enumerate(self.boosting.valid_sets):
            if vs is data:
                out = [(name, mn, mv, h)
                       for (_, mn, mv, h) in self.boosting.eval_one_valid(i)]
                return out + self._custom_eval(
                    feval, name, self.boosting.valid_scores[i], vs)
        raise ValueError(
            "Data should be either valid data or training data")

    def set_train_data_name(self, name: str) -> "Booster":
        self._train_data_name = name
        return self

    def attr(self, key: str):
        """reference: Booster.attr (basic.py:2914) — plain string
        attributes held Python-side."""
        return self._attr.get(key)

    def set_attr(self, **kwargs) -> "Booster":
        for key, value in kwargs.items():
            if value is None:
                self._attr.pop(key, None)
            elif isinstance(value, str):
                self._attr[key] = value
            else:
                raise ValueError("Only string values are accepted")
        return self

    def eval_valid(self, feval=None):
        out = list(self.boosting.eval_valid())
        if feval is not None:
            for i, name in enumerate(self.boosting.valid_names):
                out += self._custom_eval(feval, name, self.boosting.valid_scores[i],
                                         self.boosting.valid_sets[i])
        return out

    def _custom_eval(self, feval, name, score, dataset):
        if feval is None:
            return []
        # float64: the reference's scores are double end-to-end, and the
        # builtin metrics here compute in f64 — a custom feval computing
        # the same quantity must see the same precision or the two drift
        # at the ~1e-7 the reference suite asserts against
        s = np.asarray(score, np.float64)
        if self.boosting.num_tree_per_iteration == 1:
            s = s[0]
        ret = feval(s, dataset)
        if isinstance(ret, tuple):
            ret = [ret]
        return [(name, mn, mv, hib) for (mn, mv, hib) in ret]

    # ------------------------------------------------------------- inference

    @property
    def models(self) -> List[HostTree]:
        return self.boosting.models if self.boosting is not None else self._loaded["models"]

    @property
    def num_tree_per_iteration(self) -> int:
        return (self.boosting.num_tree_per_iteration if self.boosting is not None
                else self._loaded["num_tree_per_iteration"])

    @property
    def num_class(self) -> int:
        return self.config.num_class if self.boosting is not None \
            else self._loaded["num_class"]

    def _forest(self, start_iter: int, stop_iter: int):
        """StackedForest over models[start*K : stop*K], cached per range."""
        from .predict import StackedForest
        K = self.num_tree_per_iteration
        # keyed on the boosting's monotonic models_version (bumped on every
        # extend/rollback/refit/DART-scale), not object ids — CPython id
        # reuse after rollback+retrain could alias a stale forest
        version = getattr(self.boosting, "models_version", 0)
        key = (start_iter, stop_iter, len(self.models), version)
        cached = getattr(self, "_forest_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        forest = StackedForest(self.models[start_iter * K:stop_iter * K])
        self._forest_cache = (key, forest)
        return forest

    def _device_forest(self, forest):
        """DeviceForest for ``forest``, cached alongside the host cache."""
        from .predict import DeviceForest
        cached = getattr(self, "_device_forest_cache", None)
        if cached is not None and cached[0] is forest:
            return cached[1]
        dev = DeviceForest(forest)
        self._device_forest_cache = (forest, dev)
        return dev

    def predict(self, data, num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, start_iteration: int = 0,
                **kwargs) -> np.ndarray:
        """reference: basic.py:2281 Booster.predict / _InnerPredictor.

        Sparse (scipy) inputs are predicted in bounded row chunks without
        materializing the full dense matrix.  ``pred_early_stop`` /
        ``pred_early_stop_freq`` / ``pred_early_stop_margin`` kwargs mirror
        the reference (src/boosting/prediction_early_stop.cpp).
        """
        from .utils.timer import global_timer
        if isinstance(data, str):
            # file-path prediction input (reference: Predictor reads the
            # data file through the parsers, src/application/predictor.hpp)
            from .io_utils import load_prediction_file
            data = load_prediction_file(data, self.num_features(),
                                        dict(self.params))
        if hasattr(data, "dtypes") and hasattr(data, "columns"):
            # pandas: re-apply the training category mappings (reference:
            # predict routes through _data_from_pandas with the stored
            # pandas_categorical, basic.py:523)
            from .dataset import _data_from_pandas
            data = _data_from_pandas(
                data, None, None,
                getattr(self, "pandas_categorical", None))[0]
        if hasattr(data, "values"):
            data = data.values
        n_feat = (data.shape[1] if hasattr(data, "shape")
                  and len(getattr(data, "shape", ())) == 2 else None)
        disable_check = kwargs.get("predict_disable_shape_check",
                                   self.config.predict_disable_shape_check)
        if (n_feat is not None and n_feat != self.num_features()
                and not disable_check):
            raise LightGBMError(
                f"The number of features in data ({n_feat}) is not the same "
                f"as it was in training data ({self.num_features()}).\n"
                "You can set ``predict_disable_shape_check=true`` to discard "
                "this error, but please be aware what you are doing.")
        if hasattr(data, "tocsr"):  # scipy sparse: chunked densify
            from .predict import predict_csr_chunked
            return predict_csr_chunked(
                lambda chunk: self.predict(
                    chunk, num_iteration=num_iteration, raw_score=raw_score,
                    pred_leaf=pred_leaf, pred_contrib=pred_contrib,
                    start_iteration=start_iteration, **kwargs),
                data)
        with global_timer.section("Booster::Predict"):
            return self._predict_inner(
                data, num_iteration, raw_score, pred_leaf, pred_contrib,
                start_iteration, **kwargs)

    def _predict_inner(self, data, num_iteration=None, raw_score=False,
                       pred_leaf=False, pred_contrib=False,
                       start_iteration=0, **kwargs) -> np.ndarray:
        X = np.ascontiguousarray(np.asarray(data, np.float64))
        if X.ndim == 1:
            X = X[None, :]
        K = self.num_tree_per_iteration
        models = self.models
        n_total_iter = len(models) // max(K, 1)
        if num_iteration is None or num_iteration < 0:
            # best_iteration is already a 1-based count of iterations to keep
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else n_total_iter)
        stop_iter = min(start_iteration + num_iteration, n_total_iter)

        use_device = bool(kwargs.get("device", False))

        if pred_leaf:
            forest = self._forest(start_iteration, stop_iter)
            if use_device:
                return self._device_forest(forest).predict_leaf(X)
            return forest.predict_leaf(X)
        if pred_contrib:
            from .utils.shap import tree_shap_batch
            F = self.num_features()
            out = np.zeros((X.shape[0], K, F + 1), np.float64)
            for it in range(start_iteration, stop_iter):
                for k in range(K):
                    tree_shap_batch(models[it * K + k], X, out[:, k, :])
            return out.reshape(X.shape[0], -1) if K > 1 else out[:, 0, :]

        early_stop = None
        # reference: the Predictor applies margin-based early stopping to
        # raw-score prediction too (predictor.hpp constructs the early-stop
        # instance independently of is_raw_score)
        if kwargs.get("pred_early_stop"):
            from .predict import make_early_stop
            obj = (self.objective_name or "").split(" ")[0]
            kind = ("binary" if obj == "binary"
                    else "multiclass" if obj in ("multiclass", "softmax",
                                                 "multiclassova", "ova")
                    else "none")
            early_stop = make_early_stop(
                kind,
                float(kwargs.get("pred_early_stop_margin", 10.0)),
                int(kwargs.get("pred_early_stop_freq", 10)))

        forest = self._forest(start_iteration, stop_iter)
        if use_device and early_stop is None:
            raw = self._device_forest(forest).predict_raw(X, num_class=K)
        else:
            raw = forest.predict_raw(X, num_class=K, early_stop=early_stop)
        if self.average_output and stop_iter > start_iteration:
            raw /= (stop_iter - start_iteration)
        if raw_score:
            return raw[0] if K == 1 else raw.T
        conv = self._convert_output(raw)
        return conv[0] if (K == 1 and conv.shape[0] == 1) else conv.T

    def serve(self, config=None, **overrides):
        """In-process inference server over this model (docs/SERVING.md).

        Returns a ``serving.Server``: thread-safe ``submit``/``predict``
        with micro-batching into power-of-two shape buckets, per-request
        deadlines, queue backpressure, atomic model hot-swap
        (``swap_model``), a JSON-dumpable metrics registry, and graceful
        drain on ``close()``.  Keyword overrides populate a
        ``serving.ServingConfig`` (e.g. ``max_batch_rows=512,
        backend="host"``).

        No process boundary is crossed: where the reference serves
        predictions through the C API from caller threads
        (src/application/predictor.hpp row-parallel OpenMP), here
        concurrent callers' rows are coalesced into one padded device
        batch per bucket shape so XLA compiles once per
        (model, bucket, num_class) and never again.
        """
        from .serving import Server
        return Server(self, config=config, **overrides)

    def _convert_output(self, raw: np.ndarray) -> np.ndarray:
        obj = self.objective_name.split(" ")[0] if self.objective_name else ""
        if obj == "binary":
            sig = self._objective_param("sigmoid", 1.0)
            return 1.0 / (1.0 + np.exp(-sig * raw))
        if obj in ("multiclass", "softmax"):
            e = np.exp(raw - raw.max(axis=0, keepdims=True))
            return e / e.sum(axis=0, keepdims=True)
        if obj in ("multiclassova", "ova"):
            sig = self._objective_param("sigmoid", 1.0)
            return 1.0 / (1.0 + np.exp(-sig * raw))
        if obj in ("poisson", "gamma", "tweedie"):
            return np.exp(raw)
        if obj in ("cross_entropy_lambda", "xentlambda"):
            return np.log1p(np.exp(raw))
        if obj in ("cross_entropy", "xentropy"):
            return 1.0 / (1.0 + np.exp(-raw))
        if obj == "regression" and self._objective_param_flag("sqrt"):
            return np.sign(raw) * raw * raw
        return raw

    def _objective_param(self, key: str, default: float) -> float:
        for tok in (self.objective_name or "").split(" ")[1:]:
            if tok.startswith(f"{key}:"):
                return float(tok.split(":", 1)[1])
        if self.boosting is not None:
            return float(getattr(self.config, key, default))
        return default

    def _objective_param_flag(self, key: str) -> bool:
        return key in (self.objective_name or "").split(" ")[1:]

    def num_features(self) -> int:
        if self.boosting is not None:
            return self.train_set.num_total_features
        return self._loaded["max_feature_idx"] + 1

    def num_data(self) -> int:
        return self.train_set.num_data if self.train_set else 0

    def feature_name(self) -> List[str]:
        if self.boosting is not None:
            return self.train_set.feature_names
        return self._loaded["feature_names"]

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        F = self.num_features()
        imp = np.zeros(F, np.float64)
        K = self.num_tree_per_iteration
        models = self.models
        if iteration is None:
            # reference: Booster.feature_importance defaults to
            # best_iteration (basic.py:2744)
            iteration = self.best_iteration
        stop = len(models) if iteration is None or iteration <= 0 \
            else iteration * K
        for ht in models[:stop]:
            ns = ht.num_leaves - 1
            for s in range(ns):
                f = int(ht.split_feature[s])
                if importance_type == "split":
                    imp[f] += 1
                else:
                    imp[f] += max(float(ht.split_gain[s]), 0.0)
        if importance_type == "split":
            return imp.astype(np.int64)
        return imp

    def trees_to_dataframe(self):
        """Preorder node table over the model dump — the reference's exact
        column set (basic.py:1906: tree_index, node_depth, node_index,
        children, parent_index, split fields, missing handling,
        value/weight/count)."""
        import pandas as pd
        if self.num_trees() == 0:
            raise LightGBMError(
                "There are no trees in this Booster and thus nothing "
                "to parse")
        fnames = self.feature_name()

        def is_split(nd):
            return "split_index" in nd

        def nidx(nd, ti):
            kind = "S" if is_split(nd) else "L"
            num = nd.get("split_index" if is_split(nd) else "leaf_index", 0)
            return f"{ti}-{kind}{num}"

        rows = []

        def walk(nd, ti, depth, parent):
            rec = {
                "tree_index": ti, "node_depth": depth,
                "node_index": nidx(nd, ti), "left_child": None,
                "right_child": None, "parent_index": parent,
                "split_feature": (fnames[nd["split_feature"]]
                                  if is_split(nd) else None),
                "split_gain": None, "threshold": None, "decision_type": None,
                "missing_direction": None, "missing_type": None,
                "value": None, "weight": None, "count": None,
            }
            if is_split(nd):
                rec.update(
                    left_child=nidx(nd["left_child"], ti),
                    right_child=nidx(nd["right_child"], ti),
                    split_gain=nd["split_gain"], threshold=nd["threshold"],
                    decision_type=nd["decision_type"],
                    missing_direction=("left" if nd["default_left"]
                                       else "right"),
                    missing_type=nd["missing_type"],
                    value=nd["internal_value"], weight=nd["internal_weight"],
                    count=nd["internal_count"])
                rows.append(rec)
                walk(nd["left_child"], ti, depth + 1, rec["node_index"])
                walk(nd["right_child"], ti, depth + 1, rec["node_index"])
            else:
                rec["value"] = nd["leaf_value"]
                if parent is not None:
                    # single-node trees keep weight/count as None
                    # (reference _is_single_node_tree, basic.py:1944)
                    rec["weight"] = nd.get("leaf_weight")
                    rec["count"] = nd.get("leaf_count")
                rows.append(rec)

        for t in self.dump_model()["tree_info"]:
            walk(t["tree_structure"], t["tree_index"], 1, None)
        return pd.DataFrame(rows)

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style=False):
        """reference: basic.py:2762 get_split_value_histogram (incl. the
        xgboost_style (SplitValue, Count) table form)."""
        fnames = self.feature_name()
        fidx = fnames.index(feature) if isinstance(feature, str) else int(feature)
        vals = []
        for t in self.models:
            for s in range(t.num_leaves - 1):
                if int(t.split_feature[s]) == fidx:
                    if int(t.decision_type[s]) & 1:
                        raise LightGBMError(
                            "Cannot compute split value histogram for the "
                            "categorical feature")
                    vals.append(float(t.threshold[s]))
        if bins is None or (isinstance(bins, int) and xgboost_style):
            n_unique = len(np.unique(vals))
            bins = max(min(n_unique, bins) if bins is not None else n_unique,
                       1)
        hist, bin_edges = np.histogram(vals, bins=bins)
        if xgboost_style:
            ret = np.column_stack((bin_edges[1:], hist))
            ret = ret[ret[:, 1] > 0]
            try:
                import pandas as pd
                return pd.DataFrame(ret, columns=["SplitValue", "Count"])
            except ImportError:
                return ret
        return hist, bin_edges

    # -------------------------------------------------------------- model IO

    @property
    def sub_model_name(self) -> str:
        if self.boosting is not None:
            return {"gbdt": "tree", "dart": "tree", "goss": "tree", "rf": "tree"}.get(
                self.config.boosting, "tree")
        return self._loaded["sub_model_name"]

    @property
    def average_output(self) -> bool:
        if self.boosting is not None:
            return self.config.boosting in ("rf", "random_forest")
        return self._loaded["average_output"]

    @property
    def objective_name(self) -> str:
        if self.boosting is not None and self.objective is not None:
            return self._objective_to_string()
        if self._loaded is not None:
            return self._loaded["objective_name"]
        return ""

    def _objective_to_string(self) -> str:
        c = self.config
        name = self.objective.name
        if name == "binary":
            return f"binary sigmoid:{c.sigmoid:g}"
        if name in ("multiclass", "multiclassova"):
            s = f"{name} num_class:{c.num_class}"
            if name == "multiclassova":
                s += f" sigmoid:{c.sigmoid:g}"
            return s
        if name == "lambdarank":
            return "lambdarank"
        if name == "regression" and c.reg_sqrt:
            return "regression sqrt"
        return name

    @property
    def label_index(self) -> int:
        return 0

    @property
    def max_feature_idx(self) -> int:
        return self.num_features() - 1

    @property
    def feature_names(self) -> List[str]:
        return self.feature_name()

    @property
    def feature_infos(self) -> List[str]:
        """reference format: [min:max] per numeric feature, ':'-joined cats."""
        if self.boosting is None:
            return self._loaded["feature_infos"]
        out = []
        ds = self.train_set
        for f in range(ds.num_total_features):
            m = ds.bin_mappers[f] if f < len(ds.bin_mappers) else None
            if m is None or m.is_trivial:
                out.append("none")
            elif m.bin_type == BinType.CATEGORICAL:
                out.append(":".join(str(c) for c in m.bin_2_categorical))
            else:
                out.append(f"[{m.min_val:g}:{m.max_val:g}]")
        return out

    @property
    def params_str(self) -> str:
        return "\n".join(f"[{k}: {v}]" for k, v in sorted(self.params.items()))

    def feature_importance_int(self):
        imp = self.feature_importance("split")
        names = self.feature_name()
        return [(names[i], int(imp[i])) for i in range(len(imp))]

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        # reference: Booster.save_model/model_to_string default
        # num_iteration to best_iteration (basic.py:2407,2490) so an
        # early-stopped model round-trips at its best point
        if num_iteration is None:
            num_iteration = self.best_iteration
        out = save_model_to_string(self, num_iteration, start_iteration)
        # category value lists ride in the model file (reference:
        # _dump_pandas_categorical, basic.py:385)
        import json as _json
        pc = getattr(self, "pandas_categorical", None)

        def _default(o):
            import numpy as _np
            if isinstance(o, _np.generic):
                return o.item()
            raise TypeError(f"not JSON serializable: {type(o)}")

        out += ("\npandas_categorical:"
                + _json.dumps(pc, default=_default) + "\n")
        return out

    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        # atomic for local paths — temp sibling + os.replace, parent dirs
        # created — so a crash mid-write can never leave a truncated model
        # and snapshot_out into a nonexistent dir works; scheme:// paths
        # route through the pluggable file-system seam (reference:
        # VirtualFileWriter, src/io/file_io.cpp)
        from .utils.file_io import write_atomic
        write_atomic(filename,
                     self.model_to_string(num_iteration, start_iteration))
        return self

    def _init_from_string(self, s: str) -> None:
        self._loaded = load_model_from_string(s)
        self.objective = None
        self.pandas_categorical = self._loaded.get("pandas_categorical")

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> dict:
        """JSON model dump (reference: gbdt_model_text.cpp:21 DumpModel;
        num_iteration defaults to best_iteration, basic.py:2536)."""
        if num_iteration is None:
            num_iteration = self.best_iteration
        K = max(self.num_tree_per_iteration, 1)
        total_iter = len(self.models) // K
        start = max(0, int(start_iteration))
        stop = (total_iter if num_iteration <= 0
                else min(total_iter, start + int(num_iteration)))
        models = self.models[start * K: stop * K]

        def node_to_dict(t: HostTree, node: int) -> dict:
            if node < 0:
                li = ~node
                return {
                    "leaf_index": int(li),
                    "leaf_value": float(t.leaf_value[li]),
                    "leaf_weight": float(t.leaf_weight[li]) if len(t.leaf_weight) > li else 0.0,
                    "leaf_count": int(t.leaf_count[li]) if len(t.leaf_count) > li else 0,
                }
            dt = int(t.decision_type[node])
            return {
                "split_index": int(node),
                "split_feature": int(t.split_feature[node]),
                "split_gain": float(t.split_gain[node]),
                "threshold": float(t.threshold[node]),
                "decision_type": "==" if dt & 1 else "<=",
                "default_left": bool(dt & 2),
                "missing_type": ["None", "Zero", "NaN"][(dt >> 2) & 3],
                "internal_value": float(t.internal_value[node]),
                "internal_weight": float(t.internal_weight[node]),
                "internal_count": int(t.internal_count[node]),
                "left_child": node_to_dict(t, int(t.left_child[node])),
                "right_child": node_to_dict(t, int(t.right_child[node])),
            }

        return {
            "name": self.sub_model_name,
            "version": "v3",
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": self.label_index,
            "max_feature_idx": self.max_feature_idx,
            "objective": self.objective_name,
            "average_output": self.average_output,
            "feature_names": self.feature_names,
            "tree_info": [
                {"tree_index": i, "num_leaves": t.num_leaves,
                 "num_cat": t.num_cat, "shrinkage": t.shrinkage,
                 "tree_structure": node_to_dict(t, 0 if t.num_leaves > 1 else -1)}
                for i, t in enumerate(models)
            ],
        }

    def __copy__(self):
        return self.__deepcopy__(None)

    def __deepcopy__(self, _memo):
        """reference: Booster.__deepcopy__ — a model-string round trip."""
        return Booster(model_str=self.model_to_string(num_iteration=0))

    def __getstate__(self):
        """Pickle as the serialized model plus light host state (the live
        boosting state holds device buffers and ctypes handles)."""
        return {
            "params": self.params,
            "best_iteration": self.best_iteration,
            "best_score": self.best_score,
            "_attr": self._attr,
            "_train_data_name": self._train_data_name,
            "model_str": self.model_to_string(num_iteration=0),
        }

    def __setstate__(self, state):
        model_str = state.pop("model_str")
        self.__dict__.update(state)
        self.config = Config.from_params(dict(self.params))
        self._loaded = None
        self.boosting = None
        self.train_set = None
        self.name_valid_sets = []
        self._init_from_string(model_str)

    def free_dataset(self) -> "Booster":
        return self

    def set_network(self, machines, local_listen_port: int = 12400,
                    listen_time_out: int = 120,
                    num_machines: int = 1) -> "Booster":
        """Start the multi-host JAX runtime from a reference-style machine
        list (reference: Booster.set_network, basic.py:1867 ->
        LGBM_NetworkInit; here it maps onto jax.distributed — see
        parallel/network.py)."""
        from .parallel.network import init_network
        init_network(machines=machines, local_listen_port=local_listen_port,
                     listen_time_out=listen_time_out,
                     num_machines=num_machines)
        return self

    def free_network(self) -> "Booster":
        from .parallel.network import free_network
        free_network()
        return self
