"""Out-of-core streaming data plane.

Trains datasets whose binned matrix never fits host RAM or HBM at once:
the two-level budget planner (``ops.planner.plan_stream``) elects
row-block streaming, the matrix spills to a checksummed block store
(``blockstore.BlockStore``) and a double-buffered pump feeds device row
blocks to a host-driven grower that folds per-leaf histograms across
blocks before each split scan (``stream``).  See docs/PERF.md
"out-of-core streaming".
"""

from ..ops.planner import (StreamPlan, host_limit_bytes,  # noqa: F401
                           plan_stream, predict_host_peak_bytes,
                           predict_stream_device_peak_bytes)
from .blockstore import (BlockStore, BlockStoreCorruptError,  # noqa: F401
                         FORMAT as BLOCKSTORE_FORMAT)
from .stream import (BlockPump, StreamGrower,  # noqa: F401
                     default_spill_dir, host_rss_bytes,
                     host_rss_peak_bytes, maybe_stream_setup)

__all__ = [
    "BlockPump", "BlockStore", "BlockStoreCorruptError", "StreamGrower",
    "StreamPlan", "default_spill_dir", "host_limit_bytes",
    "host_rss_bytes", "host_rss_peak_bytes", "maybe_stream_setup",
    "plan_stream", "predict_host_peak_bytes",
    "predict_stream_device_peak_bytes",
]
