"""Checksummed spill store for bin-packed row blocks (out-of-core plane).

The binned matrix of a dataset that cannot be resident on host RAM or
HBM lives here instead: feature-major ``[G, rows]`` row blocks written
ATOMICALLY (``file_io.write_atomic`` — temp sibling + os.replace, the
PR 2 checkpoint convention) under a ``manifest.json`` carrying a sha256
per block, so a torn write or bit-rot surfaces as a loud
``BlockStoreCorruptError`` instead of silently wrong trees.  Reads are
memory-mapped (``numpy.memmap``) for random access, or ``readinto`` a
caller-owned buffer for the block pump's bounded-RSS sequential scans
(mapped page-cache pages would count toward the RSS peak the planner
budgets).

reference analogue: XGBoost's external-memory page files (the
block-compressed feature pages of arXiv 1806.11248); here a page is a
fixed row range of the ONE dense feature-major matrix this repo's
kernels consume, so a block device_puts with no host-side reshape.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import List, Optional

import numpy as np

from ..utils.file_io import write_atomic

FORMAT = "lgbm_tpu.blockstore.v1"
MANIFEST = "manifest.json"


class BlockStoreCorruptError(RuntimeError):
    """A block's bytes do not match the manifest checksum (or the
    manifest itself is unreadable/inconsistent)."""


def _sha256(buf) -> str:
    return hashlib.sha256(buf).hexdigest()


class BlockStore:
    """Directory of ``block_NNNNN.bin`` files + an atomic manifest.

    Lifecycle: ``create`` -> ``append_rows``/``write_block`` ->
    ``finalize`` (writes the manifest; the store is unreadable before),
    or ``open`` an existing finalized store.  ``from_array`` spills a
    resident host matrix in one call.
    """

    def __init__(self, path: str, meta: dict, writable: bool = False):
        self.path = str(path)
        self.num_rows = int(meta["num_rows"])
        self.num_cols = int(meta["num_cols"])
        self.block_rows = int(meta["block_rows"])
        self.dtype = np.dtype(meta["dtype"])
        self._blocks: List[dict] = list(meta.get("blocks", []))
        self._writable = writable
        self._buf: Optional[np.ndarray] = None   # [block_rows, G] writer buf
        self._buf_fill = 0
        self._rows_written = sum(int(b["rows"]) for b in self._blocks)
        self._verified: set = set()

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, path: str, num_rows: int, num_cols: int, dtype,
               block_rows: int) -> "BlockStore":
        if num_rows <= 0 or num_cols <= 0 or block_rows <= 0:
            raise ValueError("num_rows, num_cols and block_rows must be > 0")
        os.makedirs(path, exist_ok=True)
        return cls(path, {
            "num_rows": num_rows, "num_cols": num_cols,
            "block_rows": min(int(block_rows), int(num_rows)),
            "dtype": str(np.dtype(dtype)), "blocks": [],
        }, writable=True)

    @classmethod
    def from_array(cls, path: str, arr: np.ndarray,
                   block_rows: int) -> "BlockStore":
        """Spill a resident row-major [n, G] binned matrix."""
        st = cls.create(path, arr.shape[0], arr.shape[1], arr.dtype,
                        block_rows)
        st.append_rows(arr)
        return st.finalize()

    def append_rows(self, rows: np.ndarray) -> "BlockStore":
        """Buffer row-major ``[r, G]`` rows; full blocks flush to disk as
        feature-major ``[G, block_rows]`` files.  Any chunk sizes
        compose — the final ragged block is flushed by ``finalize``."""
        if not self._writable:
            raise RuntimeError("BlockStore is read-only (already finalized)")
        rows = np.asarray(rows)
        if rows.ndim != 2 or rows.shape[1] != self.num_cols:
            raise ValueError(
                f"expected [r, {self.num_cols}] rows, got {rows.shape}")
        if self._rows_written + self._buf_fill + rows.shape[0] > self.num_rows:
            raise ValueError(
                f"append past the end: "
                f"{self._rows_written + self._buf_fill}+{rows.shape[0]} > "
                f"{self.num_rows}")
        rows = rows.astype(self.dtype, copy=False)
        pos = 0
        while pos < rows.shape[0]:
            if self._buf is None:
                self._buf = np.empty((self.block_rows, self.num_cols),
                                     self.dtype)
                self._buf_fill = 0
            take = min(self.block_rows - self._buf_fill, rows.shape[0] - pos)
            self._buf[self._buf_fill:self._buf_fill + take] = \
                rows[pos:pos + take]
            self._buf_fill += take
            pos += take
            if self._buf_fill == self.block_rows:
                self._flush_block()
        return self

    def _flush_block(self) -> None:
        data = np.ascontiguousarray(self._buf[:self._buf_fill].T)  # [G, r]
        raw = data.tobytes()
        name = f"block_{len(self._blocks):05d}.bin"
        write_atomic(os.path.join(self.path, name), raw)
        self._blocks.append({"file": name, "rows": int(self._buf_fill),
                             "sha256": _sha256(raw), "size": len(raw)})
        self._rows_written += self._buf_fill
        self._buf_fill = 0

    def finalize(self) -> "BlockStore":
        """Flush the ragged tail block and write the manifest atomically.
        The manifest is the commit point: an interrupted spill leaves no
        manifest and ``open`` refuses the directory."""
        if not self._writable:
            return self
        if self._buf_fill:
            self._flush_block()
        if self._rows_written != self.num_rows:
            raise ValueError(
                f"finalize with {self._rows_written}/{self.num_rows} rows "
                "appended")
        write_atomic(os.path.join(self.path, MANIFEST), json.dumps({
            "format": FORMAT, "num_rows": self.num_rows,
            "num_cols": self.num_cols, "block_rows": self.block_rows,
            "dtype": str(self.dtype), "blocks": self._blocks,
        }, indent=1))
        self._writable = False
        self._buf = None
        return self

    # -- reading -----------------------------------------------------------

    @classmethod
    def open(cls, path: str) -> "BlockStore":
        mp = os.path.join(path, MANIFEST)
        try:
            with open(mp) as fh:
                meta = json.load(fh)
        except (OSError, ValueError) as e:
            raise BlockStoreCorruptError(
                f"unreadable blockstore manifest at {mp}: {e}") from e
        if meta.get("format") != FORMAT:
            raise BlockStoreCorruptError(
                f"{mp}: unknown blockstore format {meta.get('format')!r}")
        st = cls(path, meta, writable=False)
        if st._rows_written != st.num_rows:
            raise BlockStoreCorruptError(
                f"{mp}: manifest covers {st._rows_written} of "
                f"{st.num_rows} rows")
        return st

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    def block_bounds(self, i: int):
        """(start_row, rows) of block ``i`` in the pinned block order."""
        start = i * self.block_rows
        return start, int(self._blocks[i]["rows"])

    def nbytes(self) -> int:
        return sum(int(b["size"]) for b in self._blocks)

    def read_block(self, i: int, out: Optional[np.ndarray] = None,
                   verify: Optional[bool] = None) -> np.ndarray:
        """Block ``i`` as feature-major ``[G, rows]``.

        ``out=None`` returns a read-only ``np.memmap`` view; passing a
        preallocated ``[G, block_rows]`` buffer reads into its prefix
        instead (the pump's bounded-RSS path).  The checksum is verified
        on the first read of each block per open (``verify`` overrides);
        a mismatch raises ``BlockStoreCorruptError`` — loudly, never
        wrong trees.
        """
        if self._writable:
            raise RuntimeError("BlockStore not finalized yet")
        b = self._blocks[i]
        fp = os.path.join(self.path, b["file"])
        rows = int(b["rows"])
        shape = (self.num_cols, rows)
        check = (i not in self._verified) if verify is None else verify
        if out is not None:
            view = out.reshape(-1)[:self.num_cols * rows]
            with open(fp, "rb") as fh:
                got = fh.readinto(memoryview(view.view(np.uint8)))
            if got != int(b["size"]):
                raise BlockStoreCorruptError(
                    f"{fp}: short read ({got} of {b['size']} bytes)")
            data = view.reshape(shape)
        else:
            try:
                data = np.memmap(fp, dtype=self.dtype, mode="r", shape=shape)
            except (OSError, ValueError) as e:
                raise BlockStoreCorruptError(f"{fp}: {e}") from e
        if check:
            digest = _sha256(memoryview(np.ascontiguousarray(data)
                                        .view(np.uint8).reshape(-1)))
            if digest != b["sha256"]:
                raise BlockStoreCorruptError(
                    f"{fp}: checksum mismatch (manifest {b['sha256'][:12]}…,"
                    f" file {digest[:12]}…) — the spill store is corrupt; "
                    "rebuild the dataset")
            self._verified.add(i)
        return data

    def cleanup(self) -> None:
        """Delete the store directory (best-effort)."""
        shutil.rmtree(self.path, ignore_errors=True)
