"""Bulk offline scoring: the blockstore pump pointed at inference.

Streamed training (data/stream.py) reads checksummed feature blocks
through a double-buffered ``BlockPump`` to FOLD histograms; this module
drives the same pump through fixed-shape ROUTING programs to score
datasets that dwarf both memories — ROADMAP item 5(c)'s billion-row
offline pass, the symmetric twin of out-of-core training:

- **input** is a finalized ``BlockStore`` of raw ``[F, rows]`` float32
  feature blocks (sha256-verified on read, torn writes surface loudly);
- **programs** are the ONE block-sized bucket of the serving AOT family
  (``fleet.aot.make_bulk_program``): a resumed run deserializes instead
  of re-tracing, so a crash costs no recompile on restart;
- **output** banks per-block ``[K, rows]`` float64 raw scores through a
  ``ScoreSink`` whose manifest is atomically REWRITTEN after every
  block — each rewrite is a commit point, so resume-after-kill skips
  exactly the banked blocks and reproduces the rest byte-identically
  (scores come off the same program + the serving epilogue, and f64
  leaf accumulation is per-row independent — block boundaries cannot
  change a single bit);
- **placement** shards blocks across ``fleet.topology`` devices
  ICI-before-DCN (``plan_block_shards``): the home slice fills first in
  round-robin, spillover crosses the slow tier last — PV-Tree's
  elect-before-you-ship rule applied to batch work distribution.

Serving bit-parity contract: a banked block equals
``DeviceForest.predict_raw_padded`` on the same rows exactly — the
scorer routes through the SAME traversal program family and the SAME
probed epilogue (device leaf-sum only where the one-time bit-exactness
probe passed, ``predict.gather_leaf_sum`` on the host otherwise).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import instant as _instant, span as _span
from ..utils.file_io import write_atomic
from ..utils.log import log_info, log_warning
from .blockstore import BlockStore
from .stream import BlockPump, host_rss_peak_bytes

SCORE_FORMAT = "lgbm_tpu.scorestore.v1"
SCORE_MANIFEST = "score_manifest.json"


class ScoreSinkError(RuntimeError):
    """A score block's bytes do not match its manifest checksum, or an
    existing sink's geometry contradicts the requested run."""


def _sha256(buf) -> str:
    return hashlib.sha256(buf).hexdigest()


class ScoreSink:
    """Directory of ``scores_NNNNN.bin`` float64 ``[K, rows]`` blocks
    under an atomically rewritten manifest.

    The write protocol inverts the BlockStore's: there the manifest is a
    single commit point at ``finalize`` (a half-spilled store is
    worthless), here every block is independently valuable, so
    ``write_block`` lands the block file atomically and THEN rewrites
    the whole manifest atomically — after a kill at any instant, the
    manifest names exactly the blocks whose bytes are fully on disk, and
    ``open_or_create`` on the same path resumes by skipping them.
    """

    def __init__(self, path: str, meta: dict):
        self.path = str(path)
        self.num_rows = int(meta["num_rows"])
        self.num_class = int(meta["num_class"])
        self.block_rows = int(meta["block_rows"])
        self.num_blocks = int(meta["num_blocks"])
        self.model_digest = str(meta["model_digest"])
        self._blocks: Dict[int, dict] = {
            int(k): v for k, v in meta.get("blocks", {}).items()}

    @classmethod
    def open_or_create(cls, path: str, num_rows: int, num_class: int,
                       block_rows: int, num_blocks: int,
                       model_digest: str) -> "ScoreSink":
        """Open an existing sink (validating that it belongs to THIS
        run's geometry and model — resuming someone else's scores would
        silently interleave two models) or create an empty one."""
        mp = os.path.join(path, SCORE_MANIFEST)
        if os.path.exists(mp):
            try:
                with open(mp) as fh:
                    meta = json.load(fh)
            except (OSError, ValueError) as e:
                raise ScoreSinkError(
                    f"unreadable score manifest at {mp}: {e}") from e
            if meta.get("format") != SCORE_FORMAT:
                raise ScoreSinkError(
                    f"{mp}: unknown score-sink format "
                    f"{meta.get('format')!r}")
            want = {"num_rows": int(num_rows), "num_class": int(num_class),
                    "block_rows": int(block_rows),
                    "num_blocks": int(num_blocks),
                    "model_digest": str(model_digest)}
            got = {k: (str(meta.get(k)) if k == "model_digest"
                       else int(meta.get(k, -1))) for k in want}
            if got != want:
                raise ScoreSinkError(
                    f"{mp}: existing sink disagrees with this run "
                    f"(sink {got}, run {want}) — choose a fresh output "
                    "directory or delete the stale one")
            return cls(path, meta)
        os.makedirs(path, exist_ok=True)
        sink = cls(path, {
            "num_rows": int(num_rows), "num_class": int(num_class),
            "block_rows": int(block_rows), "num_blocks": int(num_blocks),
            "model_digest": str(model_digest), "blocks": {}})
        sink._write_manifest()
        return sink

    # -- manifest ----------------------------------------------------------

    def _write_manifest(self) -> None:
        write_atomic(os.path.join(self.path, SCORE_MANIFEST), json.dumps({
            "format": SCORE_FORMAT, "num_rows": self.num_rows,
            "num_class": self.num_class, "block_rows": self.block_rows,
            "num_blocks": self.num_blocks,
            "model_digest": self.model_digest,
            "blocks": {str(k): self._blocks[k]
                       for k in sorted(self._blocks)},
        }, indent=1))

    def banked(self) -> set:
        """Block indices whose scores are committed on disk."""
        return set(self._blocks)

    @property
    def complete(self) -> bool:
        return len(self._blocks) == self.num_blocks

    def nbytes(self) -> int:
        return sum(int(b["size"]) for b in self._blocks.values())

    # -- blocks ------------------------------------------------------------

    def write_block(self, i: int, scores: np.ndarray) -> None:
        """Bank block ``i``'s ``[K, rows]`` float64 scores: atomic block
        file first, atomic manifest rewrite second (the commit point)."""
        scores = np.ascontiguousarray(scores, np.float64)
        if scores.ndim != 2 or scores.shape[0] != self.num_class:
            raise ValueError(
                f"expected [{self.num_class}, rows] scores for block {i}, "
                f"got {scores.shape}")
        raw = scores.tobytes()
        name = f"scores_{int(i):05d}.bin"
        write_atomic(os.path.join(self.path, name), raw)
        self._blocks[int(i)] = {
            "file": name, "rows": int(scores.shape[1]),
            "sha256": _sha256(raw), "size": len(raw)}
        self._write_manifest()

    def read_block(self, i: int) -> np.ndarray:
        """Block ``i`` as ``[K, rows]`` float64, checksum-verified."""
        b = self._blocks.get(int(i))
        if b is None:
            raise ScoreSinkError(f"score block {i} is not banked")
        fp = os.path.join(self.path, b["file"])
        with open(fp, "rb") as fh:
            raw = fh.read()
        if len(raw) != int(b["size"]) or _sha256(raw) != b["sha256"]:
            raise ScoreSinkError(
                f"{fp}: checksum mismatch — the score bank is corrupt; "
                "delete the block (or the sink) and re-run to re-score")
        return np.frombuffer(raw, np.float64).reshape(
            self.num_class, int(b["rows"])).copy()


def plan_block_shards(num_blocks: int, devices: Sequence) -> Tuple[int, ...]:
    """Assign each block a ``DeviceSpec.device_id`` round-robin in
    ICI-before-DCN order: the coordinator's slice (the first device's)
    fills first, remote slices take spillover last — the bulk analogue
    of the serving router's device-local-first dispatch."""
    devices = tuple(devices)
    if not devices:
        raise ValueError("plan_block_shards needs at least one device")
    home = devices[0].slice_id
    order = sorted(devices, key=lambda d: (d.slice_id != home,
                                           d.slice_id, d.device_id))
    return tuple(order[i % len(order)].device_id
                 for i in range(max(int(num_blocks), 0)))


class BulkScorer:
    """Stream a feature BlockStore through one fixed-shape routing
    program and bank raw scores with crash-resume (module docstring).

    ``device_forest`` is a ``predict.DeviceForest`` (any precision /
    variant — the program is its AOT export arm, so the scores are the
    variant-independent routing verdict).  ``devices`` defaults to the
    single local device; multi-device runs pass ``plan_devices(n)`` and
    score only the blocks ``plan_block_shards`` assigns to
    ``local_device_id`` — every participant resumes into the SAME sink,
    whose per-block manifest commits make concurrent banking safe to
    interleave at block granularity.
    """

    def __init__(self, device_forest, store: BlockStore, sink_path: str,
                 num_class: int = 1, devices=None,
                 local_device_id: int = 0, aot_store=None,
                 ledger=None, digest: Optional[str] = None):
        if store.dtype != np.dtype(np.float32):
            raise ValueError(
                f"bulk scoring expects a float32 feature store, got "
                f"{store.dtype}")
        self.dev = device_forest
        self.store = store
        self.sink_path = str(sink_path)
        self.K = max(int(num_class), 1)
        if devices is None:
            from ..fleet.topology import plan_devices
            devices = plan_devices(1)
        self.devices = tuple(devices)
        self.local_device_id = int(local_device_id)
        self.aot_store = aot_store
        self.ledger = ledger
        if digest is None:
            from ..serving.registry import forest_digest
            digest = forest_digest(device_forest.forest)
        self.digest = str(digest)

    # -- device programs ---------------------------------------------------

    def _build_programs(self):
        import jax
        import jax.numpy as jnp

        from ..fleet.aot import make_bulk_program
        F = int(self.store.num_cols)
        br = int(self.store.block_rows)
        program, source = make_bulk_program(
            self.dev, F, br, self.digest, self.aot_store)

        # feature blocks arrive device-resident as [F, rows]; the routing
        # program wants the padded row-major [block_rows, F] bucket shape
        def prep(xb):
            X = xb.T.astype(jnp.float32)
            pad = br - X.shape[0]
            return jnp.pad(X, ((0, pad), (0, 0))) if pad else X

        return program, source, jax.jit(prep)

    def _score_block(self, leaves_dev, rows: int) -> np.ndarray:
        """Serving epilogue on one block's [T, block_rows] leaves: device
        f32 sum only where the DeviceForest's one-time probe proved it
        bit-exact, host f64 gather otherwise — predict_raw_padded's exact
        decision, so banked scores == serving scores bit for bit."""
        if self.dev.leaf_value is not None and \
                self.dev._epilogue_verified(self.K):
            raw = np.asarray(self.dev._leaf_sum_jit(leaves_dev, self.K),
                             np.float64)
            return raw[:, :rows]
        from ..predict import gather_leaf_sum
        leaves = np.asarray(leaves_dev)[:, :rows]
        return gather_leaf_sum(self.dev.forest, leaves, self.K)

    # -- residency ---------------------------------------------------------

    def _predicted_peaks(self) -> Tuple[int, int]:
        """(device, host) peak-byte predictions from the planner's byte
        models: routing planes + one bucket program on device; the pump's
        read-ahead window + one score block on host."""
        from ..ops import planner as _planner
        f = self.dev.forest
        F = int(self.store.num_cols)
        br = int(self.store.block_rows)
        accel = None
        dp = _planner.predict_forest_bytes(
            num_trees=int(f.num_trees),
            nodes_dim=int(f.split_feature.shape[1]),
            leaves_dim=int(f.leaf_value.shape[1]),
            precision=self.dev.precision,
            cat_words=int(f.cat_words.size), accel=accel,
            routing_only=self.dev.routing_only)
        dp += _planner.predict_program_bytes(
            num_trees=int(f.num_trees), bucket_rows=br, features=F,
            accel=accel)
        hp = 3 * F * br * 4 + self.K * br * 8
        return int(dp), int(hp)

    # -- the run -----------------------------------------------------------

    def run(self, max_blocks: Optional[int] = None) -> dict:
        """Score every un-banked block assigned to this device; returns
        a stats dict.  ``max_blocks`` caps the number of blocks banked
        THIS call (the crash-injection seam the resume tests kill at) —
        a capped run exits cleanly with the sink partially committed,
        exactly the state a SIGKILL between manifest rewrites leaves."""
        import jax

        nb = int(self.store.num_blocks)
        sink = ScoreSink.open_or_create(
            self.sink_path, int(self.store.num_rows), self.K,
            int(self.store.block_rows), nb, self.digest)
        shards = plan_block_shards(nb, self.devices)
        mine = [i for i in range(nb) if shards[i] == self.local_device_id]
        banked = sink.banked()
        todo = [i for i in mine if i not in banked]
        skipped = len(mine) - len(todo)
        if max_blocks is not None:
            todo = todo[:max(int(max_blocks), 0)]

        program, source, prep = self._build_programs()
        pred_dev, pred_host = self._predicted_peaks()
        lease = None
        if self.ledger is not None:
            lease = self.ledger.try_lease(
                f"bulk:{self.digest}", pred_dev, plane="serving")
            if lease is None:
                log_warning(
                    "bulk scorer: residency ledger denied a "
                    f"{pred_dev}-byte serving lease; scoring anyway — "
                    "expect HBM pressure against the co-resident planes")

        _instant("bulk.plan", blocks=nb, mine=len(mine), skipped=skipped,
                 todo=len(todo), program=source,
                 predicted_device_peak_bytes=pred_dev,
                 predicted_host_peak_bytes=pred_host)
        rows_scored = 0
        blocks_scored = 0
        t0 = time.perf_counter()
        try:
            with _span("bulk.run", blocks=len(todo)):
                for i, start, rows, xb in self._pump_blocks(todo):
                    with _span("bulk.block", block=i, rows=rows):
                        leaves = program(prep(xb))
                        raw = self._score_block(leaves, rows)
                        sink.write_block(i, raw)
                    _obs_registry.counter("bulk_blocks_total").inc()
                    rows_scored += int(rows)
                    blocks_scored += 1
        finally:
            if lease is not None:
                self.ledger.release(lease)
        elapsed = max(time.perf_counter() - t0, 1e-9)

        measured_dev = 0
        try:
            ms = jax.local_devices()[0].memory_stats() or {}
            measured_dev = int(ms.get("peak_bytes_in_use", 0))
        except Exception:  # noqa: BLE001 — CPU backends have no stats
            pass
        rps = rows_scored / elapsed
        stats = {
            "rows_scored": rows_scored,
            "blocks_scored": blocks_scored,
            "skipped_blocks": skipped,
            "total_blocks": nb,
            "complete": sink.complete,
            "seconds": elapsed,
            "rows_per_sec": rps,
            "bulk_rows_per_sec_per_device": rps / max(len(self.devices), 1),
            "num_devices": len(self.devices),
            "program_source": source,
            "predicted_device_peak_bytes": pred_dev,
            "predicted_host_peak_bytes": pred_host,
            "measured_device_peak_bytes": measured_dev,
            "measured_host_peak_bytes": host_rss_peak_bytes(),
        }
        log_info(
            f"bulk scorer: {blocks_scored} blocks / {rows_scored} rows in "
            f"{elapsed:.2f}s ({rps / 1e6:.3f} Mrow/s, {skipped} banked "
            f"blocks skipped, program={source})")
        return stats

    def _pump_blocks(self, todo: List[int]):
        """Yield ``(index, start, rows, device_block)`` for ``todo``.

        A fresh full scan rides the double-buffered ``BlockPump``
        (read-ahead overlaps H2D with compute); a resume/sharded subset
        reads exactly its own blocks instead — re-pumping banked blocks
        just to discard them would re-pay their disk+H2D bytes.
        """
        import jax
        if len(todo) == self.store.num_blocks:
            yield from BlockPump(self.store)
            return
        buf = np.empty((self.store.num_cols, self.store.block_rows),
                       self.store.dtype)
        for i in todo:
            start, rows = self.store.block_bounds(i)
            view = self.store.read_block(i, out=buf)
            _obs_registry.counter("stream_blocks_total").inc()
            yield i, start, rows, jax.device_put(np.ascontiguousarray(view))
