"""Out-of-core streamed training: block pump + host-driven tree grower.

The resident growers (grower.py / grower_rounds.py) are single jitted
programs over a device-resident ``[G, n]`` binned matrix.  When the
two-level budget planner (ops/planner.py ``plan_stream``) rules full
residency out on EITHER memory, this module trains the same trees with
the matrix living in a checksummed spill store (data/blockstore.py):

- per-row state (scores, gradients, bagging/GOSS weights, leaf routing)
  stays device-resident — it is O(n), not O(n*G);
- every histogram pass re-streams the matrix block by block through a
  double-buffered pump (``BlockPump``: ``jax.device_put`` of block t+1
  overlaps compute on block t), folding per-leaf histograms across
  blocks BEFORE the split scan — the one-pass-per-level access pattern
  of the GPU learners (arXiv 1706.08359, 1806.11248);
- the round/commit logic mirrors the batched-frontier grower
  (grower_rounds.py) op for op, driven from the host between block
  passes instead of inside a ``lax.while_loop``.

Bit-parity contract (tests/test_stream.py): quantized payloads fold in
int32 — associative, so streamed == resident is BYTE-identical model
text.  f32 payloads fold through the carry-in kernels
(ops/histogram.py ``init=``) in PINNED ascending block order, which
continues the exact per-bin add sequence of the resident
scatter-formulation kernels — streamed == resident is bit-identical
when both runs pin the scatter segment path (the CPU default;
``LGBM_TPU_SEGHIST=scatter`` pins it on accelerators, where the
sorted-arena formulation sums in a different order).

Bagging/GOSS masks are evaluated per block (the [n] mask is sliced with
the rows), so sampled workloads stream no extra bytes per excluded row
beyond the binned block itself.
"""

from __future__ import annotations

import functools
import os
import queue
import tempfile
import threading
import weakref
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..grower import GrowerConfig, TreeArrays, _LeafBest, row_goes_left
from ..grower_rounds import _pad_scatter
from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import instant as _instant, span as _span
from ..obs.watchdog import beat as _beat
from ..ops.histogram import (build_histogram, build_histogram_int,
                             quant_levels, segment_histogram,
                             segment_histogram_int, take_from_table)
from ..ops.split import SplitResult, best_split_for_leaf, leaf_output
from ..utils.log import log_info, log_warning
from .blockstore import BlockStore


def host_rss_bytes() -> int:
    """Current resident-set size of this process (VmRSS), 0 if unknown."""
    return _proc_status_kb("VmRSS:") * 1024


def host_rss_peak_bytes() -> int:
    """Peak resident-set size of this process (VmHWM), falling back to
    the CURRENT RSS on kernels that do not report a high-water mark —
    the measured twin of the planner's predicted host peak."""
    peak = _proc_status_kb("VmHWM:")
    return (peak or _proc_status_kb("VmRSS:")) * 1024


def _proc_status_kb(key: str) -> int:
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith(key):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    return 0


def default_spill_dir() -> str:
    base = os.environ.get("LGBM_TPU_STREAM_DIR")
    if base:
        os.makedirs(base, exist_ok=True)
        return tempfile.mkdtemp(prefix="blocks_", dir=base)
    return tempfile.mkdtemp(prefix="lgbm_tpu_stream_")


class BlockPump:
    """Double-buffered host->device block iterator over a BlockStore.

    A daemon reader thread stays up to ``depth`` blocks ahead: it reads
    block t+1 into a fresh host buffer (``readinto`` — bounded RSS, no
    page-cache mappings inflating VmHWM) and dispatches its
    ``jax.device_put`` while the consumer computes on block t.  Yields
    ``(index, start_row, rows, device_block)`` in the pinned ascending
    block order every parity claim depends on.
    """

    def __init__(self, store: BlockStore, depth: int = 2,
                 prefetch: bool = True):
        self.store = store
        self.depth = max(int(depth), 1)
        self.prefetch = prefetch

    def _load(self, i: int):
        start, rows = self.store.block_bounds(i)
        buf = np.empty((self.store.num_cols, rows), self.store.dtype)
        self.store.read_block(i, out=buf)
        return i, start, rows, jax.device_put(buf)

    def __iter__(self):
        nb = self.store.num_blocks
        _obs_registry.counter("stream_passes_total").inc()
        if not self.prefetch:
            for i in range(nb):
                _obs_registry.counter("stream_blocks_total").inc()
                _beat("stream.pump", count=i + 1)
                yield self._load(i)
            return
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def reader():
            try:
                for i in range(nb):
                    if stop.is_set():
                        return
                    with _span("stream.block_put", block=i):
                        item = self._load(i)
                    q.put(item)
                q.put(None)
            except BaseException as e:   # surfaced on the consumer side
                q.put(e)

        t = threading.Thread(target=reader, daemon=True,
                             name="lgbm-stream-pump")
        t.start()
        gauge = _obs_registry.gauge("stream_blocks_inflight")
        try:
            while True:
                item = q.get()
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                gauge.set(q.qsize() + 1)
                _obs_registry.counter("stream_blocks_total").inc()
                # pump heartbeat: a wedged spill store / reader thread
                # goes stale here and the watchdog names the stall
                _beat("stream.pump", count=item[0] + 1)
                yield item
        finally:
            stop.set()
            gauge.set(0)
            # drain so the reader's blocked put() can observe stop
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass


class IngestPump:
    """Double-buffered host->device iterator over RAW float chunks —
    ``BlockPump``'s ingest twin (ops/ingest.py's device binning path).

    The source is the host [n, F] float32 matrix (or anything row-
    sliceable to one); a daemon reader thread slices chunk t+1 and
    dispatches its ``jax.device_put`` while the consumer's bucketize+
    pack kernel runs on chunk t, so raw floats never materialize whole
    on device and the H2D copy hides under compute.  Yields
    ``(index, start_row, rows, device_chunk)`` in pinned ascending
    order (resume-safe: the binned matrix fills front to back).

    With multiple ``devices``, chunk placement round-robins ICI-before-
    DCN via ``plan_block_shards`` (data/score.py) — each device bins
    only its own row shard of the construction.
    """

    def __init__(self, source, chunk_rows: int, depth: int = 2,
                 devices=None, prefetch: bool = True):
        self.source = source
        self.n = int(source.shape[0])
        self.chunk_rows = max(int(chunk_rows), 1)
        self.num_chunks = max(-(-self.n // self.chunk_rows), 1)
        self.depth = max(int(depth), 1)
        self.prefetch = prefetch
        self.devices = list(devices) if devices else None
        if self.devices and len(self.devices) > 1:
            # describe the jax devices through the topology seam (device
            # i = spec i, the row-major mesh order), then round-robin
            # chunks ICI-before-DCN; the returned device_ids index
            # straight back into ``self.devices``
            from ..fleet.topology import plan_devices
            from .score import plan_block_shards
            specs = plan_devices(len(self.devices))
            self._owner = list(plan_block_shards(self.num_chunks, specs))
        else:
            self._owner = [0] * self.num_chunks

    def _load(self, i: int):
        start = i * self.chunk_rows
        rows = min(self.chunk_rows, self.n - start)
        chunk = np.ascontiguousarray(self.source[start:start + rows],
                                     dtype=np.float32)
        dev = self.devices[self._owner[i]] if self.devices else None
        return i, start, rows, jax.device_put(chunk, dev)

    def __iter__(self):
        if not self.prefetch:
            for i in range(self.num_chunks):
                _obs_registry.counter("ingest_blocks_total").inc()
                _beat("ingest.pump", count=i + 1)
                yield self._load(i)
            return
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()

        def reader():
            try:
                for i in range(self.num_chunks):
                    if stop.is_set():
                        return
                    with _span("ingest.block_put", block=i):
                        item = self._load(i)
                    q.put(item)
                q.put(None)
            except BaseException as e:   # surfaced on the consumer side
                q.put(e)

        t = threading.Thread(target=reader, daemon=True,
                             name="lgbm-ingest-pump")
        t.start()
        gauge = _obs_registry.gauge("ingest_blocks_inflight")
        try:
            while True:
                item = q.get()
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                gauge.set(q.qsize() + 1)
                _obs_registry.counter("ingest_blocks_total").inc()
                # pump heartbeat: a wedged reader thread goes stale here
                _beat("ingest.pump", count=item[0] + 1)
                yield item
        finally:
            stop.set()
            gauge.set(0)
            # drain so the reader's blocked put() can observe stop
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass


class StreamContext:
    """Everything the streamed executor hangs off a GBDT instance."""

    def __init__(self, store: BlockStore, plan):
        self.store = store
        self.plan = plan
        self.grower: Optional["StreamGrower"] = None


def _config_stream_blockers(b) -> list:
    """Config features the streamed executor does not cover (the resident
    path keeps them); mirrors the fused-kernel context gate's shape."""
    cc = b.config
    meta = b.meta.resolved()
    blockers = []
    if not getattr(type(b), "_stream_ok", True):
        blockers.append(f"boosting={b.boosting_type}")
    if b._mesh is not None:
        blockers.append(f"tree_learner={b.tree_learner_type} sharding")
    if meta.has_bundles:
        blockers.append("EFB bundles")
    if bool(meta.is_categorical.any()):
        blockers.append("categorical features")
    if cc.monotone_constraints:
        blockers.append("monotone_constraints")
    if cc.extra_trees:
        blockers.append("extra_trees")
    if cc.feature_fraction_bynode < 1.0:
        blockers.append("feature_fraction_bynode")
    if (cc.cegb_penalty_split > 0.0 or cc.cegb_penalty_feature_coupled
            or cc.cegb_penalty_feature_lazy):
        blockers.append("CEGB")
    if cc.forcedsplits_filename:
        blockers.append("forced splits")
    return blockers


def maybe_stream_setup(b) -> bool:
    """Decide streamed vs resident execution for booster ``b`` and, when
    streaming, stand up the spill store.  Called by ``GBDT.__init__`` in
    place of the whole-matrix device upload; returns True when the
    booster trains out-of-core (``b.binned`` stays None).
    """
    from ..ops.planner import plan_stream
    ds = b.train_set
    store = getattr(ds, "_block_store", None)
    n, G = b._binned_shape
    plan = plan_stream(
        rows=n, features=G, num_bins=b.num_bins,
        num_leaves=b.config.num_leaves, num_class=b.num_tree_per_iteration,
        quant=bool(b.config.use_quantized_grad),
        method=b.config.tpu_hist_method,
        round_width=b.config.tpu_round_width)
    _instant("planner.plan_stream", rows=n, features=G, **plan.summary())
    if not plan.stream and (store is None or ds.binned is not None):
        # resident fits (or streaming is disabled) and the matrix is
        # available — a leftover spill store from an earlier booster
        # does not force streaming when residency is the better verdict
        return False
    blockers = _config_stream_blockers(b)
    if blockers:
        if store is not None and ds.binned is None:
            from ..config import LightGBMError
            raise LightGBMError(
                "the training Dataset is block-backed (out-of-core spill "
                "store), which requires a streaming-compatible config; "
                "unsupported here: " + ", ".join(blockers))
        log_warning(
            "out-of-core streaming elected by the two-level budget "
            f"planner ({plan.reason}) but not supported with "
            + ", ".join(blockers)
            + "; training resident — expect memory pressure "
            "(LGBM_TPU_STREAM=0 silences this)")
        return False
    if not plan.feasible and store is None:
        log_warning(
            "stream planner: predicted peaks "
            f"(device {plan.predicted_device_peak_bytes / 1e9:.2f} GB, "
            f"host {plan.predicted_host_peak_bytes / 1e9:.2f} GB) exceed "
            "a budget even at block_rows="
            f"{plan.block_rows}; training may OOM")
    if store is None:
        # spill the resident host matrix once; subsequent boosters on the
        # same Dataset (cv folds, resume rebuilds) reuse the store
        path = default_spill_dir()
        with _span("stream.spill", rows=n, block_rows=plan.block_rows):
            store = BlockStore.from_array(path, ds.host_binned(),
                                          plan.block_rows)
        ds._block_store = store
        ds._block_store_owned = True
        weakref.finalize(ds, BlockStore.cleanup, store)
        if ds.free_raw_data:
            ds.release_host_binned()
        log_info(
            f"out-of-core streaming: spilled {n} rows x {G} columns to "
            f"{path} ({store.num_blocks} blocks of {store.block_rows} "
            f"rows, {store.nbytes() / 1e9:.2f} GB; {plan.reason})")
    if not plan.stream:
        # a block-backed Dataset whose host matrix is gone streams even
        # when residency would have fit — re-state the plan in streamed
        # terms (the store's real geometry, streamed-mode predicted
        # peaks) so checkpoint provenance and the trace record what the
        # run actually does, not the election that never applied
        from ..ops.planner import (predict_host_peak_bytes,
                                   predict_stream_device_peak_bytes)
        dp = predict_stream_device_peak_bytes(
            n, G, b.num_bins, store.block_rows, b.config.num_leaves,
            b.num_tree_per_iteration, bool(b.config.use_quantized_grad))
        hp = predict_host_peak_bytes(
            n, G, 1 if b.num_bins <= 256 else 2, store.block_rows)[0]
        plan = plan._replace(
            stream=True, block_rows=int(store.block_rows),
            num_blocks=int(store.num_blocks),
            predicted_device_peak_bytes=dp,
            predicted_host_peak_bytes=hp,
            feasible=(dp <= plan.device_budget_bytes
                      and hp <= plan.host_budget_bytes),
            reason="block-backed dataset (the spill store is the only "
                   "copy of the binned matrix)")
    b._stream = StreamContext(store, plan)
    b.stream_plan = plan
    _obs_registry.gauge("stream_block_rows").set(int(store.block_rows))
    _obs_registry.gauge("stream_num_blocks").set(int(store.num_blocks))
    _obs_registry.gauge("host_rss_peak_bytes").set(host_rss_peak_bytes())
    return True


class StreamCarry(NamedTuple):
    """Between-round device state of one streamed tree (the [L]-sized
    slice of grower_rounds' Carry, plus the [n] leaf routing)."""

    tree: TreeArrays
    best: _LeafBest
    hist: jax.Array            # [L, ch, G, B] hist cache
    leaf_sg: jax.Array
    leaf_sh: jax.Array
    leaf_cnt: jax.Array
    leaf_parent_side: jax.Array
    split_idx: jax.Array
    leaf_id: jax.Array         # [n] i32


class StreamGrower:
    """Host-driven mirror of ``grower_rounds._grow_tree_rounds_traced``
    whose per-row work is folded over spill-store blocks.

    Every [L]/[KCAP]-sized decision (candidate ordering, exact-prefix
    validation, split application, cache refresh) ports the rounds
    grower's expressions verbatim; the per-row passes (histogram fold +
    candidate routing) run per block through the carry-in kernel seam.
    Gated by ``maybe_stream_setup`` to the numeric unsharded case —
    exactly the contexts where the two formulations are bit-equal.
    """

    def __init__(self, b):
        self.b = b
        cfg: GrowerConfig = b.grower_cfg
        self.cfg = cfg
        meta = b.meta.resolved()
        self.L = cfg.num_leaves
        self.B = cfg.num_bins
        self.G = int(b._binned_shape[1])
        self.n = int(b.num_data)
        self.F = len(meta.num_bin)
        self.KCAP = min(max(self.L - 1, 1), max(1, cfg.round_width))
        self.quant = cfg.quant
        self.tile = cfg.tile_rows if cfg.tile_rows > 0 else None
        # pallas/fused point kernels have no carry-in seam; the fold uses
        # the staged scatter/matmul family (auto resolution)
        m = cfg.hist_method
        self.hist_method = "auto" if m in ("pallas", "fused") else m
        (self.num_bin, self.missing_type, self.default_bin, self.is_cat,
         self.feat_group, self.feat_start) = b.meta.as_runtime_arrays()
        self.hp = cfg.hp
        self._q_levels = quant_levels(cfg.quant_bins) if self.quant else None
        self._build_fns()

    def pump(self) -> BlockPump:
        return BlockPump(self.b._stream.store)

    # ------------------------------------------------------------- programs

    def _build_fns(self):
        L, B, G, KCAP = self.L, self.B, self.G, self.KCAP
        F = len(self.b.meta.resolved().num_bin)
        hp = self.hp
        cfg = self.cfg
        quant = self.quant
        tile = self.tile
        num_bin, missing_type = self.num_bin, self.missing_type
        default_bin, is_cat = self.default_bin, self.is_cat
        feat_group, feat_start = self.feat_group, self.feat_start
        iota_L = jnp.arange(L, dtype=jnp.int32)
        iota_K = jnp.arange(KCAP, dtype=jnp.int32)

        def split_conv(ghist, cnt, qscales):
            if not quant:
                return ghist
            from ..ops.split import quant_rescale_hist
            return quant_rescale_hist(ghist, qscales[0], qscales[1], cnt)

        def one_leaf_best(fm, qscales, ghist, sg, sh, cnt, depth):
            hist = split_conv(ghist, cnt, qscales)
            r = best_split_for_leaf(
                hist, sg, sh, cnt, num_bin, missing_type, default_bin,
                is_cat, hp, feature_mask=fm, monotone_constraints=None,
                leaf_output_bounds=None, has_categorical=False,
                extra_rand_u=None)
            if cfg.max_depth > 0:
                r = r._replace(gain=jnp.where(depth >= cfg.max_depth,
                                              -jnp.inf, r.gain))
            return r

        def search_all(fm, qscales, hists, sgs, shs, cnts, depths):
            return jax.vmap(functools.partial(one_leaf_best, fm, qscales))(
                hists, sgs, shs, cnts, depths)

        def cache_from(sr: SplitResult) -> _LeafBest:
            return _LeafBest(
                gain=sr.gain, feature=sr.feature, threshold=sr.threshold,
                default_left=sr.default_left,
                left_sum_grad=sr.left_sum_grad,
                left_sum_hess=sr.left_sum_hess, left_count=sr.left_count,
                right_sum_grad=sr.right_sum_grad,
                right_sum_hess=sr.right_sum_hess,
                right_count=sr.right_count,
                is_categorical=sr.is_categorical, cat_bitset=sr.cat_bitset)

        # ---- root histogram fold + initial carry ------------------------
        def root_block(acc, block, start, grad, hess, mask, gq, hq):
            C = block.shape[1]
            w = jax.lax.dynamic_slice(mask, (start,), (C,))
            if quant:
                g = jax.lax.dynamic_slice(gq, (start,), (C,))
                h = jax.lax.dynamic_slice(hq, (start,), (C,))
                return acc + build_histogram_int(
                    block, g, h, w > 0, B, method=self.hist_method,
                    levels=self._q_levels, tile_rows=tile)
            g = jax.lax.dynamic_slice(grad, (start,), (C,))
            h = jax.lax.dynamic_slice(hess, (start,), (C,))
            return build_histogram(block, g, h, w, B,
                                   method=self.hist_method,
                                   tile_rows=tile, init=acc)

        self._root_block = jax.jit(root_block)

        def root_commit(root_hist, grad, hess, mask, fmask, gq, hq, gs, hs):
            if quant:
                member = mask > 0
                root_sg = jnp.sum(jnp.where(member, gq, 0).astype(
                    jnp.int32)).astype(jnp.float32) * gs
                root_sh = jnp.sum(jnp.where(member, hq, 0).astype(
                    jnp.int32)).astype(jnp.float32) * hs
                root_cnt = jnp.sum(member.astype(jnp.float32))
                qscales = (gs, hs)
                hist_cache = jnp.zeros((L, 2, G, B), jnp.int32) \
                    .at[0].set(root_hist)
            else:
                root_sg = jnp.sum(grad * mask)
                root_sh = jnp.sum(hess * mask)
                root_cnt = jnp.sum(mask)
                qscales = (jnp.float32(1.0), jnp.float32(1.0))
                hist_cache = jnp.zeros((L, 3, G, B), jnp.float32) \
                    .at[0].set(root_hist)
            tree = TreeArrays.empty(L)
            leaf_sg = jnp.zeros(L, jnp.float32).at[0].set(root_sg)
            leaf_sh = jnp.zeros(L, jnp.float32).at[0].set(root_sh)
            leaf_cnt = jnp.zeros(L, jnp.float32).at[0].set(root_cnt)
            best = cache_from(search_all(
                fmask, qscales, hist_cache, leaf_sg, leaf_sh, leaf_cnt,
                tree.leaf_depth))
            return StreamCarry(
                tree=tree, best=best, hist=hist_cache, leaf_sg=leaf_sg,
                leaf_sh=leaf_sh, leaf_cnt=leaf_cnt,
                leaf_parent_side=jnp.zeros(L, jnp.int32),
                split_idx=jnp.array(0, jnp.int32),
                leaf_id=jnp.zeros(self.n, jnp.int32))

        self._root_commit = jax.jit(root_commit)

        def active_gains(c: StreamCarry):
            active = iota_L < c.tree.num_leaves
            return jnp.where(active, c.best.gain, -jnp.inf)

        def cond_state(c: StreamCarry):
            return c.split_idx, jnp.max(active_gains(c))

        self._cond = jax.jit(cond_state)

        # ---- per-round candidate tables (device [L] gathers feed the
        # per-block routing; mirrors the rounds grower's router table) ---
        def round_tables(c: StreamCarry):
            gains = active_gains(c)
            pos = gains > 0.0
            npos = jnp.sum(pos.astype(jnp.int32))
            budget = (L - c.tree.num_leaves).astype(jnp.int32)
            k = jnp.minimum(jnp.minimum(npos, budget), KCAP)
            order = jnp.argsort(-gains, stable=True)
            rank = jnp.zeros(L, jnp.int32).at[order].set(iota_L)
            idl = jnp.clip(order[:KCAP], 0, L - 1)
            b_ = c.best
            feat_l = jnp.clip(b_.feature, 0, F - 1)
            live_l = pos & (rank < k)
            tables = (
                jnp.where(live_l, rank, KCAP),        # crank per leaf
                feat_group[feat_l],                    # group column
                b_.threshold,
                b_.default_left,
                missing_type[feat_l],
                default_bin[feat_l],
                num_bin[feat_l],
                feat_start[feat_l],
                b_.left_count <= b_.right_count,       # smaller-child side
            )
            return tables, gains, rank, k, idl

        self._tables = jax.jit(round_tables)

        # ---- per-block routing + segment-histogram fold -----------------
        def block_step(seg, block, start, grad, hess, mask, leaf_id,
                       tables, gq, hq):
            C = block.shape[1]
            (crank_l, grp_l, thr_l, dl_l, mt_l, db_l, nb_l, fs_l,
             sl_l) = tables
            leaf = jax.lax.dynamic_slice(leaf_id, (start,), (C,))
            w = jax.lax.dynamic_slice(mask, (start,), (C,))
            crank = crank_l[leaf]
            grp = grp_l[leaf]
            nb = nb_l[leaf]
            col = jnp.take_along_axis(block, grp[None, :],
                                      axis=0)[0].astype(jnp.int32)
            dec = col - fs_l[leaf] + 1
            binf = jnp.where((dec >= 1) & (dec < nb), dec, 0)
            gl = row_goes_left(binf, thr_l[leaf], dl_l[leaf], None, None,
                               mt_l[leaf], db_l[leaf], nb)
            row_small = gl == sl_l[leaf]
            slot = jnp.where(row_small, crank, KCAP)
            if quant:
                g = jax.lax.dynamic_slice(gq, (start,), (C,))
                h = jax.lax.dynamic_slice(hq, (start,), (C,))
                seg = seg + segment_histogram_int(
                    block, g, h, w > 0, slot, KCAP, B,
                    levels=self._q_levels, tile_rows=tile)
            else:
                g = jax.lax.dynamic_slice(grad, (start,), (C,))
                h = jax.lax.dynamic_slice(hess, (start,), (C,))
                member = (slot < KCAP) & (w > 0)
                seg = segment_histogram(
                    block, g, h, w, jnp.where(member, slot, KCAP), KCAP,
                    B, tile_rows=tile, init=seg)
            return seg, gl, crank

        self._block_step = jax.jit(block_step)

        def seg_zero():
            ch = 2 if quant else 3
            dt = jnp.int32 if quant else jnp.float32
            return jnp.zeros((KCAP, ch, G, B), dt)

        self._seg_zero = seg_zero

        # ---- children search + exact-prefix validation + commit ---------
        def round_commit(c: StreamCarry, seg, gl_full, crank_full, gains,
                         rank, k, idl, fmask, qscales):
            b_ = c.best
            small_left = b_.left_count <= b_.right_count
            ph = c.hist[idl]
            lg_, lh_, lc_ = (b_.left_sum_grad[idl], b_.left_sum_hess[idl],
                             b_.left_count[idl])
            rg_, rh_, rc_ = (b_.right_sum_grad[idl],
                             b_.right_sum_hess[idl], b_.right_count[idl])
            depth_c = c.tree.leaf_depth[idl] + 1
            sl = small_left[idl][:, None, None, None]
            h_left = jnp.where(sl, seg, ph - seg)
            h_right = ph - h_left
            res = search_all(
                fmask, qscales,
                jnp.concatenate([h_left, h_right]),
                jnp.concatenate([lg_, rg_]), jnp.concatenate([lh_, rh_]),
                jnp.concatenate([lc_, rc_]),
                jnp.concatenate([depth_c, depth_c]))

            cg = jnp.where(jnp.isnan(res.gain), -jnp.inf, res.gain)
            pair_max = jnp.maximum(cg[:KCAP], cg[KCAP:])
            pair_max = jnp.where(iota_K < k, pair_max, -jnp.inf)
            pcm = jax.lax.cummax(pair_max)
            sel_sorted = gains[idl]
            follow = (iota_K == 0) | (sel_sorted >= jnp.concatenate(
                [jnp.full((1,), -jnp.inf), pcm[:-1]]))
            if cfg.rounds_relaxed:
                m = k
            else:
                m = jnp.minimum(k, jnp.cumprod(
                    follow.astype(jnp.int32)).sum().astype(jnp.int32))

            pos = gains > 0.0
            sel = pos & (rank < m)
            node_of = c.split_idx + rank
            newleaf_of = c.tree.num_leaves + rank
            feat = b_.feature
            lg, lh, lc = (b_.left_sum_grad, b_.left_sum_hess, b_.left_count)
            rg, rh, rc = (b_.right_sum_grad, b_.right_sum_hess,
                          b_.right_count)
            tree = c.tree
            pn = jnp.maximum(tree.leaf_parent, 0)
            fixl = sel & (tree.leaf_parent >= 0) & (c.leaf_parent_side == 0)
            fixr = sel & (tree.leaf_parent >= 0) & (c.leaf_parent_side == 1)
            left_child = _pad_scatter(tree.left_child, pn, node_of, fixl)
            right_child = _pad_scatter(tree.right_child, pn, node_of, fixr)
            parent_out = leaf_output(c.leaf_sg, c.leaf_sh, hp.lambda_l1,
                                     hp.lambda_l2, hp.max_delta_step)
            new_depth = tree.leaf_depth + 1
            ps = functools.partial(_pad_scatter, idx=node_of, sel=sel)
            tree = tree._replace(
                split_feature=ps(tree.split_feature, val=feat),
                threshold_bin=ps(tree.threshold_bin, val=b_.threshold),
                default_left=ps(tree.default_left, val=b_.default_left),
                is_categorical=ps(tree.is_categorical,
                                  val=b_.is_categorical),
                cat_bitset=ps(tree.cat_bitset, val=b_.cat_bitset),
                left_child=ps(left_child, val=~iota_L),
                right_child=ps(right_child, val=~newleaf_of),
                split_gain=ps(tree.split_gain, val=b_.gain),
                internal_value=ps(tree.internal_value, val=parent_out),
                internal_weight=ps(tree.internal_weight, val=c.leaf_sh),
                internal_count=ps(tree.internal_count, val=c.leaf_cnt),
                leaf_parent=_pad_scatter(
                    jnp.where(sel, node_of, tree.leaf_parent),
                    newleaf_of, node_of, sel),
                leaf_depth=_pad_scatter(
                    jnp.where(sel, new_depth, tree.leaf_depth),
                    newleaf_of, new_depth, sel),
                num_leaves=tree.num_leaves + m,
            )
            leaf_parent_side = _pad_scatter(
                jnp.where(sel, 0, c.leaf_parent_side),
                newleaf_of, jnp.ones(L, jnp.int32), sel)
            new_leaf_id = jnp.where((crank_full < m) & ~gl_full,
                                    c.tree.num_leaves + crank_full,
                                    c.leaf_id)
            leaf_sg = _pad_scatter(jnp.where(sel, lg, c.leaf_sg),
                                   newleaf_of, rg, sel)
            leaf_sh = _pad_scatter(jnp.where(sel, lh, c.leaf_sh),
                                   newleaf_of, rh, sel)
            leaf_cnt = _pad_scatter(jnp.where(sel, lc, c.leaf_cnt),
                                    newleaf_of, rc, sel)
            small = seg[jnp.clip(rank, 0, KCAP - 1)]
            hist_left = jnp.where(small_left[:, None, None, None],
                                  small, c.hist - small)
            hist_right = c.hist - hist_left
            selb = sel[:, None, None, None]
            hist = _pad_scatter(jnp.where(selb, hist_left, c.hist),
                                newleaf_of, hist_right, sel)
            idc = jnp.concatenate([idl, jnp.clip(c.tree.num_leaves + iota_K,
                                                 0, L - 1)])
            valid_m = jnp.concatenate([iota_K < m, iota_K < m])
            new = cache_from(res)
            best = jax.tree_util.tree_map(
                lambda base, v: _pad_scatter(base, idc, v, valid_m),
                c.best, new)
            return StreamCarry(
                tree=tree, best=best, hist=hist, leaf_sg=leaf_sg,
                leaf_sh=leaf_sh, leaf_cnt=leaf_cnt,
                leaf_parent_side=leaf_parent_side,
                split_idx=c.split_idx + m, leaf_id=new_leaf_id)

        self._round_commit = jax.jit(round_commit)

        # ---- finalize (mirrors grower_rounds' epilogue) ------------------
        def finish(c: StreamCarry, grad, hess, mask):
            tree = c.tree
            leaf_sh_out = c.leaf_sh
            if quant and cfg.quant_renew:
                from ..ops.renew import quant_train_renew_leaf
                sg_t, sh_t = quant_train_renew_leaf(c.leaf_id, grad, hess,
                                                    mask, L)
                lv = leaf_output(sg_t, sh_t, hp.lambda_l1, hp.lambda_l2,
                                 hp.max_delta_step)
                leaf_sh_out = sh_t
            else:
                lv = leaf_output(c.leaf_sg, c.leaf_sh, hp.lambda_l1,
                                 hp.lambda_l2, hp.max_delta_step)
            active = iota_L < tree.num_leaves
            tree = tree._replace(
                leaf_value=jnp.where(active, lv, 0.0),
                leaf_weight=jnp.where(active, leaf_sh_out, 0.0),
                leaf_count=jnp.where(active, c.leaf_cnt, 0.0),
            )
            return tree, c.leaf_id

        self._finish = jax.jit(finish)

        # ---- iteration-level pieces -------------------------------------
        if quant:
            from ..ops.histogram import quantize_gradients
            qb = cfg.quant_bins
            stoch = bool(self.b.config.stochastic_rounding)
            self._quantize = jax.jit(
                lambda g, h, w, key: quantize_gradients(
                    g, h, w, qb, key, stochastic=stoch, axis_name=None))

        # leaf-scale + gather + score-add run in ONE program with the
        # scaled tree as a co-output — the exact dataflow of iter_body's
        # epilogue, so XLA's rounding decisions (the FMA-contraction
        # class boosting/macro.py documents) match the resident programs
        # bit for bit; splitting scale and add across jit boundaries
        # measurably drifts the carried score by 1 ulp per iteration
        def scale_add(score, tree, lid, lr, k):
            tree = tree._replace(
                leaf_value=tree.leaf_value * lr,
                internal_value=tree.internal_value * lr)
            score = score.at[k].add(take_from_table(tree.leaf_value, lid))
            return score, tree

        self._scale_add = jax.jit(scale_add, static_argnums=(4,))

        obj = self.b.objective
        renew_pct = obj.renew_percentile if obj is not None else None
        self._use_renew = renew_pct is not None
        if self._use_renew:
            from ..ops.renew import leaf_percentile
            label_a = self.b._macro_ctx["label"]
            weight_a = self.b._macro_ctx["weight"]
            pctv = float(renew_pct)

            def renew(tree, leaf_id, score_k, mask):
                residual = label_a - score_k
                w = mask * weight_a
                pct = leaf_percentile(leaf_id, residual, w, L, pctv)
                active = iota_L < tree.num_leaves
                return tree._replace(
                    leaf_value=jnp.where(active, pct, tree.leaf_value))

            self._renew = jax.jit(renew)

    # ------------------------------------------------------------ training

    def grow(self, grad_k, hess_k, mask, fmask, qvals):
        """Grow one streamed tree; returns (TreeArrays, leaf_id)."""
        if self.quant:
            gq, hq = qvals[0], qvals[1]
            qscales = (qvals[2], qvals[3])
        else:
            z8 = jnp.zeros((1,), jnp.int8)
            gq = hq = z8
            qscales = (jnp.float32(1.0), jnp.float32(1.0))
        ch = 2 if self.quant else 3
        dt = jnp.int32 if self.quant else jnp.float32
        acc = jnp.zeros((ch, self.G, self.B), dt)
        with _span("stream.root_pass"):
            for (_i, start, _rows, blk) in self.pump():
                acc = self._root_block(acc, blk, start, grad_k, hess_k,
                                       mask, gq, hq)
        c = self._root_commit(acc, grad_k, hess_k, mask, fmask, gq, hq,
                              qscales[0], qscales[1])
        rounds = 0
        while True:
            split_idx, max_gain = jax.device_get(self._cond(c))
            if int(split_idx) >= self.L - 1 or not float(max_gain) > 0.0:
                break
            tables, gains, rank, k, idl = self._tables(c)
            seg = self._seg_zero()
            gl_parts, crank_parts = [], []
            with _span("stream.round_pass", round=rounds):
                for (_i, start, _rows, blk) in self.pump():
                    seg, gl_b, cr_b = self._block_step(
                        seg, blk, start, grad_k, hess_k, mask, c.leaf_id,
                        tables, gq, hq)
                    gl_parts.append(gl_b)
                    crank_parts.append(cr_b)
            gl_full = jnp.concatenate(gl_parts)
            crank_full = jnp.concatenate(crank_parts)
            c = self._round_commit(c, seg, gl_full, crank_full, gains,
                                   rank, k, idl, fmask, qscales)
            rounds += 1
        return self._finish(c, grad_k, hess_k, mask)

    def run_iteration(self, grad, hess, mask, lr, rng, fmasks):
        """One boosting iteration (K trees) — the streamed twin of
        gbdt.py's ``iter_body``; returns (new_score, stacked trees,
        [K, 2] quant scales)."""
        b = self.b
        K = b.num_tree_per_iteration
        score = b.train_score
        trees = []
        qscale_rows = []
        for k in range(K):
            qvals = None
            if self.quant:
                qkey = jax.random.fold_in(
                    jax.random.fold_in(rng, 0x51475442), k)
                qvals = self._quantize(grad[k], hess[k], mask, qkey)
                qscale_rows.append(jnp.stack([qvals[2], qvals[3]]))
            with _span("stream.tree", k=k):
                tree, leaf_id = self.grow(grad[k], hess[k], mask,
                                          fmasks[k], qvals)
            if self._use_renew:
                tree = self._renew(tree, leaf_id, score[k], mask)
            score, tree = self._scale_add(score, tree, leaf_id, lr, k)
            trees.append(tree)
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)
        qscales = (jnp.stack(qscale_rows) if self.quant
                   else jnp.zeros((K, 2), jnp.float32))
        _obs_registry.gauge("host_rss_peak_bytes").set(host_rss_peak_bytes())
        return score, stacked, qscales
