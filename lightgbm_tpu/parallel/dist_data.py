"""Distributed dataset construction: sharded FindBin + bin-mapper allgather.

reference: DatasetLoader::ConstructBinMappersFromTextData, distributed
branch (src/io/dataset_loader.cpp:913-1000): with num_machines > 1 each
rank runs FindBin only for features ``f % num_machines == rank`` over its
LOCAL sample, serializes its BinMappers, and a Network::Allgather
distributes them so every rank ends with the identical full mapper set.

TPU-native deltas:
- the transport is a byte-allgather over the JAX multi-host runtime
  (jax.experimental.multihost_utils) instead of sockets/MPI, with an
  ``allgather_bytes`` injection seam — the LGBM_NetworkInitWithFunctions
  analogue (c_api.h:1036) — so tests drive the protocol with a fake
  in-process "mesh" of K simulated ranks;
- per-feature sample nonzero masks ride along in the same allgather:
  this package's EFB groups define the SHARED [n, G] device layout that
  data-parallel psums assume, so grouping must be computed from the global
  sample (the reference's per-machine feature histograms never needed
  cross-machine layout agreement).
"""

from __future__ import annotations

import json
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..binning import BinMapper, BinType
from ..dataset import Dataset, _as_2d, _sample_indices

AllgatherBytes = Callable[[bytes], List[bytes]]


def jax_allgather_bytes(payload: bytes) -> List[bytes]:
    """Byte allgather over the JAX multi-host runtime (DCN).

    Two tiny device collectives: lengths first, then the padded buffers
    (reference: Network::Allgather with per-rank block sizes,
    network.h:89-120).
    """
    import jax
    from jax.experimental import multihost_utils

    world = jax.process_count()
    if world == 1:
        return [payload]
    lens = multihost_utils.process_allgather(
        np.asarray([len(payload)], np.int64))
    lens = np.asarray(lens).reshape(-1)
    mx = int(lens.max())
    buf = np.zeros(mx, np.uint8)
    buf[:len(payload)] = np.frombuffer(payload, np.uint8)
    allb = np.asarray(multihost_utils.process_allgather(buf))
    allb = allb.reshape(world, mx)
    return [allb[r, :int(lens[r])].tobytes() for r in range(world)]


def _encode_sample(S: int, cols: dict, F: int) -> bytes:
    """Binary framing for the phase-1 payload (no JSON/hex blow-up):
    [S:i64][F:i64][nvals per feature: F x i64][all vals f64][all masks
    packbits, ceil(S/8) bytes per feature]."""
    head = np.empty(2 + F, np.int64)
    head[0], head[1] = S, F
    vals_parts, mask_parts = [], []
    for f in range(F):
        v, m = cols[f]
        head[2 + f] = len(v)
        vals_parts.append(np.ascontiguousarray(v, np.float64).tobytes())
        mask_parts.append(np.packbits(m.astype(np.uint8)).tobytes())
    return head.tobytes() + b"".join(vals_parts) + b"".join(mask_parts)


def _decode_sample(blob: bytes):
    """Inverse of _encode_sample: returns (S, {f: vals}, {f: mask})."""
    S, F = np.frombuffer(blob, np.int64, count=2)
    S, F = int(S), int(F)
    nvals = np.frombuffer(blob, np.int64, count=F, offset=16)
    off = 16 + 8 * F
    vals = {}
    for f in range(F):
        nv = int(nvals[f])
        vals[f] = np.frombuffer(blob, np.float64, count=nv, offset=off)
        off += 8 * nv
    mask_bytes = (S + 7) // 8
    masks = {}
    for f in range(F):
        packed = np.frombuffer(blob, np.uint8, count=mask_bytes, offset=off)
        masks[f] = np.unpackbits(packed)[:S].astype(bool)
        off += mask_bytes
    return S, vals, masks


def distributed_bin_mappers(
    local_sample: np.ndarray,       # [S_local, F] this rank's sampled rows
    params: Optional[dict] = None,
    categorical: Sequence[int] = (),
    rank: Optional[int] = None,
    world: Optional[int] = None,
    allgather_bytes: Optional[AllgatherBytes] = None,
    resilience=None,
):
    """Returns (bin_mappers [F], sample_nonzero {feature -> bool [S_total]},
    total_sample_cnt) — identical on every rank.

    Feature shard = ``f % world == rank`` (the reference's mod partition,
    dataset_loader.cpp:924).  FindBin for a shard runs over the GLOBAL
    sample (every rank's sampled values for that feature travel in the
    allgather), matching the reference, which gathers per-feature sample
    values before binning them on the owning rank.

    ``resilience`` (a ``resilience.retry.ResilienceConfig``, or implied
    by ``params['network_resilience']=True``) routes both allgather
    rounds through ``resilient_allgather`` — CRC framing, deadline +
    backoff, rank-consistent verdict — so a flaky transport retries or
    aborts consistently on every rank instead of hanging or silently
    consuming a corrupted payload.  With ``degraded_fallback`` set, a
    permanent collective failure falls back LOUDLY to single-rank
    binning over the local sample (mappers then differ across ranks —
    only for salvage runs, never silent).
    """
    p = dict(params or {})
    sample = _as_2d(local_sample)
    if allgather_bytes is None:
        allgather_bytes = jax_allgather_bytes
    if rank is None or world is None:
        import jax
        rank = jax.process_index()
        world = jax.process_count()

    from ..resilience.retry import ResilienceConfig
    res = resilience if resilience is not None else \
        ResilienceConfig.from_params(p)
    if res is not None and world > 1:
        from ..resilience.retry import CollectiveError, make_resilient
        from ..utils.log import log_warning
        wrapped = make_resilient(allgather_bytes, world=world, rank=rank,
                                 config=res, label="distributed_bin_mappers")
        try:
            return _bin_mappers_impl(sample, p, categorical, rank, world,
                                     wrapped)
        except CollectiveError:
            if not res.degraded_fallback:
                raise
            log_warning(
                "distributed_bin_mappers: COLLECTIVE FAILED PERMANENTLY; "
                f"rank {rank} continuing DEGRADED as a single-rank binning "
                "over its local sample ONLY — bin mappers will NOT agree "
                "across ranks (network_degraded_fallback=True)")
            return _bin_mappers_impl(sample, p, categorical, 0, 1,
                                     lambda b: [b])
    return _bin_mappers_impl(sample, p, categorical, rank, world,
                             allgather_bytes)


def _bin_mappers_impl(sample, p, categorical, rank, world, allgather_bytes):
    S, F = sample.shape
    # phase 1: every rank contributes its sampled VALUES for every feature
    # (NaN and non-zero only — zeros are implicit, like the reference's
    # sparse sample representation) plus its nonzero/NaN mask, in a binary
    # framing (raw f64 values + packbits masks)
    cols = {}
    for f in range(F):
        col = np.asarray(sample[:, f], np.float64)
        keep = np.isnan(col) | (np.abs(col) > 1e-35)
        cols[f] = (col[keep], keep)
    parts = allgather_bytes(_encode_sample(S, cols, F))
    assert len(parts) == world, (len(parts), world)
    decoded = [_decode_sample(b) for b in parts]
    total_sample_cnt = int(sum(d[0] for d in decoded))
    all_vals = {
        f: np.concatenate([d[1][f] for d in decoded]) for f in range(F)}
    sample_nonzero_full = {
        f: np.concatenate([d[2][f] for d in decoded]) for f in range(F)}

    # phase 2: bin my feature shard over the global sample, allgather the
    # serialized mappers (dataset_loader.cpp:985 Allgather of CopyTo blobs)
    from ..dataset import _load_forced_bins
    forced_bounds = _load_forced_bins(p, F)
    max_bin = int(p.get("max_bin", 255))
    mine = {}
    for f in range(rank, F, world):
        m = BinMapper()
        m.find_bin(
            all_vals[f], total_sample_cnt, max_bin,
            min_data_in_bin=int(p.get("min_data_in_bin", 3)),
            min_split_data=int(p.get("min_data_in_leaf", 20)),
            pre_filter=bool(p.get("feature_pre_filter", True)),
            bin_type=(BinType.CATEGORICAL if f in categorical
                      else BinType.NUMERICAL),
            use_missing=bool(p.get("use_missing", True)),
            zero_as_missing=bool(p.get("zero_as_missing", False)),
            forced_upper_bounds=forced_bounds.get(f, ()),
        )
        mine[str(f)] = m.to_dict()
    parts2 = allgather_bytes(json.dumps(mine).encode())
    mappers: List[Optional[BinMapper]] = [None] * F
    for blob in parts2:
        for fs, d in json.loads(blob.decode()).items():
            mappers[int(fs)] = BinMapper.from_dict(d)
    assert all(m is not None for m in mappers)
    return mappers, sample_nonzero_full, total_sample_cnt


def construct_distributed(
    local_data,
    label=None,
    params: Optional[dict] = None,
    categorical_feature: Sequence[int] = (),
    rank: Optional[int] = None,
    world: Optional[int] = None,
    allgather_bytes: Optional[AllgatherBytes] = None,
    resilience=None,
) -> Dataset:
    """Build this rank's Dataset over its LOCAL rows with GLOBALLY agreed
    bin mappers and EFB layout (so data-parallel histogram psums line up).

    reference flow: DatasetLoader::LoadFromFile with num_machines > 1 —
    local rows, distributed ConstructBinMappersFromTextData, then the
    normal second pass pushes local rows through the shared mappers.
    """
    p = dict(params or {})
    data = _as_2d(local_data)
    n_local, F = data.shape
    sample_cnt = int(p.get("bin_construct_sample_cnt", 200000))
    seed = int(p.get("data_random_seed", 1))
    sample_idx = _sample_indices(n_local, sample_cnt, seed)
    mappers, sample_nonzero, total_sample_cnt = distributed_bin_mappers(
        data[sample_idx], params=p, categorical=categorical_feature,
        rank=rank, world=world, allgather_bytes=allgather_bytes,
        resilience=resilience)

    ds = Dataset(data, label=label, params=p,
                 categorical_feature=list(categorical_feature) or "auto")
    ds.num_data, ds.num_total_features = n_local, F
    ds.feature_names = [f"Column_{i}" for i in range(F)]
    ds.bin_mappers = mappers
    ds.used_features = [f for f, m in enumerate(mappers) if not m.is_trivial]
    nz = {j: sample_nonzero[f] for j, f in enumerate(ds.used_features)}
    ds._build_groups(nz, total_sample_cnt)
    dtype = np.uint8 if ds.max_group_bin <= 256 else np.uint16
    ds.binned = np.zeros((n_local, ds.num_groups), dtype=dtype)
    ds._bin_block(data, None, ds.binned)
    if ds.metadata.label is None:
        ds.metadata.label = np.zeros(n_local, np.float32)
    ds.constructed = True
    ds.raw_data = None
    return ds


def make_fake_allgather(world: int, timeout: Optional[float] = None):
    """In-process simulated transport for tests: K ranks run in K threads
    and rendezvous at a barrier per allgather round — the
    NetworkInitWithFunctions-style injection seam (c_api.h:1036) driven
    without a real second host.  Returns ``fn_for(rank)``.

    Rounds are indexed by a PER-RANK call counter and each round gets its
    own barrier, so a broken rendezvous (a rank that stalled past
    ``timeout`` or died) poisons only that round: every waiter raises
    ``BrokenBarrierError`` and the next call starts a fresh round — the
    shape ``resilience.retry`` needs to retry against.  ``timeout=None``
    (the default) waits forever, the original rendezvous semantics.
    """
    import threading

    barriers: dict = {}
    bufs: dict = {}
    rounds = [0] * world
    lock = threading.Lock()

    def fn_for(rank: int) -> AllgatherBytes:
        def allgather(payload: bytes) -> List[bytes]:
            with lock:
                r = rounds[rank]
                rounds[rank] += 1
                if r not in barriers:
                    barriers[r] = threading.Barrier(world)
                bar = barriers[r]
                buf = bufs.setdefault(r, {})
                buf[rank] = payload
            bar.wait(timeout)            # everyone has written
            out = [buf[q] for q in range(world)]
            bar.wait(timeout)            # everyone has read; round retired
            with lock:                   # old rounds can't be re-entered
                barriers.pop(r - 4, None)
                bufs.pop(r - 4, None)
            return out
        return allgather

    return fn_for
