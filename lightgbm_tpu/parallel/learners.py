"""Distributed tree learners over a jax.sharding.Mesh.

TPU-native replacement for the reference's distributed learners
(src/treelearner/{feature,data,voting}_parallel_tree_learner.cpp) and the
whole src/network/ transport/topology layer: the three reduction points —
histogram reduce-scatter, best-split sync, scalar sums — become
`lax.psum`/`lax.all_gather` inside the jitted grow step over ICI, selected
by how the Mesh axes shard the data:

- data parallel: rows sharded over axis "data"; histograms psum'd; every
  device then finds the identical best split (the reference's
  ReduceScatter + per-machine ownership + best-split allreduce,
  data_parallel_tree_learner.cpp:149-241, collapses into one psum).
- feature parallel: features sharded over axis "feature"; local best splits
  merged by all_gather+argmax (SyncUpGlobalBestSplit,
  parallel_tree_learner.h:190), partition mask broadcast by psum.
- 2-D: both at once (not expressible in the reference at all).

With ``use_quantized_grad`` the data- and voting-parallel reductions move
INTEGER histograms (``ops.histogram.psum_quant_hist`` inside the growers):
[2, F, B] i32 — 8 bytes/cell vs the f32 path's 12 — narrowed to int16
(4 bytes/cell) when the static rows x quant-level bound proves overflow
impossible, so the ICI payload shrinks with the quantization width
(``ops.histogram.hist_payload_bytes`` is the accounting twin).

The factory mirrors CreateTreeLearner (src/treelearner/tree_learner.cpp:13).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..dataset import FeatureMeta
from ..grower import GrowerConfig, TreeArrays, grow_tree
from .collectives import (DCN_AXIS, HYBRID_AXES, ICI_AXIS,  # noqa: F401
                          axis_size)

DATA_AXIS = "data"
FEATURE_AXIS = "feature"

# a data axis may be ONE mesh axis ("data", the historical single-tier
# layout) or the hybrid outermost-first tuple ("dcn", "ici") of
# make_hybrid_mesh — every helper below accepts both
DataAxis = Union[str, Tuple[str, ...]]


def shard_map_compat(f=None, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` with old-jax fallback.

    The repo targets the stable ``jax.shard_map`` API (``check_vma``);
    jax <= 0.4.x only ships ``jax.experimental.shard_map.shard_map``
    (``check_rep``).  Every shard_map call site routes through here so
    the distributed paths work on both.  Usable directly or as a
    decorator factory (``f=None``)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    else:
        from jax.experimental.shard_map import shard_map as sm
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_vma)
    if f is None:
        return functools.partial(sm, **kw)
    return sm(f, **kw)


def pad_rows_to(n: int, devices: int) -> int:
    return (n + devices - 1) // devices * devices


def fused_best_payload_bytes(num_features: int) -> int:
    """Bytes of ONE per-feature-best tuple set (the fused megakernel's
    writeback: gain, bin, direction, left grad/hess/count — 6 cells × F,
    ops/fused.py) — what a collective would move if it exchanged
    candidates instead of histograms: F·~6 cells vs the histogram
    payload's F·B·ch (``ops.histogram.hist_payload_bytes``).  Pure
    accounting, reported by tools/hist_probe.py next to the histogram
    payloads; the EXACT data-parallel reduction still psums histograms
    (gains are not summable across shards — the same reason
    voting-parallel exchanges elected candidates, PV-Tree).  This is the
    DCN/ICI headroom figure the voting/fused combination targets."""
    return 6 * num_features * 4


def make_sharded_grower(
    mesh: Mesh,
    meta: FeatureMeta,
    cfg: GrowerConfig,
    data_axis: Optional[DataAxis] = DATA_AXIS,
    feature_axis: Optional[str] = None,
    auto_plan: bool = True,
):
    """Build a jitted sharded grow-tree callable.

    Inputs must be sharded/padded by the caller:
      binned_t [F_pad, n_pad] (feature-major), grad/hess/row_mask [n_pad]
    (pad rows with row_mask = 0; pad features with trivial bins).
    Returns fn(binned_t, grad, hess, row_mask) -> (TreeArrays, leaf_id).

    ``auto_plan``: when ``cfg.tile_rows`` is unset (0), run the HBM
    budget planner (ops/planner.py) at trace time over the PER-SHARD
    shapes, so the standalone learners obey the same memory verdict as
    engine-driven training (row tiling, record-arena hoisting).
    """
    if feature_axis and meta.resolved().has_bundles \
            and cfg.num_feature_shards <= 1:
        raise NotImplementedError(
            "feature-axis sharding over EFB bundles requires the shard-major "
            "group layout (GBDT._build_group_sharding); train through the "
            "engine (lgb.train with tree_learner=feature) or disable "
            "bundling for this standalone grower")
    if cfg.hist_method == "fused" and feature_axis:
        # recorded design exclusion: under FEATURE sharding each shard
        # owns different columns and the winner is elected by a pmax
        # gather over per-shard SplitResults — the fused kernel's
        # in-kernel scan + writeback layout doesn't ride that exchange,
        # so feature-parallel growth stays on the staged family.  DATA
        # sharding keeps fused: the rounds grower splits the kernel at
        # the collective seam (accumulate → psum of the smaller-child
        # hists → sibling-derive + scan on the reduced arena,
        # grower_rounds.py) — gains never cross the wire, exactly like
        # the staged arm.
        from ..utils.log import log_info
        log_info("hist_method=fused is not a feature-parallel arm (the "
                 "winner exchange moves SplitResults, not histograms); "
                 "feature-sharded growth uses the staged kernel family")
        cfg = cfg._replace(hist_method="auto")
    row_spec = P(data_axis) if data_axis else P()
    binned_spec = (P(feature_axis, data_axis) if feature_axis
                   else P(None, data_axis))

    @functools.partial(
        shard_map_compat, mesh=mesh,
        in_specs=(binned_spec, row_spec, row_spec, row_spec),
        out_specs=(P(), row_spec),
        check_vma=False,
    )
    def sharded(binned_t, grad, hess, row_mask):
        run_cfg = cfg
        if auto_plan and cfg.tile_rows == 0:
            # trace-time planning over the local (per-shard) shapes —
            # binned_t here is already the device slice
            from ..ops.planner import apply_plan
            run_cfg, plan = apply_plan(cfg, int(binned_t.shape[1]),
                                       int(binned_t.shape[0]),
                                       fused_ok=(feature_axis is None))
            if not plan.feasible:
                from ..utils.log import log_warning
                log_warning(
                    "HBM planner: predicted peak "
                    f"{plan.predicted_peak_bytes / 1e9:.2f} GB exceeds "
                    f"the {plan.budget_bytes / 1e9:.2f} GB budget even "
                    f"at tile_rows={plan.tile_rows}; training may OOM "
                    "(LGBM_TPU_HBM_BYTES / LGBM_TPU_TILE_ROWS override)")
        out = grow_tree(
            binned_t, grad, hess, row_mask, meta, run_cfg,
            axis_name=data_axis, feature_axis_name=feature_axis)
        # CEGB-enabled configs return (tree, leaf_id, cegb_state); this
        # standalone grower drops the cross-tree state (single-tree API)
        return out[0], out[1]

    return jax.jit(sharded)


def shard_dataset(mesh: Mesh, binned: np.ndarray, *row_arrays,
                  data_axis: DataAxis = DATA_AXIS):
    """Pad rows to the data-axis size and place arrays on the mesh.

    ``binned`` is the HOST row-major [n, F] matrix; the device copy is
    feature-major [F, n_pad] (ops/histogram.py LAYOUT DOCTRINE).
    ``data_axis`` may be the hybrid ``("dcn", "ici")`` tuple: rows then
    shard over BOTH tiers in the mesh's row-major device order — an
    elastic re-tile after a slice loss is just this call over the
    re-planned smaller mesh (docs/RESILIENCE.md)."""
    ndev = axis_size(mesh, data_axis)
    n = binned.shape[0]
    n_pad = pad_rows_to(n, ndev)
    out = []
    b = np.ascontiguousarray(np.pad(binned, ((0, n_pad - n), (0, 0))).T)
    out.append(jax.device_put(b, NamedSharding(mesh, P(None, data_axis))))
    for arr in row_arrays:
        a = np.pad(np.asarray(arr), (0, n_pad - n))
        out.append(jax.device_put(a, NamedSharding(mesh, P(data_axis))))
    return out, n_pad


def put_stacked_rows(mesh: Mesh, data_axis: DataAxis,
                     stacked: jax.Array) -> jax.Array:
    """Place a ``[c, n_pad]`` stack of per-iteration row arrays (bagging /
    GOSS masks for a fused macro-step chunk, boosting/macro.py) with the
    ROW axis sharded like every other per-row array, so the chunk scan's
    per-step slices feed shard_map without a cross-device gather."""
    return jax.device_put(stacked, NamedSharding(mesh, P(None, data_axis)))


def make_mesh(n_devices: Optional[int] = None,
              axes: Tuple[str, ...] = (DATA_AXIS,),
              shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axes) - 1)
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axes)


def simulated_slices() -> int:
    """``LGBM_TPU_NUM_SLICES``: simulated DCN slice count for
    single-process runs (the whole hybrid plane then exercises under
    ``--xla_force_host_platform_device_count=N`` on CPU); 0/unset = no
    simulation."""
    v = os.environ.get("LGBM_TPU_NUM_SLICES", "").strip()
    try:
        return max(int(v), 0) if v else 0
    except ValueError:
        return 0


def make_hybrid_mesh(n_devices: Optional[int] = None,
                     num_slices: Optional[int] = None) -> Mesh:
    """Two-axis ``("dcn", "ici")`` mesh: slices over the slow cross-host
    tier, each slice's devices over the fast ICI tier.

    Real multi-host (``jax.distributed`` initialized): one slice per
    process, its local devices on the ICI axis — the physical topology.
    Single-process: ``num_slices`` (or LGBM_TPU_NUM_SLICES) PARTITIONS
    the local devices into simulated slices; the collectives then
    exercise the exact tiered reduction schedule the pod would run.
    Device order is row-major over (slice, device-in-slice) — the same
    linear order as the flat single-axis mesh, so flat and hybrid
    shardings place identical row blocks on identical devices (the
    bit-parity tests lean on this).
    """
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    nd = len(devs)
    if num_slices is None:
        num_slices = (jax.process_count() if jax.process_count() > 1
                      else simulated_slices()) or 1
    s = max(int(num_slices), 1)
    if nd % s != 0:
        raise ValueError(
            f"cannot partition {nd} devices into {s} slices; "
            f"num_slices must divide the device count")
    arr = np.asarray(devs).reshape(s, nd // s)
    return Mesh(arr, HYBRID_AXES)


def data_axis_of(mesh: Mesh) -> DataAxis:
    """The row-sharding axis spec for ``mesh``: the hybrid tuple when the
    mesh carries the ("dcn", "ici") axes, else the flat "data" axis."""
    if DCN_AXIS in mesh.axis_names and ICI_AXIS in mesh.axis_names:
        return HYBRID_AXES
    return DATA_AXIS


def _hybrid_cfg(cfg: GrowerConfig, mesh: Mesh,
                data_axis: DataAxis) -> GrowerConfig:
    """Thread the hybrid mesh's shape + the planner's reduction election
    into the grower config (no-op on a flat mesh)."""
    if data_axis != HYBRID_AXES:
        return cfg
    total = axis_size(mesh, data_axis)
    slices = int(mesh.shape[DCN_AXIS])
    if cfg.num_machines <= 1 or cfg.num_machines != total:
        cfg = cfg._replace(num_machines=total)
    from ..ops.planner import plan_collectives
    plan = plan_collectives(
        features=0, num_bins=cfg.num_bins, rows_global=0,
        quant=cfg.quant, quant_bins=cfg.quant_bins,
        num_slices=slices, devices_per_slice=total // slices,
        voting_k=cfg.voting_top_k)
    return cfg._replace(num_slices=slices,
                        hier_reduce=plan.hierarchical,
                        pinned_reduce=plan.pinned)


def create_parallel_grower(tree_learner: str, mesh: Mesh, meta: FeatureMeta,
                           cfg: GrowerConfig):
    """Factory mirroring CreateTreeLearner (tree_learner.cpp:13-36).

    tree_learner: serial | data | feature | voting | data_feature (2-D).
    A hybrid ``make_hybrid_mesh`` mesh routes rows over BOTH tiers and
    threads the tiered-reduction election (ops/planner.plan_collectives)
    into the grower config; when the config carries a ``num_machines``
    that disagrees with the mesh's actual shard count, the mesh wins —
    LOUDLY (the reference would deadlock on such a mismatch; here it
    would silently mis-scale voting's local constraints).
    """
    data_axis = data_axis_of(mesh)
    if tree_learner in ("data", "voting", "data_parallel",
                        "voting_parallel", "data_feature", "2d"):
        shards = axis_size(mesh, data_axis)
        if cfg.num_machines > 1 and cfg.num_machines != shards:
            from ..utils.log import log_warning
            log_warning(
                f"num_machines={cfg.num_machines} disagrees with the "
                f"mesh's actual data-shard count ({shards}); using the "
                "mesh — fix num_machines (or the machine list) so the "
                "configured world matches the devices actually present")
            cfg = cfg._replace(num_machines=shards)
    if tree_learner in ("data", "data_parallel"):
        cfg = _hybrid_cfg(cfg, mesh, data_axis)
        return make_sharded_grower(mesh, meta, cfg, data_axis=data_axis,
                                   feature_axis=None)
    if tree_learner in ("feature", "feature_parallel"):
        return make_sharded_grower(mesh, meta, cfg, data_axis=None,
                                   feature_axis=FEATURE_AXIS)
    if tree_learner in ("voting", "voting_parallel"):
        # real PV-Tree voting (reference voting_parallel_tree_learner.cpp),
        # consistent with the GBDT engine path: the grower runs its top-k
        # vote + elected-features-only psum when voting_top_k > 0.  Default
        # top_k mirrors the reference config default (config.h top_k = 20).
        if cfg.voting_top_k <= 0:
            cfg = cfg._replace(voting_top_k=20)
        if cfg.num_machines <= 1:
            cfg = cfg._replace(num_machines=axis_size(mesh, data_axis))
        cfg = _hybrid_cfg(cfg, mesh, data_axis)
        return make_sharded_grower(mesh, meta, cfg, data_axis=data_axis,
                                   feature_axis=None)
    if tree_learner in ("data_feature", "2d"):
        return make_sharded_grower(mesh, meta, cfg, data_axis=DATA_AXIS,
                                   feature_axis=FEATURE_AXIS)
    raise ValueError(f"unknown tree_learner {tree_learner!r}")
