"""Multi-host (DCN) runtime initialization from reference-style configs.

The reference's distributed story is `Network::Init` over a socket/MPI
machine list (src/network/linkers_socket.cpp:23-188: parse `machine_list`,
bind `local_listen_port`, all-to-all connect).  The TPU build's transport
IS the JAX runtime: collectives run as XLA psum/all_gather over ICI within
a slice and DCN across hosts, and multi-host process wiring is
``jax.distributed.initialize(coordinator, num_processes, process_id)``.
This module maps the reference's config surface (``machines`` /
``machine_list_filename`` / ``local_listen_port`` / ``num_machines``,
config.h:190-210) onto that call, so a LightGBM-style machine list starts
a multi-host JAX mesh:

- the FIRST machine in the list is the coordinator (the reference's rank-0
  by list order, linkers_socket.cpp:64-76);
- this process's rank is its position in the list, matched by local
  hostname/IP (the reference matches on the bound interface);
- after ``init_network``, ``jax.devices()`` spans all hosts and the
  data/feature/voting learners shard over the global mesh unchanged —
  their collectives are already expressed over Mesh axes.

``Booster.set_network`` and the CLI route here.
"""

from __future__ import annotations

import os
import socket
from typing import List, Optional, Tuple

from ..utils.log import log_info, log_warning


def parse_machine_list(machines: Optional[str] = None,
                       machine_list_file: Optional[str] = None) -> List[Tuple[str, int]]:
    """reference: Linkers::Linkers reads `machines` ("ip1:port1,ip2:port2")
    or one host:port per line of `machine_list_filename`
    (linkers_socket.cpp:23-63)."""
    entries: List[str] = []
    if machines:
        entries = [tok for tok in str(machines).replace("\n", ",").split(",")
                   if tok.strip()]
    elif machine_list_file:
        from ..utils.file_io import exists, open_file
        if not exists(machine_list_file):
            # reference: Log::Fatal on an unreadable machine list file
            # (linkers_socket.cpp:27) — fail loudly instead of silently
            # training single-machine
            raise ValueError(
                f"machine_list_file {str(machine_list_file)!r} does not "
                "exist; every machine needs the same host:port list file")
        with open_file(machine_list_file) as fh:
            entries = [ln.strip() for ln in fh.read().splitlines()
                       if ln.strip()]
    out = []
    for e in entries:
        host, _, port = e.strip().partition(":")
        if not host:
            raise ValueError(f"machine list entry {e!r} has no host")
        try:
            out.append((host, int(port) if port else 12400))
        except ValueError:
            raise ValueError(
                f"machine list entry {e!r}: port {port!r} is not an "
                "integer") from None
    return out


def _local_identifiers() -> set:
    ids = {"localhost", "127.0.0.1", socket.gethostname()}
    try:
        ids.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    try:
        ids.update(i[4][0] for i in socket.getaddrinfo(
            socket.gethostname(), None))
    except OSError:
        pass
    return ids


def resolve_rank(machine_list: List[Tuple[str, int]],
                 local_listen_port: Optional[int] = None) -> int:
    """This process's rank = its position in the machine list (the
    reference matches the bound interface+port, linkers_socket.cpp:64-76).
    When several entries share the local host (multi-process-per-host),
    ``local_listen_port`` disambiguates."""
    local = _local_identifiers()
    matches = [i for i, (h, p) in enumerate(machine_list) if h in local]
    if not matches:
        raise ValueError(
            f"none of the machine-list hosts {[h for h, _ in machine_list]} "
            f"matches this host ({sorted(local)}); set machines= to include "
            "this machine")
    if len(matches) > 1 and local_listen_port is not None:
        port_matches = [i for i in matches
                        if machine_list[i][1] == local_listen_port]
        if port_matches:
            return port_matches[0]
    return matches[0]


def init_network(machines: Optional[str] = None,
                 local_listen_port: Optional[int] = None,
                 listen_time_out: int = 120,
                 num_machines: Optional[int] = None,
                 machine_list_file: Optional[str] = None,
                 dry_run: bool = False):
    """Start the multi-host JAX runtime from a reference-style machine list.

    reference seam: Network::Init (network.cpp:29-58) /
    LGBM_NetworkInit (c_api.h).  Returns (coordinator_address,
    num_processes, process_id); with ``dry_run`` nothing is initialized
    (for tests and introspection).
    """
    if listen_time_out is None:
        listen_time_out = 120      # the signature default, for explicit None
    # this value is exported into JAX_COORDINATION_SERVICE_TIMEOUT_SECS; a
    # zero/negative (or unparseable) timeout would make every coordination
    # call fail instantly (or never)
    try:
        ok = float(listen_time_out) > 0
    except (TypeError, ValueError):
        ok = False
    if not ok:
        raise ValueError(
            f"listen_time_out must be a positive number of seconds, "
            f"got {listen_time_out!r}")
    ml = parse_machine_list(machines, machine_list_file)
    if not ml and num_machines in (None, 0, 1):
        log_warning("init_network: no machine list and num_machines<=1; "
                    "nothing to do")
        return None
    if not ml:
        raise ValueError("init_network needs machines= or machine_list_file=")
    n = num_machines or len(ml)
    if n > len(ml):
        raise ValueError(
            f"num_machines={n} but machine list has {len(ml)} entries")
    ml = ml[:n]
    rank = resolve_rank(ml, local_listen_port)
    host0, port0 = ml[0]
    coordinator = f"{host0}:{port0}"
    if dry_run:
        return coordinator, n, rank
    import jax
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        log_warning("init_network: jax.distributed already initialized")
        return coordinator, n, rank
    if n == 1:
        log_info("init_network: single machine; skipping jax.distributed")
        return coordinator, n, rank
    os.environ.setdefault("JAX_COORDINATION_SERVICE_TIMEOUT_SECS",
                          str(max(1, round(float(listen_time_out)))))
    log_info(f"init_network: jax.distributed.initialize("
             f"{coordinator!r}, num_processes={n}, process_id={rank})")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=n, process_id=rank)
    return coordinator, n, rank


def free_network() -> None:
    """reference: Network::Dispose / LGBM_NetworkFree."""
    import jax
    try:
        if getattr(jax.distributed, "is_initialized", lambda: False)():
            jax.distributed.shutdown()
    except Exception as e:   # noqa: BLE001 — best-effort teardown
        log_warning(f"free_network: {e}")
