"""Multi-host (DCN) runtime initialization from reference-style configs.

The reference's distributed story is `Network::Init` over a socket/MPI
machine list (src/network/linkers_socket.cpp:23-188: parse `machine_list`,
bind `local_listen_port`, all-to-all connect).  The TPU build's transport
IS the JAX runtime: collectives run as XLA psum/all_gather over ICI within
a slice and DCN across hosts, and multi-host process wiring is
``jax.distributed.initialize(coordinator, num_processes, process_id)``.
This module maps the reference's config surface (``machines`` /
``machine_list_filename`` / ``local_listen_port`` / ``num_machines``,
config.h:190-210) onto that call, so a LightGBM-style machine list starts
a multi-host JAX mesh:

- the FIRST machine in the list is the coordinator (the reference's rank-0
  by list order, linkers_socket.cpp:64-76);
- this process's rank is its position in the list, matched by local
  hostname/IP (the reference matches on the bound interface);
- after ``init_network``, ``jax.devices()`` spans all hosts and the
  data/feature/voting learners shard over the global mesh unchanged —
  their collectives are already expressed over Mesh axes.

``Booster.set_network`` and the CLI route here.
"""

from __future__ import annotations

import os
import socket
from typing import List, NamedTuple, Optional, Tuple

from ..utils.log import log_info, log_warning

# the last REAL (non-dry-run) init_network call: its num_machines /
# local_listen_port round-trip into mesh_plan so the reference's config
# surface actually steers the hybrid mesh construction instead of being
# parsed and dropped
_LAST_INIT: Optional[dict] = None


class MeshPlan(NamedTuple):
    """How the data-parallel mesh partitions into DCN slices.

    ``num_slices > 1`` elects the hybrid ``("dcn", "ici")`` mesh
    (parallel/learners.make_hybrid_mesh); 1 keeps the flat single-axis
    layout.  ``source`` records which signal decided (real process
    topology > simulated slices env > num_machines config > flat)."""

    num_slices: int
    devices_per_slice: int
    total_shards: int
    source: str                 # "distributed" | "env" | "num_machines"
    #                             | "flat"

    @property
    def hybrid(self) -> bool:
        return self.num_slices > 1


def last_network_init() -> Optional[dict]:
    """The recorded (non-dry-run) ``init_network`` call, or None."""
    return _LAST_INIT


def mesh_plan(n_devices: int,
              num_machines: Optional[int] = None,
              local_listen_port: Optional[int] = None) -> MeshPlan:
    """Partition ``n_devices`` data shards into DCN slices.

    Priority:
    1. a real multi-host runtime (``jax.distributed`` initialized, >1
       process): one slice per process — the physical topology; a
       configured ``num_machines`` that DISAGREES with it warns loudly
       (the reference would deadlock waiting for the missing machines;
       here the silent failure mode is mis-scaled voting constraints);
    2. ``LGBM_TPU_NUM_SLICES``: simulated slices for single-process runs;
    3. ``num_machines`` (or the last ``init_network``'s): num_machines
       slices when it divides the device count — the reference's
       machine-count key steering the DCN tier directly;
    4. flat single-tier mesh.
    """
    from .learners import simulated_slices
    nd = max(int(n_devices), 1)
    if num_machines is None and _LAST_INIT is not None:
        num_machines = _LAST_INIT.get("num_machines")
        if local_listen_port is None:
            local_listen_port = _LAST_INIT.get("local_listen_port")
    nm = int(num_machines or 0)

    def warn_mismatch(actual: int, what: str):
        if nm > 1 and nm != actual:
            log_warning(
                f"num_machines={nm} disagrees with {what} ({actual}); "
                "using the actual topology — fix num_machines / the "
                "machine list so the configured world matches the "
                "devices actually present"
                + (f" (local_listen_port={local_listen_port})"
                   if local_listen_port else ""))

    try:
        import jax
        procs = jax.process_count()
    except Exception:   # noqa: BLE001 — planning must work pre-backend
        procs = 1
    if procs > 1:
        warn_mismatch(procs, "the live process count")
        s = procs if nd % procs == 0 else 1
        return MeshPlan(s, nd // s, nd, "distributed")
    sim = simulated_slices()
    per_env = os.environ.get("LGBM_TPU_SLICE_DEVICES", "").strip()
    try:
        per = max(int(per_env), 1) if per_env else 0
    except ValueError:
        per = 0
    if sim >= 1 and (sim > 1 or per):
        # simulated slice topology (single-process): LGBM_TPU_NUM_SLICES
        # partitions the devices; LGBM_TPU_SLICE_DEVICES additionally
        # bounds the per-slice device count — how an elastic shrink
        # (resilience/elastic.py) expresses the survivors' smaller world
        # without a real re-launch
        per_c = per or (nd // sim if nd % sim == 0 else 0)
        if per_c and sim * per_c <= nd:
            warn_mismatch(sim, "LGBM_TPU_NUM_SLICES")
            return MeshPlan(sim, per_c, sim * per_c, "env")
    if nm > 1:
        if nd % nm == 0 and nd // nm > 1:
            # num_machines "machines", each owning an equal slice of the
            # local devices — the reference's machine-count key steering
            # the DCN tier directly (a single-device-per-machine split
            # has no fast tier to reduce first, so it stays flat below)
            return MeshPlan(nm, nd // nm, nd, "num_machines")
        total = min(nd, nm)
        warn_mismatch(total, "the flat shard count this device set allows")
        return MeshPlan(1, total, total, "flat")
    return MeshPlan(1, nd, nd, "flat")


def parse_machine_list(machines: Optional[str] = None,
                       machine_list_file: Optional[str] = None) -> List[Tuple[str, int]]:
    """reference: Linkers::Linkers reads `machines` ("ip1:port1,ip2:port2")
    or one host:port per line of `machine_list_filename`
    (linkers_socket.cpp:23-63)."""
    entries: List[str] = []
    if machines:
        entries = [tok for tok in str(machines).replace("\n", ",").split(",")
                   if tok.strip()]
    elif machine_list_file:
        from ..utils.file_io import exists, open_file
        if not exists(machine_list_file):
            # reference: Log::Fatal on an unreadable machine list file
            # (linkers_socket.cpp:27) — fail loudly instead of silently
            # training single-machine
            raise ValueError(
                f"machine_list_file {str(machine_list_file)!r} does not "
                "exist; every machine needs the same host:port list file")
        with open_file(machine_list_file) as fh:
            entries = [ln.strip() for ln in fh.read().splitlines()
                       if ln.strip()]
    out = []
    for e in entries:
        host, _, port = e.strip().partition(":")
        if not host:
            raise ValueError(f"machine list entry {e!r} has no host")
        try:
            out.append((host, int(port) if port else 12400))
        except ValueError:
            raise ValueError(
                f"machine list entry {e!r}: port {port!r} is not an "
                "integer") from None
    return out


def _local_identifiers() -> set:
    ids = {"localhost", "127.0.0.1", socket.gethostname()}
    try:
        ids.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    try:
        ids.update(i[4][0] for i in socket.getaddrinfo(
            socket.gethostname(), None))
    except OSError:
        pass
    return ids


def resolve_rank(machine_list: List[Tuple[str, int]],
                 local_listen_port: Optional[int] = None) -> int:
    """This process's rank = its position in the machine list (the
    reference matches the bound interface+port, linkers_socket.cpp:64-76).
    When several entries share the local host (multi-process-per-host),
    ``local_listen_port`` disambiguates."""
    local = _local_identifiers()
    matches = [i for i, (h, p) in enumerate(machine_list) if h in local]
    if not matches:
        raise ValueError(
            f"none of the machine-list hosts {[h for h, _ in machine_list]} "
            f"matches this host ({sorted(local)}); set machines= to include "
            "this machine")
    if len(matches) > 1 and local_listen_port is not None:
        port_matches = [i for i in matches
                        if machine_list[i][1] == local_listen_port]
        if port_matches:
            return port_matches[0]
    return matches[0]


def init_network(machines: Optional[str] = None,
                 local_listen_port: Optional[int] = None,
                 listen_time_out: int = 120,
                 num_machines: Optional[int] = None,
                 machine_list_file: Optional[str] = None,
                 dry_run: bool = False):
    """Start the multi-host JAX runtime from a reference-style machine list.

    reference seam: Network::Init (network.cpp:29-58) /
    LGBM_NetworkInit (c_api.h).  Returns (coordinator_address,
    num_processes, process_id); with ``dry_run`` nothing is initialized
    (for tests and introspection).
    """
    if listen_time_out is None:
        listen_time_out = 120      # the signature default, for explicit None
    # this value is exported into JAX_COORDINATION_SERVICE_TIMEOUT_SECS; a
    # zero/negative (or unparseable) timeout would make every coordination
    # call fail instantly (or never)
    try:
        ok = float(listen_time_out) > 0
    except (TypeError, ValueError):
        ok = False
    if not ok:
        raise ValueError(
            f"listen_time_out must be a positive number of seconds, "
            f"got {listen_time_out!r}")
    ml = parse_machine_list(machines, machine_list_file)
    if not ml and num_machines in (None, 0, 1):
        log_warning("init_network: no machine list and num_machines<=1; "
                    "nothing to do")
        return None
    if not ml:
        raise ValueError("init_network needs machines= or machine_list_file=")
    n = num_machines or len(ml)
    if n > len(ml):
        raise ValueError(
            f"num_machines={n} but machine list has {len(ml)} entries")
    ml = ml[:n]
    rank = resolve_rank(ml, local_listen_port)
    host0, port0 = ml[0]
    coordinator = f"{host0}:{port0}"
    if dry_run:
        return coordinator, n, rank
    # round-trip the reference config surface into the mesh plan: the
    # num_machines/local_listen_port this process was wired with are what
    # mesh_plan consults when the GBDT layer builds the hybrid mesh
    global _LAST_INIT
    _LAST_INIT = {"num_machines": n, "rank": rank,
                  "local_listen_port": local_listen_port,
                  "coordinator": coordinator}
    import jax
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        log_warning("init_network: jax.distributed already initialized")
        return coordinator, n, rank
    if n == 1:
        log_info("init_network: single machine; skipping jax.distributed")
        return coordinator, n, rank
    os.environ.setdefault("JAX_COORDINATION_SERVICE_TIMEOUT_SECS",
                          str(max(1, round(float(listen_time_out)))))
    log_info(f"init_network: jax.distributed.initialize("
             f"{coordinator!r}, num_processes={n}, process_id={rank})")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=n, process_id=rank)
    return coordinator, n, rank


def free_network() -> None:
    """reference: Network::Dispose / LGBM_NetworkFree."""
    global _LAST_INIT
    _LAST_INIT = None
    import jax
    try:
        if getattr(jax.distributed, "is_initialized", lambda: False)():
            jax.distributed.shutdown()
    except Exception as e:   # noqa: BLE001 — best-effort teardown
        log_warning(f"free_network: {e}")
