"""Tiered (ICI x DCN) collective primitives for pod-scale meshes.

The reference's ``Network`` layer (src/network/) moves histogram payloads
over ONE transport; a TPU pod has TWO with a ~10-50x bandwidth gap
between them: the intra-slice ICI torus and the cross-host DCN
(PAPER.md §2.6).  Every reduction in the sharded growers routes through
this module so one policy decides how a payload crosses the ladder —
including the fused megakernel's collective seam (ops/fused.py): the
sharded fused path accumulates smaller-child hists in VMEM, reduces
exactly those through these tiers, and scans the reduced arena
in-kernel, so only hists ever cross the wire and the routing (hence the
integer-payload bit-pattern) is identical to the staged arm's:

- **flat** — one ``lax.psum`` over every data axis at once (the XLA
  runtime picks the schedule).  Correct everywhere; on a multi-slice
  mesh the full payload effectively crosses the slow tier.
- **hierarchical** — reduce the FAST tier first (psum over ``"ici"``),
  then the slow one (psum over ``"dcn"``): the DCN hop runs between
  num_slices participants instead of num_devices, and voting-parallel
  can elect features per SLICE so only elected columns ever cross DCN
  (grower.py ``leaf_best_voting``).
- **pinned** — determinism mode for f32 parity testing: each tier is
  reduced as ``all_gather`` + a fixed-order sum over the gathered axis,
  innermost (fast) tier first.  Under ``pinned`` the flat and
  hierarchical arms share one canonical tier-ordered association, so
  their models are text-identical — that IS the pinned reduction order.
  Integer (quantized) payloads never need pinning: integer addition is
  associative, so flat == hierarchical is byte-identical for free.

Axis names here may be a single mesh axis (``"data"``, the historical
single-tier layout) or an outermost-first tuple (``("dcn", "ici")``,
the hybrid mesh of ``parallel.learners.make_mesh``).  All helpers accept
``None`` (unsharded) and degrade to identity.

Trace: each tier reduction is wrapped in a ``collective.reduce`` span at
trace time (one span per tier per call site, tagged with the tier name
and payload bytes), so a trace file shows the two-hop ladder the same
way ``trace.grow_tree`` shows program construction
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import lax

from ..obs.flight import global_flight as _flight
from ..obs.trace import span as _span

# hybrid mesh axis names (outermost-first: slices over DCN, devices of a
# slice over ICI) — parallel.learners.make_mesh builds this layout
DCN_AXIS = "dcn"
ICI_AXIS = "ici"
HYBRID_AXES: Tuple[str, str] = (DCN_AXIS, ICI_AXIS)

AxisName = Union[None, str, Tuple[str, ...]]


def axis_names(axis_name: AxisName) -> Tuple[str, ...]:
    """Normalize ``None | str | tuple`` to an outermost-first tuple."""
    if axis_name is None:
        return ()
    if isinstance(axis_name, str):
        return (axis_name,)
    return tuple(axis_name)


def axis_size(mesh, axis_name: AxisName) -> int:
    """Total shard count of ``axis_name`` over ``mesh`` (product over a
    tuple of axes; 1 for None)."""
    out = 1
    for ax in axis_names(axis_name):
        out *= int(mesh.shape[ax])
    return out


def axis_index_flat(axis_name: AxisName):
    """Linearized rank along (possibly tuple) ``axis_name`` — the
    outermost axis is most significant, matching the device order of the
    hybrid mesh and of a flat ``all_gather`` over the same tuple."""
    names = axis_names(axis_name)
    if not names:
        return jnp.int32(0)
    idx = lax.axis_index(names[0])
    for ax in names[1:]:
        idx = idx * lax.psum(1, ax) + lax.axis_index(ax)
    return idx


def _nbytes(x) -> int:
    try:
        return int(x.size) * int(jnp.dtype(x.dtype).itemsize)
    except Exception:  # noqa: BLE001 — tracing corner; accounting only
        return 0


def _pinned_tier_sum(x, ax: str):
    """Deterministic one-tier reduction: gather the tier in rank order
    and reduce over the gathered axis with one fixed-shape XLA reduce.
    Both the flat and hierarchical pinned arms run THIS code per tier,
    so their sums share one association and match bitwise."""
    return lax.all_gather(x, ax).sum(axis=0)


def psum_tiered(x, axis_name: AxisName, *, hierarchical: bool = False,
                pinned: bool = False):
    """Sum ``x`` across the data axes under the active reduction policy.

    - single axis, default policy: exactly ``lax.psum(x, axis)`` — the
      historical single-tier path, bit-for-bit unchanged;
    - ``hierarchical``: innermost (fast) tier first, one psum per tier;
    - ``pinned``: canonical tier-ordered deterministic sums (see module
      docstring); implies the hierarchical order.
    """
    names = axis_names(axis_name)
    if not names:
        return x
    # trace-time only (once per compile): the flight ring records which
    # reduction route this program was built with — a forensic bundle
    # from a pod failure shows the elected ladder without a trace file
    _flight.note("collective.route", tiers=list(names),
                 hierarchical=bool(hierarchical and len(names) > 1),
                 pinned=bool(pinned), bytes=_nbytes(x))
    if pinned:
        for ax in reversed(names):
            with _span("collective.reduce", tier=ax, bytes=_nbytes(x),
                       pinned=True):
                x = _pinned_tier_sum(x, ax)
        return x
    if hierarchical and len(names) > 1:
        for ax in reversed(names):
            with _span("collective.reduce", tier=ax, bytes=_nbytes(x)):
                x = lax.psum(x, ax)
        return x
    with _span("collective.reduce", tier="+".join(names), bytes=_nbytes(x)):
        return lax.psum(x, names if len(names) > 1 else names[0])


def psum_int_tiered(x, axis_name: AxisName, *, hierarchical: bool = False,
                    narrow: Optional[object] = None):
    """Integer twin of ``psum_tiered`` (quantized histograms): no pinning
    needed — integer addition is exact — but the int16 narrowing of
    ``ops.histogram.quant_psum_narrow`` must apply per tier.  ``narrow``
    is the dtype to move on the wire (e.g. ``jnp.int16``) or None.

    The narrowing bound is computed against the GLOBAL row count, and
    every partial (per-tier) sum of per-row contributions is bounded by
    the same rows x max-level product, so a bound that admits the flat
    psum admits each hierarchical stage too.
    """
    names = axis_names(axis_name)
    if not names:
        return x
    dtype = x.dtype
    wire = x.astype(narrow) if narrow is not None else x
    if hierarchical and len(names) > 1:
        for ax in reversed(names):
            with _span("collective.reduce", tier=ax, bytes=_nbytes(wire)):
                wire = lax.psum(wire, ax)
        return wire.astype(dtype) if narrow is not None else wire
    with _span("collective.reduce", tier="+".join(names),
               bytes=_nbytes(wire)):
        wire = lax.psum(wire, names if len(names) > 1 else names[0])
    return wire.astype(dtype) if narrow is not None else wire


def pmax_tiered(x, axis_name: AxisName):
    """Max across the data axes (max is associative and commutative, so
    one fused pmax is always exact — no policy needed)."""
    names = axis_names(axis_name)
    if not names:
        return x
    return lax.pmax(x, names if len(names) > 1 else names[0])


def all_gather_tiered(x, axis_name: AxisName):
    """Gather across every data axis, outermost-major order — the same
    linear rank order as ``axis_index_flat``."""
    names = axis_names(axis_name)
    if not names:
        return x[None] if hasattr(x, "ndim") else jnp.asarray(x)[None]
    return lax.all_gather(x, names if len(names) > 1 else names[0])
