"""Distributed tree learners over jax.sharding meshes."""
