"""Serving-path errors.

All inherit LightGBMError so existing callers' except clauses still
catch them, with distinct types for the three rejection reasons the
backpressure/deadline/shutdown semantics need (docs/SERVING.md).
"""

from ..config import LightGBMError


class ServingError(LightGBMError):
    """Base class for serving-subsystem failures."""


class QueueFull(ServingError):
    """Backpressure: admitting the request would exceed max_queue_rows.

    Raised AT SUBMIT (reject-with-error) rather than queueing into
    unbounded latency; the caller should shed or retry with backoff.
    """


class DeadlineExceeded(ServingError):
    """The request's deadline expired while it waited in the queue."""


class ServerClosed(ServingError):
    """Submit after close(), or pending work failed by close(drain=False)."""


class SwapQuarantined(ServingError):
    """A hot-swap candidate failed its pre-promotion probe batch (raised,
    or produced non-finite output) and was NOT promoted; serving continues
    on the previous model (registry.py swap probe)."""


class LowPrecisionQuarantined(SwapQuarantined):
    """A bf16/int8 candidate's measured probe-batch accuracy delta
    exceeded its declared ``accuracy_budget`` and it was NOT promoted
    (registry.py low-precision probe; docs/SERVING.md fleet section).
    Subclasses SwapQuarantined so existing quarantine handlers catch it."""


class ModelNotFound(ServingError):
    """A fleet request named a model the registry does not hold
    (fleet/registry.py) — a routing error, not an overload condition."""


class DeviceLost(ServingError):
    """A serving device of a pod fleet is gone (preempted, vanished, or
    health-declared dead).  RETRIABLE by construction: replicas serve
    bit-identical scores, so the router re-dispatches the request to a
    surviving replica instead of surfacing this to the caller
    (fleet/router.py; docs/RESILIENCE.md failover section)."""
