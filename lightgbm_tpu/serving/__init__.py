"""In-process serving subsystem: micro-batched, shape-bucketed forest
inference with model hot-swap and metrics (docs/SERVING.md).

Quick start::

    server = booster.serve(max_batch_rows=512)     # or lgb.serve(path)
    fut = server.submit(X)                         # thread-safe, batched
    scores = fut.result()
    server.swap_model("model_v2.txt")              # atomic, warm first
    print(server.metrics_json())
    server.close()                                 # graceful drain

Module map: ``server`` (facade: submit/deadlines/backpressure/drain),
``batcher`` (micro-batch scheduler + bucket ladder), ``registry``
(compiled-program LRU + model hot-swap), ``metrics`` (JSON-dumpable
instrument registry), ``errors`` (typed rejections).
"""

from .batcher import BucketLadder
from .errors import (DeadlineExceeded, DeviceLost,
                     LowPrecisionQuarantined, ModelNotFound, QueueFull,
                     ServerClosed, ServingError, SwapQuarantined)
from .metrics import MetricsRegistry
from .registry import CompiledModel, ModelRegistry, ProgramRegistry
from .server import Server, ServingConfig

__all__ = [
    "Server", "ServingConfig", "BucketLadder", "MetricsRegistry",
    "ProgramRegistry", "ModelRegistry", "CompiledModel",
    "ServingError", "QueueFull", "DeadlineExceeded", "ServerClosed",
    "SwapQuarantined", "LowPrecisionQuarantined", "ModelNotFound",
    "DeviceLost",
]
