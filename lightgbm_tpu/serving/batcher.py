"""Micro-batch scheduler: coalesce concurrent predict requests into
padded, bucket-shaped device batches.

The reference LightGBM predictor parallelizes rows across OpenMP threads
(src/application/predictor.hpp); the XLA-native analogue of that
throughput trick is SHAPE REUSE: concurrent requests are concatenated
into one batch, padded up to a small ladder of power-of-two row buckets,
and run through a program compiled once per (model digest, bucket,
num_class) — after warmup the accelerator only ever sees shapes it has
already compiled (see arXiv:1806.11248 / arXiv:1706.08359 for the
GPU-batching version of the same argument).

Scheduling policy (one daemon thread):

* pop the oldest queued item, then keep popping for at most
  ``batch_window_ms`` or until adding the next item would overflow the
  largest bucket — latency is bounded by the window, throughput by the
  bucket ladder;
* an item that would overflow is carried (never reordered past) into the
  next batch, so the queue stays FIFO;
* items whose deadline expired while queued are rejected at pop time
  (reject-with-error beats unbounded latency under overload);
* requests larger than the top bucket are split by the server into
  top-bucket-sized work items that share one result buffer, so arbitrary
  request sizes ride the same fixed shape set.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from ..obs.flight import global_flight as _flight
from ..obs.trace import span as _span
from ..obs.watchdog import beat as _beat
from .errors import DeadlineExceeded, ServerClosed


class BucketLadder:
    """Power-of-two row buckets in [min_rows, max_rows].

    ``bucket_for(n)`` returns the smallest bucket >= n; n must not exceed
    ``max_rows`` (the server splits oversized requests first).
    """

    def __init__(self, min_rows: int = 8, max_rows: int = 1024):
        if min_rows < 1 or max_rows < min_rows:
            raise ValueError("need 1 <= min_rows <= max_rows")

        def pow2(v):
            p = 1
            while p < v:
                p <<= 1
            return p

        self.min_rows = pow2(min_rows)
        self.max_rows = pow2(max_rows)
        self.buckets: List[int] = []
        b = self.min_rows
        while b < self.max_rows:
            self.buckets.append(b)
            b <<= 1
        self.buckets.append(self.max_rows)

    def bucket_for(self, n: int) -> int:
        if n > self.max_rows:
            raise ValueError(f"{n} rows exceed top bucket {self.max_rows}")
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_rows


class WorkItem:
    """One schedulable unit: a (<= top bucket)-row slice of a request.

    ``request`` owns the result buffer and completion accounting; the
    item only knows which rows it covers.
    """

    __slots__ = ("request", "X", "offset", "enqueued_at")

    def __init__(self, request, X: np.ndarray, offset: int):
        self.request = request
        self.X = X                      # [n_item, F] float64 view
        self.offset = offset            # row offset inside the request
        self.enqueued_at = time.monotonic()

    @property
    def n(self) -> int:
        return self.X.shape[0]


class Batch:
    """Items coalesced for one program invocation."""

    __slots__ = ("items", "rows", "bucket")

    def __init__(self, items: List[WorkItem], bucket: int):
        self.items = items
        self.rows = sum(it.n for it in items)
        self.bucket = bucket

    def padded_input(self) -> np.ndarray:
        X0 = self.items[0].X
        out = np.zeros((self.bucket, X0.shape[1]), np.float64)
        pos = 0
        for it in self.items:
            out[pos:pos + it.n] = it.X
            pos += it.n
        return out


class MicroBatcher:
    """FIFO queue + scheduler thread turning items into Batches.

    ``run_batch(batch)`` is the execution callback (the Server binds it to
    the program registry); it must scatter results / exceptions onto the
    items' requests itself.
    """

    def __init__(self, ladder: BucketLadder, run_batch: Callable,
                 metrics, batch_window_ms: float = 2.0,
                 max_queue_rows: int = 1 << 16,
                 beat_name: str = "serving.batcher"):
        self.ladder = ladder
        self.run_batch = run_batch
        self.metrics = metrics
        self.batch_window_s = max(batch_window_ms, 0.0) / 1e3
        self.max_queue_rows = max_queue_rows
        # per-replica liveness: a pod fleet names each replica's beat
        # (fleet/router.py health scoring) so ONE wedged device goes
        # stale by name instead of hiding behind a shared heartbeat
        self.beat_name = beat_name
        self._q = collections.deque()           # guarded-by: _lock
        self._carry: Optional[WorkItem] = None  # guarded-by: _lock
        self._queued_rows = 0                   # guarded-by: _lock
        self._lock = threading.Lock()
        self._work_ready = threading.Condition(self._lock)
        self._closed = False
        self._draining = False
        self._thread = threading.Thread(target=self._loop,
                                        name="lgbt-serving-batcher",
                                        daemon=True)
        self._thread.start()

    def _set_depth_gauges(self) -> None:
        """Sync both depth gauges to the truth (lock held).  The carried
        item is queued-but-not-in-_q, so it counts in both."""
        self.metrics.gauge("queue_depth_rows").set(self._queued_rows)
        self.metrics.gauge("queue_depth_items").set(
            len(self._q) + (1 if self._carry is not None else 0))

    def queued_rows(self) -> int:
        """Rows currently occupying the queue (carry included) — the
        fleet's weighted-admission input (fleet/registry.py).  A plain
        int attribute read: atomic under the GIL, intentionally lock-free
        on the submit path."""
        return self._queued_rows

    # ------------------------------------------------------------- enqueue

    def submit_items(self, items: List[WorkItem]) -> None:
        """Atomically enqueue every work item of ONE request — all or
        nothing, so a split request can never be half-admitted (a
        mid-split QueueFull would leave doomed siblings queued).  Raises
        ServerClosed / QueueFull upward through the server (which owns
        reject accounting)."""
        total = sum(it.n for it in items)
        with self._lock:
            if self._closed:
                raise ServerClosed("server is shut down")
            if self._queued_rows + total > self.max_queue_rows:
                from .errors import QueueFull
                raise QueueFull(
                    f"queue depth {self._queued_rows} rows + {total} would "
                    f"exceed max_queue_rows={self.max_queue_rows}")
            self._q.extend(items)
            self._queued_rows += total
            self._set_depth_gauges()
            self._work_ready.notify()

    # ----------------------------------------------------------- scheduler

    def _pop(self, timeout: Optional[float]) -> Optional[WorkItem]:
        """Next item (carry first), or None on timeout / drain-complete."""
        with self._lock:
            if self._carry is not None:
                it, self._carry = self._carry, None
                self._queued_rows -= it.n
                self._set_depth_gauges()
                return it
            if not self._q:
                if self._closed:
                    return None
                self._work_ready.wait(timeout)
                if not self._q:
                    return None
            it = self._q.popleft()
            self._queued_rows -= it.n
            self._set_depth_gauges()
            return it

    def _unpop(self, item: WorkItem) -> None:
        with self._lock:            # close(drain=False) also reads _carry
            self._carry = item
            # the carry still occupies the queue for backpressure: a
            # popped-but-deferred top-bucket item must not open a
            # max_queue_rows + bucket admission hole
            self._queued_rows += item.n
            self._set_depth_gauges()

    def _expired(self, item: WorkItem, now: float) -> bool:
        dl = item.request.deadline
        return dl is not None and now > dl

    def _loop(self) -> None:
        while True:
            # liveness heartbeat every scheduler turn (idle turns wake at
            # the pop timeout): a dead batcher thread goes stale within
            # ~0.1s of real time, whatever the queue holds (watchdog.py)
            _beat(self.beat_name)
            item = self._pop(timeout=0.1)
            if item is None:
                with self._lock:
                    if self._closed and not self._q and self._carry is None:
                        return
                continue
            now = time.monotonic()
            if item.request.is_settled():
                # cancelled by the caller, or sibling item of a request
                # already failed (QueueFull mid-split, deadline): results
                # would be discarded — don't spend device work on them
                self.metrics.counter("items_dropped_settled").inc()
                continue
            if self._expired(item, now):
                if item.request.fail_item(DeadlineExceeded(
                        "deadline expired after "
                        f"{(now - item.enqueued_at) * 1e3:.1f} ms in queue")):
                    self.metrics.counter("requests_rejected_deadline").inc()
                continue
            items = [item]
            rows = item.n
            window_end = now + self.batch_window_s
            while rows < self.ladder.max_rows:
                remaining = window_end - time.monotonic()
                nxt = self._pop(timeout=max(remaining, 0.0))
                if nxt is None:
                    if remaining <= 0:
                        break
                    continue
                if nxt.request.is_settled():
                    self.metrics.counter("items_dropped_settled").inc()
                    continue
                if self._expired(nxt, time.monotonic()):
                    if nxt.request.fail_item(DeadlineExceeded(
                            "deadline expired in queue")):
                        self.metrics.counter(
                            "requests_rejected_deadline").inc()
                    continue
                if rows + nxt.n > self.ladder.max_rows:
                    self._unpop(nxt)
                    break
                items.append(nxt)
                rows += nxt.n
            batch = Batch(items, self.ladder.bucket_for(rows))
            self._record_batch(batch)
            try:
                with _span("serving.dispatch", rows=batch.rows,
                           bucket=batch.bucket, items=len(batch.items)):
                    self.run_batch(batch)
            except Exception as e:  # noqa: BLE001 — fail items, keep serving
                for it in batch.items:
                    it.request.fail_item(e)

    def _record_batch(self, batch: Batch) -> None:
        m = self.metrics
        m.counter("batches_total").inc()
        # the flight ring sees every dispatched batch even with tracing
        # off (forensics for a wedged/quarantined serving process)
        _flight.note("serving.batch", rows=batch.rows,
                     bucket=batch.bucket, items=len(batch.items))
        m.histogram("batch_rows", buckets=tuple(
            float(b) for b in self.ladder.buckets)).observe(batch.rows)
        from .metrics import RATIO_BUCKETS
        m.histogram("batch_fill_ratio", buckets=RATIO_BUCKETS).observe(
            batch.rows / batch.bucket)
        submitters = {it.request.submitter for it in batch.items}
        m.histogram("batch_submitters",
                    buckets=(1.0, 2.0, 4.0, 8.0, 16.0)).observe(
            len(submitters))
        if len(submitters) >= 2:
            m.counter("multi_submitter_batches").inc()
        now = time.monotonic()
        for it in batch.items:
            m.histogram("queue_wait_ms").observe(
                (now - it.enqueued_at) * 1e3)

    # ------------------------------------------------------------ shutdown

    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting work.  ``drain=True`` serves everything already
        queued before the thread exits; ``drain=False`` fails it."""
        with self._lock:
            self._closed = True
            if not drain:
                pending = list(self._q)
                if self._carry is not None:
                    pending.insert(0, self._carry)
                    self._carry = None
                self._q.clear()
                self._queued_rows = 0
                self._set_depth_gauges()
            self._work_ready.notify_all()
        if not drain:
            for it in pending:
                it.request.fail_item(ServerClosed("server shut down"))
        self._thread.join(timeout)
