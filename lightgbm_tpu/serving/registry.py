"""Compiled-program + model registry: one executable per
(model digest, row bucket, num_class), plus atomic model hot-swap.

XLA specializes a jitted program per input shape, so the serving layer's
job is to make sure the device only ever sees shapes from the bucket
ladder and to know — cheaply, by key lookup — whether a (model, bucket)
pair has been compiled before.  ``ProgramRegistry`` is that lookup: an
LRU of predict callables keyed ``(digest, bucket_rows, num_class)``.  A
miss builds the callable and counts a ``compile_events`` metric (the
first invocation triggers the actual XLA compile, unless the persistent
compilation cache already has the executable); a hit is free.  Eviction
is bookkeeping — the underlying device executable lives in the model's
``DeviceForest`` jit cache and is freed when the model object is
released, not per-program.

``ModelRegistry`` owns the serving pointer: ``swap()`` builds the new
model's forests, optionally pre-runs every bucket the old model had
warmed (in the caller's thread or a background one), then atomically
flips ``active``.  Requests are pinned to the model they were admitted
against at submit (server.py), so a swap never drops, corrupts, or
generation-mixes in-flight work; the old model is garbage-collected when
its last request completes and its programs age out of the LRU.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional, Set, Tuple

import numpy as np

from .errors import SwapQuarantined


def forest_digest(forest) -> str:
    """Stable content hash of a StackedForest's semantic arrays."""
    h = hashlib.sha256()
    for a in (forest.split_feature, forest.threshold, forest.left,
              forest.right, forest.leaf_value, forest.is_cat,
              forest.default_left, forest.missing_type,
              forest.cat_offset, forest.cat_nwords, forest.cat_words):
        h.update(np.ascontiguousarray(a).tobytes())
    h.update(np.int64([forest.num_trees]).tobytes())
    return h.hexdigest()[:16]


class CompiledModel:
    """One immutable loaded model: booster + host forest (+ device forest
    for the "device" backend), its digest, and its output transform."""

    def __init__(self, booster, backend: str = "device",
                 num_iteration: Optional[int] = None,
                 start_iteration: int = 0):
        self.booster = booster
        self.backend = backend
        K = max(booster.num_tree_per_iteration, 1)
        self.num_class = K
        n_total_iter = len(booster.models) // K
        if num_iteration is None or num_iteration < 0:
            num_iteration = (booster.best_iteration
                             if booster.best_iteration > 0 else n_total_iter)
        stop_iter = min(start_iteration + num_iteration, n_total_iter)
        self.num_iterations = stop_iter - start_iteration
        self.forest = booster._forest(start_iteration, stop_iter)
        self.num_features = booster.num_features()
        # share Booster.predict's cached DeviceForest: predict() then
        # serve() on the same model must not re-trace per shape twice
        self.device_forest = (booster._device_forest(self.forest)
                              if backend == "device" else None)
        self.digest = forest_digest(self.forest)
        self.average_output = bool(getattr(booster, "average_output", False))

    def make_program(self, bucket_rows: int) -> Callable:
        """Predict callable for one bucket shape: [bucket, F] float64
        padded batch -> raw scores [K, bucket] float64.

        Both backends are bit-identical to ``StackedForest.predict_raw``
        per row — "host" unconditionally (it IS predict_raw on the padded
        batch; per-row work is independent of the padding rows), "device"
        for float32-precision feature values (DeviceForest's documented
        routing-exactness domain; leaf-value accumulation happens on the
        host in float64 in the same order as predict_raw).
        """
        K = self.num_class
        if self.backend == "host":
            forest = self.forest

            def run(Xpad: np.ndarray) -> np.ndarray:
                return forest.predict_raw(Xpad, num_class=K)

            return run
        dev = self.device_forest

        def run(Xpad: np.ndarray) -> np.ndarray:
            return dev.predict_raw_padded(Xpad, num_class=K)

        return run

    def scale_raw(self, raw: np.ndarray) -> np.ndarray:
        """The average_output division Booster.predict applies to BOTH
        raw and transformed output (basic.py _predict_inner) — identity
        for every boosting mode but rf."""
        if self.average_output and self.num_iterations > 0:
            raw = raw / self.num_iterations
        return raw

    def transform_raw(self, raw: np.ndarray) -> np.ndarray:
        """predict()'s objective transform for ALREADY-SCALED raw
        [K, n]; returns [K, n]."""
        return self.booster._convert_output(raw)


class ProgramRegistry:
    """LRU of predict programs keyed (digest, bucket_rows, num_class)."""

    def __init__(self, metrics, max_programs: int = 64):
        self.metrics = metrics
        self.max_programs = max_programs
        self._lock = threading.Lock()
        self._lru: "OrderedDict[Tuple[str, int, int], Callable]" = \
            OrderedDict()
        # (bucket, num_class) shapes ever served — the warm set for swaps
        self.seen_buckets: Set[Tuple[int, int]] = set()

    def get(self, model: CompiledModel, bucket_rows: int) -> Callable:
        key = (model.digest, bucket_rows, model.num_class)
        with self._lock:
            prog = self._lru.get(key)
            if prog is not None:
                self._lru.move_to_end(key)
                self.metrics.counter("bucket_hits").inc()
                return prog
        # build outside the lock (jit-wrapper creation is cheap, but the
        # first call compiles; never serialize other buckets behind it)
        prog = model.make_program(bucket_rows)
        with self._lock:
            race = self._lru.get(key)
            if race is not None:
                self._lru.move_to_end(key)
                self.metrics.counter("bucket_hits").inc()
                return race
            self._lru[key] = prog
            self.seen_buckets.add((bucket_rows, model.num_class))
            self.metrics.counter("bucket_misses").inc()
            self.metrics.counter("compile_events").inc()
            while len(self._lru) > self.max_programs:
                self._lru.popitem(last=False)
                self.metrics.counter("program_evictions").inc()
        return prog

    def warm(self, model: CompiledModel,
             buckets: Optional[Set[Tuple[int, int]]] = None) -> int:
        """Pre-run ``model``'s program on zeros for every bucket-rows
        value in ``buckets`` (default: every shape ever served) so the
        XLA compile happens BEFORE the model starts taking traffic.
        The num_class half of the seen keys is ignored — the new model's
        own K applies, so warm still covers every bucket when a swap
        changes the class count.  Returns the number of buckets warmed."""
        with self._lock:
            todo = sorted({b for b, _k in (buckets if buckets is not None
                                           else self.seen_buckets)})
        n = 0
        for bucket_rows in todo:
            prog = self.get(model, bucket_rows)
            prog(np.zeros((bucket_rows, model.num_features), np.float64))
            n += 1
        return n


class ModelRegistry:
    """The serving pointer + hot-swap protocol."""

    def __init__(self, booster, programs: ProgramRegistry, metrics,
                 backend: str = "device",
                 num_iteration: Optional[int] = None,
                 start_iteration: int = 0):
        self.programs = programs
        self.metrics = metrics
        self.backend = backend
        self._swap_lock = threading.Lock()    # serializes swaps, not reads
        self._seq_lock = threading.Lock()     # ticket allocation only
        self._active = CompiledModel(booster, backend=backend,
                                     num_iteration=num_iteration,
                                     start_iteration=start_iteration)
        metrics.gauge("active_model_digest").set(self._active.digest)
        metrics.gauge("model_generation").set(0)
        self._generation = 0
        self._swap_seq = 0          # ticket order of swap() CALLS
        self._applied_seq = 0       # highest ticket that has flipped

    @property
    def active(self) -> CompiledModel:
        # plain attribute read: atomic under the GIL, no lock on the
        # per-batch hot path
        return self._active

    # rows for the pre-promotion probe batch when no bucket has ever been
    # served (otherwise the smallest seen bucket is used)
    probe_rows = 8

    def _probe(self, model: CompiledModel) -> None:
        """Run one probe batch through the candidate BEFORE promotion; a
        raise or a non-finite raw score quarantines the swap — the active
        pointer never flips to a model that cannot serve.  (The serving
        counterpart of the checkpoint manifest: corruption is caught at
        the boundary, not by the first unlucky request.)"""
        with self.programs._lock:
            seen = sorted(b for b, _k in self.programs.seen_buckets)
        rows = seen[0] if seen else self.probe_rows
        try:
            raw = model.make_program(rows)(
                np.zeros((rows, model.num_features), np.float64))
            raw = model.scale_raw(np.asarray(raw, np.float64))
        except SwapQuarantined:
            raise
        except Exception as e:  # noqa: BLE001 — any probe failure quarantines
            self.metrics.counter("swap_quarantines").inc()
            raise SwapQuarantined(
                f"hot-swap candidate {model.digest} failed its probe batch "
                f"({rows} rows): {e!r}; swap rolled back") from e
        if not np.isfinite(raw).all():
            self.metrics.counter("swap_quarantines").inc()
            raise SwapQuarantined(
                f"hot-swap candidate {model.digest} produced non-finite "
                f"probe output; swap rolled back")

    def swap(self, booster, warm: bool = True, block: bool = True,
             num_iteration: Optional[int] = None,
             start_iteration: int = 0,
             probe: bool = True) -> "threading.Thread | None":
        """Load ``booster`` as the new serving model.

        With ``warm=True`` every bucket shape ever served is pre-compiled
        for the new model before the pointer flips, so the first
        post-swap batches pay no compile latency.  ``block=False`` does
        the warm+flip in a daemon thread and returns it (the flip still
        happens only after warmup; serving continues on the old model
        meanwhile).  With ``probe=True`` (default) the candidate must
        first survive a probe batch — exceptions or non-finite output
        quarantine it (``SwapQuarantined``; ``swap_quarantines`` metric)
        and the old model keeps serving."""
        new = CompiledModel(booster, backend=self.backend,
                            num_iteration=num_iteration,
                            start_iteration=start_iteration)
        # ticket taken at CALL time: two block=False swaps whose daemon
        # threads win the lock out of order must still converge on the
        # later call's model, not the later lock acquirer's.  Allocation
        # uses its own lock so block=False returns immediately even while
        # a previous swap holds _swap_lock through a long warm/compile.
        with self._seq_lock:
            self._swap_seq += 1
            seq = self._swap_seq

        def do_swap():
            try:
                with self._swap_lock:
                    if seq < self._applied_seq:
                        return      # a newer swap already landed
                    if probe:
                        self._probe(new)
                    if warm:
                        self.programs.warm(new)
                    self._applied_seq = seq
                    self._active = new
                    self._generation += 1
                    self.metrics.counter("hot_swaps").inc()
                    self.metrics.gauge("active_model_digest").set(new.digest)
                    self.metrics.gauge("model_generation").set(
                        self._generation)
            except Exception:
                # count on BOTH paths: the blocking caller sees the raise,
                # but a dashboard reading metrics must too
                self.metrics.counter("swap_failures").inc()
                raise

        if block:
            do_swap()
            return None

        def do_swap_bg():
            # a warm/compile failure must not vanish with the daemon
            # thread: park the exception on the handle so "joined dead
            # thread + unchanged generation" is readable as a FAILED
            # swap, not a slow one
            try:
                do_swap()
            except Exception as e:  # noqa: BLE001
                t.exception = e

        t = threading.Thread(target=do_swap_bg, name="lgbt-serving-swap",
                             daemon=True)
        t.exception = None
        t.start()
        return t
