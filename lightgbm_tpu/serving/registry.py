"""Compiled-program + model registry: one executable per
(model digest, row bucket, num_class), plus atomic model hot-swap.

XLA specializes a jitted program per input shape, so the serving layer's
job is to make sure the device only ever sees shapes from the bucket
ladder and to know — cheaply, by key lookup — whether a (model, bucket)
pair has been compiled before.  ``ProgramRegistry`` is that lookup: an
LRU of predict callables keyed ``(digest, bucket_rows, num_class)``.  A
miss builds the callable and counts a ``compile_events`` metric (the
first invocation triggers the actual XLA compile, unless the persistent
compilation cache already has the executable); a hit is free.  Eviction
is bookkeeping — the underlying device executable lives in the model's
``DeviceForest`` jit cache and is freed when the model object is
released, not per-program.

``ModelRegistry`` owns the serving pointer: ``swap()`` builds the new
model's forests, optionally pre-runs every bucket the old model had
warmed (in the caller's thread or a background one), then atomically
flips ``active``.  Requests are pinned to the model they were admitted
against at submit (server.py), so a swap never drops, corrupts, or
generation-mixes in-flight work; the old model is garbage-collected when
its last request completes and its programs age out of the LRU.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Callable, Dict, Optional, Set, Tuple

import numpy as np

from .errors import LowPrecisionQuarantined, SwapQuarantined


def forest_digest(forest) -> str:
    """Stable content hash of a StackedForest's semantic arrays."""
    h = hashlib.sha256()
    for a in (forest.split_feature, forest.threshold, forest.left,
              forest.right, forest.leaf_value, forest.is_cat,
              forest.default_left, forest.missing_type,
              forest.cat_offset, forest.cat_nwords, forest.cat_words):
        h.update(np.ascontiguousarray(a).tobytes())
    h.update(np.int64([forest.num_trees]).tobytes())
    return h.hexdigest()[:16]


class CompiledModel:
    """One immutable loaded model: booster + host forest (+ device forest
    for the "device" backend), its digest, and its output transform.

    ``precision`` opts the model into low-precision serving ("bf16" /
    "int8"): the served forest is the quantized twin
    (fleet/lowprec.quantize_forest) — distinct digest, host-gathered
    leaves, narrowed device thresholds — while ``forest_full`` keeps the
    exact forest for the accuracy probe.  ``aot`` is an optional
    fleet.aot.AOTStore consulted before compiling a bucket program.
    The device arrays are EVICTABLE (``drop_device``/``restore_device``,
    driven by the fleet's shared-HBM plan): programs read the pointer at
    call time and fall back to the bit-identical host path while the
    model is evicted."""

    def __init__(self, booster, backend: str = "device",
                 num_iteration: Optional[int] = None,
                 start_iteration: int = 0,
                 precision: str = "f32", aot=None):
        self.booster = booster
        self.backend = backend
        self.precision = precision
        self.aot = aot
        K = max(booster.num_tree_per_iteration, 1)
        self.num_class = K
        n_total_iter = len(booster.models) // K
        if num_iteration is None or num_iteration < 0:
            num_iteration = (booster.best_iteration
                             if booster.best_iteration > 0 else n_total_iter)
        stop_iter = min(start_iteration + num_iteration, n_total_iter)
        self.num_iterations = stop_iter - start_iteration
        self.forest_full = booster._forest(start_iteration, stop_iter)
        if precision != "f32":
            from ..fleet.lowprec import quantize_forest
            self.forest = quantize_forest(self.forest_full, precision)
        else:
            self.forest = self.forest_full
        self.num_features = booster.num_features()
        self.device_forest = None
        if backend == "device":
            self.restore_device()
        self.digest = forest_digest(self.forest)
        self.average_output = bool(getattr(booster, "average_output", False))

    # --------------------------------------------------------- device state

    def restore_device(self) -> None:
        """(Re-)upload the routing arrays; no-op off the device backend or
        when already resident."""
        if self.backend != "device" or self.device_forest is not None:
            return
        if self.precision == "f32":
            # share Booster.predict's cached DeviceForest: predict() then
            # serve() on the same model must not re-trace per shape twice
            self.device_forest = self.booster._device_forest(self.forest)
        else:
            from ..predict import DeviceForest
            self.device_forest = DeviceForest(
                self.forest, precision=self.precision, routing_only=True)

    def drop_device(self) -> None:
        """Release the device routing arrays (fleet eviction).  Serving
        continues through the host path — bit-identical for the same
        inputs — until ``restore_device``."""
        dropped, self.device_forest = self.device_forest, None
        cache = getattr(self.booster, "_device_forest_cache", None)
        if (cache is not None and dropped is not None
                and cache[1] is dropped):
            self.booster._device_forest_cache = None

    def make_program(self, bucket_rows: int) -> Callable:
        """Predict callable for one bucket shape: [bucket, F] float64
        padded batch -> raw scores [K, bucket] float64.

        Both backends are bit-identical to ``StackedForest.predict_raw``
        (of the SERVED forest — the quantized twin under low precision)
        per row — "host" unconditionally (it IS predict_raw on the padded
        batch; per-row work is independent of the padding rows), "device"
        for float32-precision feature values (DeviceForest's documented
        routing-exactness domain; leaf-value accumulation happens on the
        host in float64 in the same order as predict_raw).
        """
        K = self.num_class
        forest = self.forest
        if self.backend == "host":

            def run(Xpad: np.ndarray) -> np.ndarray:
                return forest.predict_raw(Xpad, num_class=K)

            return run
        if self.aot is not None and self.device_forest is not None:
            from ..fleet.aot import make_aot_program
            prog = make_aot_program(self.aot, self, bucket_rows)
            if prog is not None:
                return prog
        model = self

        def run(Xpad: np.ndarray) -> np.ndarray:
            dev = model.device_forest
            if dev is None:     # evicted mid-flight: host path, same bits
                return forest.predict_raw(Xpad, num_class=K)
            return dev.predict_raw_padded(Xpad, num_class=K)

        # built while evicted: nothing traces or compiles until the model
        # is restored, so the program registry must not count it as a
        # compile_event (that counter is the AOT zero-compile
        # cold-start discriminator)
        run.host_fallback = self.device_forest is None
        return run

    def export_aot(self, store, buckets) -> int:
        """Serialize this model's routing program for ``buckets`` into
        ``store`` (fleet.aot.AOTStore); returns entries written."""
        if self.device_forest is None:
            return 0
        return store.export_device_forest(
            self.device_forest, self.num_features, buckets, self.digest)

    def measure_accuracy(self, X: np.ndarray) -> float:
        """max |served raw - full-precision raw| over probe rows ``X``
        (0.0 for f32 models by construction)."""
        if self.precision == "f32":
            return 0.0
        from ..fleet.lowprec import measure_accuracy_delta
        return measure_accuracy_delta(self.forest_full, self.forest, X,
                                      num_class=self.num_class)

    def scale_raw(self, raw: np.ndarray) -> np.ndarray:
        """The average_output division Booster.predict applies to BOTH
        raw and transformed output (basic.py _predict_inner) — identity
        for every boosting mode but rf."""
        if self.average_output and self.num_iterations > 0:
            raw = raw / self.num_iterations
        return raw

    def transform_raw(self, raw: np.ndarray) -> np.ndarray:
        """predict()'s objective transform for ALREADY-SCALED raw
        [K, n]; returns [K, n]."""
        return self.booster._convert_output(raw)


class ProgramRegistry:
    """LRU of predict programs keyed (digest, bucket_rows, num_class)."""

    def __init__(self, metrics, max_programs: int = 64):
        self.metrics = metrics
        self.max_programs = max_programs
        self._lock = threading.Lock()
        self._lru: "OrderedDict[Tuple[str, int, int], Callable]" = \
            OrderedDict()
        # (bucket, num_class) shapes ever served — the warm set for swaps
        self.seen_buckets: Set[Tuple[int, int]] = set()

    def get(self, model: CompiledModel, bucket_rows: int) -> Callable:
        key = (model.digest, bucket_rows, model.num_class)
        with self._lock:
            prog = self._lru.get(key)
            if prog is not None:
                self._lru.move_to_end(key)
                self.metrics.counter("bucket_hits").inc()
                return prog
        # build outside the lock (jit-wrapper creation is cheap, but the
        # first call compiles; never serialize other buckets behind it)
        prog = model.make_program(bucket_rows)
        with self._lock:
            race = self._lru.get(key)
            if race is not None:
                self._lru.move_to_end(key)
                self.metrics.counter("bucket_hits").inc()
                return race
            self._lru[key] = prog
            self.seen_buckets.add((bucket_rows, model.num_class))
            self.metrics.counter("bucket_misses").inc()
            if getattr(prog, "aot", False):
                # restored from the AOT serving cache (fleet/aot.py):
                # no trace, backend compile rides the persistent cache —
                # the zero-compile cold-start discriminator
                self.metrics.counter("aot_program_loads").inc()
            elif getattr(prog, "host_fallback", False):
                # device-backend program built while the model was
                # evicted: serves through the host path, no compile
                self.metrics.counter("host_fallback_builds").inc()
            else:
                self.metrics.counter("compile_events").inc()
            while len(self._lru) > self.max_programs:
                self._lru.popitem(last=False)
                self.metrics.counter("program_evictions").inc()
        return prog

    def evict_model(self, digest: str) -> int:
        """Drop every cached program of one model digest (fleet residency
        eviction/restore: the next ``get`` rebuilds against the model's
        CURRENT device/host state).  Returns the number evicted."""
        with self._lock:
            keys = [k for k in self._lru if k[0] == digest]
            for k in keys:
                del self._lru[k]
            if keys:
                self.metrics.counter("program_evictions").inc(len(keys))
        return len(keys)

    def warm(self, model: CompiledModel,
             buckets: Optional[Set[Tuple[int, int]]] = None) -> int:
        """Pre-run ``model``'s program on zeros for every bucket-rows
        value in ``buckets`` (default: every shape ever served) so the
        XLA compile happens BEFORE the model starts taking traffic.
        The num_class half of the seen keys is ignored — the new model's
        own K applies, so warm still covers every bucket when a swap
        changes the class count.  Returns the number of buckets warmed."""
        with self._lock:
            todo = sorted({b for b, _k in (buckets if buckets is not None
                                           else self.seen_buckets)})
        n = 0
        for bucket_rows in todo:
            prog = self.get(model, bucket_rows)
            prog(np.zeros((bucket_rows, model.num_features), np.float64))
            n += 1
        return n


class ModelRegistry:
    """The serving pointer + hot-swap protocol."""

    def __init__(self, booster, programs: ProgramRegistry, metrics,
                 backend: str = "device",
                 num_iteration: Optional[int] = None,
                 start_iteration: int = 0,
                 precision: str = "f32",
                 accuracy_budget: Optional[float] = None,
                 probe_X=None, aot=None):
        self.programs = programs
        self.metrics = metrics
        self.backend = backend
        self.precision = precision
        self.accuracy_budget = accuracy_budget
        self.probe_X = probe_X
        self.aot = aot
        self._swap_lock = threading.Lock()    # serializes swaps, not reads
        self._seq_lock = threading.Lock()     # ticket allocation only
        self._active = CompiledModel(booster, backend=backend,
                                     num_iteration=num_iteration,
                                     start_iteration=start_iteration,
                                     precision=precision, aot=aot)
        # a low-precision model must pass its accuracy budget BEFORE it
        # ever serves — construction is the same admission boundary a
        # swap probe guards
        self._probe_lowprec(self._active)
        metrics.gauge("active_model_digest").set(self._active.digest)
        metrics.gauge("model_generation").set(0)
        self._generation = 0
        self._swap_seq = 0          # ticket order of swap() CALLS
        self._applied_seq = 0       # highest ticket that has flipped

    @property
    def active(self) -> CompiledModel:
        # plain attribute read: atomic under the GIL, no lock on the
        # per-batch hot path
        return self._active

    # rows for the pre-promotion probe batch when no bucket has ever been
    # served (otherwise the smallest seen bucket is used)
    probe_rows = 8

    def _probe(self, model: CompiledModel) -> None:
        """Run one probe batch through the candidate BEFORE promotion; a
        raise or a non-finite raw score quarantines the swap — the active
        pointer never flips to a model that cannot serve.  (The serving
        counterpart of the checkpoint manifest: corruption is caught at
        the boundary, not by the first unlucky request.)"""
        with self.programs._lock:
            seen = sorted(b for b, _k in self.programs.seen_buckets)
        rows = seen[0] if seen else self.probe_rows
        try:
            raw = model.make_program(rows)(
                np.zeros((rows, model.num_features), np.float64))
            raw = model.scale_raw(np.asarray(raw, np.float64))
        except SwapQuarantined:
            raise
        except Exception as e:  # noqa: BLE001 — any probe failure quarantines
            self.metrics.counter("swap_quarantines").inc()
            raise self._quarantine(SwapQuarantined(
                f"hot-swap candidate {model.digest} failed its probe batch "
                f"({rows} rows): {e!r}; swap rolled back"),
                digest=model.digest) from e
        if not np.isfinite(raw).all():
            self.metrics.counter("swap_quarantines").inc()
            raise self._quarantine(SwapQuarantined(
                f"hot-swap candidate {model.digest} produced non-finite "
                f"probe output; swap rolled back"), digest=model.digest)

    def _quarantine(self, err: SwapQuarantined, **extra) -> SwapQuarantined:
        """Flight-dump the quarantine (the serving pointer never flipped
        — this bundle is the postmortem of WHY) and hand back the error
        for the caller to raise.  Dumping never raises (flight.py)."""
        from ..obs.flight import global_flight
        global_flight.dump(f"serving.swap:{type(err).__name__}", exc=err,
                           extra=extra or None)
        return err

    def _probe_rows(self, model: CompiledModel) -> np.ndarray:
        """Probe rows for the low-precision accuracy measurement: the
        caller-supplied batch when given (representative data routes far
        more realistically than noise), else a deterministic
        float32-precise standard-normal batch."""
        if self.probe_X is not None:
            return np.asarray(self.probe_X, np.float64)
        rng = np.random.RandomState(0x1F1EE7)
        return rng.randn(256, model.num_features) \
            .astype(np.float32).astype(np.float64)

    def _probe_lowprec(self, model: CompiledModel) -> None:
        """Measure a bf16/int8 candidate's raw-score drift on the probe
        batch and QUARANTINE it when the drift exceeds the declared
        ``accuracy_budget`` — the low-precision counterpart of ``_probe``:
        a model that cannot meet its own budget never serves.  The
        measured delta is journaled either way (``lowprec_accuracy_delta``
        gauge) so operators see what the precision actually costs."""
        if model.precision == "f32":
            return
        delta = model.measure_accuracy(self._probe_rows(model))
        self.metrics.gauge("lowprec_accuracy_delta").set(delta)
        self.metrics.gauge("lowprec_precision").set(model.precision)
        if self.accuracy_budget is not None and delta > self.accuracy_budget:
            self.metrics.counter("swap_quarantines").inc()
            self.metrics.counter("lowprec_quarantines").inc()
            raise self._quarantine(LowPrecisionQuarantined(
                f"{model.precision} candidate {model.digest} measured "
                f"probe accuracy delta {delta:.3e} over the declared "
                f"budget {self.accuracy_budget:.3e}; not promoted"),
                digest=model.digest, precision=model.precision,
                accuracy_delta=delta)

    def swap(self, booster, warm: bool = True, block: bool = True,
             num_iteration: Optional[int] = None,
             start_iteration: int = 0,
             probe: bool = True) -> "threading.Thread | None":
        """Load ``booster`` as the new serving model.

        With ``warm=True`` every bucket shape ever served is pre-compiled
        for the new model before the pointer flips, so the first
        post-swap batches pay no compile latency.  ``block=False`` does
        the warm+flip in a daemon thread and returns it (the flip still
        happens only after warmup; serving continues on the old model
        meanwhile).  With ``probe=True`` (default) the candidate must
        first survive a probe batch — exceptions or non-finite output
        quarantine it (``SwapQuarantined``; ``swap_quarantines`` metric)
        and the old model keeps serving.  A registry configured for
        low-precision serving additionally holds the candidate to its
        ``accuracy_budget`` (``LowPrecisionQuarantined``)."""
        new = CompiledModel(booster, backend=self.backend,
                            num_iteration=num_iteration,
                            start_iteration=start_iteration,
                            precision=self.precision, aot=self.aot)
        # ticket taken at CALL time: two block=False swaps whose daemon
        # threads win the lock out of order must still converge on the
        # later call's model, not the later lock acquirer's.  Allocation
        # uses its own lock so block=False returns immediately even while
        # a previous swap holds _swap_lock through a long warm/compile.
        with self._seq_lock:
            self._swap_seq += 1
            seq = self._swap_seq

        def do_swap():
            try:
                with self._swap_lock:
                    if seq < self._applied_seq:
                        return      # a newer swap already landed
                    if probe:
                        self._probe(new)
                        self._probe_lowprec(new)
                    if warm:
                        self.programs.warm(new)
                    self._applied_seq = seq
                    self._active = new
                    self._generation += 1
                    self.metrics.counter("hot_swaps").inc()
                    self.metrics.gauge("active_model_digest").set(new.digest)
                    self.metrics.gauge("model_generation").set(
                        self._generation)
            except Exception:
                # count on BOTH paths: the blocking caller sees the raise,
                # but a dashboard reading metrics must too
                self.metrics.counter("swap_failures").inc()
                raise

        if block:
            do_swap()
            return None

        def do_swap_bg():
            # a warm/compile failure must not vanish with the daemon
            # thread: park the exception on the handle so "joined dead
            # thread + unchanged generation" is readable as a FAILED
            # swap, not a slow one
            try:
                do_swap()
            except Exception as e:  # noqa: BLE001
                t.exception = e

        t = threading.Thread(target=do_swap_bg, name="lgbt-serving-swap",
                             daemon=True)
        t.exception = None
        t.start()
        return t
