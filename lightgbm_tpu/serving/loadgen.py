"""Threaded mixed-shape load driver for the serving subsystem.

The one request-storm implementation shared by ``bench.py``'s serving
stage and ``tools/serve_smoke.py`` (their drivers used to be near-twins;
a fix to one — e.g. dead-thread error accounting — kept missing the
other).  Deliberately not a benchmark harness: it fires, optionally
verifies bit-equality, and reports honest completed counts.

``fire_requests`` additionally speaks **shadow mode** for the model
lifecycle (docs/LIFECYCLE.md): a ``mirror_fraction`` sample of live
requests is replayed against a candidate server and the summary's
``shadow`` section carries the per-request raw-score drift and latency
deltas — mirrored work is accounted SEPARATELY so the live path's
shed/latency numbers stay honest.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np


def fire_requests(server, n_requests: int, n_threads: int,
                  max_request_rows: int, num_features: int,
                  verify_forest=None, timeout: float = 300.0,
                  shadow_server=None, mirror_fraction: float = 0.25,
                  seed: int = 100) -> dict:
    """Fire ``n_requests`` (rounded down to a multiple of ``n_threads``)
    mixed-size requests of float32-precise rows from ``n_threads``
    threads; return completed/row counts, wall time, and per-thread
    errors.  With ``verify_forest`` every response is checked bit-equal
    to ``verify_forest.predict_raw`` (the serving acceptance bar).

    ``QueueFull`` sheds and ``DeadlineExceeded`` expiries on the LIVE
    path are counted as typed outcomes (``shed`` / ``expired``), not as
    thread-killing errors — under deliberate overload both are correct
    behavior, and a shed must not erase the rest of a thread's clean
    numbers.

    **Shadow mode** (docs/LIFECYCLE.md): with ``shadow_server`` a
    ``mirror_fraction`` sample of completed live requests is ALSO sent
    to the candidate, and the summary's ``shadow`` section reports the
    mirrored count, per-request candidate-vs-live raw-score drift
    (max/mean of per-request max |delta|), candidate latencies, the
    per-request latency delta, non-finite candidate outputs, and
    candidate-side errors — all SEPARATE from the live counts, so live
    shed/latency accounting stays honest under mirroring.
    """
    from .errors import DeadlineExceeded, QueueFull

    per_thread = n_requests // n_threads
    done = [0] * n_threads
    rows_served = [0] * n_threads
    lock = threading.Lock()
    mismatches: list = []
    errors: list = []
    live = {"shed": 0, "expired": 0, "lat_ms": []}
    shadow = {"mirrored": 0, "drift": [], "lat_ms": [], "lat_delta_ms": [],
              "nonfinite": 0, "errors": []}

    def mirror(tidx: int, Xr, out, live_lat: float) -> None:
        t0 = time.perf_counter()
        try:
            cand = shadow_server.predict(Xr, timeout=timeout)
        except Exception as e:  # a candidate failure is candidate
            with lock:          # evidence, never a live-path error
                shadow["mirrored"] += 1
                shadow["errors"].append(
                    f"thread {tidx}: {type(e).__name__}: {str(e)[:200]}")
            return
        lat = (time.perf_counter() - t0) * 1e3
        cand = np.asarray(cand, np.float64)
        finite = bool(np.isfinite(cand).all())
        with lock:
            shadow["mirrored"] += 1
            shadow["lat_ms"].append(lat)
            shadow["lat_delta_ms"].append(lat - live_lat)
            if finite:
                shadow["drift"].append(float(np.max(np.abs(
                    cand - np.asarray(out, np.float64)))))
            else:
                shadow["nonfinite"] += 1

    def worker(tidx: int) -> None:
        r = np.random.RandomState(seed + tidx)
        try:
            for _ in range(per_thread):
                m = int(r.randint(1, max_request_rows + 1))
                Xr = r.randn(m, num_features).astype(np.float32) \
                    .astype(np.float64)
                do_mirror = (shadow_server is not None
                             and r.rand() < mirror_fraction)
                t0 = time.perf_counter()
                try:
                    out = server.predict(Xr, timeout=timeout)
                except QueueFull:
                    with lock:
                        live["shed"] += 1
                    continue
                except DeadlineExceeded:
                    with lock:
                        live["expired"] += 1
                    continue
                lat = (time.perf_counter() - t0) * 1e3
                rows_served[tidx] += m
                done[tidx] += 1
                with lock:
                    live["lat_ms"].append(lat)
                if verify_forest is not None and not np.array_equal(
                        out, verify_forest.predict_raw(Xr)[0]):
                    mismatches.append((tidx, m))
                if do_mirror:
                    mirror(tidx, Xr, out, lat)
        except Exception as e:  # a dead thread must not bank clean numbers
            errors.append(f"thread {tidx}: {type(e).__name__}: {str(e)[:200]}")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out = {
        "requests": sum(done),
        "requests_planned": per_thread * n_threads,
        "rows": sum(rows_served),
        "shed": live["shed"],
        "expired": live["expired"],
        "wall_seconds": time.perf_counter() - t0,
        "latency_ms": _latency_summary(live["lat_ms"]),
        "mismatches": mismatches,
        "errors": errors,
    }
    if shadow_server is not None:
        drift = np.asarray(shadow["drift"], np.float64)
        out["shadow"] = {
            "mirrored": shadow["mirrored"],
            "drift_max": (round(float(drift.max()), 6)
                          if drift.size else None),
            "drift_mean": (round(float(drift.mean()), 6)
                           if drift.size else None),
            "nonfinite": shadow["nonfinite"],
            "latency_ms": _latency_summary(shadow["lat_ms"]),
            "latency_delta_ms": _latency_summary(shadow["lat_delta_ms"]),
            "errors": shadow["errors"],
        }
    return out


def _latency_summary(lat_ms: list) -> dict:
    """p50/p90/p99 + mean/max from client-measured latencies (exact
    percentiles over the sample, not histogram-bucket interpolation)."""
    if not lat_ms:
        return {"count": 0}
    a = np.asarray(lat_ms, np.float64)
    return {
        "count": int(a.size),
        "mean": round(float(a.mean()), 3),
        "p50": round(float(np.percentile(a, 50)), 3),
        "p90": round(float(np.percentile(a, 90)), 3),
        "p99": round(float(np.percentile(a, 99)), 3),
        "max": round(float(a.max()), 3),
    }


def fire_fleet_requests(fleet, mix: dict, n_requests: int, n_threads: int,
                        max_request_rows: int, verify: Optional[dict] = None,
                        timeout: float = 300.0, seed: int = 100) -> dict:
    """Multi-model traffic storm against a ``fleet.Fleet`` or
    ``fleet.router.PodFleet``.

    ``mix`` maps model name -> traffic weight: every request picks its
    model by weighted draw, so the fleet bench models a real mixed
    workload instead of N sequential single-model storms.  Sheds
    (``QueueFull`` — the fleet's weighted-admission or brownout
    verdict) and deadline expiries (``DeadlineExceeded`` — the model's
    SLO class rejecting queue-aged work) are counted per model, NOT as
    errors: under deliberate overload both are the correct, typed
    behavior.  Any OTHER per-request failure is a typed-``failed``
    outcome — counted, recorded, and the storm continues, so a failover
    drill measures exactly how many requests a lost device cost instead
    of losing a whole thread's numbers.  ``verify`` maps model name ->
    full-precision ``StackedForest``; every verified response must be
    bit-equal to ``predict_raw`` (the serving acceptance bar — only
    meaningful for f32-precision models).

    The summary carries per-model request/row counts, CLIENT-measured
    latency percentiles, per-outcome counts (``outcomes``:
    completed/shed/expired/failed), and **availability** = 1 −
    failed / (completed + failed) — typed shed/expired excluded from
    both sides, because rejecting work you cannot serve on time is
    correct behavior, not unavailability.  Failover tests and the bench
    assert this number, not a vibe (None before any non-typed outcome).
    """
    from .errors import DeadlineExceeded, QueueFull

    names = sorted(mix)
    w = np.asarray([float(mix[n]) for n in names], np.float64)
    p = w / w.sum()
    feats = {n: fleet.entry(n).model.num_features for n in names}
    classes = {n: fleet.entry(n).model.num_class for n in names}
    per_thread = n_requests // n_threads
    lock = threading.Lock()
    per_model = {n: {"requests": 0, "rows": 0, "shed": 0, "expired": 0,
                     "failed": 0, "lat_ms": [], "mismatches": 0}
                 for n in names}
    errors: list = []
    failures: list = []

    def worker(tidx: int) -> None:
        r = np.random.RandomState(seed + tidx)
        try:
            for _ in range(per_thread):
                name = names[int(r.choice(len(names), p=p))]
                m = int(r.randint(1, max_request_rows + 1))
                Xr = r.randn(m, feats[name]).astype(np.float32) \
                    .astype(np.float64)
                t0 = time.perf_counter()
                try:
                    out = fleet.predict(name, Xr, timeout=timeout)
                except QueueFull:
                    with lock:
                        per_model[name]["shed"] += 1
                    continue
                except DeadlineExceeded:
                    with lock:
                        per_model[name]["expired"] += 1
                    continue
                except Exception as e:  # noqa: BLE001 — a failed request
                    with lock:          # is an OUTCOME, not a dead thread
                        per_model[name]["failed"] += 1
                        failures.append(
                            f"thread {tidx} [{name}]: "
                            f"{type(e).__name__}: {str(e)[:200]}")
                    continue
                lat = (time.perf_counter() - t0) * 1e3
                ok = True
                if verify is not None and name in verify:
                    K = classes[name]
                    ref = verify[name].predict_raw(Xr, num_class=K)
                    ok = np.array_equal(out, ref[0] if K == 1 else ref.T)
                with lock:
                    s = per_model[name]
                    s["requests"] += 1
                    s["rows"] += m
                    s["lat_ms"].append(lat)
                    if not ok:
                        s["mismatches"] += 1
        except Exception as e:  # a dead thread must not bank clean numbers
            errors.append(
                f"thread {tidx}: {type(e).__name__}: {str(e)[:200]}")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    def availability(completed: int, failed: int):
        return (None if completed + failed == 0
                else round(1.0 - failed / (completed + failed), 6))

    models_out = {}
    for n in names:
        s = per_model[n]
        models_out[n] = {
            "weight": float(mix[n]),
            "requests": s["requests"],
            "rows": s["rows"],
            "shed": s["shed"],
            "expired": s["expired"],
            "failed": s["failed"],
            "availability": availability(s["requests"], s["failed"]),
            "mismatches": s["mismatches"],
            "latency_ms": _latency_summary(s["lat_ms"]),
        }
    completed = sum(s["requests"] for s in per_model.values())
    failed = sum(s["failed"] for s in per_model.values())
    shed = sum(s["shed"] for s in per_model.values())
    expired = sum(s["expired"] for s in per_model.values())
    return {
        "requests": completed,
        "requests_planned": per_thread * n_threads,
        "rows": sum(s["rows"] for s in per_model.values()),
        "shed": shed,
        "expired": expired,
        "failed": failed,
        "outcomes": {"completed": completed, "shed": shed,
                     "expired": expired, "failed": failed},
        "availability": availability(completed, failed),
        "mismatches": sum(s["mismatches"] for s in per_model.values()),
        "wall_seconds": wall,
        "errors": errors,
        "failures": failures,
        "models": models_out,
    }
