"""Threaded mixed-shape load driver for the serving subsystem.

The one request-storm implementation shared by ``bench.py``'s serving
stage and ``tools/serve_smoke.py`` (their drivers used to be near-twins;
a fix to one — e.g. dead-thread error accounting — kept missing the
other).  Deliberately not a benchmark harness: it fires, optionally
verifies bit-equality, and reports honest completed counts.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np


def fire_requests(server, n_requests: int, n_threads: int,
                  max_request_rows: int, num_features: int,
                  verify_forest=None, timeout: float = 300.0) -> dict:
    """Fire ``n_requests`` (rounded down to a multiple of ``n_threads``)
    mixed-size requests of float32-precise rows from ``n_threads``
    threads; return completed/row counts, wall time, and per-thread
    errors.  With ``verify_forest`` every response is checked bit-equal
    to ``verify_forest.predict_raw`` (the serving acceptance bar).
    """
    per_thread = n_requests // n_threads
    done = [0] * n_threads
    rows_served = [0] * n_threads
    mismatches: list = []
    errors: list = []

    def worker(tidx: int) -> None:
        r = np.random.RandomState(100 + tidx)
        try:
            for _ in range(per_thread):
                m = int(r.randint(1, max_request_rows + 1))
                Xr = r.randn(m, num_features).astype(np.float32) \
                    .astype(np.float64)
                out = server.predict(Xr, timeout=timeout)
                rows_served[tidx] += m
                done[tidx] += 1
                if verify_forest is not None and not np.array_equal(
                        out, verify_forest.predict_raw(Xr)[0]):
                    mismatches.append((tidx, m))
        except Exception as e:  # a dead thread must not bank clean numbers
            errors.append(f"thread {tidx}: {type(e).__name__}: {str(e)[:200]}")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return {
        "requests": sum(done),
        "requests_planned": per_thread * n_threads,
        "rows": sum(rows_served),
        "wall_seconds": time.perf_counter() - t0,
        "mismatches": mismatches,
        "errors": errors,
    }
