"""Thread-safe serving metrics: counters, gauges, histograms.

The serving subsystem is instrumented the way an RPC server would be
(request/batch latency histograms, batch-fill ratio, bucket hit rate,
compile events, queue depth), but in-process and dependency-free: a
``MetricsRegistry`` is a named bag of instruments whose ``to_dict()``
snapshot is plain JSON — ``bench.py`` and ``tools/serve_smoke.py`` print
it verbatim, and the tier-1 tests assert against it (compile counter,
multi-submitter batches).

The resilience subsystem reports through the same registry: hot-swap
probe rejections count ``swap_quarantines`` (registry.py), and a
``MetricsRegistry`` passed to ``resilience.retry.resilient_allgather``
collects ``collective_clean`` / ``collective_retries`` /
``collective_retries_recovered`` / ``collective_aborts``.

Instruments are deliberately simple — a histogram is fixed upper-bound
buckets plus count/sum/min/max, not a quantile sketch: the consumers here
are tests and benchmark JSON, where exact bucket counts beat approximate
percentiles.  Every mutation takes the owning registry's single lock;
serving-path mutation rates (one batch every few ms) are far below where
lock sharding would matter.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Dict, List, Optional, Sequence

# default latency bucket upper bounds, milliseconds (log-ish ladder)
LATENCY_BUCKETS_MS = (0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0,
                      200.0, 500.0, 1000.0, 2000.0, 5000.0, math.inf)
# fill-ratio buckets: deciles of rows / bucket_capacity
RATIO_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)


class Counter:
    """Monotonic counter."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-set value (numeric or short string, e.g. a model digest)."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with count/sum/min/max.

    ``buckets`` are inclusive upper bounds in ascending order; the last
    bound may be +inf (it is reported as the string "inf" in JSON).
    """

    def __init__(self, lock: threading.Lock,
                 buckets: Sequence[float] = LATENCY_BUCKETS_MS):
        self._lock = lock
        self.bounds: List[float] = list(buckets)
        if self.bounds[-1] != math.inf:
            self.bounds.append(math.inf)
        self._counts = [0] * len(self.bounds)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    break

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def snapshot(self) -> dict:
        with self._lock:
            if self._count == 0:
                return {"count": 0, "sum": 0.0}
            return {
                "count": self._count,
                "sum": round(self._sum, 6),
                "mean": round(self._sum / self._count, 6),
                "min": round(self._min, 6),
                "max": round(self._max, 6),
                "buckets": {
                    ("inf" if math.isinf(b) else repr(b)): c
                    for b, c in zip(self.bounds, self._counts) if c
                },
            }


class MetricsRegistry:
    """Named instrument registry; ``counter``/``gauge``/``histogram`` are
    get-or-create so call sites never race on registration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._reg_lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._reg_lock:
            if name not in self._counters:
                self._counters[name] = Counter(self._lock)
            return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        with self._reg_lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(self._lock)
            return self._gauges[name]

    def histogram(self, name: str,
                  buckets: Sequence[float] = LATENCY_BUCKETS_MS) -> Histogram:
        with self._reg_lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(self._lock, buckets)
            return self._histograms[name]

    def to_dict(self) -> dict:
        """JSON-ready snapshot (schema: docs/SERVING.md)."""
        with self._reg_lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: g.value for k, g in sorted(gauges.items())},
            "histograms": {k: h.snapshot() for k, h in sorted(hists.items())},
        }

    def dump_json(self, path: Optional[str] = None, indent: int = 1) -> str:
        s = json.dumps(self.to_dict(), indent=indent, sort_keys=True)
        if path is not None:
            with open(path, "w") as f:
                f.write(s)
        return s
