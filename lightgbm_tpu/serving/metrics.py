"""Back-compat shim: the serving metrics registry was promoted to
``lightgbm_tpu.obs.metrics`` as the single process-wide instrument
registry (training, serving, resilience and the bench all report through
it — docs/OBSERVABILITY.md).

This module re-exports the full historical surface so every existing
import path (``from lightgbm_tpu.serving.metrics import MetricsRegistry``,
the tier-1 serving tests, ``tools/serve_smoke.py``) keeps working
unchanged, and ``MetricsRegistry.to_dict()`` keeps its exact key layout
(``counters``/``gauges``/``histograms`` — schema: docs/SERVING.md).
"""

from ..obs.metrics import (LATENCY_BUCKETS_MS, RATIO_BUCKETS, Counter, Gauge,
                           Histogram, MetricsRegistry)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "LATENCY_BUCKETS_MS", "RATIO_BUCKETS",
]
