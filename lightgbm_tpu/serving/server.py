"""In-process serving facade: sync/async submit, deadlines, backpressure,
model hot-swap, graceful drain.

``Server`` is the one class users touch (``Booster.serve()`` /
``lightgbm_tpu.serve()`` construct it).  A request is validated and cut
into <= top-bucket work items at submit time; the micro-batch scheduler
(batcher.py) coalesces items from ALL submitters into padded
bucket-shaped batches; the program registry (registry.py) maps each
(model, bucket) pair to its compiled predict program.  Results are
scattered back into a per-request float64 buffer and the request's
future resolves when its last item lands — so a request spanning several
batches, or a batch mixing several requests, both just work.

Correctness contract: with ``raw_score=True`` (default) the values a
future resolves to are bit-identical to ``Booster.predict(raw_score=
True)`` — i.e. ``StackedForest.predict_raw`` plus the average_output
division (identity for every boosting mode but rf) — unconditionally on
the "host" backend, and for float32-precision feature values on the
"device" backend (see DeviceForest.predict_raw_padded).  Overload is surfaced as typed errors
at submit (QueueFull) or completion (DeadlineExceeded), never as
unbounded queueing latency.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import instant as _instant
from ..obs.trace import span as _span
from .batcher import Batch, BucketLadder, MicroBatcher, WorkItem
from .errors import QueueFull, ServerClosed, ServingError
from .metrics import MetricsRegistry
from .registry import ModelRegistry, ProgramRegistry


@dataclass
class ServingConfig:
    """Knobs for Server; every field has a serving-sane default."""

    min_bucket_rows: int = 8          # smallest padded batch shape
    max_batch_rows: int = 1024        # top bucket; larger requests split
    batch_window_ms: float = 2.0      # max extra latency spent coalescing
    max_queue_rows: int = 1 << 16     # backpressure: reject beyond this
    default_deadline_ms: Optional[float] = None   # None = no deadline
    backend: str = "device"           # "device" | "host"
    max_programs: int = 64            # program-LRU capacity
    raw_score: bool = True            # False: predict()-style transform
    num_iteration: Optional[int] = None
    start_iteration: int = 0
    # opt-in low-precision serving (docs/SERVING.md fleet section):
    # "bf16" / "int8" serve the quantized twin of the model, held to
    # accuracy_budget on a probe batch (probe_X, else deterministic
    # noise) at admission AND at every hot-swap; "f32" (default) keeps
    # raw-score bit-parity with Booster.predict(raw_score=True)
    precision: str = "f32"
    accuracy_budget: Optional[float] = None
    probe_X: Optional[object] = None
    # AOT serving-program cache directory (fleet/aot.py); None = look at
    # LGBM_TPU_COMPILE_CACHE/serving, "" / "off" = disabled
    aot_dir: Optional[str] = None
    # liveness-beat name of this server's batcher thread (watchdog.py);
    # a pod fleet names each replica's beat so per-replica health
    # scoring can tell WHICH device wedged (fleet/router.py)
    heartbeat_name: str = "serving.batcher"

    def __post_init__(self):
        if self.backend not in ("device", "host"):
            raise ValueError(f"unknown serving backend {self.backend!r}")
        if self.precision not in ("f32", "bf16", "int8"):
            raise ValueError(f"unknown serving precision "
                             f"{self.precision!r}")


class _Request:
    """Submit-side accounting for one predict call: result buffer, item
    countdown, future, deadline, and the model the request was admitted
    against — pinned at submit so a hot-swap mid-flight can neither mix
    model generations inside one multi-item request nor run rows
    validated for F features through a model expecting F'."""

    __slots__ = ("n", "out", "future", "submitter", "deadline", "model",
                 "t_submit", "_remaining", "_lock", "_settled")

    def __init__(self, n: int, num_class: int, n_items: int,
                 deadline: Optional[float], model):
        self.n = n
        self.model = model
        self.out = np.zeros((num_class, n), np.float64)
        self.future: Future = Future()
        self.submitter = threading.get_ident()
        self.deadline = deadline
        self.t_submit = time.monotonic()
        self._remaining = n_items
        self._lock = threading.Lock()
        self._settled = False    # a future may settle exactly once

    def is_settled(self) -> bool:
        """True once the future has an outcome — including caller-side
        cancellation (asyncio.wait_for on apredict cancels the wrapped
        Future): the scheduler drops settled items at pop time instead of
        spending device work on results nobody will read."""
        with self._lock:
            if not self._settled and self.future.cancelled():
                self._settled = True
            return self._settled

    def fail_item(self, exc: Exception) -> bool:
        """Fail the whole request; True iff THIS call settled it (so a
        split request rejected item-by-item counts once, not n times)."""
        with self._lock:
            if self._settled:
                return False
            self._settled = True
        try:
            self.future.set_exception(exc)
            return True
        except InvalidStateError:       # cancelled under our feet
            return False

    def complete_item(self, server: "Server", offset: int,
                      raw_part: np.ndarray) -> None:
        """Install one item's [K, n_item] raw slice; resolve when last."""
        self.out[:, offset:offset + raw_part.shape[1]] = raw_part
        with self._lock:
            if self._settled:
                return
            self._remaining -= 1
            done = self._remaining == 0
            if done:
                self._settled = True
        if done:
            server._finalize(self)


class Server:
    """Micro-batched, shape-bucketed, hot-swappable forest inference."""

    def __init__(self, booster, config: Optional[ServingConfig] = None,
                 **overrides):
        if config is None:
            config = ServingConfig(**overrides)
        elif overrides:
            raise ValueError("pass either config or keyword overrides")
        self.config = config
        self.metrics = MetricsRegistry()
        # serving publishes its own warmth gauges (family-keyed, counted
        # off the AOT export store) so training's cold-start bar stays
        # attributable (utils/platform.py)
        from ..utils.platform import enable_compile_cache
        enable_compile_cache(family="serving")
        self.ladder = BucketLadder(config.min_bucket_rows,
                                   config.max_batch_rows)
        self.programs = ProgramRegistry(self.metrics,
                                        max_programs=config.max_programs)
        self.aot = self._resolve_aot(config.aot_dir)
        self.models = ModelRegistry(
            booster, self.programs, self.metrics, backend=config.backend,
            num_iteration=config.num_iteration,
            start_iteration=config.start_iteration,
            precision=config.precision,
            accuracy_budget=config.accuracy_budget,
            probe_X=config.probe_X, aot=self.aot)
        self._batcher = MicroBatcher(
            self.ladder, self._run_batch, self.metrics,
            batch_window_ms=config.batch_window_ms,
            max_queue_rows=config.max_queue_rows,
            beat_name=config.heartbeat_name)
        self._closed = False
        # join the unified process registry (docs/OBSERVABILITY.md): the
        # per-server registry stays authoritative (tests/serve_smoke read
        # it), but a process-wide snapshot / Prometheus scrape sees every
        # live server as a named component; detached at close()
        self._obs_component = _obs_registry.attach_child(
            "serving", self.metrics)
        # active observability: hold this server's request p99 to the
        # configured SLO ceiling (watchdog.py; never breaches unless a
        # ceiling is set), and start the env-gated metrics endpoint
        from ..obs.http import maybe_start_from_env as _http_from_env
        from ..obs.watchdog import (global_watchdog,
                                    maybe_start_from_env as _wd_from_env)
        self._wd_hist = f"serving_p99:{self._obs_component}"
        global_watchdog.watch_histogram_p99(
            self._wd_hist, self.metrics.histogram("request_latency_ms"))
        _wd_from_env()
        _http_from_env()

    @staticmethod
    def _resolve_aot(aot_dir):
        """AOT serving-program store (fleet/aot.py): an explicit dir wins;
        ``None`` follows LGBM_TPU_COMPILE_CACHE/serving (the PR 5
        persistent cache, extended to serving buckets); "" / "off"
        disables."""
        from ..fleet.aot import AOTStore, aot_dir_from_env
        if aot_dir is None:
            aot_dir = aot_dir_from_env()
        elif not str(aot_dir).strip() or \
                str(aot_dir).strip().lower() in ("0", "off", "none"):
            aot_dir = None
        return AOTStore(aot_dir) if aot_dir else None

    def _ladder_rows(self, buckets) -> set:
        """Map requested row counts through the bucket ladder (default:
        the whole ladder) — traffic only ever sees bucket shapes, so
        warming or exporting a raw row count would build a shape never
        served.  Shared by ``warm`` and ``export_aot`` so the exported
        buckets can never diverge from the warmed ones."""
        return {self.ladder.bucket_for(min(b, self.ladder.max_rows))
                for b in (buckets if buckets is not None
                          else self.ladder.buckets)}

    def export_aot(self, path: Optional[str] = None, buckets=None) -> int:
        """Serialize the active model's routing programs for ``buckets``
        (default: the whole ladder) into the AOT store so a fresh
        replica's first request pays no trace and no fresh XLA compile
        (fleet/aot.py).  Returns the number of entries written."""
        from ..fleet.aot import AOTStore
        store = AOTStore(path) if path is not None else self.aot
        if store is None:
            raise ServingError(
                "no AOT store configured: pass path=, set aot_dir, or "
                "set LGBM_TPU_COMPILE_CACHE")
        model = self.models.active
        rows = self._ladder_rows(buckets)
        return model.export_aot(store, rows)

    # --------------------------------------------------------------- submit

    def submit(self, X, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue a predict request; returns a concurrent.futures.Future
        resolving to raw scores [n] (num_class == 1) or [n, K].

        Raises QueueFull / ServerClosed synchronously; resolves the
        future with DeadlineExceeded if the request's deadline (argument,
        else config.default_deadline_ms) expires before execution."""
        if self._closed:
            self.metrics.counter("requests_rejected_closed").inc()
            raise ServerClosed("server is shut down")
        # ALWAYS copy: work items hold row views until the pad-copy runs
        # (up to batch_window_ms + queue delay later), so a caller
        # refilling a preallocated buffer must not corrupt queued rows
        X = np.array(X, np.float64, order="C")
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2:
            raise ServingError(f"expected 2-D input, got shape {X.shape}")
        model = self.models.active
        if X.shape[1] != model.num_features:
            raise ServingError(
                f"request has {X.shape[1]} features, model expects "
                f"{model.num_features}")
        n = X.shape[0]
        if n > self.config.max_queue_rows:
            # no amount of caller backoff can ever admit this request
            # (QueueFull would promise retryability it cannot deliver)
            raise ServingError(
                f"request of {n} rows exceeds max_queue_rows="
                f"{self.config.max_queue_rows}; raise max_queue_rows or "
                "chunk the request")
        K = model.num_class
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        top = self.ladder.max_rows
        n_items = max((n + top - 1) // top, 1)
        req = _Request(n, K, n_items, deadline, model)
        if n == 0:
            req.future.set_result(self._shape_result(req.out, K))
            return req.future
        self.metrics.counter("requests_total").inc()
        self.metrics.counter("rows_total").inc(n)
        items = [WorkItem(req, X[i * top:(i + 1) * top], i * top)
                 for i in range(n_items)]
        try:
            # all-or-nothing: a rejected request leaves nothing queued
            self._batcher.submit_items(items)
        except (QueueFull, ServerClosed) as e:
            if isinstance(e, QueueFull):
                self.metrics.counter("requests_rejected_queue_full").inc()
            else:
                self.metrics.counter("requests_rejected_closed").inc()
            req.fail_item(e)
            raise
        # after submit_items: a QueueFull-rejected request must not show
        # up in the trace as admitted
        _instant("serving.admit", rows=n, items=n_items)
        return req.future

    def predict(self, X, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous submit + wait.  On wait timeout the request is
        cancelled so its queued items stop holding backpressure budget
        (the scheduler drops settled items at pop)."""
        fut = self.submit(X, deadline_ms=deadline_ms)
        try:
            return fut.result(timeout)
        except FuturesTimeoutError:
            fut.cancel()
            raise

    async def apredict(self, X, deadline_ms: Optional[float] = None):
        """Asyncio-native submit: awaits the result without blocking the
        event loop (the concurrent Future is bridged to an asyncio one)."""
        import asyncio
        loop = asyncio.get_running_loop()
        return await asyncio.wrap_future(
            self.submit(X, deadline_ms=deadline_ms), loop=loop)

    # ------------------------------------------------------------ execution

    def _run_batch(self, batch: Batch) -> None:
        # items carry the model their request was pinned to at submit;
        # outside a swap transition that is one group (and one program
        # run on the batch's own bucket), during one it is two — never a
        # mix of generations inside a single program invocation
        groups: dict = {}
        for it in batch.items:
            groups.setdefault(id(it.request.model), []).append(it)
        for items in groups.values():
            model = items[0].request.model
            sub = (batch if len(groups) == 1 else
                   Batch(items, self.ladder.bucket_for(
                       sum(it.n for it in items))))
            prog = self.programs.get(model, sub.bucket)
            t0 = time.perf_counter()
            with _span("serving.batch", rows=sub.rows, bucket=sub.bucket):
                raw = prog(sub.padded_input())       # [K, bucket] f64
            self.metrics.histogram("batch_latency_ms").observe(
                (time.perf_counter() - t0) * 1e3)
            pos = 0
            for it in items:
                it.request.complete_item(self, it.offset,
                                         raw[:, pos:pos + it.n])
                pos += it.n

    def _shape_result(self, raw: np.ndarray, K: int) -> np.ndarray:
        return raw[0] if K == 1 else raw.T

    def _finalize(self, req: _Request) -> None:
        K = req.out.shape[0]
        # average_output scaling applies to raw scores too, exactly as
        # Booster.predict(raw_score=True) does (identity except for rf)
        raw = req.model.scale_raw(req.out)
        if not self.config.raw_score:
            raw = req.model.transform_raw(raw)
        try:
            req.future.set_result(self._shape_result(raw, K))
        except InvalidStateError:       # cancelled mid-flight: the caller
            self.metrics.counter("requests_cancelled").inc()
            return                      # saw a timeout, not a completion
        self.metrics.counter("requests_completed").inc()
        lat_ms = (time.monotonic() - req.t_submit) * 1e3
        self.metrics.histogram("request_latency_ms").observe(lat_ms)
        _instant("serving.complete", rows=req.n, latency_ms=round(lat_ms, 3))

    def warm(self, buckets=None) -> int:
        """Pre-compile the active model's predict programs — for
        ``buckets`` (an iterable of row counts) or the whole ladder — so
        the first real requests pay no XLA compile latency.  Returns the
        number of buckets warmed.  (``swap_model(warm=True)`` gives the
        same guarantee for replacement models.)"""
        model = self.models.active
        # map through the ladder: traffic only ever sees bucket shapes,
        # so warming a raw row count would compile a shape never served
        rows = self._ladder_rows(buckets)
        return self.programs.warm(model,
                                  {(b, model.num_class) for b in rows})

    # ------------------------------------------------------------- hot swap

    def swap_model(self, booster_or_path, warm: bool = True,
                   block: bool = True, probe: bool = True):
        """Replace the serving model without dropping in-flight requests.

        ``booster_or_path``: a Booster or a model-file path.  With
        ``warm=True`` (default) every bucket shape served so far is
        pre-compiled for the new model before the atomic pointer flip;
        ``block=False`` runs warm+flip in a background thread and returns
        it immediately — join it, or poll metrics' model_generation; a
        warm failure sets the thread's ``exception`` attribute and the
        ``swap_failures`` counter instead of flipping.  With ``probe=True``
        (default) the candidate runs a probe batch first and is
        QUARANTINED (``SwapQuarantined``, swap rolled back,
        ``swap_quarantines`` counter) on exception or non-finite output."""
        booster = self._as_booster(booster_or_path)
        return self.models.swap(
            booster, warm=warm, block=block, probe=probe,
            num_iteration=self.config.num_iteration,
            start_iteration=self.config.start_iteration)

    @staticmethod
    def _as_booster(booster_or_path):
        from ..basic import Booster
        if isinstance(booster_or_path, Booster):
            return booster_or_path
        return Booster(model_file=str(booster_or_path))

    # ------------------------------------------------------------- lifecycle

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop accepting requests; ``drain=True`` completes everything
        already queued, ``drain=False`` fails it with ServerClosed."""
        if self._closed:
            return
        self._closed = True
        self._batcher.close(drain=drain, timeout=timeout)
        _obs_registry.detach_child(self._obs_component)
        from ..obs.watchdog import global_watchdog
        global_watchdog.unwatch_histogram(self._wd_hist)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    # ------------------------------------------------------------- metrics

    def metrics_dict(self) -> dict:
        return self.metrics.to_dict()

    def metrics_json(self, path: Optional[str] = None) -> str:
        return self.metrics.dump_json(path)

    def prometheus_text(self, prefix: str = "lgbt_serving") -> str:
        """This server's instruments in Prometheus text exposition format
        (the process-wide scrape is
        ``obs.metrics.global_registry.to_prometheus()``)."""
        return self.metrics.to_prometheus(prefix=prefix)
