"""Atomic rollout journal: the crash-safe source of truth for one
guarded promotion.

Every phase transition of a rollout (verify -> quarantine -> shadow ->
ramp[i] -> cutover -> promoted / rolled_back) is journaled BEFORE the
transition's side effects run, through ``utils.file_io.write_atomic``
(temp sibling + ``os.replace``; the ``open_file`` scheme seam, so a
``chaos://`` journal exercises the crash-mid-write shape).  A restarted
pipeline reads the journal and either finishes the bookkeeping of a
cutover that already committed or rolls back — it can NEVER
double-promote, because the cutover intent (phase ``cutover`` +
candidate digest) is durable before the serving pointer flips and the
fleet's live digest is the commit witness (rollout.py ``resume``).

One journal file per rollout directory; a finished record (``promoted``
/ ``rolled_back``) is left in place as the postmortem record until the
next rollout overwrites it.
"""

from __future__ import annotations

import json
import time
from typing import Optional

from ..utils.file_io import exists, open_file, write_atomic

FORMAT = "lgbt-rollout/1"
JOURNAL_NAME = "rollout.json"

# phase order is load-bearing for resume(): everything before "cutover"
# is side-effect-free on the LIVE serving pointer (the canary is a
# separate fleet entry), so a crash there always rolls back cleanly
PHASES = ("verify", "quarantine", "shadow", "ramp", "cutover")
TERMINAL = ("promoted", "rolled_back")


class RolloutJournalError(RuntimeError):
    """The journal exists but cannot be trusted (unreadable / unknown
    format) — the pipeline refuses to guess rollout state."""


class RolloutJournal:
    """Crash-safe state record for one promotion pipeline."""

    def __init__(self, path: str):
        self.path = str(path)

    # ------------------------------------------------------------- read

    def load(self) -> Optional[dict]:
        """The current record, or None when no rollout was ever
        journaled here.  An unreadable or foreign-format file raises
        ``RolloutJournalError`` — resuming from a corrupt journal must be
        an explicit operator decision, never a silent guess."""
        if not exists(self.path):
            return None
        try:
            with open_file(self.path, "r") as fh:
                rec = json.loads(fh.read())
        except Exception as e:
            raise RolloutJournalError(
                f"rollout journal {self.path}: unreadable ({e})") from e
        if rec.get("format") != FORMAT:
            raise RolloutJournalError(
                f"rollout journal {self.path}: format "
                f"{rec.get('format')!r} != {FORMAT!r}")
        return rec

    def in_progress(self) -> Optional[dict]:
        rec = self.load()
        if rec is not None and rec.get("status") == "in_progress":
            return rec
        return None

    # ------------------------------------------------------------ write

    def _write(self, rec: dict) -> dict:
        rec = dict(rec, format=FORMAT, updated_unix=time.time())
        write_atomic(self.path, json.dumps(rec, indent=1, sort_keys=True))
        return rec

    def begin(self, live_name: str, candidate_bundle: str,
              candidate_digest: str, previous_bundle: Optional[str],
              previous_digest: str, ramp) -> dict:
        """Open a new rollout record (status ``in_progress``, phase
        ``verify``).  Refuses while another rollout is still in progress
        — two concurrent pipelines over one journal would race the
        serving pointer."""
        stale = self.in_progress()
        if stale is not None:
            raise RolloutJournalError(
                f"rollout journal {self.path}: a rollout of candidate "
                f"{stale.get('candidate_bundle')!r} is still in_progress "
                f"(phase {stale.get('phase')!r}); resume() or roll it "
                "back first")
        return self._write({
            "status": "in_progress", "phase": "verify", "ramp_step": -1,
            "live_name": live_name,
            "candidate_bundle": candidate_bundle,
            "candidate_digest": candidate_digest,
            "previous_bundle": previous_bundle,
            "previous_digest": previous_digest,
            "ramp": list(ramp), "gate": None, "evidence": None,
        })

    def phase(self, rec: dict, phase: str, ramp_step: int = -1) -> dict:
        if phase not in PHASES:
            raise ValueError(f"unknown rollout phase {phase!r}")
        return self._write(dict(rec, phase=phase, ramp_step=ramp_step))

    def promoted(self, rec: dict) -> dict:
        return self._write(dict(rec, status="promoted", phase="cutover"))

    def rolled_back(self, rec: dict, gate: str, evidence: dict) -> dict:
        return self._write(dict(rec, status="rolled_back", gate=gate,
                                evidence=evidence))
