"""Continual-training candidate build: warm-start boosting from the
deployed model over FRESH rows, binned against the deployed model's
frozen bin mappers.

The reference's continued-training seam (``train(init_model=...)``,
engine.py) stacks the deployed model's trees under the new booster and
starts boosting from its predictions; this module supplies the data
half of the loop:

* ``fresh_dataset`` bins new rows with the DEPLOYED training set's bin
  mappers (``Dataset(reference=...)``), so candidate histograms live on
  the exact bin grid the deployed model was grown on — a refresh never
  silently re-bins the world;
* chunked loads ride the PR 8 streaming plane
  (``Dataset.from_reference_streaming`` + ``push_rows``): host RSS
  stays O(chunk), and the deployed model's raw scores over each chunk
  are computed AT PUSH TIME (``_init_model_raw_scores``) so the
  warm-start needs no resident raw feature matrix;
* ``train_candidate`` runs the warm-start and returns the candidate
  booster; ``save_candidate`` writes the sha256-manifested bundle
  (resilience/checkpoint.py) the guarded rollout promotes from.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

import numpy as np


def booster_digest(booster) -> str:
    """Content digest of a booster's full forest — the identity the
    rollout journal records and the rollback bit-parity check compares
    (serving/registry.forest_digest over every trained iteration)."""
    from ..serving.registry import forest_digest
    K = max(booster.num_tree_per_iteration, 1)
    n_iter = len(booster.models) // K
    return forest_digest(booster._forest(0, n_iter))


def fresh_dataset(reference, X=None, label=None,
                  chunks: Optional[Iterable[Tuple]] = None,
                  num_rows: Optional[int] = None,
                  predictor=None, params: Optional[dict] = None):
    """A training Dataset of fresh rows on ``reference``'s frozen bin
    grid.

    Resident form: ``fresh_dataset(ref, X, y)`` keeps the raw rows
    (``free_raw_data=False``) so ``train(init_model=...)`` can predict
    its init scores.  Streamed form: ``chunks`` is an iterable of
    ``(X_chunk, y_chunk)`` pairs totalling ``num_rows`` rows — each
    chunk is binned and released, and when ``predictor`` (the deployed
    booster) is given its raw scores over each chunk are accumulated as
    ``_init_model_raw_scores``, which ``engine._apply_init_model``
    consumes instead of re-predicting from raw data the streamed
    dataset never kept."""
    from ..dataset import Dataset
    if chunks is None:
        if X is None:
            raise ValueError("fresh_dataset needs X (resident) or "
                             "chunks (streamed)")
        return Dataset(X, label=label, reference=reference,
                       params=dict(params or {}), free_raw_data=False)
    if num_rows is None:
        raise ValueError("streamed fresh_dataset needs num_rows")
    ds = Dataset.from_reference_streaming(reference, num_rows,
                                          params=dict(params or {}))
    labels = []
    scores = [] if predictor is not None else None
    for xc, yc in chunks:
        xc = np.asarray(xc)
        ds.push_rows(xc)
        labels.append(np.asarray(yc, np.float32).reshape(-1))
        if scores is not None:
            scores.append(np.asarray(
                predictor.predict(xc, raw_score=True), np.float64))
    if not ds.constructed:
        raise ValueError(
            f"streamed fresh_dataset: chunks covered "
            f"{int(ds._pushed.sum())}/{num_rows} rows")
    ds.metadata.label = np.concatenate(labels)
    if scores is not None:
        ds._init_model_raw_scores = np.concatenate(
            [s.reshape(len(s), -1) for s in scores], axis=0)
    return ds


def train_candidate(deployed, train_set, params: dict,
                    num_boost_round: int, **train_kw):
    """Warm-start ``num_boost_round`` fresh boosting rounds from the
    DEPLOYED model over ``train_set`` (``lgb.train(init_model=...)``:
    the deployed trees are stacked under the candidate and boosting
    resumes from their predictions).  Compatibility between the init
    model and the train set is validated up front
    (``engine.InitModelCompatibilityError``), not by a shape failure
    mid-boost."""
    from ..engine import train
    return train(dict(params), train_set, num_boost_round,
                 init_model=deployed, verbose_eval=False, **train_kw)


def refresh_many(deployed, train_sets, params_list, num_boost_round: int,
                 **train_kw):
    """Warm-start a whole per-segment model FAMILY in one batched run.

    A production deployment rarely refreshes one model: per-segment
    families (per-region, per-surface) retrain on the same cadence, and
    each segment's candidate is an independent small training that
    leaves the chip idle.  This routes the family through
    ``multi.train_many`` stacked mode — one Dataset per segment (each on
    its deployed model's frozen bin grid via ``fresh_dataset``), one
    deployed booster per segment as ``init_models`` — so structurally
    compatible segments advance in ONE vmapped dispatch while each
    candidate stays byte-identical to its solo ``train_candidate`` run.
    Returns the candidate boosters in segment order."""
    from ..multi import train_many
    deployed = list(deployed)
    if len(deployed) != len(params_list):
        raise ValueError(
            f"refresh_many: {len(deployed)} deployed models for "
            f"{len(params_list)} configs")
    return train_many(list(params_list), list(train_sets), num_boost_round,
                      init_models=deployed, **train_kw)


def save_candidate(booster, manager) -> str:
    """Write the candidate's checkpoint bundle (atomic, sha256
    manifest) through ``manager`` (resilience.CheckpointManager);
    returns the bundle path the rollout phase verifies and promotes."""
    return manager.save(booster, iteration=booster.current_iteration())
