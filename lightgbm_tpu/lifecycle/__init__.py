"""Guarded model lifecycle: continual training, canary promotion,
automated rollback (docs/LIFECYCLE.md).

Closes the ROADMAP "never serve a stale model" loop by composing the
existing planes into one guarded cycle:

* **refresh** (refresh.py) — warm-start boosting from the DEPLOYED
  model over fresh rows binned on its frozen bin grid (engine
  ``init_model`` + the PR 8 streaming plane), banked as an atomic
  sha256-manifested checkpoint bundle (PR 2);
* **promote** (rollout.py) — probe-batch quarantine -> shadow traffic
  (mirrored raw-score drift + client-measured p99 vs declared budgets)
  -> staged canary weight ramp through the serving ``Fleet`` (PR 9) ->
  atomic probed cutover;
* **rollback** — any gate breach (drift, latency, error rate,
  non-finite outputs, corrupt bundle, failed cutover probe) restores
  the previous verified bundle and dumps a flight-recorder bundle
  naming the gate (PR 11); the rollout journal (journal.py) makes a
  crashed pipeline resume-or-roll-back, never double-promote;
* **freshness** — ``model_age_seconds`` is a watchdog SLO: a live
  model past its age ceiling breaches ``freshness:<name>``.
"""

from .journal import RolloutJournal, RolloutJournalError
from .refresh import (booster_digest, fresh_dataset, save_candidate,
                      train_candidate)
from .rollout import (CANARY_SUFFIX, LifecycleConfig, LifecycleController,
                      LifecycleError, RollbackFailed, replay_traffic)

__all__ = [
    "LifecycleController", "LifecycleConfig", "LifecycleError",
    "RollbackFailed", "RolloutJournal", "RolloutJournalError",
    "CANARY_SUFFIX", "replay_traffic", "booster_digest",
    "fresh_dataset", "train_candidate", "save_candidate",
]
