"""Guarded rollout: probe quarantine -> shadow traffic -> staged canary
ramp -> cutover, with automated rollback to the previous verified
bundle on any gate breach.

``LifecycleController`` owns one live model name inside a serving
``Fleet`` and drives a candidate bundle through the promotion pipeline
(docs/LIFECYCLE.md).  Every transition is journaled atomically BEFORE
its side effects (journal.py), the live serving pointer only moves at
the final cutover swap (which itself re-probes and flips atomically,
serving/registry.py), and every breach — raw-score drift over budget,
candidate p99 over budget, candidate error rate, non-finite outputs, a
corrupt bundle, a failed cutover probe — rolls the fleet back to the
previous verified model and dumps a flight-recorder bundle NAMING the
gate (``lifecycle:<gate>``).  A crashed pipeline is resumed with
``resume()``: a journaled cutover whose flip committed is finished
idempotently; anything earlier rolls back.  It can never
double-promote.

Chaos seams: the candidate's serving path accepts a
``resilience.faults.ChaosRegistry`` (site ``serving``: delay / nan /
error), and the journal + bundles ride the ``chaos://`` filesystem
through the ``open_file`` seam — the chaos matrix in
tests/test_lifecycle.py injects a fault at every gate and asserts the
fleet's served output stays byte-identical to the pre-promotion model.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple

import numpy as np

from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import instant as _instant
from ..obs.trace import span as _span
from ..resilience.checkpoint import (CheckpointError, CheckpointManager,
                                     load_checkpoint)
from ..serving.errors import ModelNotFound
from ..utils.log import log_info, log_warning
from .journal import RolloutJournal
from .refresh import booster_digest, fresh_dataset, save_candidate, \
    train_candidate

_DRIFT_ENV = "LGBM_TPU_LIFECYCLE_DRIFT_BUDGET"
_P99_ENV = "LGBM_TPU_LIFECYCLE_P99_MS"
_MIRROR_ENV = "LGBM_TPU_LIFECYCLE_MIRROR"
_RAMP_ENV = "LGBM_TPU_LIFECYCLE_RAMP"
_DIR_ENV = "LGBM_TPU_LIFECYCLE_DIR"

CANARY_SUFFIX = "!canary"


class LifecycleError(RuntimeError):
    """Base class for lifecycle pipeline failures."""


class RollbackFailed(LifecycleError):
    """The rollback itself could not restore the previous model — the
    one failure the pipeline cannot degrade through; loud by design."""


@dataclass
class LifecycleConfig:
    """Promotion budgets and ramp schedule; every knob has an env twin
    (docs/LIFECYCLE.md) so a deployment tunes gates without code."""

    drift_budget: float = 10.0          # max |cand - live| raw score
    p99_budget_ms: Optional[float] = None   # candidate p99 ceiling
    error_budget: float = 0.0           # allowed candidate error fraction
    mirror_fraction: float = 0.25       # shadow mirror probability
    ramp: Tuple[float, ...] = (0.05, 0.25, 0.5)
    min_mirrored: int = 4               # drift verdict needs a sample
    canary_weight: float = 0.1          # fleet admission weight floor
    keep_bundles: int = 4               # CheckpointManager retention
    freshness_max_age_s: Optional[float] = None  # watchdog freshness SLO

    def __post_init__(self):
        # a directly-passed config must obey the same bounds as the env
        # path: an empty ramp would skip every canary stage and cut
        # over with zero gated exposure
        if not self.ramp or not all(0.0 < float(f) <= 1.0
                                    for f in self.ramp):
            raise ValueError(
                f"ramp fractions must be in (0, 1]: {self.ramp}")
        if not 0.0 <= float(self.mirror_fraction) <= 1.0:
            raise ValueError(
                f"mirror_fraction must be in [0, 1]: "
                f"{self.mirror_fraction}")

    @classmethod
    def from_env(cls, **overrides) -> "LifecycleConfig":
        cfg = cls(**overrides)
        env = os.environ.get
        v = env(_DRIFT_ENV, "").strip()
        if v and "drift_budget" not in overrides:
            cfg.drift_budget = float(v)
        v = env(_P99_ENV, "").strip()
        if v and "p99_budget_ms" not in overrides:
            cfg.p99_budget_ms = float(v)
        v = env(_MIRROR_ENV, "").strip()
        if v and "mirror_fraction" not in overrides:
            cfg.mirror_fraction = float(v)
        v = env(_RAMP_ENV, "").strip()
        if v and "ramp" not in overrides:
            cfg.ramp = tuple(float(t) for t in v.split(",") if t.strip())
        if not cfg.ramp or not all(0.0 < f <= 1.0 for f in cfg.ramp):
            raise ValueError(f"ramp fractions must be in (0, 1]: "
                             f"{cfg.ramp}")
        return cfg


class _ArmStats:
    """Client-measured accounting for one serving arm in one phase."""

    __slots__ = ("lat_ms", "requests", "errors", "nonfinite")

    def __init__(self):
        self.lat_ms: list = []
        self.requests = 0
        self.errors = 0
        self.nonfinite = 0

    def p99(self) -> Optional[float]:
        if not self.lat_ms:
            return None
        return float(np.percentile(np.asarray(self.lat_ms, np.float64),
                                   99))

    def summary(self) -> dict:
        return {"requests": self.requests, "errors": self.errors,
                "nonfinite": self.nonfinite,
                "p99_ms": (round(self.p99(), 3)
                           if self.lat_ms else None)}


class _TrafficStats:
    """One phase window of live/candidate traffic measurements; appended
    under the controller's stats lock (loadgen fires from threads)."""

    def __init__(self):
        self.live = _ArmStats()
        self.cand = _ArmStats()
        self.drift: list = []           # per-mirrored max |delta|
        self.mirrored = 0

    def drift_max(self) -> Optional[float]:
        return float(max(self.drift)) if self.drift else None

    def summary(self) -> dict:
        return {"live": self.live.summary(),
                "candidate": self.cand.summary(),
                "mirrored": self.mirrored,
                "drift_max": (round(self.drift_max(), 6)
                              if self.drift else None)}


def replay_traffic(X, requests: int = 32, rows: int = 16,
                   seed: int = 7) -> Callable:
    """A synchronous traffic driver replaying row windows of ``X``
    through ``controller.predict`` — the zero-dependency default for
    tests and the smoke; real deployments pass their own driver (e.g.
    serving/loadgen threads)."""
    X = np.asarray(X, np.float64)

    def drive(controller, phase: str, fraction: float) -> None:
        r = np.random.RandomState(seed)
        for _ in range(requests):
            i = int(r.randint(0, max(X.shape[0] - rows, 1)))
            controller.predict(X[i:i + rows])

    return drive


class LifecycleController:
    """One live model's guarded lifecycle: refresh -> promote ->
    rollback, over a serving Fleet (module docstring)."""

    def __init__(self, fleet, live_name: str,
                 directory: Optional[str] = None,
                 config: Optional[LifecycleConfig] = None,
                 chaos=None, seed: int = 0, **overrides):
        if directory is None:
            directory = os.environ.get(_DIR_ENV, "").strip()
            if not directory:
                raise ValueError("pass directory= or set "
                                 f"{_DIR_ENV} (bundle + journal home)")
        self.fleet = fleet
        self.live_name = live_name
        self.config = config if config is not None \
            else LifecycleConfig.from_env(**overrides)
        if config is not None and overrides:
            raise ValueError("pass either config or keyword overrides")
        self.directory = str(directory).rstrip("/")
        self.manager = CheckpointManager(
            self.directory, prefix="lifecycle",
            keep_last=self.config.keep_bundles)
        self.journal = RolloutJournal(
            f"{self.directory}/rollout.json")
        self.canary_name = live_name + CANARY_SUFFIX
        self._chaos = chaos
        self._cand_call: Optional[Callable] = None
        self._rng = np.random.RandomState(seed)
        self._phase = "idle"
        self._fraction = 0.0
        self._stats = _TrafficStats()
        self._lock = threading.Lock()   # stats + rng (loadgen threads)
        # the frozen-bin-grid Dataset and params of the LAST refresh:
        # a promoted candidate is reloaded from bundle model text (no
        # train_set), so successive refreshes keep binning fresh rows
        # on the original deployed grid
        self._base = None
        self._params: Optional[dict] = None
        self._rec: Optional[dict] = None    # latest journal record
        # the pre-promotion live booster: the in-process rollback anchor
        # when no verified bundle older than the candidate exists (a
        # FIRST promotion under a fresh manager directory)
        self._prev_booster = None
        # freshness is a first-class SLO (obs/watchdog.py): the live
        # model's age is measured from the last promotion; a stale model
        # past the ceiling breaches ``freshness:<name>`` and dumps
        from ..obs.watchdog import global_watchdog
        global_watchdog.watch_freshness(
            live_name, max_age_s=self.config.freshness_max_age_s)
        global_watchdog.mark_fresh(live_name)

    # ----------------------------------------------------------- refresh

    def refresh(self, X=None, y=None, chunks=None,
                num_rows: Optional[int] = None,
                params: Optional[dict] = None,
                num_boost_round: int = 10, base=None) -> Tuple[str, object]:
        """Continual-training step: warm-start ``num_boost_round``
        rounds from the DEPLOYED model over fresh rows (resident ``X, y``
        or streamed ``chunks``; refresh.py bins them on the deployed
        training set's frozen bin grid) and bank the candidate as an
        atomic sha256-manifested bundle.  Returns ``(bundle_path,
        candidate_booster)`` — ``promote`` takes it from there."""
        deployed = self.fleet.entry(self.live_name).model.booster
        if base is None:
            base = (deployed.train_set if deployed.train_set is not None
                    else self._base)
        if base is None:
            raise LifecycleError(
                "refresh needs the deployed model's training Dataset "
                "(frozen bin mappers): pass base= or serve a booster "
                "that retains train_set")
        if params is None:
            params = self._params if self._params is not None \
                else (dict(deployed.params) or None)
        if params is None:
            raise LifecycleError(
                "refresh: the deployed booster carries no params; pass "
                "params= explicitly")
        params = {k: v for k, v in dict(params).items()
                  if k not in ("num_iterations",)}
        self._base, self._params = base, dict(params)
        with _span("lifecycle.refresh", rounds=num_boost_round):
            ds = fresh_dataset(base, X, y, chunks=chunks,
                               num_rows=num_rows, predictor=deployed,
                               params={k: v for k, v in params.items()
                                       if k != "verbosity"})
            cand = train_candidate(deployed, ds, params, num_boost_round)
            bundle = save_candidate(cand, self.manager)
        _obs_registry.counter("lifecycle_refreshes_total").inc()
        return bundle, cand

    # ------------------------------------------------------------ routing

    def predict(self, X, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None) -> np.ndarray:
        """The traffic front door while a rollout is active: routes the
        request live-vs-candidate by the current ramp fraction, mirrors
        a ``mirror_fraction`` sample of live requests to the candidate
        for drift/latency comparison, and records client-measured
        per-arm stats the gates judge.  Candidate failures NEVER fail
        the caller — they are recorded and the request degrades to the
        live model."""
        with self._lock:
            phase = self._phase
            take_cand = (phase == "ramp"
                         and self._rng.rand() < self._fraction)
            mirror = (phase in ("shadow", "ramp") and not take_cand
                      and self._rng.rand() < self.config.mirror_fraction)
        out = None
        if take_cand:
            out = self._candidate_request(X, deadline_ms, timeout)
        if out is None:
            t0 = time.perf_counter()
            out = self.fleet.predict(self.live_name, X,
                                     deadline_ms=deadline_ms,
                                     timeout=timeout)
            lat = (time.perf_counter() - t0) * 1e3
            with self._lock:
                self._stats.live.requests += 1
                self._stats.live.lat_ms.append(lat)
        if mirror:
            self._mirror(X, out, deadline_ms, timeout)
        return out

    def _candidate_request(self, X, deadline_ms, timeout):
        """Serve one request from the canary; None on failure (the
        caller degrades to the live arm)."""
        t0 = time.perf_counter()
        try:
            out = self._call_candidate(X, deadline_ms, timeout)
        except Exception as e:  # noqa: BLE001 — recorded, degraded
            with self._lock:
                self._stats.cand.errors += 1
            log_warning(f"lifecycle: candidate request failed "
                        f"({type(e).__name__}: {str(e)[:120]}); "
                        "degrading to live")
            return None
        lat = (time.perf_counter() - t0) * 1e3
        finite = bool(np.isfinite(out).all())
        with self._lock:
            self._stats.cand.requests += 1
            self._stats.cand.lat_ms.append(lat)
            if not finite:
                self._stats.cand.nonfinite += 1
        if not finite:
            return None                 # never hand a NaN to a caller
        return out

    def _mirror(self, X, live_out, deadline_ms, timeout) -> None:
        """Shadow one live request onto the candidate and record the
        raw-score drift + candidate latency; mirror failures are
        candidate evidence, never caller failures."""
        t0 = time.perf_counter()
        try:
            cand = self._call_candidate(X, deadline_ms, timeout)
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self._stats.cand.errors += 1
                self._stats.mirrored += 1
            log_warning(f"lifecycle: shadow mirror failed "
                        f"({type(e).__name__}: {str(e)[:120]})")
            return
        lat = (time.perf_counter() - t0) * 1e3
        cand = np.asarray(cand, np.float64)
        finite = bool(np.isfinite(cand).all())
        drift = (float(np.max(np.abs(cand - np.asarray(live_out,
                                                       np.float64))))
                 if finite else float("inf"))
        with self._lock:
            self._stats.mirrored += 1
            self._stats.cand.requests += 1
            self._stats.cand.lat_ms.append(lat)
            if not finite:
                self._stats.cand.nonfinite += 1
            else:
                self._stats.drift.append(drift)
        _obs_registry.counter("lifecycle_mirrored_total").inc()

    def _call_candidate(self, X, deadline_ms, timeout):
        call = self._cand_call
        if call is None:
            raise ModelNotFound("no candidate is registered")
        return call(X, deadline_ms, timeout)

    # -------------------------------------------------------------- gates

    def _check_gates(self, phase: str) -> Optional[Tuple[str, dict]]:
        """Judge the CURRENT phase window against the declared budgets;
        returns (gate, evidence) on the first breach, None when clean."""
        with self._lock:
            st = self._stats
            drift_max = st.drift_max()
            cand_p99 = st.cand.p99()
            cand_req = st.cand.requests
            cand_err = st.cand.errors
            nonfinite = st.cand.nonfinite
            mirrored = st.mirrored
        if drift_max is not None:
            _obs_registry.gauge("lifecycle_drift_max").set(
                round(drift_max, 6))
        if cand_p99 is not None:
            _obs_registry.gauge("lifecycle_candidate_p99_ms").set(
                round(cand_p99, 3))
        if nonfinite:
            return "nonfinite", {"phase": phase, "nonfinite": nonfinite,
                                 "candidate_requests": cand_req}
        total = cand_req + cand_err
        if total and cand_err / total > self.config.error_budget:
            return "error-rate", {
                "phase": phase, "errors": cand_err, "requests": cand_req,
                "error_rate": round(cand_err / total, 4),
                "budget": self.config.error_budget}
        if mirrored >= self.config.min_mirrored and drift_max is not None \
                and drift_max > self.config.drift_budget:
            return "drift", {"phase": phase, "drift_max": drift_max,
                             "budget": self.config.drift_budget,
                             "mirrored": mirrored}
        if self.config.p99_budget_ms is not None and cand_p99 is not None \
                and cand_p99 > self.config.p99_budget_ms:
            return "latency", {"phase": phase,
                               "candidate_p99_ms": round(cand_p99, 3),
                               "budget_ms": self.config.p99_budget_ms}
        return None

    def _enter_phase(self, phase: str, fraction: float) -> None:
        with self._lock:
            self._phase = phase
            self._fraction = fraction
            self._stats = _TrafficStats()
        _obs_registry.gauge("lifecycle_phase").set(phase)
        _obs_registry.gauge("lifecycle_canary_fraction").set(fraction)
        _instant("lifecycle.phase", phase=phase, fraction=fraction)

    # ------------------------------------------------------------ promote

    def promote(self, bundle_path: str, probe_X=None,
                traffic: Optional[Callable] = None) -> dict:
        """Drive ``bundle_path`` through the guarded rollout.  Returns a
        summary dict with ``status`` ``"promoted"`` or
        ``"rolled_back"`` (+ the breached ``gate``); unexpected
        exceptions roll back first, then re-raise.

        ``traffic`` is called as ``traffic(controller, phase, fraction)``
        for the shadow phase and each ramp step, and must drive requests
        through ``controller.predict`` so the gates have a measured
        sample; defaults to ``replay_traffic(probe_X)``."""
        if traffic is None:
            if probe_X is None:
                raise ValueError("promote needs traffic= or probe_X=")
            traffic = replay_traffic(probe_X)
        live = self.fleet.entry(self.live_name)
        prev_digest = live.model.digest
        self._prev_booster = live.model.booster
        cand_name = os.path.basename(str(bundle_path))
        prev_names = [n for n in self.manager.bundles() if n < cand_name]
        rec = self.journal.begin(
            self.live_name, str(bundle_path), "",
            prev_names[-1] if prev_names else None, prev_digest,
            self.config.ramp)
        # the LATEST journal record: _promote_inner rebinds its local
        # through every phase, and the outer handler must roll back with
        # the real phase/digest (a post-flip failure can only un-flip
        # when the candidate digest is present)
        self._rec = rec
        summary = {"bundle": str(bundle_path), "phases": {},
                   "previous_digest": prev_digest}
        with _span("lifecycle.promote", bundle=str(bundle_path)):
            try:
                return self._promote_inner(rec, bundle_path, probe_X,
                                           traffic, live, summary)
            except LifecycleError:
                raise
            except Exception as e:
                # an unexpected pipeline failure is itself a gate: the
                # fleet must come back to the previous verified model
                self._rollback(self._rec, "pipeline-error", {
                    "error": f"{type(e).__name__}: {str(e)[:400]}"},
                    summary)
                raise

    def _promote_inner(self, rec, bundle_path, probe_X, traffic, live,
                       summary) -> dict:
        from ..basic import Booster
        cfg = self.config

        # ---- verify: the manifest checksums are the corruption gate
        try:
            ck = load_checkpoint(str(bundle_path))
            candidate = Booster(model_str=ck.model_str,
                                params={"verbosity": -1})
        except Exception as e:  # noqa: BLE001 — CheckpointError or ANY
            # decode failure: the bundle cannot be trusted
            return self._rollback(rec, "bundle-verify", {
                "bundle": str(bundle_path),
                "error": f"{type(e).__name__}: {str(e)[:400]}"}, summary)
        cand_digest = booster_digest(candidate)
        rec = self._journal_phase(
            dict(rec, candidate_digest=cand_digest), "verify")
        summary["candidate_digest"] = cand_digest
        summary["phases"]["verify"] = {"iteration": ck.iteration}

        # ---- quarantine: probe batch before the candidate ever serves
        rec = self._journal_phase(rec, "quarantine")
        probe = self._probe_rows(probe_X, candidate)
        try:
            raw = np.asarray(candidate.predict(probe, raw_score=True),
                             np.float64)
        except Exception as e:  # noqa: BLE001 — any probe failure gates
            return self._rollback(rec, "probe", {
                "error": f"{type(e).__name__}: {str(e)[:400]}"}, summary)
        if not np.isfinite(raw).all():
            return self._rollback(rec, "probe", {
                "nonfinite_outputs": int((~np.isfinite(raw)).sum()),
                "probe_rows": int(probe.shape[0])}, summary)
        summary["phases"]["quarantine"] = {
            "probe_rows": int(probe.shape[0]), "finite": True}

        # ---- register the canary entry (its own Server; the live
        # pointer is untouched until cutover)
        self._remove_canary()
        self.fleet.add_model(self.canary_name, candidate,
                             weight=cfg.canary_weight,
                             deadline_class=self.fleet.entry(
                                 self.live_name).deadline_class)
        # pre-compile the canary's bucket programs: the latency gate
        # must judge steady-state serving, not first-request XLA
        # compiles (the same reason swap_model warms before flipping)
        self.fleet.entry(self.canary_name).server.warm()
        self._arm_candidate_call()

        # ---- shadow: mirrored traffic, zero user exposure
        rec = self._journal_phase(rec, "shadow")
        self._enter_phase("shadow", 0.0)
        traffic(self, "shadow", 0.0)
        summary["phases"]["shadow"] = self._stats.summary()
        breach = self._check_gates("shadow")
        if breach:
            return self._rollback(rec, *breach, summary)

        # ---- ramp: staged canary exposure through the fleet
        live_weight = live.weight
        steps = []
        for i, f in enumerate(cfg.ramp):
            rec = self._journal_phase(rec, "ramp", ramp_step=i)
            self.fleet.set_weight(
                self.canary_name,
                max(f * live_weight, cfg.canary_weight))
            self._enter_phase("ramp", f)
            traffic(self, "ramp", f)
            steps.append(dict(self._stats.summary(), fraction=f))
            summary["phases"]["ramp"] = steps
            breach = self._check_gates(f"ramp[{i}]")
            if breach:
                return self._rollback(rec, *breach, summary)

        # ---- cutover: journal the intent, then the atomic probed swap
        rec = self._journal_phase(rec, "cutover")
        self._enter_phase("idle", 0.0)
        try:
            live.server.swap_model(candidate, probe=True)
        except Exception as e:  # noqa: BLE001 — quarantined swap gates
            return self._rollback(rec, "cutover-probe", {
                "error": f"{type(e).__name__}: {str(e)[:400]}"}, summary)
        self._finish_promotion(rec)
        summary["status"] = "promoted"
        summary["live_digest"] = self.fleet.entry(
            self.live_name).model.digest
        return summary

    def _journal_phase(self, rec, phase, ramp_step: int = -1) -> dict:
        rec = self.journal.phase(rec, phase, ramp_step=ramp_step)
        self._rec = rec
        return rec

    def _probe_rows(self, probe_X, candidate) -> np.ndarray:
        if probe_X is not None:
            return np.asarray(probe_X, np.float64)
        rng = np.random.RandomState(0x11FE)
        return rng.randn(64, candidate.num_features()) \
            .astype(np.float32).astype(np.float64)

    def _arm_candidate_call(self) -> None:
        def call(X, deadline_ms, timeout):
            return self.fleet.predict(self.canary_name, X,
                                      deadline_ms=deadline_ms,
                                      timeout=timeout)

        if self._chaos is not None:
            self._cand_call = self._chaos.wrap_predict(call)
        else:
            self._cand_call = call

    def _remove_canary(self) -> None:
        self._cand_call = None
        try:
            self.fleet.remove_model(self.canary_name, drain=False)
        except ModelNotFound:
            pass

    def _finish_promotion(self, rec) -> None:
        """Post-flip bookkeeping — idempotent, so a crash-resume that
        finds the flip committed can finish it again safely."""
        self._remove_canary()
        self._enter_phase("idle", 0.0)
        self.journal.promoted(rec)
        self._prev_booster = None       # release the rollback anchor
        _obs_registry.counter("lifecycle_promotions_total").inc()
        from ..obs.watchdog import global_watchdog
        global_watchdog.mark_fresh(self.live_name)
        _instant("lifecycle.promoted",
                 model=self.live_name,
                 digest=rec.get("candidate_digest"))
        log_info(f"lifecycle: promoted {rec.get('candidate_bundle')} "
                 f"as {self.live_name!r}")

    # ----------------------------------------------------------- rollback

    def _rollback(self, rec: dict, gate: str, evidence: dict,
                  summary: Optional[dict] = None) -> dict:
        """Degrade to the previous verified model: unregister the
        canary, un-flip the live pointer if (and only if) the cutover
        committed, journal the verdict, and dump a forensic bundle
        naming the breached gate."""
        self._enter_phase("idle", 0.0)
        self._remove_canary()
        restored = False
        live = self.fleet.entry(self.live_name)
        cand_digest = rec.get("candidate_digest") or None
        if cand_digest and live.model.digest == cand_digest:
            # the flip landed before the breach/crash: pin the newest
            # verified bundle OLDER than the failed candidate (a
            # concurrent refresh may have saved a newer one); a first
            # promotion with no older bundle falls back to the
            # in-memory pre-promotion booster
            from ..basic import Booster
            try:
                try:
                    prev = self.manager.latest_verified(
                        before=rec.get("candidate_bundle"))
                    prev_booster = Booster(model_str=prev.model_str,
                                           params={"verbosity": -1})
                except CheckpointError:
                    if self._prev_booster is None:
                        raise
                    prev_booster = self._prev_booster
                live.server.swap_model(prev_booster, probe=True)
                restored = True
            except Exception as e:
                raise RollbackFailed(
                    f"lifecycle rollback [{gate}] could not restore the "
                    f"previous verified bundle: {e}") from e
        rec = self.journal.rolled_back(rec, gate, evidence)
        _obs_registry.counter("lifecycle_rollbacks_total",
                              labels={"gate": gate}).inc()
        _instant("lifecycle.rollback", gate=gate)
        from ..obs.flight import global_flight
        global_flight.dump(f"lifecycle:{gate}", extra={
            "gate": gate, "evidence": evidence,
            "candidate_bundle": rec.get("candidate_bundle"),
            "candidate_digest": cand_digest,
            "previous_digest": rec.get("previous_digest"),
            "live_pointer_restored": restored})
        log_warning(f"lifecycle: ROLLED BACK [{gate}] {evidence}")
        out = dict(summary or {}, status="rolled_back", gate=gate,
                   evidence=evidence,
                   live_digest=self.fleet.entry(
                       self.live_name).model.digest)
        return out

    # ------------------------------------------------------------- resume

    def resume(self) -> dict:
        """Recover from a crashed pipeline using the journal alone.
        A journaled cutover whose flip committed (the live digest IS the
        candidate digest) finishes its bookkeeping; every other
        in-progress state rolls back to the previous verified model.
        Never double-promotes: the candidate digest was durable before
        the flip, and this check is idempotent."""
        rec = self.journal.in_progress()
        if rec is None:
            return {"status": "idle"}
        live = self.fleet.entry(self.live_name)
        if rec.get("phase") == "cutover" \
                and rec.get("candidate_digest") \
                and live.model.digest == rec["candidate_digest"]:
            self._finish_promotion(rec)
            return {"status": "promoted", "resumed": True,
                    "live_digest": live.model.digest}
        out = self._rollback(rec, "crash-resume", {
            "phase": rec.get("phase"), "ramp_step": rec.get("ramp_step")})
        out["resumed"] = True
        return out
