"""Fault tolerance for training and collectives (docs/RESILIENCE.md).

- ``checkpoint``: checksummed atomic checkpoint bundles, keep-last-K
  retention, corruption fallback, bit-identical resume state.
- ``faults``: deterministic seeded chaos injection over the allgather
  and pluggable-file-system seams.
- ``retry``: ``resilient_allgather`` — CRC framing, deadline + backoff,
  rank-consistent verdict round, consistent abort.
- ``elastic``: shrink-rejoin after a preempted slice — rank-consistent
  membership probe, shrunk-world re-plan, resume-on-a-smaller-mesh.
"""

from .checkpoint import (Checkpoint, CheckpointCorruptError, CheckpointError,
                         CheckpointManager, CheckpointNotFoundError,
                         load_checkpoint, resolve_resume_point,
                         restore_booster, save_checkpoint)
from .elastic import (SliceLostError, apply_world, membership_probe,
                      plan_shrunk_world, shrink_and_resume)
from .faults import ChaosRegistry, FaultSpec, parse_schedule
from .retry import (CollectiveError, ResilienceConfig, make_resilient,
                    resilient_allgather)

__all__ = [
    "Checkpoint", "CheckpointCorruptError", "CheckpointError",
    "CheckpointManager", "CheckpointNotFoundError", "load_checkpoint",
    "resolve_resume_point", "restore_booster", "save_checkpoint",
    "ChaosRegistry", "FaultSpec", "parse_schedule",
    "CollectiveError", "ResilienceConfig", "make_resilient",
    "resilient_allgather",
    "SliceLostError", "apply_world", "membership_probe",
    "plan_shrunk_world", "shrink_and_resume",
]
