"""Checksummed atomic checkpoint bundles with bit-identical resume.

A checkpoint is ONE file (a zip container) holding three members:

- ``manifest.json`` — format tag, iteration, and a sha256 + size per
  member; verified on every load, so a truncated or bit-flipped bundle is
  detected before any state is trusted;
- ``model.txt``   — the reference-format model text at the checkpoint
  iteration (human-readable, loadable by stock LightGBM on its own);
- ``state.pkl``   — the exact mutable training state captured by
  ``GBDT.capture_state`` (host trees, device score arrays, every RNG
  stream, DART drop/weight state, engine-level eval history and
  early-stopping state), so a resumed run replays the SAME random
  decisions and produces a bit-identical model (boosting/gbdt.py).

The reference has no training checkpoint at all — its ``snapshot_freq``
writes a bare model file in place (gbdt.cpp:259-263), which a crash
mid-write truncates and which cannot restore bagging/DART RNG state.
Bundles are written via ``utils.file_io.write_atomic`` (temp sibling +
``os.replace`` locally; the ``open_file``/``register_file_system`` seam
for remote schemes), so ``snapshot_out`` pointing at gs://... works the
moment a file system is registered for it.

``CheckpointManager`` adds a keep-last-K retention policy driven by an
``index.json`` (also written atomically, so bundle discovery never needs
a directory listing — remote schemes stay listable-free) and
``latest_verified()``, which walks newest-to-oldest skipping corrupt
bundles with a loud warning.

``state.pkl`` is a pickle: only resume from checkpoint directories you
trust, exactly like any other pickle-bearing format.
"""

from __future__ import annotations

import hashlib
import io
import json
import pickle
import time
import zipfile
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..obs.metrics import global_registry as _obs_registry
from ..obs.trace import span as _span
from ..utils.file_io import exists, open_file, remove, write_atomic
from ..utils.log import log_info, log_warning

FORMAT = "lgbt-ckpt/1"
BUNDLE_SUFFIX = ".lgbckpt"
INDEX_NAME = "index.json"


class CheckpointError(RuntimeError):
    """Base class for checkpoint load failures."""


class CheckpointCorruptError(CheckpointError):
    """The bundle exists but fails structural or checksum verification."""


class CheckpointNotFoundError(CheckpointError):
    """No (verifiable) bundle at the requested location."""


@dataclass
class Checkpoint:
    """A verified, decoded bundle."""

    iteration: int
    model_str: str
    boosting_state: dict
    booster_state: dict = field(default_factory=dict)
    engine_state: dict = field(default_factory=dict)
    manifest: dict = field(default_factory=dict)
    path: Optional[str] = None


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def build_bundle_bytes(booster, iteration: int,
                       engine_state: Optional[dict] = None) -> bytes:
    """Serialize ``booster``'s full training state into bundle bytes."""
    model_txt = booster.model_to_string(num_iteration=-1).encode()
    state = {
        "boosting": booster.boosting.capture_state(),
        "booster": {
            "best_iteration": booster.best_iteration,
            "best_score": booster.best_score,
            "attr": dict(booster._attr),
        },
        "engine": dict(engine_state or {}),
    }
    state_pkl = pickle.dumps(state, protocol=4)
    # provenance only, never validated on restore: resumed runs replay
    # bit-identically under ANY chunk decomposition (the macro-step loop
    # body is chunk-size-invariant, boosting/macro.py), so a bundle from
    # a chunked run restores into a per-iteration run and vice versa
    from ..boosting.macro import chunk_cap
    # hist_plan likewise: row tiling is bit-invariant (pinned tile-major
    # accumulation, ops/planner.py), so a bundle from a tiled run
    # restores into an untiled one and vice versa — recorded so an OOM
    # post-mortem can see what the planner chose
    plan = getattr(booster.boosting, "hist_plan", None)
    # out-of-core provenance (lightgbm_tpu/data/): streamed == resident
    # is bit-invariant (pinned block order), so a bundle from a streamed
    # run restores into a resident one and vice versa; the plan + the
    # spill store's block geometry are recorded so a mid-stream resume's
    # post-mortem can see what the pump was doing
    splan = getattr(booster.boosting, "stream_plan", None)
    sctx = getattr(booster.boosting, "_stream", None)
    stream_prov = None
    if splan is not None:
        stream_prov = dict(splan.summary())
        if sctx is not None:
            stream_prov["store_path"] = sctx.store.path
            stream_prov["store_block_rows"] = int(sctx.store.block_rows)
            stream_prov["store_num_blocks"] = int(sctx.store.num_blocks)
    # pod-scale provenance (parallel/collectives.py): the mesh shape and
    # the elected reduction schedule this bundle trained under.  Never
    # validated on restore — hierarchical == flat is bit-invariant for
    # quantized payloads and pinned f32, and an ELASTIC resume (slice
    # loss, docs/RESILIENCE.md) restores into a re-planned SMALLER mesh
    # on purpose; recorded so a shrink post-mortem can see both worlds
    cplan = getattr(booster.boosting, "collective_plan", None)
    manifest = {
        "format": FORMAT,
        "iteration": int(iteration),
        "chunk_cap": chunk_cap(),
        "hist_plan": plan.summary() if plan is not None else None,
        "stream_plan": stream_prov,
        "collective_plan": cplan.summary() if cplan is not None else None,
        "members": {
            "model.txt": {"sha256": _sha256(model_txt),
                          "size": len(model_txt)},
            "state.pkl": {"sha256": _sha256(state_pkl),
                          "size": len(state_pkl)},
        },
    }
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("manifest.json", json.dumps(manifest, indent=1))
        zf.writestr("model.txt", model_txt)
        zf.writestr("state.pkl", state_pkl)
    return buf.getvalue()


def decode_bundle_bytes(blob: bytes, path: Optional[str] = None) -> Checkpoint:
    """Verify manifest checksums and decode; raises CheckpointCorruptError
    on ANY structural or checksum mismatch."""
    where = path or "<bytes>"
    try:
        zf = zipfile.ZipFile(io.BytesIO(blob))
        manifest = json.loads(zf.read("manifest.json").decode())
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint {where}: unreadable container ({e})") from e
    if manifest.get("format") != FORMAT:
        raise CheckpointCorruptError(
            f"checkpoint {where}: format {manifest.get('format')!r} != "
            f"{FORMAT!r}")
    members = {}
    for name, meta in manifest.get("members", {}).items():
        try:
            data = zf.read(name)
        except Exception as e:
            raise CheckpointCorruptError(
                f"checkpoint {where}: missing member {name!r} ({e})") from e
        if len(data) != meta.get("size") or _sha256(data) != meta.get("sha256"):
            raise CheckpointCorruptError(
                f"checkpoint {where}: member {name!r} fails its manifest "
                "checksum (truncated or bit-flipped)")
        members[name] = data
    if "model.txt" not in members or "state.pkl" not in members:
        raise CheckpointCorruptError(
            f"checkpoint {where}: manifest lists no model/state members")
    try:
        state = pickle.loads(members["state.pkl"])
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint {where}: state.pkl checksummed OK but failed to "
            f"unpickle ({e})") from e
    return Checkpoint(
        iteration=int(manifest["iteration"]),
        model_str=members["model.txt"].decode(),
        boosting_state=state["boosting"],
        booster_state=state.get("booster", {}),
        engine_state=state.get("engine", {}),
        manifest=manifest,
        path=path,
    )


def save_checkpoint(booster, path: str, iteration: Optional[int] = None,
                    engine_state: Optional[dict] = None) -> str:
    """Write one atomic bundle to ``path``; returns the path."""
    if iteration is None:
        iteration = booster.current_iteration()
    t0 = time.perf_counter()
    with _span("checkpoint.save", iteration=int(iteration)):
        write_atomic(path,
                     build_bundle_bytes(booster, iteration, engine_state))
    _obs_registry.histogram("checkpoint_save_ms").observe(
        (time.perf_counter() - t0) * 1e3)
    return str(path)


def load_checkpoint(path: str) -> Checkpoint:
    """Read + verify one bundle."""
    if not exists(path):
        raise CheckpointNotFoundError(f"no checkpoint at {path!r}")
    t0 = time.perf_counter()
    with _span("checkpoint.load", path=str(path)):
        try:
            with open_file(path, "rb") as fh:
                blob = fh.read()
        except CheckpointError:
            raise
        except Exception as e:
            raise CheckpointCorruptError(
                f"checkpoint {path}: unreadable ({e})") from e
        ck = decode_bundle_bytes(blob, path=str(path))
    _obs_registry.histogram("checkpoint_load_ms").observe(
        (time.perf_counter() - t0) * 1e3)
    return ck


def restore_booster(booster, ckpt: Checkpoint) -> None:
    """Push a verified checkpoint's state back into a freshly-built
    Booster (same params / train_set / valid sets as the original run)."""
    booster.boosting.restore_state(ckpt.boosting_state)
    bs = ckpt.booster_state
    booster.best_iteration = bs.get("best_iteration", -1)
    booster.best_score = bs.get("best_score", {})
    booster._attr = dict(bs.get("attr", {}))


class CheckpointManager:
    """Keep-last-K bundle directory with an atomically-updated index.

    Layout::

        <directory>/ckpt_iter_00000010.lgbckpt
        <directory>/index.json      {"format": ..., "bundles": [oldest..newest]}
    """

    def __init__(self, directory: str, prefix: str = "ckpt",
                 keep_last: int = 3):
        self.directory = str(directory).rstrip("/")
        self.prefix = prefix
        self.keep_last = max(1, int(keep_last))

    # ----------------------------------------------------------- paths/index

    def path_for(self, iteration: int) -> str:
        return (f"{self.directory}/{self.prefix}_iter_"
                f"{int(iteration):08d}{BUNDLE_SUFFIX}")

    @property
    def index_path(self) -> str:
        return f"{self.directory}/{INDEX_NAME}"

    def _read_index(self) -> List[str]:
        try:
            with open_file(self.index_path, "r") as fh:
                idx = json.loads(fh.read())
            return [str(b) for b in idx.get("bundles", [])]
        except Exception:
            return []

    def _write_index(self, bundles: List[str]) -> None:
        write_atomic(self.index_path,
                     json.dumps({"format": FORMAT, "bundles": bundles},
                                indent=1))

    def bundles(self) -> List[str]:
        """Bundle FILENAMES oldest-to-newest: the index when readable,
        plus (local paths only) anything on disk the index missed — a
        crash between bundle write and index write must not orphan the
        newest checkpoint."""
        names = self._read_index()
        if "://" not in self.directory:
            import os
            try:
                on_disk = sorted(
                    f for f in os.listdir(self.directory)
                    if f.startswith(self.prefix) and f.endswith(BUNDLE_SUFFIX))
            except OSError:
                on_disk = []
            known = set(names)
            for f in on_disk:
                if f not in known:
                    names.append(f)
            names.sort()
        return names

    # ----------------------------------------------------------- save / load

    def save(self, booster, iteration: int,
             engine_state: Optional[dict] = None) -> str:
        path = self.path_for(iteration)
        save_checkpoint(booster, path, iteration, engine_state)
        names = [n for n in self.bundles()
                 if n != path.rsplit("/", 1)[-1]]
        names.append(path.rsplit("/", 1)[-1])
        # retention: drop oldest beyond keep_last (index first, so a
        # reader never sees an indexed-but-deleted bundle)
        drop, keep = names[:-self.keep_last], names[-self.keep_last:]
        self._write_index(keep)
        for name in drop:
            if not remove(f"{self.directory}/{name}"):
                log_warning(f"checkpoint retention: could not delete "
                            f"{self.directory}/{name} (no remover for the "
                            "backend, or delete refused); leaving it")
        log_info(f"checkpoint: wrote {path} (keep_last={self.keep_last})")
        return path

    def latest_verified(self, before: Optional[str] = None) -> Checkpoint:
        """Newest bundle that passes verification; corrupt ones are
        skipped with a loud warning.  Raises CheckpointNotFoundError when
        nothing survives.

        ``before`` (a bundle path/filename, or an iteration number)
        restricts the walk to bundles strictly OLDER than it — the
        lifecycle rollback pin: "the newest verified bundle older than
        the failed candidate", so a rollback can never race a
        concurrent save into re-promoting the model it is rolling
        back (docs/LIFECYCLE.md)."""
        names = self.bundles()
        if before is not None:
            cutoff = (self.path_for(before) if isinstance(before, int)
                      else str(before)).rsplit("/", 1)[-1]
            names = [n for n in names if n < cutoff]
        errors: List[Tuple[str, str]] = []
        for name in reversed(names):
            path = f"{self.directory}/{name}"
            try:
                ck = load_checkpoint(path)
                if errors:
                    log_warning(
                        "checkpoint: newest bundle(s) CORRUPT, falling back "
                        f"to {path}: "
                        + "; ".join(f"{n}: {e}" for n, e in errors))
                return ck
            except CheckpointError as e:
                log_warning(f"checkpoint: skipping corrupt bundle {path}: {e}")
                errors.append((name, str(e)))
        raise CheckpointNotFoundError(
            f"no verifiable checkpoint bundle under {self.directory!r} "
            f"(saw {len(names)}, all corrupt)" if names else
            f"no checkpoint bundles under {self.directory!r}")


def resolve_resume_point(resume_from: str) -> Checkpoint:
    """``resume_from`` may be a bundle FILE or a manager DIRECTORY; a
    directory resolves to its newest verified bundle."""
    p = str(resume_from)
    if p.endswith(BUNDLE_SUFFIX):
        return load_checkpoint(p)
    if "://" not in p:
        import os
        if os.path.isfile(p):
            return load_checkpoint(p)
        if not os.path.isdir(p):
            raise CheckpointNotFoundError(f"resume_from={p!r}: no such "
                                          "bundle file or directory")
    return CheckpointManager(p).latest_verified()
