"""Elastic shrink-rejoin: preemptible-capacity training, first class.

A pod trained on preemptible capacity loses slices mid-run.  The
reference's socket `Network` would simply wedge; this module closes the
loop the PR 2 resilience subsystem opened (docs/RESILIENCE.md):

1. **detect** — the training side's one cross-host dependency is the
   collective plane.  A lost slice surfaces as ``resilient_allgather``'s
   rank-consistent ``CollectiveError``: every SURVIVING rank aborts the
   round together, within the deadline, instead of hanging
   (resilience/retry.py).
2. **agree** — ``membership_probe`` runs a liveness allgather (8-byte
   rank stamps through the same CRC/verdict machinery) over a candidate
   world.  A committed round IS the agreement: every listed rank saw
   every other rank's stamp and voted ok.  A consistent failure means
   the candidate world still contains a dead member — shrink further.
3. **re-plan** — ``plan_shrunk_world`` re-partitions the surviving
   devices into slices (``parallel/network.MeshPlan``), and
   ``apply_world`` expresses it through the mesh-plan seam the GBDT
   layer already consults (LGBM_TPU_NUM_SLICES / LGBM_TPU_SLICE_DEVICES
   for the single-process simulation; a real pod re-launch sets the
   process topology instead).
4. **resume** — ``lgb.train(..., resume_from=<ckpt dir>)`` over the
   re-planned mesh restores the latest VERIFIED bundle
   (``CheckpointManager.latest_verified`` skips a torn newest), and
   ``GBDT.restore_state`` re-tiles every per-row array from the old
   world's row layout into the new one — ``shard_dataset``'s padding
   over the smaller mesh.  Eval history and early-stopping patience ride
   the bundle's callback states, so the shrunk run continues the same
   learning curve.
"""

from __future__ import annotations

import struct
from typing import Callable, List, Optional

from ..utils.log import log_info, log_warning
from .retry import CollectiveError, ResilienceConfig, resilient_allgather

_STAMP = struct.Struct("<4sI")
_MAGIC = b"LGEL"


class SliceLostError(RuntimeError):
    """The candidate world cannot commit a membership round: at least one
    member is gone.  ``world`` carries the candidate that failed."""

    def __init__(self, world: int, reason: str):
        super().__init__(
            f"membership probe failed for world={world}: {reason}; "
            "shrink the world and re-probe (docs/RESILIENCE.md)")
        self.world = world


def membership_probe(allgather_bytes: Callable[[bytes], List[bytes]],
                     *, world: int, rank: int,
                     config: Optional[ResilienceConfig] = None,
                     metrics=None) -> List[int]:
    """Rank-consistent liveness round over a candidate ``world``.

    Every rank allgathers an 8-byte stamp through
    ``resilient_allgather`` (CRC framing + verdict round).  On commit,
    returns the sorted member ranks — every one of them observed the
    full set and voted ok, so the membership IS agreed.  On a consistent
    abort raises ``SliceLostError``: some candidate member is gone (or
    the transport to it is), and the caller should shrink and re-probe
    with a fresh transport for the smaller world.
    """
    cfg = config or ResilienceConfig(deadline_s=10.0, max_retries=2)
    from ..obs.flight import global_flight
    try:
        # flight_dump=False: the SliceLostError bundle below is the
        # specific forensic record — one event must not dump twice
        parts = resilient_allgather(
            _STAMP.pack(_MAGIC, rank), allgather_bytes,
            world=world, rank=rank, config=cfg,
            label="membership_probe", metrics=metrics,
            flight_dump=False)
    except CollectiveError as e:
        err = SliceLostError(world, str(e))
        # a lost slice is exactly the 3am event the flight recorder
        # exists for: bundle the ring + mesh fingerprint before raising
        global_flight.on_exception("elastic.membership", err)
        raise err from e
    members = []
    for p in parts:
        if len(p) != _STAMP.size or p[:4] != _MAGIC:
            err = SliceLostError(world, f"malformed member stamp {p!r}")
            global_flight.on_exception("elastic.membership", err)
            raise err
        members.append(int(_STAMP.unpack(p)[1]))
    return sorted(members)


def plan_shrunk_world(num_slices: int, devices_per_slice: int,
                      lost_slices: int):
    """Re-partition after ``lost_slices`` preempted slices: the survivors
    keep their per-slice device count (their ICI topology is physical),
    only the DCN tier shrinks.  Returns a ``parallel.network.MeshPlan``;
    raises when nothing survives."""
    from ..parallel.network import MeshPlan
    s = max(int(num_slices), 1) - max(int(lost_slices), 0)
    if s < 1:
        raise SliceLostError(
            int(num_slices), f"all {num_slices} slices lost")
    d = max(int(devices_per_slice), 1)
    return MeshPlan(s, d, s * d, "elastic")


def apply_world(plan) -> None:
    """Express a (shrunk) world through the mesh-plan seam
    (``parallel/network.mesh_plan``) so the next booster construction
    builds the re-planned mesh and ``restore_state`` re-tiles into it.

    Single-process simulation: sets LGBM_TPU_NUM_SLICES /
    LGBM_TPU_SLICE_DEVICES.  On a real pod the orchestration layer
    relaunches ``jax.distributed`` with the surviving hosts instead —
    the mesh plan's priority order then reads the live topology and
    these env values are ignored.
    """
    import os
    os.environ["LGBM_TPU_NUM_SLICES"] = str(int(plan.num_slices))
    os.environ["LGBM_TPU_SLICE_DEVICES"] = str(int(plan.devices_per_slice))
    log_info(
        f"elastic: world re-planned to {plan.num_slices} slice(s) x "
        f"{plan.devices_per_slice} device(s) = {plan.total_shards} shards "
        f"(source={plan.source})")


def shrink_and_resume(params: dict, train_set, ckpt_dir: str,
                      *, num_slices: int, devices_per_slice: int,
                      lost_slices: int = 1, num_boost_round: int = 100,
                      **train_kw):
    """One-call shrink-rejoin for the surviving process: re-plan the
    world, then resume from the newest VERIFIED bundle in ``ckpt_dir``
    over the smaller mesh.  Returns the resumed Booster.

    The caller reaches here after ``membership_probe`` (or training's
    own ``CollectiveError``) established the loss; ``lost_slices`` is
    how many DCN participants are gone.  Keyword args pass through to
    ``lgb.train`` (callbacks, valid sets, snapshot_freq for continued
    checkpointing, ...).
    """
    plan = plan_shrunk_world(num_slices, devices_per_slice, lost_slices)
    log_warning(
        f"elastic: {lost_slices} slice(s) lost from a "
        f"{num_slices}x{devices_per_slice} world; resuming from the "
        f"latest verified bundle in {ckpt_dir!r} on the shrunk "
        f"{plan.num_slices}x{plan.devices_per_slice} mesh")
    apply_world(plan)
    from ..engine import train as _train
    return _train(params, train_set, num_boost_round=num_boost_round,
                  resume_from=ckpt_dir, **train_kw)
