"""Deterministic, seeded fault injection over the existing seams.

Two seams exist already and both are wrapped, never monkeypatched:

- the ``allgather_bytes`` injection seam of ``parallel/dist_data.py``
  (the LGBM_NetworkInitWithFunctions analogue) — ``wrap_allgather``
  returns a transport with scheduled payload corruption (drop /
  truncate / bit-flip), latency (delay) and wedges (stall);
- the pluggable file system of ``utils/file_io.py`` —
  ``install_filesystem`` registers a ``chaos://`` scheme whose opener
  proxies to the real path underneath while injecting ENOSPC, silent
  partial writes (the "crash mid-write" shape) and transient errors;
- a predict callable (the lifecycle canary's serving path,
  docs/LIFECYCLE.md) — ``wrap_predict`` injects latency spikes
  (``serving.delay``), NaN outputs (``serving.nan``) and hard failures
  (``serving.error``) so every rollout gate can be driven to breach.

Faults are SCHEDULED, not sprayed: a ``FaultSpec`` names a site
(``allgather`` / ``fs``), a kind, the 0-based op index at which it fires
on that site, and optionally the rank it applies to.  The compact string
syntax (docs/RESILIENCE.md)::

    allgather.bitflip@2:rank=1,allgather.delay@0:sec=0.05,fs.enospc@1

means "bit-flip rank 1's 3rd allgather send, delay everyone's 1st by
50 ms, ENOSPC the 2nd chaos:// write open".  ``prob=`` turns a spec
probabilistic; draws come from one ``numpy.RandomState(seed)``, so a
chaos run replays bit-identically under the same seed and schedule.

Transport faults corrupt the OUTBOUND frame by default (every receiver
sees the damage — the CRC-detect path); ``recv`` kinds corrupt one entry
of the RECEIVED list on the faulted rank only, which is exactly the
asymmetric case the verdict round of ``retry.resilient_allgather``
exists for.
"""

from __future__ import annotations

import errno
import io
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..utils.file_io import open_file, register_file_system, remove, \
    unregister_file_system
from ..utils.log import log_warning

ALLGATHER_KINDS = ("drop", "truncate", "bitflip", "delay", "stall",
                   "recv_bitflip", "recv_truncate")
FS_KINDS = ("enospc", "partial", "transient")
# the serving site wraps a predict callable (lifecycle canary path,
# docs/LIFECYCLE.md): ``delay`` injects a latency spike (arg/sec
# seconds), ``nan`` poisons one output element, ``error`` raises — the
# exact failure shapes the rollout gates must catch
SERVING_KINDS = ("delay", "nan", "error")
# the device site kills one SIMULATED serving device of a pod fleet
# (fleet/router.py; docs/RESILIENCE.md failover section): ``error``
# fails one batch execution (a transient XLA / driver fault), ``wedge``
# blocks the device's batcher thread (arg/sec seconds, default forever
# — the preempted-but-not-dead shape whose heartbeat goes stale), and
# ``vanish`` makes the device gone for good (every later dispatch fails
# fast with DeviceLost).  ``rank=`` selects the device id; the 0-based
# op index counts batch executions on that device.  wedge/vanish are
# PERSISTENT: once fired the device stays down until the registry is
# discarded — a replan, not a retry, is the recovery path.  ``delay``
# sleeps arg/sec seconds (default 0.05) before the batch executes and
# then SUCCEEDS — the latency-inflation shape (a contended device under
# co-resident training) that brownout controllers must catch without a
# single typed failure.
DEVICE_KINDS = ("wedge", "error", "vanish", "delay")


class FaultInjected(OSError):
    """Raised by injected transient file-system faults."""


@dataclass
class FaultSpec:
    site: str                   # "allgather" | "fs" | "serving" | "device"
    kind: str
    at: int                     # 0-based op index on that (site, rank)
    rank: Optional[int] = None  # allgather rank / device id; None = all
    prob: float = 1.0           # fire probability when the index matches
    arg: float = 0.0            # delay/stall seconds, etc.
    fired: int = 0

    def __post_init__(self):
        kinds = {"allgather": ALLGATHER_KINDS, "fs": FS_KINDS,
                 "serving": SERVING_KINDS, "device": DEVICE_KINDS}
        ok = kinds.get(self.site)
        if ok is None:
            raise ValueError(f"unknown fault site {self.site!r}")
        if self.kind not in ok:
            raise ValueError(
                f"unknown {self.site} fault kind {self.kind!r}; "
                f"one of {ok}")


def parse_schedule(schedule: str) -> List[FaultSpec]:
    """Parse the compact comma-separated schedule syntax (module doc)."""
    specs: List[FaultSpec] = []
    for tok in (schedule or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        head, _, opts = tok.partition(":")
        try:
            site_kind, _, at = head.partition("@")
            site, _, kind = site_kind.partition(".")
            spec = FaultSpec(site=site, kind=kind, at=int(at or 0))
        except ValueError:
            raise
        except Exception as e:
            raise ValueError(f"bad fault token {tok!r}: {e}") from e
        for opt in filter(None, opts.split(":")):
            k, _, v = opt.partition("=")
            if k == "rank":
                spec.rank = int(v)
            elif k == "prob":
                spec.prob = float(v)
            elif k in ("sec", "arg"):
                spec.arg = float(v)
            else:
                raise ValueError(f"bad fault option {opt!r} in {tok!r}")
        specs.append(spec)
    return specs


class ChaosRegistry:
    """Holds the schedule, the seeded RNG and per-(site, rank) op
    counters; hands out wrapped seams.  Thread-safe — fake-mesh ranks run
    on threads."""

    def __init__(self, schedule: "str | Sequence[FaultSpec]" = (),
                 seed: int = 0):
        import numpy as np
        self.specs = (parse_schedule(schedule)
                      if isinstance(schedule, str) else list(schedule))
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()
        self._counts: Dict[tuple, int] = {}
        self._downed: Dict[int, str] = {}   # device id -> "wedge"|"vanish"
        self.log: List[str] = []     # every fault actually fired

    # ------------------------------------------------------------ core match

    def _next_op(self, site: str, rank: Optional[int]) -> int:
        with self._lock:
            key = (site, rank)
            n = self._counts.get(key, 0)
            self._counts[key] = n + 1
            return n

    def _due(self, site: str, rank: Optional[int], op: int) -> List[FaultSpec]:
        out = []
        with self._lock:
            for s in self.specs:
                if s.site != site or s.at != op:
                    continue
                if site in ("allgather", "device") and s.rank is not None \
                        and s.rank != rank:
                    continue
                if s.prob < 1.0 and self._rng.rand() >= s.prob:
                    continue
                s.fired += 1
                self.log.append(f"{site}[{'' if rank is None else rank}]"
                                f".{s.kind}@{op}")
                out.append(s)
        return out

    # ------------------------------------------------------------- allgather

    def wrap_allgather(self, fn: Callable[[bytes], List[bytes]],
                       rank: int) -> Callable[[bytes], List[bytes]]:
        """Chaos transport for one rank.  Faults consume the transport
        round (a dropped send still participates with a tombstone), so
        rank-local round counters never desynchronize — which is what
        lets retry recover instead of phase-shifting forever."""

        def chaotic(payload: bytes) -> List[bytes]:
            op = self._next_op("allgather", rank)
            send = payload
            recv_specs = []
            for s in self._due("allgather", rank, op):
                if s.kind == "drop":
                    send = b"\x00LGBT-CHAOS-DROPPED"
                elif s.kind == "truncate":
                    send = send[:max(1, len(send) // 2)]
                elif s.kind == "bitflip":
                    i = min(len(send) - 1, 8 + (s.at % max(1, len(send) - 8)))
                    send = send[:i] + bytes([send[i] ^ 0x40]) + send[i + 1:]
                elif s.kind == "delay":
                    time.sleep(s.arg or 0.05)
                elif s.kind == "stall":
                    time.sleep(s.arg or 3600.0)
                else:
                    recv_specs.append(s)
            out = fn(send)
            for s in recv_specs:
                victim = (rank + 1) % max(1, len(out))
                blob = out[victim]
                if s.kind == "recv_truncate":
                    out = list(out)
                    out[victim] = blob[:max(1, len(blob) // 2)]
                elif s.kind == "recv_bitflip" and blob:
                    i = min(len(blob) - 1, 8)
                    out = list(out)
                    out[victim] = (blob[:i] + bytes([blob[i] ^ 0x40])
                                   + blob[i + 1:])
            return out

        return chaotic

    # -------------------------------------------------------------- serving

    def wrap_predict(self, fn: Callable) -> Callable:
        """Chaos wrapper for a predict callable (the lifecycle canary's
        serving path): scheduled ``serving.delay`` sleeps before the
        call (a mid-ramp latency spike), ``serving.error`` raises
        instead of serving, ``serving.nan`` poisons one element of the
        returned scores — each at its 0-based call index, exactly like
        the other sites."""

        def chaotic(*args, **kwargs):
            import numpy as np
            op = self._next_op("serving", None)
            post = []
            for s in self._due("serving", None, op):
                if s.kind == "delay":
                    time.sleep(s.arg or 0.05)
                elif s.kind == "error":
                    raise FaultInjected(
                        errno.EIO, "chaos: injected serving error")
                else:
                    post.append(s)
            out = fn(*args, **kwargs)
            for s in post:
                if s.kind == "nan":
                    out = np.array(out, dtype=np.float64, copy=True)
                    out.reshape(-1)[0] = np.nan
            return out

        return chaotic

    # --------------------------------------------------------------- device

    def device_down(self, device_id: int) -> Optional[str]:
        """The persistent down-state of a simulated device: ``"wedge"`` /
        ``"vanish"`` once such a fault fired (or ``down_device`` was
        called), else None.  The pod router consults this at dispatch so
        a vanished device fails FAST instead of queueing work a dead
        batcher will never pop."""
        with self._lock:
            return self._downed.get(int(device_id))

    def down_device(self, device_id: int, kind: str = "vanish") -> None:
        """Imperatively kill a device NOW — the mid-run kill switch for
        failover drills (tools/fleet_smoke.py) where the interesting
        moment is wall-clock ("under load"), not a batch index."""
        if kind not in ("wedge", "vanish"):
            raise ValueError(f"device down kind must be wedge|vanish, "
                             f"got {kind!r}")
        with self._lock:
            self._downed[int(device_id)] = kind
            self.log.append(f"device[{device_id}].{kind}@manual")

    def wrap_device_batch(self, device_id: int, fn: Callable) -> Callable:
        """Chaos wrapper for one simulated serving device's batch
        executor (the MicroBatcher ``run_batch`` seam).  Scheduled
        ``device.error`` fails this one batch (transient — the router
        retries elsewhere); ``device.wedge`` marks the device down and
        blocks the batcher thread (its liveness beat goes stale — the
        health-scored death the watchdog detects); ``device.vanish``
        marks the device down and raises ``DeviceLost``.  A device
        already down keeps failing/blocking on every later batch."""
        did = int(device_id)

        def chaotic(batch):
            from ..serving.errors import DeviceLost
            op = self._next_op("device", did)
            for s in self._due("device", did, op):
                if s.kind in ("wedge", "vanish"):
                    with self._lock:
                        self._downed[did] = s.kind
                elif s.kind == "delay":
                    # latency inflation, not failure: the batch still
                    # succeeds after the stall (brownout-detection shape)
                    time.sleep(s.arg if s.arg else 0.05)
                elif s.kind == "error":
                    raise FaultInjected(
                        errno.EIO,
                        f"chaos: injected device {did} batch error")
            state = self.device_down(did)
            if state == "vanish":
                raise DeviceLost(f"chaos: device {did} vanished")
            if state == "wedge":
                # the wedged device's batcher blocks here: in-flight
                # items never complete, the per-replica heartbeat goes
                # stale, and only the router's drain/replan recovers
                spec = next((s for s in self.specs
                             if s.site == "device" and s.kind == "wedge"
                             and (s.rank is None or s.rank == did)), None)
                time.sleep((spec.arg if spec is not None and spec.arg
                            else 3600.0))
                raise DeviceLost(f"chaos: device {did} wedged")
            return fn(batch)

        return chaotic

    # ----------------------------------------------------------- file system

    def install_filesystem(self, scheme: str = "chaos") -> str:
        """Register ``<scheme>://<path>`` proxying to ``<path>`` with fs
        faults applied at open/write time; returns the scheme."""
        registry = self

        def opener(path: str, mode: str = "r"):
            real = path.split("://", 1)[1]
            writing = any(c in mode for c in "wa+x")
            if writing and "://" not in real:
                # object stores create "directories" implicitly; the local
                # proxy must too or every chaos:// write needs a mkdir
                import os
                d = os.path.dirname(os.path.abspath(real))
                if d:
                    os.makedirs(d, exist_ok=True)
            if writing:
                op = registry._next_op("fs", None)
                for s in registry._due("fs", None, op):
                    if s.kind == "enospc":
                        raise FaultInjected(
                            errno.ENOSPC, "chaos: no space left on device",
                            real)
                    if s.kind == "transient":
                        raise FaultInjected(
                            errno.EIO, "chaos: transient write error", real)
                    if s.kind == "partial":
                        return _PartialWriter(real, mode)
            return open_file(real, mode)

        def remover(path: str):
            remove(path.split("://", 1)[1])

        register_file_system(scheme, opener, remover)
        return scheme

    def uninstall_filesystem(self, scheme: str = "chaos") -> None:
        unregister_file_system(scheme)


class _PartialWriter:
    """File-like that buffers writes, then SILENTLY persists only the
    first half on close — the on-disk shape of a crash mid-write on a
    backend without atomic rename.  Checksums, not luck, must catch it."""

    def __init__(self, real_path: str, mode: str):
        self._real = real_path
        self._binary = "b" in mode
        self._buf = io.BytesIO() if self._binary else io.StringIO()
        self.closed = False

    def write(self, data):
        return self._buf.write(data)

    def flush(self):
        pass

    def close(self):
        if self.closed:
            return
        self.closed = True
        data = self._buf.getvalue()
        half = data[:max(1, len(data) // 2)]
        with open_file(self._real, "wb" if self._binary else "w") as fh:
            fh.write(half)
        log_warning(f"chaos: partial write persisted "
                    f"{len(half)}/{len(data)} bytes to {self._real}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
