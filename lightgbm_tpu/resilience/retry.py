"""Resilient byte-allgather: CRC framing, deadline, backoff, rank-consistent
verdict.

The cross-machine allgather in ``parallel/dist_data.py`` is the one
dependency distributed construction has on a degraded DCN, and the raw
seam (``jax_allgather_bytes`` or a test mesh) has no deadline, no retry
and no corruption detection.  ``resilient_allgather`` wraps ANY
``AllgatherBytes`` callable with:

- **per-attempt CRC framing** — every payload travels as
  ``magic | version | attempt | crc32 | length | bytes``; a truncated,
  bit-flipped, dropped (tombstoned) or round-mixed entry is detected on
  receipt, never silently consumed;
- **a rank-consistent verdict round** — after each payload round every
  rank broadcasts its 1-byte ok/bad verdict through the SAME transport;
  the attempt commits only when every rank voted ok, so a corruption
  visible to one receiver makes ALL ranks retry together (no rank can
  run ahead on data another rank rejected);
- **deadline + exponential backoff with deterministic per-rank jitter** —
  attempts stop at ``max_retries`` or the wall-clock deadline, whichever
  first; each transport call is time-bounded (a stalled transport thread
  is abandoned, never joined), so the caller NEVER hangs;
- on exhaustion every rank raises ``CollectiveError`` within the
  deadline — a consistent abort, not a wedge.

reference anchor: Network::Allgather (network.h:89-120) assumes a
healthy socket ring; the communication-efficient parallel GBDT line of
work (PAPERS.md) identifies exactly this collective as the step that
must survive degraded networks.
"""

from __future__ import annotations

import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..utils.log import log_warning

MAGIC = b"LGRA"     # payload frame
VMAGIC = b"LGRV"    # verdict frame
_VERSION = 1
_HEAD = struct.Struct("<BIIQ")   # version, attempt, crc32, payload length


class CollectiveError(RuntimeError):
    """Allgather failed permanently (deadline / retries exhausted).
    Raised on every rank — the consistent-abort signal."""


@dataclass(frozen=True)
class ResilienceConfig:
    """Knobs for ``resilient_allgather`` (params surface:
    ``network_deadline`` seconds, ``network_retries``,
    ``network_backoff`` base seconds, ``network_degraded_fallback``)."""

    deadline_s: float = 30.0
    max_retries: int = 4
    base_backoff_s: float = 0.05
    backoff_cap_s: float = 2.0
    jitter_seed: int = 0
    degraded_fallback: bool = False

    @classmethod
    def from_params(cls, params: dict) -> "Optional[ResilienceConfig]":
        """None unless ``network_resilience`` is truthy."""
        p = params or {}
        if not p.get("network_resilience", False):
            return None
        return cls(
            deadline_s=float(p.get("network_deadline", 30.0)),
            max_retries=int(p.get("network_retries", 4)),
            base_backoff_s=float(p.get("network_backoff", 0.05)),
            jitter_seed=int(p.get("network_jitter_seed",
                                  p.get("data_random_seed", 1))),
            degraded_fallback=bool(p.get("network_degraded_fallback",
                                         False)),
        )


def frame_payload(payload: bytes, attempt: int) -> bytes:
    return MAGIC + _HEAD.pack(_VERSION, attempt,
                              zlib.crc32(payload) & 0xFFFFFFFF,
                              len(payload)) + payload


def unframe_payload(blob: bytes,
                    attempt: int) -> Tuple[Optional[bytes], str]:
    """Returns (payload, "") or (None, reason)."""
    head = len(MAGIC) + _HEAD.size
    if len(blob) < head:
        return None, f"short frame ({len(blob)} bytes)"
    if blob[:len(MAGIC)] != MAGIC:
        return None, "bad magic"
    ver, att, crc, length = _HEAD.unpack(blob[len(MAGIC):head])
    if ver != _VERSION:
        return None, f"version {ver}"
    if att != attempt:
        return None, f"attempt {att} != {attempt} (round-mixed)"
    payload = blob[head:]
    if len(payload) != length:
        return None, f"truncated ({len(payload)}/{length} bytes)"
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        return None, "crc mismatch (bit-flip)"
    return payload, ""


def _call_bounded(fn: Callable[[bytes], List[bytes]], arg: bytes,
                  timeout: float) -> List[bytes]:
    """Run ``fn(arg)`` on a daemon thread, waiting at most ``timeout``
    seconds.  A stalled transport is ABANDONED (the thread leaks until
    the underlying call returns) — the alternative is hanging forever."""
    box: list = []

    def run():
        try:
            box.append(("ok", fn(arg)))
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            box.append(("err", e))

    t = threading.Thread(target=run, daemon=True,
                         name="lgbt-resilient-allgather")
    t.start()
    t.join(timeout)
    if not box:
        raise TimeoutError(f"transport call exceeded {timeout:.2f}s")
    kind, val = box[0]
    if kind == "err":
        raise val
    return val


def resilient_allgather(payload: bytes,
                        allgather_bytes: Callable[[bytes], List[bytes]],
                        *, world: int, rank: int,
                        config: Optional[ResilienceConfig] = None,
                        label: str = "allgather",
                        metrics=None,
                        flight_dump: bool = True) -> List[bytes]:
    """Allgather ``payload`` across ``world`` ranks, surviving transient
    transport faults; returns the unframed per-rank payloads.

    Raises ``CollectiveError`` (on every rank, within the deadline) when
    the transport cannot produce a round that ALL ranks verify.

    ``metrics`` defaults to the unified process registry
    (``obs.metrics.global_registry``) so collective health counters are
    always visible process-wide; pass a registry to scope them.  Every
    attempt records an ``allgather.attempt`` trace span when tracing is
    enabled (docs/OBSERVABILITY.md).
    """
    cfg = config or ResilienceConfig()
    if metrics is None:
        from ..obs.metrics import global_registry
        metrics = global_registry
    from ..obs.flight import global_flight
    from ..obs.trace import span as _span
    from ..obs.watchdog import beat as _beat
    deadline = time.monotonic() + cfg.deadline_s
    rng = np.random.RandomState(
        (int(cfg.jitter_seed) * 1000003 + rank * 7919) % (2 ** 31))
    attempt = 0
    last_reason = "no attempt ran"

    def bump(name):
        if metrics is not None:
            metrics.counter(name).inc()

    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0 or attempt > cfg.max_retries:
            bump("collective_aborts")
            err = CollectiveError(
                f"{label}: rank {rank} aborting after {attempt} attempt(s) "
                f"({'deadline exceeded' if remaining <= 0 else 'retries exhausted'}); "
                f"last failure: {last_reason}")
            # consistent abort = the forensic moment: every rank dumps
            # its own bundle (ring shows this rank's retry ladder).
            # flight_dump=False spares the bounded dump budget for
            # callers whose failure is benign (pod telemetry) or who
            # dump a more specific bundle themselves (membership probe)
            if flight_dump:
                global_flight.on_exception("collective", err)
            raise err
        _beat("collective.allgather", count=attempt)
        att_span = _span("allgather.attempt", label=label, rank=rank,
                         attempt=attempt)
        with att_span:
            # --- payload round ---------------------------------------------
            ok, parts, reason = True, None, ""
            try:
                raw = _call_bounded(allgather_bytes,
                                    frame_payload(payload, attempt),
                                    remaining)
                if len(raw) != world:
                    ok, reason = False, f"{len(raw)} parts != world {world}"
                else:
                    parts = []
                    for r, blob in enumerate(raw):
                        p, why = unframe_payload(blob, attempt)
                        if p is None:
                            ok, reason = False, f"rank {r} frame: {why}"
                            break
                        parts.append(p)
            except Exception as e:  # noqa: BLE001 — any transport fault retries
                ok, reason = False, repr(e)
            # --- verdict round: all ranks agree to commit or retry ---------
            committed = False
            remaining = deadline - time.monotonic()
            if remaining > 0:
                try:
                    vote = VMAGIC + struct.pack("<IB", attempt,
                                                1 if ok else 0)
                    votes = _call_bounded(allgather_bytes, vote, remaining)
                    if len(votes) == world:
                        committed = ok and all(
                            len(v) == len(vote) and v[:4] == VMAGIC
                            and struct.unpack("<IB", v[4:])[0] == attempt
                            and struct.unpack("<IB", v[4:])[1] == 1
                            for v in votes)
                        if ok and not committed:
                            reason = "a peer rank voted to retry"
                    else:
                        reason = reason or "verdict round incomplete"
                except Exception as e:  # noqa: BLE001
                    reason = reason or f"verdict round failed: {e!r}"
            att_span.set(ok=ok, committed=committed,
                         reason=(reason or "")[:120])
        # the flight ring sees every attempt outcome even with tracing
        # off — a CollectiveError bundle must show the retry ladder
        global_flight.note("allgather.attempt", label=label, rank=rank,
                           attempt=attempt, ok=ok, committed=committed,
                           reason=(reason or "")[:120])
        if committed:
            if attempt > 0:
                log_warning(f"{label}: rank {rank} recovered after "
                            f"{attempt} retr{'y' if attempt == 1 else 'ies'}")
            bump("collective_retries_recovered" if attempt else
                 "collective_clean")
            return parts
        last_reason = reason or "unknown"
        bump("collective_retries")
        attempt += 1
        backoff = min(cfg.backoff_cap_s,
                      cfg.base_backoff_s * (2.0 ** (attempt - 1)))
        backoff *= 0.5 + 0.5 * rng.rand()     # deterministic per-rank jitter
        time.sleep(max(0.0, min(backoff, deadline - time.monotonic())))


def make_resilient(allgather_bytes, *, world: int, rank: int,
                   config: ResilienceConfig, label: str = "allgather",
                   metrics=None):
    """Wrap a raw AllgatherBytes into one with the same signature that
    routes every round through ``resilient_allgather``."""
    def wrapped(payload: bytes) -> List[bytes]:
        return resilient_allgather(payload, allgather_bytes, world=world,
                                   rank=rank, config=config, label=label,
                                   metrics=metrics)
    return wrapped
