"""Host-side tree: raw-feature prediction, serialization, SHAP.

reference: include/LightGBM/tree.h + src/io/tree.cpp.  Device trees
(grower.TreeArrays, bin-space thresholds over used features) are converted
once per iteration into this host form with REAL feature indices and DOUBLE
thresholds so that models are self-contained (independent of any Dataset)
and text-serializable in the reference's model format.

decision_type bit layout matches the reference exactly (tree.h:19-20,214-233):
bit0 = categorical, bit1 = default_left, bits2-3 = missing type
(0 none, 1 zero, 2 nan).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .binning import BinType, MissingType

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
K_ZERO_THRESHOLD = 1e-35


@dataclass
class HostTree:
    """Flat-array tree with real feature indices and double thresholds."""

    num_leaves: int
    # internal nodes [num_leaves-1]
    split_feature: np.ndarray        # real (original) feature index
    split_feature_inner: np.ndarray  # used-feature index (training order)
    threshold: np.ndarray            # double threshold (numerical) / cat idx
    threshold_in_bin: np.ndarray     # bin threshold
    decision_type: np.ndarray        # int8 bitfield
    left_child: np.ndarray
    right_child: np.ndarray
    split_gain: np.ndarray
    internal_value: np.ndarray
    internal_weight: np.ndarray
    internal_count: np.ndarray
    # leaves [num_leaves]
    leaf_value: np.ndarray
    leaf_weight: np.ndarray
    leaf_count: np.ndarray
    # categorical storage (reference: tree.h cat_boundaries_/cat_threshold_)
    num_cat: int = 0
    cat_boundaries: np.ndarray = field(default_factory=lambda: np.zeros(1, np.int32))
    cat_threshold: np.ndarray = field(default_factory=lambda: np.zeros(0, np.uint32))
    shrinkage: float = 1.0
    # convenience copies for importance
    real_feature_index: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int32))

    # ------------------------------------------------------------- transforms

    def add_bias(self, val: float) -> None:
        """reference: Tree::AddBias (tree.h:169)."""
        self.leaf_value = self.leaf_value + val
        self.internal_value = self.internal_value + val

    def scale(self, rate: float) -> None:
        """reference: Tree::Shrinkage (tree.h:158)."""
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        self.shrinkage *= rate

    @staticmethod
    def constant(value: float) -> "HostTree":
        """reference: Tree::AsConstantTree (tree.h:180)."""
        z = lambda k=0: np.zeros(k)
        return HostTree(
            num_leaves=1,
            split_feature=np.zeros(0, np.int32), split_feature_inner=np.zeros(0, np.int32),
            threshold=z(), threshold_in_bin=np.zeros(0, np.int32),
            decision_type=np.zeros(0, np.int8),
            left_child=np.zeros(0, np.int32), right_child=np.zeros(0, np.int32),
            split_gain=z(), internal_value=z(), internal_weight=z(), internal_count=z(),
            leaf_value=np.array([value]), leaf_weight=z(1), leaf_count=z(1),
            real_feature_index=np.zeros(0, np.int32),
        )

    # ------------------------------------------------------------- prediction

    def _decide(self, fval: np.ndarray, node: int) -> np.ndarray:
        """Vectorized decision; returns bool go-left. reference: tree.h:244-300."""
        dt = int(self.decision_type[node])
        if dt & K_CATEGORICAL_MASK:
            cat_idx = int(self.threshold[node])
            lo, hi = self.cat_boundaries[cat_idx], self.cat_boundaries[cat_idx + 1]
            bitset = self.cat_threshold[lo:hi]
            iv = np.where(np.isnan(fval), -1, fval).astype(np.int64)
            valid = (iv >= 0) & (iv < (hi - lo) * 32)
            ivc = np.clip(iv, 0, max((hi - lo) * 32 - 1, 0))
            inset = (bitset[ivc // 32] >> (ivc % 32).astype(np.uint32)) & 1
            return valid & (inset == 1)
        missing_type = (dt >> 2) & 3
        nan_mask = np.isnan(fval)
        if missing_type != 2:
            fval = np.where(nan_mask, 0.0, fval)
            nan_mask = np.zeros_like(nan_mask)
        is_missing = ((missing_type == 1) & (np.abs(fval) <= K_ZERO_THRESHOLD)) | \
                     ((missing_type == 2) & nan_mask)
        default_left = bool(dt & K_DEFAULT_LEFT_MASK)
        return np.where(is_missing, default_left, fval <= self.threshold[node])

    def predict_np(self, X: np.ndarray) -> np.ndarray:
        """Raw-feature batch prediction (host)."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.full(n, self.leaf_value[0] if len(self.leaf_value) else 0.0)
        node = np.zeros(n, np.int32)
        out = np.empty(n, np.float64)
        active = node >= 0
        # iterative: process node by node (trees are small; vectorize over rows)
        while active.any():
            for nd in np.unique(node[active]):
                rows = active & (node == nd)
                fval = X[rows, self.split_feature[nd]]
                gl = self._decide(fval, nd)
                nxt = np.where(gl, self.left_child[nd], self.right_child[nd])
                node[rows] = nxt
            done = node < 0
            newly = active & done
            out[newly] = self.leaf_value[~node[newly]]
            active = active & ~done
        return out

    def predict_leaf_np(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, np.int32)
        node = np.zeros(n, np.int32)
        active = node >= 0
        while active.any():
            for nd in np.unique(node[active]):
                rows = active & (node == nd)
                gl = self._decide(X[rows, self.split_feature[nd]], nd)
                node[rows] = np.where(gl, self.left_child[nd], self.right_child[nd])
            active = active & (node >= 0)
        return (~node).astype(np.int32)

    def predict_binned_np(self, binned: np.ndarray,
                          feat_group: Optional[np.ndarray] = None,
                          feat_start: Optional[np.ndarray] = None) -> np.ndarray:
        """Bin-space batch prediction (used for rollback/DART on binned data).

        With EFB, ``binned`` holds merged group columns; pass the dataset's
        feat_group/feat_start to decode each feature's bin (see
        FeatureMeta docstring in dataset.py)."""
        n = binned.shape[0]
        if self.num_leaves <= 1:
            return np.full(n, self.leaf_value[0] if len(self.leaf_value) else 0.0)
        node = np.zeros(n, np.int32)
        out = np.empty(n, np.float64)
        active = node >= 0
        while active.any():
            for nd in np.unique(node[active]):
                rows = active & (node == nd)
                fi = self.split_feature_inner[nd]
                if feat_group is not None:
                    col = binned[rows, feat_group[fi]].astype(np.int64)
                    dec = col - int(feat_start[fi]) + 1
                    nb = int(self._feat_num_bin[nd]) if hasattr(
                        self, "_feat_num_bin") else 1 << 30
                    b = np.where((dec >= 1) & (dec < nb), dec, 0)
                else:
                    b = binned[rows, fi].astype(np.int64)
                dt = int(self.decision_type[nd])
                if dt & K_CATEGORICAL_MASK:
                    gl = self._bin_cat_decide(b, nd)
                else:
                    mt = (dt >> 2) & 3
                    thr = self.threshold_in_bin[nd]
                    mb = self._missing_bin[nd] if hasattr(self, "_missing_bin") else -1
                    is_missing = (mt != 0) & (b == mb)
                    gl = np.where(is_missing, bool(dt & K_DEFAULT_LEFT_MASK), b <= thr)
                node[rows] = np.where(gl, self.left_child[nd], self.right_child[nd])
            done = node < 0
            newly = active & done
            out[newly] = self.leaf_value[~node[newly]]
            active = active & ~done
        return out

    def _bin_cat_decide(self, b: np.ndarray, nd: int) -> np.ndarray:
        bs = self._bin_cat_bitset[nd] if hasattr(self, "_bin_cat_bitset") else None
        if bs is None:
            return np.zeros(len(b), bool)
        return ((bs[b // 32] >> (b % 32).astype(np.uint32)) & 1) == 1

    # ------------------------------------------------------------------- SHAP

    def predict_contrib_np(self, X: np.ndarray, num_features: int) -> np.ndarray:
        """Tree SHAP path attribution (reference: tree.h:137 PredictContrib,
        src/io/tree.cpp TreeSHAP).  Returns [n, num_features+1]."""
        n = X.shape[0]
        out = np.zeros((n, num_features + 1), np.float64)
        if self.num_leaves <= 1:
            out[:, -1] = self.expected_value()
            return out
        from .utils.shap import tree_shap
        for i in range(n):
            tree_shap(self, X[i], out[i])
        return out

    def expected_value(self) -> float:
        """reference: Tree::ExpectedValue — weighted mean of leaf outputs."""
        if self.num_leaves <= 1:
            return float(self.leaf_value[0]) if len(self.leaf_value) else 0.0
        tot = float(self.internal_count[0]) if len(self.internal_count) else 0.0
        if tot <= 0:
            return 0.0
        return float((self.leaf_value * self.leaf_count).sum() / tot)

    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        depth = {0: 1}
        md = 1
        for nd in range(self.num_leaves - 1):
            d = depth.get(nd, 1)
            for ch in (self.left_child[nd], self.right_child[nd]):
                if ch >= 0:
                    depth[int(ch)] = d + 1
                    md = max(md, d + 1)
                else:
                    md = max(md, d)
        return md


def tree_to_host(tree_arrays, train_set, shrinkage: float) -> HostTree:
    """Convert device TreeArrays (bin thresholds over used features) into a
    self-contained HostTree (double thresholds, real feature indices)."""
    ta = tree_arrays
    nl = int(ta.num_leaves)
    ns = max(nl - 1, 0)
    used = train_set.used_features
    mappers = train_set.bin_mappers

    split_feature_inner = np.asarray(ta.split_feature[:ns], np.int32)
    real_feat = np.array([used[f] for f in split_feature_inner], np.int32) \
        if ns else np.zeros(0, np.int32)
    thr_bin = np.asarray(ta.threshold_bin[:ns], np.int32)
    is_cat = np.asarray(ta.is_categorical[:ns], bool)
    dl = np.asarray(ta.default_left[:ns], bool)

    threshold = np.zeros(ns, np.float64)
    decision_type = np.zeros(ns, np.int8)
    missing_bin = np.full(ns, -1, np.int32)
    cat_boundaries = [0]
    cat_threshold: List[np.uint32] = []
    bin_cat_bitsets = {}
    num_cat = 0
    for s in range(ns):
        m = mappers[used[split_feature_inner[s]]]
        dt = 0
        if is_cat[s]:
            dt |= K_CATEGORICAL_MASK
            # convert bin bitset -> category-value bitset
            bin_bits = np.asarray(ta.cat_bitset[s], np.uint32)
            bin_cat_bitsets[s] = bin_bits
            cats = []
            for b in range(m.num_bin):
                if (bin_bits[b // 32] >> (b % 32)) & 1:
                    cv = m.bin_2_categorical[b] if b < len(m.bin_2_categorical) else -1
                    if cv >= 0:
                        cats.append(cv)
            max_cat = max(cats) if cats else 0
            nwords = max_cat // 32 + 1
            words = np.zeros(nwords, np.uint32)
            for cv in cats:
                words[cv // 32] |= np.uint32(1) << np.uint32(cv % 32)
            threshold[s] = num_cat
            cat_boundaries.append(cat_boundaries[-1] + nwords)
            cat_threshold.extend(words.tolist())
            num_cat += 1
            # missing type for categorical is NaN-ish; NaN goes right always
            dt |= (m.missing_type & 3) << 2
        else:
            if dl[s]:
                dt |= K_DEFAULT_LEFT_MASK
            dt |= (m.missing_type & 3) << 2
            r = m.num_bin - 1 - (1 if m.missing_type == MissingType.NAN else 0)
            tb = min(int(thr_bin[s]), max(r - 1, 0))
            threshold[s] = m.bin_upper_bound[tb]
            if m.missing_type == MissingType.NAN:
                missing_bin[s] = m.num_bin - 1
            elif m.missing_type == MissingType.ZERO:
                missing_bin[s] = m.default_bin
        decision_type[s] = dt

    ht = HostTree(
        num_leaves=nl,
        split_feature=real_feat,
        split_feature_inner=split_feature_inner,
        threshold=threshold,
        threshold_in_bin=thr_bin,
        decision_type=decision_type,
        left_child=np.asarray(ta.left_child[:ns], np.int32),
        right_child=np.asarray(ta.right_child[:ns], np.int32),
        split_gain=np.asarray(ta.split_gain[:ns], np.float64),
        internal_value=np.asarray(ta.internal_value[:ns], np.float64),
        internal_weight=np.asarray(ta.internal_weight[:ns], np.float64),
        internal_count=np.asarray(ta.internal_count[:ns], np.float64),
        leaf_value=np.asarray(ta.leaf_value[:nl], np.float64),
        leaf_weight=np.asarray(ta.leaf_weight[:nl], np.float64),
        leaf_count=np.asarray(ta.leaf_count[:nl], np.float64),
        num_cat=num_cat,
        cat_boundaries=np.asarray(cat_boundaries, np.int32),
        cat_threshold=np.asarray(cat_threshold, np.uint32),
        shrinkage=shrinkage,
        real_feature_index=real_feat,
    )
    ht._missing_bin = missing_bin
    ht._feat_num_bin = np.array(
        [mappers[used[f]].num_bin for f in split_feature_inner], np.int32)
    ht._bin_cat_bitset = bin_cat_bitsets
    return ht
