"""Jitted leaf-wise tree growth.

TPU-native redesign of LightGBM's SerialTreeLearner
(reference: src/treelearner/serial_tree_learner.cpp:149 Train loop).  The
re-design for XLA:

- No DataPartition / ordered-gradient gather (data_partition.hpp:101,
  dataset.cpp:1318): a dense per-row ``leaf_id`` vector is carried instead;
  leaf membership enters the histogram kernel as a multiplicative mask.
- All shapes static: tree arrays sized by ``num_leaves``; the grow loop is a
  ``lax.while_loop`` ending early when no split has positive gain — the
  same best-first (leaf-wise) policy as the reference (:175-193).
- The histogram cache is a dense [num_leaves, F, B, 3] HBM array; the
  smaller child is built by a masked pass, the sibling by subtraction
  (reference "subtraction trick", serial_tree_learner.cpp:380-388).
- Distributed: pass ``axis_name`` when called under shard_map with rows
  sharded across the mesh — histograms and scalar sums are psum'd, after
  which EVERY device computes the identical best split, eliminating the
  reference's best-split allreduce (parallel_tree_learner.h:190-213).

Node numbering matches the reference Tree (include/LightGBM/tree.h:60-85):
internal node s = s-th split; child pointers >= 0 are internal nodes,
negative values are leaves encoded as ``~leaf_index``; the left child keeps
the parent's leaf index, the right child gets leaf index ``num_leaves``.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .dataset import FeatureMeta
from .ops.histogram import (build_histogram, build_histogram_int,
                            capacity_schedule, compacted_histogram,
                            compacted_histogram_int, psum_quant_hist,
                            quant_levels, take_from_table)
from .ops.split import (K_EPSILON, MAX_CAT_WORDS, PerFeatureBest,
                        SplitHyperparams, SplitResult, best_split_for_leaf,
                        feature_best_splits, leaf_gain, leaf_output,
                        quant_rescale_hist)


class TreeArrays(NamedTuple):
    """Flat-array tree, fixed shapes; L leaves, L-1 internal nodes."""

    split_feature: jax.Array    # [L-1] i32 (index into used features)
    threshold_bin: jax.Array    # [L-1] i32
    default_left: jax.Array     # [L-1] bool
    is_categorical: jax.Array   # [L-1] bool
    cat_bitset: jax.Array       # [L-1, MAX_CAT_WORDS] u32 (bins going left)
    left_child: jax.Array       # [L-1] i32 (>=0 node, <0 ~leaf)
    right_child: jax.Array      # [L-1] i32
    split_gain: jax.Array       # [L-1] f32
    internal_value: jax.Array   # [L-1] f32 (output if node were a leaf)
    internal_weight: jax.Array  # [L-1] f32 (sum_hess)
    internal_count: jax.Array   # [L-1] f32
    leaf_value: jax.Array       # [L] f32
    leaf_weight: jax.Array      # [L] f32
    leaf_count: jax.Array       # [L] f32
    leaf_parent: jax.Array      # [L] i32 (internal node whose child is this leaf)
    leaf_depth: jax.Array       # [L] i32
    num_leaves: jax.Array       # scalar i32

    @staticmethod
    def empty(L: int) -> "TreeArrays":
        n = max(L - 1, 1)
        return TreeArrays(
            split_feature=jnp.zeros(n, jnp.int32),
            threshold_bin=jnp.zeros(n, jnp.int32),
            default_left=jnp.zeros(n, bool),
            is_categorical=jnp.zeros(n, bool),
            cat_bitset=jnp.zeros((n, MAX_CAT_WORDS), jnp.uint32),
            left_child=jnp.zeros(n, jnp.int32),
            right_child=jnp.zeros(n, jnp.int32),
            split_gain=jnp.zeros(n, jnp.float32),
            internal_value=jnp.zeros(n, jnp.float32),
            internal_weight=jnp.zeros(n, jnp.float32),
            internal_count=jnp.zeros(n, jnp.float32),
            leaf_value=jnp.zeros(L, jnp.float32),
            leaf_weight=jnp.zeros(L, jnp.float32),
            leaf_count=jnp.zeros(L, jnp.float32),
            leaf_parent=jnp.full(L, -1, jnp.int32),
            leaf_depth=jnp.zeros(L, jnp.int32),
            num_leaves=jnp.array(1, jnp.int32),
        )


class _LeafBest(NamedTuple):
    """Per-leaf cached best split (SoA over leaves)."""

    gain: jax.Array; feature: jax.Array; threshold: jax.Array
    default_left: jax.Array; left_sum_grad: jax.Array; left_sum_hess: jax.Array
    left_count: jax.Array; right_sum_grad: jax.Array; right_sum_hess: jax.Array
    right_count: jax.Array; is_categorical: jax.Array; cat_bitset: jax.Array

    @staticmethod
    def empty(L: int) -> "_LeafBest":
        return _LeafBest(
            gain=jnp.full(L, -jnp.inf, jnp.float32),
            feature=jnp.zeros(L, jnp.int32),
            threshold=jnp.zeros(L, jnp.int32),
            default_left=jnp.zeros(L, bool),
            left_sum_grad=jnp.zeros(L, jnp.float32),
            left_sum_hess=jnp.zeros(L, jnp.float32),
            left_count=jnp.zeros(L, jnp.float32),
            right_sum_grad=jnp.zeros(L, jnp.float32),
            right_sum_hess=jnp.zeros(L, jnp.float32),
            right_count=jnp.zeros(L, jnp.float32),
            is_categorical=jnp.zeros(L, bool),
            cat_bitset=jnp.zeros((L, MAX_CAT_WORDS), jnp.uint32),
        )

    def store(self, leaf: jax.Array, r: SplitResult) -> "_LeafBest":
        return _LeafBest(
            gain=self.gain.at[leaf].set(r.gain),
            feature=self.feature.at[leaf].set(r.feature),
            threshold=self.threshold.at[leaf].set(r.threshold),
            default_left=self.default_left.at[leaf].set(r.default_left),
            left_sum_grad=self.left_sum_grad.at[leaf].set(r.left_sum_grad),
            left_sum_hess=self.left_sum_hess.at[leaf].set(r.left_sum_hess),
            left_count=self.left_count.at[leaf].set(r.left_count),
            right_sum_grad=self.right_sum_grad.at[leaf].set(r.right_sum_grad),
            right_sum_hess=self.right_sum_hess.at[leaf].set(r.right_sum_hess),
            right_count=self.right_count.at[leaf].set(r.right_count),
            is_categorical=self.is_categorical.at[leaf].set(r.is_categorical),
            cat_bitset=self.cat_bitset.at[leaf].set(r.cat_bitset),
        )


class _LeafFeatBest(NamedTuple):
    """Per-(leaf, feature) cached split candidates (CEGB mode, SoA [L, F]).

    Unlike the reference, which bakes the CEGB penalty into cached
    SplitInfos and has to patch them when a feature's coupled penalty is
    first paid (UpdateLeafBestSplits,
    cost_effective_gradient_boosting.hpp:63-88), the gains cached here are
    penalty-FREE; the penalty is applied at selection time from the
    current used-feature state, so every cached candidate always sees the
    up-to-date coupled penalty — the reference's upgrade pass, made exact.
    The lazy (per-row on-demand) penalty IS cached per leaf (``lazy_pen``)
    because it depends on the rows in the leaf when candidates were
    computed — the same staleness the reference has.
    """

    gain: jax.Array          # [L, F] shifted gains WITHOUT cegb penalties
    threshold: jax.Array     # [L, F] i32
    default_left: jax.Array  # [L, F] bool
    left_sum_grad: jax.Array   # [L, F] f32
    left_sum_hess: jax.Array   # [L, F] f32
    left_count: jax.Array      # [L, F] f32
    cat_bitset: jax.Array    # [L, F, MAX_CAT_WORDS] u32
    lazy_pen: jax.Array      # [L, F] f32 cached on-demand penalties

    @staticmethod
    def empty(L: int, F: int) -> "_LeafFeatBest":
        return _LeafFeatBest(
            gain=jnp.full((L, F), -jnp.inf, jnp.float32),
            threshold=jnp.zeros((L, F), jnp.int32),
            default_left=jnp.zeros((L, F), bool),
            left_sum_grad=jnp.zeros((L, F), jnp.float32),
            left_sum_hess=jnp.zeros((L, F), jnp.float32),
            left_count=jnp.zeros((L, F), jnp.float32),
            cat_bitset=jnp.zeros((L, F, MAX_CAT_WORDS), jnp.uint32),
            lazy_pen=jnp.zeros((L, F), jnp.float32),
        )

    def store(self, leaf: jax.Array, pf: PerFeatureBest,
              lazy_row: jax.Array) -> "_LeafFeatBest":
        return _LeafFeatBest(
            gain=self.gain.at[leaf].set(pf.gain),
            threshold=self.threshold.at[leaf].set(pf.threshold),
            default_left=self.default_left.at[leaf].set(pf.default_left),
            left_sum_grad=self.left_sum_grad.at[leaf].set(pf.left_sum_grad),
            left_sum_hess=self.left_sum_hess.at[leaf].set(pf.left_sum_hess),
            left_count=self.left_count.at[leaf].set(pf.left_count),
            cat_bitset=self.cat_bitset.at[leaf].set(pf.cat_bitset),
            lazy_pen=self.lazy_pen.at[leaf].set(lazy_row),
        )


class GrowerConfig(NamedTuple):
    """Static (trace-time) grower configuration."""

    num_leaves: int = 31
    max_depth: int = -1
    hp: SplitHyperparams = SplitHyperparams()
    hist_method: str = "auto"
    num_bins: int = 255            # padded bin axis B
    learning_rate: float = 0.1
    compact: bool = True           # bucketed leaf-row compaction (see
                                   # ops/histogram.py capacity_schedule)
    voting_top_k: int = 0          # >0 under a data axis: voting-parallel
                                   # (PV-Tree) — only the top-k elected
                                   # features' histograms are psum'd
    num_machines: int = 1          # data-axis size (static; scales the
                                   # voting pass's local constraints)
    bynode_feature_cnt: int = 0    # >0: feature_fraction_bynode — sample
                                   # this many features per NODE (reference
                                   # ColSampler::GetByNode, col_sampler.hpp:87)
    num_feature_shards: int = 1    # feature-axis size (static); with EFB the
                                   # caller pre-arranges meta shard-major so
                                   # each shard owns whole bundles
    rounds_relaxed: bool = False   # rounds grower: skip the best-first
                                   # exactness fallback (tpu_tree_growth=
                                   # "fast"; see grower_rounds.py)
    round_width: int = 128         # rounds grower: max splits per round
                                   # (candidate-scan length / segment-slot
                                   # count; tpu_round_width)
    cegb_tradeoff: float = 1.0     # CEGB (reference cost_effective_
    cegb_penalty_split: float = 0.0  # gradient_boosting.hpp:50 DetlaGain)
    cegb_coupled: bool = False     # static: coupled-penalty array passed
    cegb_lazy: bool = False        # static: per-row on-demand penalties
    n_forced: int = 0              # static count of forced splits (reference
                                   # ForceSplits, serial_tree_learner.cpp:411)
    forced_exact_parity: bool = False  # reproduce the reference's
                                   # GatherInfoForThreshold stats convention
                                   # (bin == threshold accumulates RIGHT,
                                   # feature_histogram.hpp:527 — one bin off
                                   # vs its own DataPartition::Split) so
                                   # forced-split trees match bit-for-bit
    quant: bool = False            # quantized-gradient training: integer
                                   # [2, F, B] i32 histograms, int8 MXU
                                   # matmul, gains from rescaled int sums
                                   # (config use_quantized_grad; the GBDT
                                   # layer falls back to f32 for DART/CEGB/
                                   # monotone/extra_trees)
    quant_bins: int = 4            # num_grad_quant_bins (signed levels)
    quant_renew: bool = False      # quant_train_renew_leaf: re-fit leaf
                                   # outputs from TRUE f32 sums via the
                                   # ops/renew.py seam
    tile_rows: int = 0             # >0: stream every histogram pass
                                   # through row tiles of this size —
                                   # peak transient HBM O(tile), not
                                   # O(n*F).  Chosen by the ops/planner
                                   # HBM budget planner (LGBM_TPU_
                                   # TILE_ROWS overrides); 0 = untiled
    hist_pack: bool = True         # hoist the whole-dataset fused u32
                                   # record arena (pack_cols_u32) for
                                   # the sorted-arena gather; the
                                   # planner clears it when tiling is
                                   # active (records are then assembled
                                   # per tile inside the kernel loops)
    fused_feat_tile: int = 0       # hist_method="fused": features per
                                   # VMEM arena block of the Pallas
                                   # histogram→split megakernel
                                   # (ops/fused.py); 0 = let plan_fused
                                   # pick.  Set by ops/planner.apply_plan
    fused_block_rows: int = 0      # hist_method="fused": rows per
                                   # double-buffered tile DMA; 0 = auto
    hier_reduce: bool = False      # hybrid ("dcn","ici") mesh: reduce the
                                   # fast ICI tier before the slow DCN
                                   # tier (parallel/collectives.py); flat
                                   # when off — byte-identical for
                                   # integer payloads either way
    pinned_reduce: bool = False    # deterministic tier-ordered f32 sums
                                   # (all_gather + fixed-order reduce) so
                                   # flat == hierarchical holds for f32
                                   # model text too
    num_slices: int = 1            # dcn-axis size (static): hierarchical
                                   # voting elects top-k per SLICE, and
                                   # per-voter constraints scale by this
                                   # instead of num_machines


def _psum(x, axis_name, hierarchical: bool = False, pinned: bool = False):
    """Data-axis sum under the active reduction policy.  ``axis_name``
    may be one mesh axis or the hybrid outermost-first tuple; the default
    single-axis flat path is exactly ``lax.psum`` (unchanged HLO)."""
    if axis_name is None:
        return x
    from .parallel.collectives import psum_tiered
    return psum_tiered(x, axis_name, hierarchical=hierarchical,
                       pinned=pinned)


def row_goes_left(col: jax.Array, node_thr: jax.Array, node_dl: jax.Array,
                  node_cat, node_bitset, missing_type: jax.Array,
                  default_bin: jax.Array, num_bin: jax.Array) -> jax.Array:
    """Decision rule in bin space for one node over a column of rows.

    reference: DenseBin::SplitInner (src/io/dense_bin.hpp) — missing rows
    follow default_left, others compare bin <= threshold; categorical rows
    test bitset membership.  ``node_bitset=None`` (with ``node_cat=None``)
    is the numeric-only fast path: it skips the per-row bitset-word gather,
    which matters inside the rounds grower's candidate scan.
    """
    from .binning import MissingType
    col = col.astype(jnp.int32)
    is_missing = ((missing_type == MissingType.NAN) & (col == num_bin - 1)) | \
                 ((missing_type == MissingType.ZERO) & (col == default_bin))
    num_left = jnp.where(is_missing, node_dl, col <= node_thr)
    if node_bitset is None:
        return num_left
    word = (col // 32).astype(jnp.int32)
    bit = (col % 32).astype(jnp.uint32)
    if node_bitset.ndim == 2:  # per-row bitsets (traversal path)
        w = jnp.take_along_axis(node_bitset, word[:, None], axis=1)[:, 0]
    else:
        w = node_bitset[word]
    cat_left = ((w >> bit) & jnp.uint32(1)) == 1
    return jnp.where(node_cat, cat_left, num_left)


def grow_tree(binned_t, *args, **kwargs):
    """Grow one tree (full signature/contract: ``_grow_tree_traced``).

    The wrapper records a ``trace.grow_tree`` span around program-trace
    construction: the body runs on the HOST once per XLA compile (cached
    executions never re-enter it), so the span attributes compile-side
    cost to the grower — the seam the timer table cannot see
    (docs/OBSERVABILITY.md)."""
    from .obs.trace import span as _span
    with _span("trace.grow_tree", rows=int(binned_t.shape[1])):
        return _grow_tree_traced(binned_t, *args, **kwargs)


def _grow_tree_traced(
    binned_t: jax.Array,        # [F, n] uint8/16 feature-major (F, n
                                #   possibly per-shard; see ops/histogram.py
                                #   LAYOUT DOCTRINE)
    grad: jax.Array,            # [n] f32
    hess: jax.Array,            # [n] f32
    row_mask: jax.Array,        # [n] f32 bagging/GOSS weights (0 = excluded)
    meta: FeatureMeta,          # host numpy metadata (trace-time constants)
    cfg: GrowerConfig,
    feature_mask: Optional[jax.Array] = None,   # [F] per-tree col sample
    axis_name: Optional[str] = None,            # mesh axis sharding ROWS
    feature_axis_name: Optional[str] = None,    # mesh axis sharding FEATURES
    monotone_constraints: Optional[jax.Array] = None,  # [F] i32 in {-1,0,1}
    rng_key: Optional[jax.Array] = None,        # PRNG for extra_trees /
                                                # by-node column sampling
                                                # (replicated across shards)
    cegb_coupled_penalty: Optional[jax.Array] = None,  # [F] f32 coupled
                                                # penalties (inner feature idx)
    cegb_lazy_penalty: Optional[jax.Array] = None,     # [F] f32 per-row
                                                # on-demand penalties
    cegb_feat_used: Optional[jax.Array] = None,  # [F] bool: feature already
                                                # used in any split (carried
                                                # across trees by the caller)
    cegb_used_rows: Optional[jax.Array] = None,  # [F, n] bool: (feature, row)
                                                # pairs already paid for
                                                # (lazy mode; carried across
                                                # trees by the caller)
    forced_plan: Optional[tuple] = None,        # (leaf, feat, thr) i32 arrays
                                                # [cfg.n_forced]; see
                                                # GBDT._build_forced_plan
    meta_arrays: Optional[tuple] = None,        # (num_bin, missing_type,
                                                # default_bin, is_cat,
                                                # feat_group, feat_start) as
                                                # RUNTIME arrays -> the
                                                # compiled program is shared
                                                # across same-shaped datasets
    quant_vals: Optional[tuple] = None,         # cfg.quant: (gq [n] i8,
                                                # hq [n] i8, g_scale, h_scale)
                                                # from ops.histogram.
                                                # quantize_gradients; grad/
                                                # hess stay the TRUE f32
                                                # values (leaf renewal)
):
    """Grow one tree; returns (TreeArrays, leaf_id [n] i32).

    Distributed modes (call under shard_map over a Mesh):
    - ``axis_name``: rows sharded — histograms and scalar sums are psum'd,
      then every device finds the identical best split (DataParallel
      semantics, reference data_parallel_tree_learner.cpp, with the
      best-split sync eliminated).
    - ``feature_axis_name``: features sharded — each device scans only its
      own features (meta arrays are full-size; the local slice is taken by
      ``axis_index``), the best split is merged by all_gather + argmax
      (reference SyncUpGlobalBestSplit, parallel_tree_learner.h:190-213),
      and the owner broadcasts the partition mask via psum (replaces the
      reference's no-op because there every machine holds all features).
    Both can be combined (2-D mesh).
    """
    meta = meta.resolved()
    G, n = binned_t.shape
    L = cfg.num_leaves
    B = cfg.num_bins
    Bg = meta.max_group_bin if meta.has_bundles else B
    hp = cfg.hp

    # reduction policy over the (possibly tiered) data axis — every
    # scalar/histogram sum below routes through one closure so the
    # flat/hierarchical/pinned decision is made exactly once
    hier_rd = cfg.hier_reduce
    pinned_rd = cfg.pinned_reduce

    def psum_(x):
        return _psum(x, axis_name, hier_rd, pinned_rd)

    # full (unsliced) constraints for split-time bound propagation, which
    # looks up by GLOBAL feature index even when features are sharded
    mc_full = (jnp.asarray(monotone_constraints)
               if monotone_constraints is not None else None)
    if feature_axis_name is not None:
        # features sharded: each device's binned holds G columns of the
        # full group axis.  Without EFB those are identity groups; with EFB
        # the caller pre-arranged groups SHARD-MAJOR so every shard owns
        # whole bundles (reference partitions features after bundling,
        # feature_parallel_tree_learner.cpp:33-52) and meta.feat_group
        # already holds shard-LOCAL group indices.
        if meta.has_bundles:
            if cfg.num_feature_shards <= 1:
                raise NotImplementedError(
                    "feature-axis sharding over EFB bundles needs the "
                    "shard-major layout: set cfg.num_feature_shards to the "
                    "feature-axis size and pre-arrange meta/columns as "
                    "GBDT._build_group_sharding does (or train through the "
                    "engine, which does this automatically)")
            nsh = cfg.num_feature_shards
            F = len(meta.num_bin) // nsh
        else:
            F = G
    else:
        F = len(meta.num_bin)
    # per-feature metadata: taken from ``meta_arrays`` when the caller
    # passes them as RUNTIME values (so one compiled program serves every
    # same-shaped dataset — cv folds, sklearn fits; the bin layout is then
    # data, not an HLO constant), else embedded as trace-time constants
    if meta_arrays is not None:
        (num_bin_g, missing_type_g, default_bin_g, is_cat_g,
         feat_group_g, feat_start_g) = meta_arrays
    else:
        num_bin_g = jnp.asarray(meta.num_bin)
        missing_type_g = jnp.asarray(meta.missing_type)
        default_bin_g = jnp.asarray(meta.default_bin)
        is_cat_g = jnp.asarray(meta.is_categorical)
        feat_group_g = jnp.asarray(meta.feat_group)
        feat_start_g = jnp.asarray(meta.feat_start)
    if feature_axis_name is not None:
        fidx = lax.axis_index(feature_axis_name)
        def shard_slice(arr):
            return lax.dynamic_slice_in_dim(jnp.asarray(arr), fidx * F, F)
        num_bin = shard_slice(num_bin_g)
        missing_type = shard_slice(missing_type_g)
        default_bin = shard_slice(default_bin_g)
        is_cat = shard_slice(is_cat_g)
        if feature_mask is not None:
            feature_mask = lax.dynamic_slice_in_dim(feature_mask, fidx * F, F)
        if monotone_constraints is not None:
            monotone_constraints = lax.dynamic_slice_in_dim(
                jnp.asarray(monotone_constraints), fidx * F, F)
        f_offset = fidx * F
        if meta.has_bundles:
            feat_group = shard_slice(feat_group_g)   # shard-LOCAL groups
            feat_start = shard_slice(feat_start_g)
        else:
            feat_group = jnp.arange(F, dtype=jnp.int32)
            feat_start = jnp.ones(F, jnp.int32)
    else:
        num_bin = num_bin_g
        missing_type = missing_type_g
        default_bin = default_bin_g
        is_cat = is_cat_g
        f_offset = None
        feat_group = feat_group_g
        feat_start = feat_start_g
    has_cat = bool(meta.is_categorical.any())

    # quantized-gradient mode: integer [2, G, Bg] i32 histograms built
    # from pre-discretized int8 grad/hess (weights folded at quantization
    # time, ops/histogram.py quantize_gradients); the int->f32 rescale
    # happens ONCE per leaf search (quant_rescale_hist), everything
    # upstream of the search — cache, psum, sibling subtraction — stays
    # exact integer arithmetic
    quant = cfg.quant
    # planner-selected row tiling (ops/planner.py): every histogram pass
    # below streams tiles of this many rows; 0/None = untiled
    tile = cfg.tile_rows if cfg.tile_rows > 0 else None
    if quant:
        if quant_vals is None:
            raise ValueError("cfg.quant requires quant_vals="
                             "(gq, hq, g_scale, h_scale)")
        q_grad, q_hess, g_scale, h_scale = quant_vals
        q_levels = quant_levels(cfg.quant_bins)

        def hist_pass(w):
            return build_histogram_int(binned_t, q_grad, q_hess, w > 0, Bg,
                                       method=cfg.hist_method,
                                       levels=q_levels, tile_rows=tile)

        def split_conv(ghist, cnt, cnt_factor=None):
            return quant_rescale_hist(ghist, g_scale, h_scale, cnt,
                                      cnt_factor=cnt_factor)
    else:
        hist_fn = functools.partial(build_histogram, num_bins=Bg,
                                    method=cfg.hist_method,
                                    tile_rows=tile)

        def hist_pass(w):
            return hist_fn(binned_t, grad, hess, w)

        def split_conv(ghist, cnt, cnt_factor=None):
            return ghist
    # full-n first capacity: the "smaller" child is chosen by WEIGHTED count
    # (GOSS amplifies weights), so its raw row count may exceed n/2
    caps = capacity_schedule(n) if cfg.compact else [n]

    if meta.has_bundles:
        b_idx = jnp.arange(B, dtype=jnp.int32)

        def expand_hist(ghist, sg, sh, cnt):
            """[3, G, Bg] group histogram -> [3, F, B] per-feature histogram.

            Feature bins b>=1 gather from merged bins feat_start+b-1; bin 0
            (the shared default) is reconstructed from the leaf totals
            (reference: Dataset::FixHistogram, dataset.cpp:1410).
            """
            gather_bins = jnp.clip(feat_start[:, None] + b_idx[None, :] - 1,
                                   0, Bg - 1)                       # [F, B]
            taken = ghist[:, feat_group[:, None], gather_bins]      # [3, F, B]
            valid = (b_idx[None, :] >= 1) & (b_idx[None, :] < num_bin[:, None])
            h = jnp.where(valid[None, :, :], taken, 0.0)
            totals = jnp.stack([sg, sh, cnt])                       # [3]
            return h.at[:, :, 0].set(totals[:, None] - h.sum(axis=2))

        def expand_hist_int(ghist_i, tot_i):
            """Integer twin of expand_hist for the quantized voting path:
            same gather, bin 0 reconstructed from the [2] i32 leaf totals
            — linear, so it commutes with the elected-features psum."""
            gather_bins = jnp.clip(feat_start[:, None] + b_idx[None, :] - 1,
                                   0, Bg - 1)
            taken = ghist_i[:, feat_group[:, None], gather_bins]
            valid = (b_idx[None, :] >= 1) & (b_idx[None, :] < num_bin[:, None])
            h = jnp.where(valid[None, :, :], taken, 0)
            return h.at[:, :, 0].set(tot_i[:, None] - h.sum(axis=2))
    else:
        def expand_hist(ghist, sg, sh, cnt):
            return ghist   # identity groups: group hist IS the feature hist

        def expand_hist_int(ghist_i, tot_i):
            return ghist_i

    voting = (cfg.voting_top_k > 0 and axis_name is not None)
    if voting and feature_axis_name is not None:
        # recorded design exclusion (not a gap vs the reference): the
        # reference's tree_learner is a single choice of
        # serial|feature|data|voting — its factory cross product is
        # (learner x device), never (learner x learner)
        # (src/treelearner/tree_learner.cpp:13-36).  Voting elects features
        # to compress the DATA-axis histogram reduction; sharding features
        # at the same time removes the very all-feature local histograms
        # the vote is computed from.  The data x feature 2-D mesh already
        # exceeds the reference's composition surface.
        raise NotImplementedError("voting-parallel is a data-axis mode; "
                                  "combining it with feature sharding is "
                                  "contradictory (the vote needs all-"
                                  "feature local histograms) — use a "
                                  "data x feature mesh without voting")

    # CEGB (reference: cost_effective_gradient_boosting.hpp) — penalties are
    # subtracted from candidate gains; candidates are cached per
    # (leaf, feature) penalty-free and penalized at selection time, so the
    # coupled penalty disappears for EVERY cached candidate the moment a
    # feature is first used (UpdateLeafBestSplits semantics, made exact).
    # CEGB state (used-feature flags, lazy paid-rows bitmap, penalty
    # arrays) is indexed by GLOBAL feature id even under feature sharding;
    # per-shard views are sliced at the use sites below.
    cegb_enabled = (cfg.cegb_penalty_split > 0.0 or cfg.cegb_coupled
                    or cfg.cegb_lazy)
    if quant and cegb_enabled:
        # the GBDT layer falls back to f32 for CEGB (warn-once); reaching
        # here means a caller bypassed it
        raise NotImplementedError(
            "quantized-gradient training does not support CEGB; the "
            "booster falls back to f32 histograms for this combination")
    F_glob = len(meta.num_bin)    # global feature count (== F when unsharded)
    if cegb_enabled and voting:
        # recorded design exclusion: this build's CEGB is EXACT — it keeps
        # a per-(leaf, feature) candidate cache built from global
        # histograms and penalizes at selection time.  Voting exists to
        # avoid materializing global per-feature candidates (only elected
        # features' histograms are ever summed), so exact CEGB under
        # voting would psum every feature's histogram and degenerate
        # voting into data-parallel.  Use tree_learner=data for CEGB at
        # scale (same result, honest cost).
        raise NotImplementedError(
            "CEGB needs global per-feature candidates; voting-parallel "
            "exists to avoid building exactly those — use "
            "tree_learner=data with CEGB instead")
    if cegb_feat_used is None:
        cegb_feat_used = jnp.zeros(F_glob, bool)
    if cegb_used_rows is None:
        cegb_used_rows = jnp.zeros((F_glob, n) if cfg.cegb_lazy else (1, 1),
                                   bool)

    def _shard_view(arr, axis=0):
        """Slice a globally-indexed per-feature array to this shard."""
        if feature_axis_name is None:
            return arr
        return lax.dynamic_slice_in_dim(arr, f_offset, F, axis=axis)

    def cegb_gains(fb: "_LeafFeatBest", leaf_cnt_arr, used):
        """[L, F] penalized gains from the candidate cache (the reference's
        DetlaGain, cost_effective_gradient_boosting.hpp:50, applied
        dynamically from current state)."""
        pen = jnp.zeros((), jnp.float32)
        if cfg.cegb_penalty_split > 0.0:
            pen = pen + (cfg.cegb_tradeoff * cfg.cegb_penalty_split
                         * leaf_cnt_arr[:, None])
        if cfg.cegb_coupled:
            pen = pen + jnp.where(
                _shard_view(used)[None, :], 0.0,
                cfg.cegb_tradeoff
                * _shard_view(cegb_coupled_penalty)[None, :])
        if cfg.cegb_lazy:
            pen = pen + fb.lazy_pen
        return jnp.where(jnp.isfinite(fb.gain), fb.gain - pen, -jnp.inf)

    def cegb_lazy_row(in_leaf, used_rows):
        """[F] on-demand penalty for one leaf's rows (reference:
        CalculateOndemandCosts, cost_effective_gradient_boosting.hpp:93-113
        — the per-feature penalty times the leaf rows that have not yet
        paid for the feature)."""
        if not cfg.cegb_lazy:
            return jnp.zeros((F,), jnp.float32)
        rows_l = _shard_view(used_rows)
        cnt = (~rows_l).astype(jnp.float32) @ in_leaf.astype(jnp.float32)
        return (cfg.cegb_tradeoff * _shard_view(cegb_lazy_penalty)
                * psum_(cnt))

    def cegb_global_best_gain(fb, leaf_cnt_arr, used, num_leaves):
        """Scalar max penalized gain over active leaves, merged across
        feature shards — computed in the loop BODY and carried so the
        while-loop cond stays collective-free and replicated."""
        active = jnp.arange(L) < num_leaves
        g = cegb_gains(fb, leaf_cnt_arr, used)
        m = jnp.max(jnp.where(active[:, None], g, -jnp.inf))
        if feature_axis_name is not None:
            m = lax.pmax(m, feature_axis_name)
        return m

    # per-node randomness: extra_trees thresholds + by-node column sampling.
    # The key is REPLICATED across shards (reference syncs random seeds
    # across machines, application.cpp:169-174); by-node masks are sampled
    # over the GLOBAL feature axis then sliced per shard.
    F_total = F_glob
    use_rng = hp.extra_trees or cfg.bynode_feature_cnt > 0
    if use_rng and rng_key is None:
        rng_key = jax.random.PRNGKey(0)

    # fused Pallas histogram→split megakernel arm (ops/fused.py): per
    # split, ONE kernel streams the binned matrix once, accumulates the
    # smaller child's bins in VMEM, derives the sibling from the parent
    # arena in-kernel and scans both children's gains before writing
    # back only the smaller-child histogram (the subtraction cache's
    # input) + [2, F] per-feature-best tuples.  Monotone constraints
    # ride into the in-kernel scan (the bound propagation is hoisted
    # above the kernel call — it only needs the parent's cached sums);
    # every other special mode keeps the staged family (same trees: the
    # scan is ops.split.numeric_feature_scan either way).  The rounds
    # grower additionally lifts the categorical and data-parallel gates
    # (grower_rounds.py — the seam-split kernel); this serial arm exists
    # for mode completeness and the parity suite.
    use_fused = (cfg.hist_method == "fused" and axis_name is None
                 and feature_axis_name is None and not voting
                 and not cegb_enabled and cfg.n_forced == 0
                 and not meta.has_bundles and not has_cat
                 and not use_rng)
    if use_fused:
        from .ops.fused import fused_frontier_splits, pick_fused_best
        from .ops.histogram import _vals_t, _vals_t_int
        fused_vals = (_vals_t_int(q_grad, q_hess, row_mask > 0) if quant
                      else _vals_t(grad, hess, row_mask))
        fused_scales = (g_scale, h_scale) if quant else None

    def node_rand(key):
        """(by-node feature mask or None, extra-trees uniforms or None)."""
        fm_bn, eru = None, None
        if cfg.bynode_feature_cnt > 0:
            u = jax.random.uniform(jax.random.fold_in(key, 0), (F_total,))
            kth = -lax.top_k(-u, cfg.bynode_feature_cnt)[0][-1]
            bn = u <= kth
            if feature_axis_name is not None:
                bn = lax.dynamic_slice_in_dim(bn, f_offset, F)
            fm_bn = bn.astype(jnp.float32)
        if hp.extra_trees:
            eru = jax.random.uniform(jax.random.fold_in(key, 1), (F_total, 2))
            if feature_axis_name is not None:
                eru = lax.dynamic_slice_in_dim(eru, f_offset, F, axis=0)
        return fm_bn, eru

    def leaf_best_voting(ghist_local, sg, sh, cnt, bounds, fm, eru):
        """Voting-parallel (PV-Tree) best split: local per-feature gains ->
        top-k vote -> psum ONLY the elected features' histograms.

        reference: voting_parallel_tree_learner.cpp — local candidates with
        1/num_machines-scaled constraints (:57-59), GlobalVoting weighted by
        local leaf count (:153-182), CopyLocalHistogram + ReduceScatter of
        elected features only (:186-245).  Here the reduce-scatter+ownership
        dance collapses to one psum of a [top_k, B, 3] gather.

        Hierarchical mode (``cfg.hier_reduce`` on a ("dcn","ici") mesh):
        the FULL per-feature histogram first psums over the fast ICI tier
        only, each SLICE votes from its slice-level gains, and only the
        elected features' histograms cross the slow DCN tier — PV-Tree's
        bandwidth saver applied to exactly the expensive hop (F*B*ch
        bytes over ICI, k*B*ch over DCN; ops/planner.py plan_collectives
        is the accounting twin).
        """
        from .parallel.collectives import all_gather_tiered, axis_names
        names_v = axis_names(axis_name)
        hier_v = hier_rd and len(names_v) > 1
        # the axis the vote gathers over / elected histograms psum over:
        # the slow outermost tier under hierarchy, the whole ladder flat
        vote_axis = names_v[0] if hier_v else axis_name
        inner_axes = names_v[1:]
        # one "voter" = one slice under hierarchy, one device flat; the
        # reference's per-machine constraint scaling follows the voter
        ndev = max(cfg.num_slices, 1) if hier_v else max(cfg.num_machines, 1)
        if hier_v:
            # fast-tier reduction of the FULL histogram: after this the
            # "local" histogram is slice-level and replicated over ici
            ghist_local = (
                psum_quant_hist(ghist_local, inner_axes, rows_global,
                                cfg.quant_bins) if quant
                else _psum(ghist_local, inner_axes, pinned=pinned_rd))
        k = min(cfg.voting_top_k, F)
        hp_local = hp._replace(
            min_data_in_leaf=max(1, hp.min_data_in_leaf // ndev),
            min_sum_hessian_in_leaf=hp.min_sum_hessian_in_leaf / ndev)
        if quant:
            # local INTEGER totals from group 0 (its bins partition the
            # local rows); counts are estimated with the GLOBAL factor —
            # sh was produced as int_total * h_scale, so sh / h_scale
            # round-trips the global hessian-int total
            loc_i = ghist_local[:, 0, :].sum(axis=1)        # [2] i32
            cnt_f = cnt / jnp.maximum(jnp.round(sh / h_scale), 1.0)
            loc = (loc_i[0].astype(jnp.float32) * g_scale,
                   loc_i[1].astype(jnp.float32) * h_scale,
                   loc_i[1].astype(jnp.float32) * cnt_f)
            hist_loc = expand_hist(
                split_conv(ghist_local, cnt, cnt_factor=cnt_f),
                loc[0], loc[1], loc[2])
        else:
            loc = ghist_local[:, 0, :].sum(axis=1)   # local (sg, sh, cnt):
            # every row lands in exactly one bin of group 0, so its totals
            # are the local leaf totals
            hist_loc = expand_hist(ghist_local, loc[0], loc[1], loc[2])
        pf = feature_best_splits(
            hist_loc, loc[0], loc[1], loc[2], num_bin, missing_type,
            default_bin, is_cat, hp_local, feature_mask=fm,
            monotone_constraints=monotone_constraints,
            leaf_output_bounds=bounds, has_categorical=has_cat,
            extra_rand_u=eru)
        # weighted gain (GlobalVoting :166): local gain scaled by the local
        # share of the leaf's rows
        mean_cnt = jnp.maximum(cnt / ndev, 1.0)
        rc_loc = loc[2] - pf.left_count
        wgain = jnp.where(jnp.isfinite(pf.gain),
                          pf.gain * (pf.left_count + rc_loc) / mean_cnt,
                          -jnp.inf)
        top_g, top_i = lax.top_k(wgain, k)
        all_i = all_gather_tiered(top_i, vote_axis).reshape(-1)
        all_g = all_gather_tiered(top_g, vote_axis).reshape(-1)
        votes = jnp.full(F, -jnp.inf, jnp.float32).at[all_i].max(
            jnp.where(jnp.isfinite(all_g), all_g, -jnp.inf))
        _, elected = lax.top_k(votes, k)
        if quant:
            # the elected-features collective moves INTEGER histograms
            # ([2, k, B] i32, int16-narrowed when the static bound
            # allows) — the quantization-width payload shrink applies to
            # voting's only O(bins) collective too
            sub_i = psum_quant_hist(
                expand_hist_int(ghist_local, loc_i)[:, elected],
                vote_axis, rows_global, cfg.quant_bins)
            sub = split_conv(sub_i, cnt)
        else:
            sub = _psum(hist_loc[:, elected], vote_axis,
                        pinned=pinned_rd)             # [3, k, B]: the only
            # O(bins) collective on this tier — k*B*3 words vs
            # data-parallel's F*B*3
        r = best_split_for_leaf(
            sub, sg, sh, cnt, num_bin[elected], missing_type[elected],
            default_bin[elected], is_cat[elected], hp,
            feature_mask=(fm[elected] if fm is not None else None),
            monotone_constraints=(monotone_constraints[elected]
                                  if monotone_constraints is not None else None),
            leaf_output_bounds=bounds, has_categorical=has_cat,
            extra_rand_u=(eru[elected] if eru is not None else None))
        return r._replace(feature=elected[r.feature])

    def leaf_best(ghist, sg, sh, cnt, depth, bounds=None, key=None):
        fm_bn, eru = node_rand(key) if (use_rng and key is not None) \
            else (None, None)
        fm = feature_mask
        if fm_bn is not None:
            fm = fm_bn if fm is None else fm * fm_bn
        if voting:
            r = leaf_best_voting(ghist, sg, sh, cnt, bounds, fm, eru)
            if cfg.max_depth > 0:
                r = r._replace(gain=jnp.where(depth >= cfg.max_depth,
                                              -jnp.inf, r.gain))
            return r
        hist = expand_hist(split_conv(ghist, cnt), sg, sh, cnt)
        r = best_split_for_leaf(
            hist, sg, sh, cnt, num_bin, missing_type, default_bin, is_cat,
            hp, feature_mask=fm,
            monotone_constraints=monotone_constraints,
            leaf_output_bounds=bounds,
            has_categorical=has_cat,
            extra_rand_u=eru)
        # depth limit (reference: serial_tree_learner.cpp:261-301 pruning)
        if cfg.max_depth > 0:
            r = r._replace(gain=jnp.where(depth >= cfg.max_depth, -jnp.inf, r.gain))
        if feature_axis_name is not None:
            # merge best splits across the feature shards
            r = r._replace(feature=r.feature + f_offset)
            gathered = jax.tree_util.tree_map(
                lambda x: lax.all_gather(x, feature_axis_name), r)
            winner = jnp.argmax(gathered.gain)
            r = jax.tree_util.tree_map(lambda x: x[winner], gathered)
        return r

    def leaf_feats(ghist, sg, sh, cnt, depth, bounds=None, key=None):
        """Per-feature best candidates for one leaf, penalty-free (fills a
        row of the CEGB _LeafFeatBest cache)."""
        fm_bn, eru = node_rand(key) if (use_rng and key is not None) \
            else (None, None)
        fm = feature_mask
        if fm_bn is not None:
            fm = fm_bn if fm is None else fm * fm_bn
        hist = expand_hist(split_conv(ghist, cnt), sg, sh, cnt)
        pf = feature_best_splits(
            hist, sg, sh, cnt, num_bin, missing_type, default_bin, is_cat,
            hp, feature_mask=fm, monotone_constraints=monotone_constraints,
            leaf_output_bounds=bounds, has_categorical=has_cat,
            extra_rand_u=eru)
        if cfg.max_depth > 0:
            pf = pf._replace(gain=jnp.where(depth >= cfg.max_depth,
                                            -jnp.inf, pf.gain))
        return pf

    # ---- root ----
    # voting mode: the histogram cache holds LOCAL (per-shard) histograms;
    # only elected features are ever psum'd (inside leaf_best_voting).
    # Scalars stay global either way.  Quantized histograms psum as
    # integers with a statically-narrowed payload (psum_quant_hist) —
    # the data-parallel ICI traffic shrinks with the quantization width.
    rows_global = n * max(cfg.num_machines, 1)
    if voting:
        hist_sync = (lambda h: h)
    elif quant:
        hist_sync = (lambda h: psum_quant_hist(h, axis_name, rows_global,
                                               cfg.quant_bins,
                                               hierarchical=hier_rd))
    else:
        hist_sync = psum_
    root_hist = hist_sync(hist_pass(row_mask))
    if quant:
        member = row_mask > 0
        root_sg = psum_(jnp.sum(jnp.where(member, q_grad, 0).astype(
            jnp.int32))).astype(jnp.float32) * g_scale
        root_sh = psum_(jnp.sum(jnp.where(member, q_hess, 0).astype(
            jnp.int32))).astype(jnp.float32) * h_scale
        # counts are plain member-row counts in quantized mode (the
        # reference's bagging semantics; weights live in the int values)
        root_cnt = psum_(jnp.sum(member.astype(jnp.float32)))
    else:
        root_sg = psum_(jnp.sum(grad * row_mask))
        root_sh = psum_(jnp.sum(hess * row_mask))
        root_cnt = psum_(jnp.sum(row_mask))

    tree = TreeArrays.empty(L)
    hist_cache = jnp.zeros((L, 2, G, Bg), jnp.int32).at[0].set(root_hist) \
        if quant else \
        jnp.zeros((L, 3, G, Bg), jnp.float32).at[0].set(root_hist)
    leaf_sg = jnp.zeros(L, jnp.float32).at[0].set(root_sg)
    leaf_sh = jnp.zeros(L, jnp.float32).at[0].set(root_sh)
    leaf_cnt = jnp.zeros(L, jnp.float32).at[0].set(root_cnt)
    # which internal node points at this leaf, and on which side (0=L,1=R)
    leaf_parent_side = jnp.zeros(L, jnp.int32)
    # per-leaf monotone output bounds (reference: LeafConstraints,
    # monotone_constraints.hpp:32; propagated to descendants on each split)
    use_mc = monotone_constraints is not None
    leaf_min = jnp.full(L, -jnp.inf, jnp.float32)
    leaf_max = jnp.full(L, jnp.inf, jnp.float32)
    root_bounds = (leaf_min[0], leaf_max[0]) if use_mc else None
    # node-identity key (parent -1, side 0) — see apply_split's kl/kr
    root_key = (jax.random.fold_in(jax.random.fold_in(rng_key, 0), 0)
                if use_rng else None)
    if cegb_enabled:
        best = _LeafFeatBest.empty(L, F).store(
            jnp.array(0),
            leaf_feats(root_hist, root_sg, root_sh, root_cnt, jnp.array(0),
                       bounds=root_bounds, key=root_key),
            cegb_lazy_row(row_mask > 0, cegb_used_rows))
    else:
        best = _LeafBest.empty(L).store(
            jnp.array(0), leaf_best(root_hist, root_sg, root_sh,
                                    root_cnt, jnp.array(0),
                                    bounds=root_bounds, key=root_key))
    leaf_id = jnp.zeros(n, jnp.int32)
    is_cat_b = is_cat.astype(bool)

    class Carry(NamedTuple):
        tree: TreeArrays
        best: object          # _LeafBest, or _LeafFeatBest in CEGB mode
        hist: jax.Array
        leaf_sg: jax.Array
        leaf_sh: jax.Array
        leaf_cnt: jax.Array
        leaf_parent_side: jax.Array
        leaf_id: jax.Array
        split_idx: jax.Array  # number of splits applied so far
        leaf_min: jax.Array   # [L] monotone lower bounds
        leaf_max: jax.Array   # [L] monotone upper bounds
        cegb_used: jax.Array  # [F_glob] bool: features used in any split
        cegb_rows: jax.Array  # [F_glob, n] bool lazy-paid rows ([1,1] dummy)
        forced_aborted: jax.Array  # scalar bool: forced plan abandoned
        cegb_next_gain: jax.Array  # scalar f32: globally-merged best
        #                            penalized gain (dummy 0 when CEGB off)

    def current_selection(c: Carry):
        """Best-first choice: (leaf, SplitResult) of the max-gain leaf."""
        active = jnp.arange(L) < c.tree.num_leaves
        if cegb_enabled:
            g = cegb_gains(c.best, c.leaf_cnt, c.cegb_used)
            g = jnp.where(active[:, None], g, -jnp.inf)
            leaf = jnp.argmax(jnp.max(g, axis=1)).astype(jnp.int32)
            gl = g[leaf]
            f = jnp.argmax(gl).astype(jnp.int32)   # ties -> smaller feature
            lg = c.best.left_sum_grad[leaf, f]
            lh = c.best.left_sum_hess[leaf, f]
            lc = c.best.left_count[leaf, f]
            r = SplitResult(
                gain=gl[f], feature=f,
                threshold=c.best.threshold[leaf, f],
                default_left=c.best.default_left[leaf, f],
                left_sum_grad=lg, left_sum_hess=lh, left_count=lc,
                right_sum_grad=c.leaf_sg[leaf] - lg,
                right_sum_hess=c.leaf_sh[leaf] - lh,
                right_count=c.leaf_cnt[leaf] - lc,
                is_categorical=is_cat_b[f],
                cat_bitset=c.best.cat_bitset[leaf, f])
            if feature_axis_name is not None:
                # each shard proposes its local (leaf, feature) winner;
                # the global choice is the max gain across shards (gather
                # order = shard order, so exact ties resolve to the
                # smaller global feature id — the reference's SplitInfo
                # tie-break, split_info.hpp:126)
                r = r._replace(feature=r.feature + f_offset)
                gathered = jax.tree_util.tree_map(
                    lambda x: lax.all_gather(x, feature_axis_name),
                    (leaf, r))
                winner = jnp.argmax(gathered[1].gain)
                leaf, r = jax.tree_util.tree_map(
                    lambda x: x[winner], gathered)
        else:
            b = c.best
            gains = jnp.where(active, b.gain, -jnp.inf)
            leaf = jnp.argmax(gains).astype(jnp.int32)
            r = SplitResult(
                gain=b.gain[leaf], feature=b.feature[leaf],
                threshold=b.threshold[leaf],
                default_left=b.default_left[leaf],
                left_sum_grad=b.left_sum_grad[leaf],
                left_sum_hess=b.left_sum_hess[leaf],
                left_count=b.left_count[leaf],
                right_sum_grad=b.right_sum_grad[leaf],
                right_sum_hess=b.right_sum_hess[leaf],
                right_count=b.right_count[leaf],
                is_categorical=b.is_categorical[leaf],
                cat_bitset=b.cat_bitset[leaf])
        return leaf, r

    if cfg.n_forced > 0:
        fp_leaf = jnp.asarray(forced_plan[0], jnp.int32)
        fp_feat = jnp.asarray(forced_plan[1], jnp.int32)
        fp_thr = jnp.asarray(forced_plan[2], jnp.int32)

        def forced_split_result(c: Carry):
            """Stats for the current forced step's planned split.

            reference: GatherInfoForThreshold (feature_histogram.hpp:486).
            Left/right masses follow this grower's partition rule; with
            cfg.forced_exact_parity the reference's own convention
            (bin == threshold goes RIGHT) is reproduced instead — see
            the deviation note in docs/COMPONENTS.md.

            Learner coverage: under feature sharding the planned feature
            lives on one shard — it computes the left-mass and the others
            receive it by a psum-select (the same owner-broadcast pattern
            as apply_split's partition).  Under voting-parallel the
            histogram cache is shard-local, so the leaf's group histogram
            is psum'd over the data axis first (forced steps are few;
            this one collective replaces the reference's reduce-scatter
            on the forced path).
            """
            from .binning import MissingType
            s = c.split_idx
            leaf = fp_leaf[s]
            feat = fp_feat[s]
            thr = fp_thr[s]
            sg, sh, cnt = c.leaf_sg[leaf], c.leaf_sh[leaf], c.leaf_cnt[leaf]
            h_leaf = c.hist[leaf]
            if voting:
                # local -> global hist (integer psum in quantized mode)
                h_leaf = (psum_quant_hist(h_leaf, axis_name, rows_global,
                                          cfg.quant_bins,
                                          hierarchical=hier_rd) if quant
                          else psum_(h_leaf))
            if feature_axis_name is not None:
                lf_raw = feat - f_offset
                owns = (lf_raw >= 0) & (lf_raw < F)
                lf = jnp.clip(lf_raw, 0, F - 1)
            else:
                owns = jnp.bool_(True)
                lf = feat
            hist_f = expand_hist(split_conv(h_leaf, cnt),
                                 sg, sh, cnt)[:, lf]          # [3, B]
            b = jnp.arange(B, dtype=jnp.int32)
            nb = num_bin[lf]
            mt = missing_type[lf]
            db = default_bin[lf]
            cat = is_cat_b[lf]
            valid = b < nb
            miss_bin = jnp.where(mt == MissingType.NAN, nb - 1,
                                 jnp.where(mt == MissingType.ZERO, db, -1))
            if cfg.forced_exact_parity:
                # reference stats convention: bins >= threshold accumulate
                # on the RIGHT (GatherInfoForThresholdNumerical's loop
                # breaks at t + offset < threshold), default/NaN bins are
                # skipped from the right pass — i.e. land LEFT
                sel_num = valid & ((b < thr) | (b == miss_bin))
            else:
                # self-consistent rule: stats follow this grower's own
                # partition (bin <= threshold goes left), avoiding the
                # reference's stats-vs-partition one-bin mismatch
                sel_num = valid & ((b <= thr) | (b == miss_bin))
            sel_cat = valid & (b == thr)   # one-hot categorical forced split
            sel = jnp.where(cat, sel_cat, sel_num)
            lsum = jnp.sum(jnp.where(sel[None, :], hist_f, 0.0), axis=1)
            if feature_axis_name is not None:
                # owner shard broadcasts its numbers (and the categorical
                # flag, which downstream bitset/default_left logic needs)
                lsum = lax.psum(jnp.where(owns, lsum, 0.0),
                                feature_axis_name)
                cat = lax.psum(jnp.where(owns, cat.astype(jnp.float32),
                                         0.0), feature_axis_name) > 0.5
            lg, lh, lc = lsum[0], lsum[1], lsum[2]
            rg, rh, rc = sg - lg, sh - lh, cnt - lc
            parent_gain = leaf_gain(sg, sh + 2 * K_EPSILON,
                                    hp.lambda_l1, hp.lambda_l2)
            gain = (leaf_gain(lg, lh + K_EPSILON, hp.lambda_l1, hp.lambda_l2)
                    + leaf_gain(rg, rh + K_EPSILON, hp.lambda_l1, hp.lambda_l2)
                    - parent_gain - hp.min_gain_to_split)
            gain = jnp.where(jnp.isnan(gain), -jnp.inf, gain)
            word = (thr // 32).astype(jnp.int32)
            bit = (thr % 32).astype(jnp.uint32)
            bitset = jnp.where(
                cat,
                jnp.zeros((MAX_CAT_WORDS,), jnp.uint32).at[word].set(
                    jnp.uint32(1) << bit),
                jnp.zeros((MAX_CAT_WORDS,), jnp.uint32))
            r = SplitResult(
                gain=gain, feature=feat, threshold=thr,
                default_left=~cat, left_sum_grad=lg, left_sum_hess=lh,
                left_count=lc, right_sum_grad=rg, right_sum_hess=rh,
                right_count=rc, is_categorical=cat, cat_bitset=bitset)
            return leaf, r

    def cond(c: Carry):
        active = jnp.arange(L) < c.tree.num_leaves
        if cegb_enabled:
            # carried scalar (computed in the body, pmax-merged across
            # feature shards there) — collectives are not allowed in a
            # while-loop cond, and a per-shard max would diverge
            best_gain = c.cegb_next_gain
        else:
            best_gain = jnp.max(jnp.where(active, c.best.gain, -jnp.inf))
        more = best_gain > 0.0
        if cfg.n_forced > 0:
            more = more | ((c.split_idx < cfg.n_forced) & ~c.forced_aborted)
        return (c.split_idx < L - 1) & more

    def apply_split(c: Carry, leaf, r: SplitResult) -> Carry:
        tree, best = c.tree, c.best
        s = c.split_idx                               # new internal node index
        new_leaf = tree.num_leaves                    # right child leaf index

        feat = r.feature
        thr = r.threshold
        dl = r.default_left
        ncat = r.is_categorical
        nbits = r.cat_bitset

        # -- record node (fix the parent's dangling child pointer first)
        parent_node = tree.leaf_parent[leaf]
        side = c.leaf_parent_side[leaf]
        has_parent = parent_node >= 0
        pn = jnp.maximum(parent_node, 0)
        left_child = jnp.where(
            has_parent & (side == 0),
            tree.left_child.at[pn].set(s), tree.left_child)
        right_child = jnp.where(
            has_parent & (side == 1),
            tree.right_child.at[pn].set(s), tree.right_child)
        lg, lh, lc = r.left_sum_grad, r.left_sum_hess, r.left_count
        rg, rh, rc = r.right_sum_grad, r.right_sum_hess, r.right_count
        parent_out = leaf_output(c.leaf_sg[leaf], c.leaf_sh[leaf],
                                 hp.lambda_l1, hp.lambda_l2, hp.max_delta_step)
        new_depth = tree.leaf_depth[leaf] + 1
        tree = tree._replace(
            split_feature=tree.split_feature.at[s].set(feat),
            threshold_bin=tree.threshold_bin.at[s].set(thr),
            default_left=tree.default_left.at[s].set(dl),
            is_categorical=tree.is_categorical.at[s].set(ncat),
            cat_bitset=tree.cat_bitset.at[s].set(nbits),
            left_child=left_child.at[s].set(~leaf),
            right_child=right_child.at[s].set(~new_leaf),
            split_gain=tree.split_gain.at[s].set(r.gain),
            internal_value=tree.internal_value.at[s].set(parent_out),
            internal_weight=tree.internal_weight.at[s].set(c.leaf_sh[leaf]),
            internal_count=tree.internal_count.at[s].set(c.leaf_cnt[leaf]),
            leaf_parent=tree.leaf_parent.at[leaf].set(s).at[new_leaf].set(s),
            leaf_depth=tree.leaf_depth.at[leaf].set(new_depth).at[new_leaf].set(new_depth),
            num_leaves=tree.num_leaves + 1,
        )
        leaf_parent_side = c.leaf_parent_side.at[leaf].set(0).at[new_leaf].set(1)

        # -- partition rows of `leaf` (reference: DataPartition::Split)
        if feature_axis_name is not None:
            # split feature is global; only the owning shard has the column
            local_f = feat - f_offset
            owned = (local_f >= 0) & (local_f < F)
            lf = jnp.clip(local_f, 0, F - 1)
            col_l = jnp.take(binned_t, feat_group[lf], axis=0).astype(jnp.int32)
            dec_l = col_l - feat_start[lf] + 1
            binf_l = jnp.where((dec_l >= 1) & (dec_l < num_bin[lf]), dec_l, 0)
            gl_local = row_goes_left(binf_l, thr, dl, ncat, nbits,
                                     missing_type[lf], default_bin[lf],
                                     num_bin[lf])
            goes_left = lax.psum(
                jnp.where(owned, gl_local.astype(jnp.float32), 0.0),
                feature_axis_name) > 0.5
        else:
            # decode the feature's bin from its (possibly bundled) column
            g = feat_group[feat]
            st = feat_start[feat]
            col = jnp.take(binned_t, g, axis=0).astype(jnp.int32)
            dec = col - st + 1
            binf = jnp.where((dec >= 1) & (dec < num_bin[feat]), dec, 0)
            goes_left = row_goes_left(binf, thr, dl, ncat, nbits,
                                      missing_type[feat], default_bin[feat],
                                      num_bin[feat])
        in_leaf = c.leaf_id == leaf
        leaf_id = jnp.where(in_leaf & ~goes_left, new_leaf, c.leaf_id)

        # -- CEGB state (reference: UpdateLeafBestSplits at the top of
        # SplitInner, serial_tree_learner.cpp:529-532 — the split feature
        # becomes globally used; in lazy mode the PARENT leaf's rows have
        # now paid for it)
        cegb_used, cegb_rows = c.cegb_used, c.cegb_rows
        if cegb_enabled:
            cegb_used = cegb_used.at[feat].set(True)
        if cfg.cegb_lazy:
            in_parent = in_leaf & (row_mask > 0)
            cegb_rows = cegb_rows.at[feat].set(cegb_rows[feat] | in_parent)

        # -- leaf sums
        leaf_sg = c.leaf_sg.at[leaf].set(lg).at[new_leaf].set(rg)
        leaf_sh = c.leaf_sh.at[leaf].set(lh).at[new_leaf].set(rh)
        leaf_cnt = c.leaf_cnt.at[leaf].set(lc).at[new_leaf].set(rc)

        # -- monotone bound propagation (reference: UpdateConstraints,
        # monotone_constraints.hpp:44 — children inherit the parent's
        # bounds, and a numerical split on a constrained feature pins
        # the midpoint of the clamped child outputs between them).
        # Computed BEFORE the histogram section: it needs only the
        # committed split's sums, and the fused megakernel's in-kernel
        # scan consumes the children's bounds.
        leaf_min, leaf_max = c.leaf_min, c.leaf_max
        if use_mc:
            p_min, p_max = leaf_min[leaf], leaf_max[leaf]
            l_out = jnp.clip(leaf_output(lg, lh, hp.lambda_l1, hp.lambda_l2,
                                         hp.max_delta_step), p_min, p_max)
            r_out = jnp.clip(leaf_output(rg, rh, hp.lambda_l1, hp.lambda_l2,
                                         hp.max_delta_step), p_min, p_max)
            mid = (l_out + r_out) * 0.5
            mc_f = mc_full[feat]      # feat is a GLOBAL feature index
            upd = (~ncat) & (mc_f != 0)
            l_min = jnp.where(upd & (mc_f < 0), jnp.maximum(p_min, mid), p_min)
            l_max = jnp.where(upd & (mc_f > 0), jnp.minimum(p_max, mid), p_max)
            r_min = jnp.where(upd & (mc_f > 0), jnp.maximum(p_min, mid), p_min)
            r_max = jnp.where(upd & (mc_f < 0), jnp.minimum(p_max, mid), p_max)
            leaf_min = leaf_min.at[leaf].set(l_min).at[new_leaf].set(r_min)
            leaf_max = leaf_max.at[leaf].set(l_max).at[new_leaf].set(r_max)
            bounds_l = (l_min, l_max)
            bounds_r = (r_min, r_max)
        else:
            bounds_l = bounds_r = None

        # -- histograms: masked pass for smaller child, subtraction for sibling
        left_smaller = lc <= rc
        small_leaf = jnp.where(left_smaller, leaf, new_leaf)
        parent_hist = c.hist[leaf]
        small_member = leaf_id == small_leaf
        fused_best = None
        if use_fused:
            # one streamed pass: smaller-child bins accumulate in VMEM,
            # the sibling derives from the parent arena in-kernel, both
            # children's per-feature-best tuples come back with the
            # smaller-child histogram (ops/fused.py)
            csums = jnp.stack([jnp.stack([lg, rg]), jnp.stack([lh, rh]),
                               jnp.stack([lc, rc])])            # [3, 2]
            f_bounds = ((jnp.stack([bounds_l[0], bounds_r[0]]),
                         jnp.stack([bounds_l[1], bounds_r[1]]))
                        if use_mc else None)
            seg1, fused_best = fused_frontier_splits(
                binned_t, fused_vals, jnp.where(small_member, 0, 1), 1,
                Bg, csums, left_smaller[None], parent_hist[None],
                num_bin, missing_type, default_bin, hp,
                quant_scales=fused_scales,
                monotone_constraints=(mc_full if use_mc else None),
                child_bounds=f_bounds,
                feat_tile=(cfg.fused_feat_tile or None),
                block_rows=(cfg.fused_block_rows or None),
                tile_rows=tile)
            small_hist = seg1[0]
        elif cfg.compact and len(caps) > 1:
            if quant:
                small_hist = hist_sync(compacted_histogram_int(
                    binned_t, q_grad, q_hess, row_mask, small_member, Bg,
                    caps, method=cfg.hist_method, levels=q_levels,
                    tile_rows=tile))
            else:
                small_hist = hist_sync(
                    compacted_histogram(binned_t, grad, hess, row_mask,
                                        small_member, Bg, caps,
                                        method=cfg.hist_method,
                                        tile_rows=tile))
        else:
            small_hist = hist_sync(hist_pass(row_mask * small_member))
        large_hist = parent_hist - small_hist
        hist_l = jnp.where(left_smaller, small_hist, large_hist)
        hist_r = jnp.where(left_smaller, large_hist, small_hist)
        hist = c.hist.at[leaf].set(hist_l).at[new_leaf].set(hist_r)

        # -- best splits for the two children.  Keys derive from NODE
        # IDENTITY (parent node, side) — not application order — so the
        # batched grower (grower_rounds.py) draws identical randomness
        # per node and the two growers stay structurally identical under
        # extra_trees / feature_fraction_bynode.
        kl = jax.random.fold_in(jax.random.fold_in(rng_key, s + 1), 0) \
            if use_rng else None
        kr = jax.random.fold_in(jax.random.fold_in(rng_key, s + 1), 1) \
            if use_rng else None
        if cegb_enabled:
            pfl = leaf_feats(hist_l, lg, lh, lc, new_depth,
                             bounds=bounds_l, key=kl)
            pfr = leaf_feats(hist_r, rg, rh, rc, new_depth,
                             bounds=bounds_r, key=kr)
            in_l = (leaf_id == leaf) & (row_mask > 0)
            in_r = (leaf_id == new_leaf) & (row_mask > 0)
            best = best.store(leaf, pfl, cegb_lazy_row(in_l, cegb_rows)) \
                       .store(new_leaf, pfr, cegb_lazy_row(in_r, cegb_rows))
        elif use_fused:
            # the kernel already scanned both children: pick the best
            # feature (ties -> smaller index, like pick_best_feature),
            # then apply the depth gate exactly where leaf_best does
            res2 = pick_fused_best(fused_best, jnp.stack([lg, rg]),
                                   jnp.stack([lh, rh]),
                                   jnp.stack([lc, rc]),
                                   feature_mask=feature_mask)
            if cfg.max_depth > 0:
                res2 = res2._replace(gain=jnp.where(
                    new_depth >= cfg.max_depth, -jnp.inf, res2.gain))
            rl = jax.tree_util.tree_map(lambda x: x[0], res2)
            rr = jax.tree_util.tree_map(lambda x: x[1], res2)
            best = best.store(leaf, rl).store(new_leaf, rr)
        else:
            rl = leaf_best(hist_l, lg, lh, lc, new_depth,
                           bounds=bounds_l, key=kl)
            rr = leaf_best(hist_r, rg, rh, rc, new_depth,
                           bounds=bounds_r, key=kr)
            best = best.store(leaf, rl).store(new_leaf, rr)

        next_gain = (cegb_global_best_gain(best, leaf_cnt, cegb_used,
                                           tree.num_leaves)
                     if cegb_enabled else jnp.float32(0.0))
        return Carry(tree, best, hist, leaf_sg, leaf_sh, leaf_cnt,
                     leaf_parent_side, leaf_id, s + 1, leaf_min, leaf_max,
                     cegb_used, cegb_rows, c.forced_aborted, next_gain)

    def body(c: Carry) -> Carry:
        leaf, r = current_selection(c)
        if cfg.n_forced == 0:
            return apply_split(c, leaf, r)
        # forced phase (reference: ForceSplits BFS,
        # serial_tree_learner.cpp:411-521): while the plan lasts, the
        # planned split replaces the best-first choice; a failed forced
        # split (non-positive gain) abandons the REST of the plan and
        # training continues best-first (abort_last_forced_split :507-519)
        # forced work (with its voting/feature-shard collectives) runs
        # ONLY while the plan lasts — the predicate is replicated, so
        # every shard takes the same branch and the collectives stay
        # matched; after the forced phase, splits pay nothing extra
        in_forced = (c.split_idx < cfg.n_forced) & ~c.forced_aborted

        def _forced_dummy(cc):
            z = jnp.float32(0.0)
            return jnp.int32(0), SplitResult(
                gain=jnp.float32(-jnp.inf), feature=jnp.int32(0),
                threshold=jnp.int32(0), default_left=jnp.bool_(True),
                left_sum_grad=z, left_sum_hess=z, left_count=z,
                right_sum_grad=z, right_sum_hess=z, right_count=z,
                is_categorical=jnp.bool_(False),
                cat_bitset=jnp.zeros((MAX_CAT_WORDS,), jnp.uint32))

        f_leaf, f_r = lax.cond(in_forced, forced_split_result,
                               _forced_dummy, c)
        ok = f_r.gain > 0.0
        apply_forced = in_forced & ok
        aborted = c.forced_aborted | (in_forced & ~ok)
        leaf = jnp.where(apply_forced, f_leaf, leaf)
        r = jax.tree_util.tree_map(
            lambda a, b_: jnp.where(apply_forced, a, b_), f_r, r)
        do_split = apply_forced | (r.gain > 0.0)
        out = lax.cond(do_split,
                       lambda cc: apply_split(cc, leaf, r),
                       lambda cc: cc, c)
        return out._replace(forced_aborted=aborted)

    init_gain = (cegb_global_best_gain(best, leaf_cnt, cegb_feat_used,
                                       tree.num_leaves)
                 if cegb_enabled else jnp.float32(0.0))
    init = Carry(tree, best, hist_cache, leaf_sg, leaf_sh, leaf_cnt,
                 leaf_parent_side, leaf_id, jnp.array(0, jnp.int32),
                 leaf_min, leaf_max, cegb_feat_used, cegb_used_rows,
                 jnp.array(False), init_gain)
    out = lax.while_loop(cond, body, init)

    # finalize leaf values (clamped to monotone bounds, reference:
    # CalculateSplittedLeafOutput USE_MC, feature_histogram.hpp:697-711).
    # Quantized mode with quant_train_renew_leaf re-fits the outputs from
    # the TRUE f32 gradient sums (ops/renew.py seam), so the committed
    # leaves carry no discretization bias — only the SPLITS came from the
    # integer histograms (reference: RenewIntGradTreeOutput lineage).
    tree = out.tree
    leaf_sh_out = out.leaf_sh
    if quant and cfg.quant_renew:
        from .ops.renew import quant_train_renew_leaf
        sg_t, sh_t = quant_train_renew_leaf(out.leaf_id, grad, hess,
                                            row_mask, L)
        sg_t = psum_(sg_t)
        sh_t = psum_(sh_t)
        lv = leaf_output(sg_t, sh_t, hp.lambda_l1, hp.lambda_l2,
                         hp.max_delta_step)
        leaf_sh_out = sh_t
    else:
        lv = leaf_output(out.leaf_sg, out.leaf_sh, hp.lambda_l1,
                         hp.lambda_l2, hp.max_delta_step)
    if use_mc:
        lv = jnp.clip(lv, out.leaf_min, out.leaf_max)
    active = jnp.arange(L) < tree.num_leaves
    tree = tree._replace(
        leaf_value=jnp.where(active, lv, 0.0),
        leaf_weight=jnp.where(active, leaf_sh_out, 0.0),
        leaf_count=jnp.where(active, out.leaf_cnt, 0.0),
    )
    if cegb_enabled:
        # hand the cross-tree CEGB state back to the caller (the reference
        # keeps it in the tree learner across Train calls)
        return tree, out.leaf_id, (out.cegb_used, out.cegb_rows)
    return tree, out.leaf_id


def predict_leaf_index_binned(tree: TreeArrays, binned_t: jax.Array,
                              meta: FeatureMeta,
                              meta_arrays: Optional[tuple] = None) -> jax.Array:
    """Route binned rows ([F, n] feature-major) to leaf indices by
    iterative traversal.

    reference: Tree::Predict inline traversal (include/LightGBM/tree.h:190).
    Vectorized: all rows advance one level per iteration; done when every
    row has reached a leaf (child pointer < 0).  ``meta_arrays`` (same
    tuple as grow_tree's) makes the bin layout a runtime input so one
    compiled traversal serves every same-shaped dataset.
    """
    n = binned_t.shape[1]
    if meta_arrays is not None:
        (num_bin, missing_type, default_bin, _is_cat,
         feat_group, feat_start) = meta_arrays
    else:
        meta = meta.resolved()
        num_bin = jnp.asarray(meta.num_bin)
        missing_type = jnp.asarray(meta.missing_type)
        default_bin = jnp.asarray(meta.default_bin)
        feat_group = jnp.asarray(meta.feat_group)
        feat_start = jnp.asarray(meta.feat_start)

    # node >= 0: internal; node < 0: leaf ~node
    def cond(state):
        node, _ = state
        return jnp.any(node >= 0)

    def body(state):
        node, it = state
        nd = jnp.maximum(node, 0)
        feat = tree.split_feature[nd]
        col = binned_t[feat_group[feat], jnp.arange(n)].astype(jnp.int32)
        dec = col - feat_start[feat] + 1
        binf = jnp.where((dec >= 1) & (dec < num_bin[feat]), dec, 0)
        gl = row_goes_left(binf, tree.threshold_bin[nd], tree.default_left[nd],
                           tree.is_categorical[nd], tree.cat_bitset[nd],
                           missing_type[feat], default_bin[feat], num_bin[feat])
        nxt = jnp.where(gl, tree.left_child[nd], tree.right_child[nd])
        node = jnp.where(node >= 0, nxt, node)
        return node, it + 1

    has_split = tree.num_leaves > 1
    init_node = jnp.broadcast_to(
        jnp.where(has_split, 0, -1).astype(jnp.int32), (n,))
    node, _ = lax.while_loop(cond, body, (init_node, jnp.array(0)))
    return ~node  # leaf index


def predict_tree_binned(tree: TreeArrays, binned_t: jax.Array,
                        meta: FeatureMeta,
                        meta_arrays: Optional[tuple] = None) -> jax.Array:
    leaf = predict_leaf_index_binned(tree, binned_t, meta, meta_arrays)
    return take_from_table(tree.leaf_value, leaf)
