"""Text dataset parsing: CSV/TSV/LibSVM auto-detection + sidecar files.

reference: src/io/parser.cpp (Parser::CreateParser format auto-detect),
src/io/metadata.cpp (LoadWeights/LoadQueryBoundaries from .weight/.query
sidecar files).  Host-side; the fast path uses pandas' C engine.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def detect_format(path: str, num_probe_lines: int = 32) -> Tuple[str, bool]:
    """Return (format, has_header); format in {'csv', 'tsv', 'libsvm'}."""
    lines = []
    with open(path, "r") as fh:
        for _ in range(num_probe_lines):
            ln = fh.readline()
            if not ln:
                break
            if ln.strip():
                lines.append(ln.rstrip("\n"))
    if not lines:
        raise ValueError(f"empty data file: {path}")

    probe = lines[min(1, len(lines) - 1)]
    tokens = probe.replace("\t", " ").replace(",", " ").split()
    is_libsvm = any(":" in t for t in tokens[1:])
    if is_libsvm:
        return "libsvm", False
    fmt = "tsv" if "\t" in probe else "csv"
    # header detection: first line tokens are non-numeric
    first = lines[0].split("\t" if fmt == "tsv" else ",")
    def _is_num(s: str) -> bool:
        try:
            float(s)
            return True
        except ValueError:
            return s.strip().lower() in ("nan", "na", "")
    has_header = not all(_is_num(t) for t in first)
    return fmt, has_header


def load_text_dataset(path: str, dataset) -> np.ndarray:
    """Load a text file into a dense float matrix; sets label/weight/group on
    ``dataset`` from the label column and sidecar files.  Returns features."""
    params = dataset.params
    fmt, has_header = detect_format(path)
    header_override = params.get("header", None)
    if header_override is not None:
        has_header = bool(header_override)

    if fmt == "libsvm":
        X, y = _load_libsvm(path)
        label_idx = 0
        data = X
        labels = y
        names = None
    else:
        import pandas as pd
        sep = "\t" if fmt == "tsv" else ","
        df = pd.read_csv(path, sep=sep, header=0 if has_header else None,
                         na_values=["nan", "NA", "na", ""])
        names = [str(c) for c in df.columns] if has_header else None
        mat = df.to_numpy(dtype=np.float64)
        label_spec = params.get("label_column", params.get("label", 0))
        label_idx = _resolve_column(label_spec, names, default=0)
        labels = mat[:, label_idx].astype(np.float32) if label_idx is not None else None
        keep = [i for i in range(mat.shape[1]) if i != label_idx]
        ignore = params.get("ignore_column", params.get("ignore_feature"))
        if ignore:
            ignored = {_resolve_column(c, names) for c in str(ignore).split(",")}
            keep = [i for i in keep if i not in ignored]
        data = mat[:, keep]
        if names:
            dataset.feature_names = [names[i] for i in keep]

    if labels is not None and dataset.metadata.label is None:
        dataset.metadata.label = labels

    wfile = path + ".weight"
    if os.path.exists(wfile) and dataset.metadata.weight is None:
        dataset.metadata.weight = np.loadtxt(wfile, dtype=np.float32).reshape(-1)
    qfile = path + ".query"
    if os.path.exists(qfile) and dataset.metadata.query_boundaries is None:
        group = np.loadtxt(qfile, dtype=np.int64).reshape(-1)
        dataset.metadata.set_group(group)
    ifile = path + ".init"
    if os.path.exists(ifile) and dataset.metadata.init_score is None:
        dataset.metadata.init_score = np.loadtxt(ifile, dtype=np.float64)
    return data


def _load_libsvm(path: str) -> Tuple[np.ndarray, np.ndarray]:
    labels = []
    rows = []
    max_feat = -1
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            parts = ln.split()
            labels.append(float(parts[0]))
            row = {}
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                k, v = tok.split(":", 1)
                idx = int(k)
                row[idx] = float(v)
                max_feat = max(max_feat, idx)
            rows.append(row)
    X = np.zeros((len(rows), max_feat + 1), dtype=np.float64)
    for i, row in enumerate(rows):
        for k, v in row.items():
            X[i, k] = v
    return X, np.asarray(labels, dtype=np.float32)


def _resolve_column(spec, names, default=None):
    if spec is None:
        return default
    s = str(spec)
    if s.startswith("name:"):
        nm = s[5:]
        if names and nm in names:
            return names.index(nm)
        raise ValueError(f"unknown column {nm!r}")
    return int(s)
