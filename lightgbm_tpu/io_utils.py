"""Text dataset parsing: CSV/TSV/LibSVM auto-detection + sidecar files.

reference: src/io/parser.cpp (Parser::CreateParser format auto-detect),
src/io/metadata.cpp (LoadWeights/LoadQueryBoundaries from .weight/.query
sidecar files).  Host-side; the fast path uses pandas' C engine.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def detect_format(path: str, num_probe_lines: int = 32) -> Tuple[str, bool]:
    """Return (format, has_header); format in {'csv', 'tsv', 'libsvm'}."""
    from .utils.file_io import open_file
    lines = []
    with open_file(path, "r") as fh:
        for _ in range(num_probe_lines):
            ln = fh.readline()
            if not ln:
                break
            if ln.strip():
                lines.append(ln.rstrip("\n"))
    if not lines:
        raise ValueError(f"empty data file: {path}")

    probe = lines[min(1, len(lines) - 1)]
    tokens = probe.replace("\t", " ").replace(",", " ").split()
    is_libsvm = any(":" in t for t in tokens[1:])
    if is_libsvm:
        return "libsvm", False
    fmt = "tsv" if "\t" in probe else "csv"
    # header detection: first line tokens are non-numeric
    first = lines[0].split("\t" if fmt == "tsv" else ",")
    def _is_num(s: str) -> bool:
        try:
            float(s)
            return True
        except ValueError:
            return s.strip().lower() in ("nan", "na", "")
    has_header = not all(_is_num(t) for t in first)
    return fmt, has_header



def _resolve_label_and_columns(params, names, n_cols, dataset=None):
    """Label / ignore-column / feature-name resolution shared by the
    one-shot and two-round text loaders (the rules must never diverge)."""
    label_spec = params.get("label_column", params.get("label", 0))
    label_idx = _resolve_column(label_spec, names, default=0)
    keep = [i for i in range(n_cols) if i != label_idx]
    ignore = params.get("ignore_column", params.get("ignore_feature"))
    if ignore:
        ignored = {_resolve_column(c, names) for c in str(ignore).split(",")}
        keep = [i for i in keep if i not in ignored]
    if dataset is not None:
        fn_param = getattr(dataset, "_feature_name_param", "auto")
        if fn_param not in ("auto", None):
            dataset.feature_names = list(fn_param)
        elif names:
            dataset.feature_names = [names[i] for i in keep]
    return label_idx, keep


def load_text_dataset(path: str, dataset) -> np.ndarray:
    """Load a text file into a dense float matrix; sets label/weight/group on
    ``dataset`` from the label column and sidecar files.  Returns features."""
    params = dataset.params
    fmt, has_header = detect_format(path)
    if params.get("header", None) is not None:
        has_header = _param_bool(params, "header")

    if fmt == "libsvm":
        X, y = _load_libsvm(path)
        label_idx = 0
        data = X
        labels = y
        names = None
    else:
        import pandas as pd
        sep = "\t" if fmt == "tsv" else ","
        df = pd.read_csv(path, sep=sep, header=0 if has_header else None,
                         na_values=["nan", "NA", "na", ""])
        names = [str(c) for c in df.columns] if has_header else None
        mat = df.to_numpy(dtype=np.float64)
        label_idx, keep = _resolve_label_and_columns(
            params, names, mat.shape[1], dataset)
        labels = mat[:, label_idx].astype(np.float32) if label_idx is not None else None
        data = mat[:, keep]

    if labels is not None and dataset.metadata.label is None:
        dataset.metadata.label = labels

    wfile = path + ".weight"
    if os.path.exists(wfile) and dataset.metadata.weight is None:
        dataset.metadata.weight = np.loadtxt(wfile, dtype=np.float32).reshape(-1)
    qfile = path + ".query"
    if os.path.exists(qfile) and dataset.metadata.query_boundaries is None:
        group = np.loadtxt(qfile, dtype=np.int64).reshape(-1)
        dataset.metadata.set_group(group)
    ifile = path + ".init"
    if os.path.exists(ifile) and dataset.metadata.init_score is None:
        dataset.metadata.init_score = np.loadtxt(ifile, dtype=np.float64)
    return data


def load_prediction_file(path: str, n_model_features: int,
                         params: dict) -> np.ndarray:
    """Feature matrix for PREDICTION from a text file.

    reference: the Predictor's parser is created with the model's feature
    count, so a data file WITHOUT a label column (width == num_features)
    predicts directly while a training-style file (width == num_features+1)
    has its label column dropped (src/application/predictor.hpp parser
    setup).  LibSVM files always carry the label first.
    """
    from .dataset import _BINARY_MAGIC
    from .utils.file_io import open_file
    try:
        with open_file(path, "rb") as fh:
            is_bin = fh.read(len(_BINARY_MAGIC)) == _BINARY_MAGIC
    except OSError:
        is_bin = False
    if is_bin:
        # a binned cache carries no raw features to predict from
        # (reference: the Predictor's parser rejects it with this message)
        from .config import LightGBMError
        raise LightGBMError("Unknown format of training data")
    fmt, has_header = detect_format(path)
    if params.get("header", None) is not None:
        has_header = _param_bool(params, "header")
    if fmt == "libsvm":
        X, _ = _load_libsvm(path)
        if X.shape[1] < n_model_features:
            X = np.pad(X, ((0, 0), (0, n_model_features - X.shape[1])))
        return X
    import pandas as pd
    sep = "\t" if fmt == "tsv" else ","
    df = pd.read_csv(path, sep=sep, header=0 if has_header else None,
                     na_values=["nan", "NA", "na", ""])
    names = [str(c) for c in df.columns] if has_header else None
    mat = df.to_numpy(dtype=np.float64)
    if mat.shape[1] == n_model_features:
        return mat
    label_idx, keep = _resolve_label_and_columns(params, names,
                                                 mat.shape[1])
    return mat[:, keep]


def _load_libsvm(path: str) -> Tuple[np.ndarray, np.ndarray]:
    from .utils.file_io import open_file
    labels = []
    rows = []
    max_feat = -1
    with open_file(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            parts = ln.split()
            labels.append(float(parts[0]))
            row = {}
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                k, v = tok.split(":", 1)
                idx = int(k)
                row[idx] = float(v)
                max_feat = max(max_feat, idx)
            rows.append(row)
    X = np.zeros((len(rows), max_feat + 1), dtype=np.float64)
    for i, row in enumerate(rows):
        for k, v in row.items():
            X[i, k] = v
    return X, np.asarray(labels, dtype=np.float32)


def _resolve_column(spec, names, default=None):
    if spec is None:
        return default
    s = str(spec)
    if s.startswith("name:"):
        nm = s[5:]
        if names and nm in names:
            return names.index(nm)
        raise ValueError(f"unknown column {nm!r}")
    return int(s)


def _param_bool(params: dict, key: str, default: bool = False) -> bool:
    """Tolerant bool param: accepts real bools and 'true'/'false' strings
    (the C-API passes k=v strings, reference Config::Str2Map semantics)."""
    v = params.get(key, default)
    if isinstance(v, str):
        return v.strip().lower() not in ("false", "0", "no", "")
    return bool(v)


def load_text_dataset_two_round(path: str, dataset,
                                chunk_rows: int = 200_000) -> None:
    """Two-pass big-file loading: no full in-memory feature matrix.

    reference: ``two_round`` config (config.h:570-574) switches
    DatasetLoader to SampleTextDataFromFile (pass 1: row count + uniform
    sample) followed by ExtractFeaturesFromFile (pass 2: push rows through
    the decided bins), dataset_loader.cpp:775,1101.  Here pass 1 streams
    pandas chunks collecting labels + a vectorized reservoir sample; pass 2
    re-reads chunks and bins them into the preallocated matrix via
    ``_bin_block``.  Validation sets (``reference=``) reuse the reference
    dataset's mappers and EFB layout and skip the sampling entirely
    (LoadFromFileAlignWithOtherDataset, dataset_loader.cpp:229).
    CSV/TSV only — LibSVM files take the one-shot path (they parse sparse
    and small).  Fills ``dataset`` in place and marks it constructed.
    All reads go through the pluggable file seam (utils/file_io.py).
    """
    import pandas as pd

    from .utils.file_io import exists as fs_exists, open_file

    params = dataset.params
    fmt, has_header = detect_format(path)
    header_override = params.get("header", None)
    if header_override is not None:
        has_header = _param_bool(params, "header")
    if fmt == "libsvm":
        data = load_text_dataset(path, dataset)
        dataset.raw_data = data
        dataset._construct_inner()
        return

    sep = "\t" if fmt == "tsv" else ","
    sample_cnt = int(params.get("bin_construct_sample_cnt", 200000))
    seed = int(params.get("data_random_seed", 1))
    rng = np.random.RandomState(seed)
    use_reference = dataset.reference is not None

    def chunks():
        with open_file(path, "r") as fh:
            for chunk in pd.read_csv(fh, sep=sep,
                                     header=0 if has_header else None,
                                     na_values=["nan", "NA", "na", ""],
                                     chunksize=chunk_rows):
                yield chunk

    # ---- pass 1: row count, labels, reservoir sample -----------------------
    names = None
    labels = []
    reservoir = None          # [sample_cnt, F] float64
    n_seen = 0
    label_idx = None
    keep = None
    for chunk in chunks():
        if names is None and has_header:
            names = [str(c) for c in chunk.columns]
        mat = chunk.to_numpy(dtype=np.float64)
        if label_idx is None:
            label_idx, keep = _resolve_label_and_columns(
                params, names, mat.shape[1], dataset)
        if label_idx is not None:
            labels.append(mat[:, label_idx].astype(np.float32))
        feats = mat[:, keep]
        if not use_reference:
            if reservoir is None:
                reservoir = np.empty((sample_cnt, feats.shape[1]),
                                     np.float64)
            k = len(feats)
            if n_seen < sample_cnt:
                take = min(sample_cnt - n_seen, k)
                reservoir[n_seen:n_seen + take] = feats[:take]
                rest = np.arange(take, k)
            else:
                rest = np.arange(k)
            if len(rest):
                # vectorized reservoir acceptance (Vitter's R): row j_global
                # replaces a random slot with prob sample_cnt/(j_global+1)
                j = n_seen + rest
                r = (rng.random_sample(len(rest)) * (j + 1)).astype(np.int64)
                acc = r < sample_cnt
                reservoir[r[acc]] = feats[rest[acc]]
        n_seen += len(feats)
    n = n_seen
    if n == 0 or reservoir is None:
        raise ValueError(f"no data rows found in {path!r}")

    # ---- decide bins + EFB layout ------------------------------------------
    dataset.num_data = n
    if use_reference:
        ref = dataset.reference.construct()
        dataset.num_total_features = ref.num_total_features
        dataset.bin_mappers = ref.bin_mappers
        dataset.used_features = ref.used_features
        dataset.feature_names = ref.feature_names
        dataset.feat_group = ref.feat_group
        dataset.feat_start = ref.feat_start
        dataset.num_groups = ref.num_groups
        dataset._group_size = ref._group_size
        dataset.group_num_bin = ref.group_num_bin
        dataset.max_group_bin = ref.max_group_bin
    else:
        sample = reservoir[:min(sample_cnt, n)]
        dataset.num_total_features = sample.shape[1]
        if not dataset.feature_names:
            dataset.feature_names = [
                f"Column_{i}" for i in range(dataset.num_total_features)]
        categorical = dataset._resolve_categorical()
        dataset._fit_bin_mappers(sample, None, np.arange(len(sample)),
                                 categorical)
    dtype = np.uint8 if dataset.max_group_bin <= 256 else np.uint16
    dataset.binned = np.zeros((n, dataset.num_groups), dtype=dtype)

    # ---- pass 2: bin the rows chunk by chunk -------------------------------
    lo = 0
    for chunk in chunks():
        mat = chunk.to_numpy(dtype=np.float64)
        feats = mat[:, keep]
        dataset._bin_block(feats, None, dataset.binned[lo:lo + len(feats)])
        lo += len(feats)
    assert lo == n, (lo, n)

    if labels and dataset.metadata.label is None:
        dataset.metadata.label = np.concatenate(labels)
    for suffix, attr in ((".weight", "weight"), (".init", "init_score")):
        f = path + suffix
        if fs_exists(f) and getattr(dataset.metadata, attr) is None:
            with open_file(f) as fh:
                setattr(dataset.metadata, attr,
                        np.loadtxt(fh, dtype=np.float64))
    qfile = path + ".query"
    if fs_exists(qfile) and dataset.metadata.query_boundaries is None:
        with open_file(qfile) as fh:
            dataset.metadata.set_group(
                np.loadtxt(fh, dtype=np.int64).reshape(-1))
    if dataset.metadata.weight is not None:
        dataset.metadata.weight = dataset.metadata.weight.astype(np.float32)
    dataset.metadata.check(n)
    if dataset.metadata.label is None:
        dataset.metadata.label = np.zeros(n, np.float32)
    dataset.constructed = True
    if dataset.free_raw_data:
        dataset.raw_data = None
