"""Automated bottleneck diagnosis: join measured and planner-predicted
signals into RANKED verdicts with the evidence behind each.

ROADMAP item 3 says "stop trusting analytic models alone"; PR 6-10 left
the raw material everywhere — measured MFU/HBM-BW tables
(obs/devprof.py), per-tier payload accounting (``ops/planner.py``
``plan_collectives``), compile-cache warmth, streaming
``overlap_efficiency`` (tools/stream_probe.py), straggler skew
(obs/aggregate.py).  This module is the judgment layer: one pure
function from a flat signal dict to an ordered list of verdicts, so the
same rules serve the ``obs_doctor`` CLI, the journaled bench stage, and
the tests that inject each bottleneck.

Verdict taxonomy (docs/OBSERVABILITY.md):

- ``dcn-bound``        — the slow-tier wire time is a material fraction
                         of the iteration under the planner's link model;
- ``compile-bound``    — XLA compilation dominates wall-clock (cold
                         cache the usual suspect);
- ``input-bound``      — streaming is active but the block pump fails to
                         hide device_put behind compute;
- ``straggler``        — one slice's iterations run materially slower
                         than its peers' (names the slice);
- ``contention``       — co-resident train and serve are fighting over
                         the same devices: training has been throttled /
                         paused by brownout signals while serving p99
                         climbed (evidence: the residency-ledger lease
                         table plus the throttle/pause event counts —
                         coresident/scheduler.py);
- ``kernel-underutilized`` — none of the above, yet measured MFU says
                         the chip is mostly idle (the per-level work is
                         just too small: batch models or fuse more);
- ``healthy``          — nothing fired.

Each verdict carries ``score`` in [0, 1] (comparable across verdicts:
the ranking IS the diagnosis), a one-line human summary, and the raw
numbers as ``evidence``.  ``collect_signals`` assembles the dict from
the live registry and/or a bench journal; pure stdlib.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

# tunable rule thresholds, named so tests and docs can cite them
DCN_FRACTION_MATERIAL = 0.25      # DCN seconds / iteration seconds
COMPILE_FRACTION_MATERIAL = 0.4   # compile / (compile + train) wall
OVERLAP_EFFICIENCY_FLOOR = 1.05   # pump gain below this = no overlap
STRAGGLER_SKEW_MATERIAL = 1.15    # slowest / fastest slice
MFU_HEALTHY_FLOOR = 0.01          # below this the chip is mostly idle
CONTENTION_EVENTS_MATERIAL = 1    # >= this many throttles+pauses fires
BIN_FRACTION_MATERIAL = 0.5       # bin_seconds / train_seconds


@dataclass
class Verdict:
    name: str
    score: float                  # 0..1, comparable across verdicts
    summary: str
    evidence: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "score": round(self.score, 4),
                "summary": self.summary, "evidence": self.evidence}


def _num(v, default=0.0):
    try:
        if isinstance(v, bool):
            return float(v)
        return float(v)
    except (TypeError, ValueError):
        return default


def collect_signals(registry=None, stages: Optional[dict] = None) -> dict:
    """Assemble the diagnoser's flat signal dict from the live process
    registry and/or a bench journal's banked stages (either may be
    None/empty; absent signals simply don't fire their rules)."""
    sig: dict = {}
    if registry is None:
        from .metrics import global_registry as registry
    d = registry.to_dict()
    g = d.get("gauges", {})
    for k in ("train_ici_payload_bytes", "train_dcn_payload_bytes",
              "train_num_slices", "train_hier_reduce",
              "train_trees_per_sec", "train_iter_seconds",
              "compile_cache_warm", "pod_straggler_skew",
              "pod_straggler_slice", "pod_ici_payload_bytes",
              "pod_dcn_payload_bytes", "pod_mfu", "mfu_measured_best",
              "host_rss_peak_bytes", "trace_events_dropped"):
        if k in g:
            sig[k] = g[k]
    c = d.get("counters", {})
    sig["slo_breach_total"] = sum(
        v for k, v in c.items() if k.startswith("slo_breach_total"))
    sig["stream_blocks_total"] = c.get("stream_blocks_total", 0)
    # co-residency contention signals: brownout event counters, the
    # residency ledger's lease accounting, and the worst watched p99
    sig["coresident_throttle_total"] = sum(
        v for k, v in c.items()
        if k.startswith("coresident_throttle_total"))
    sig["coresident_pause_total"] = sum(
        v for k, v in c.items() if k.startswith("coresident_pause_total"))
    for k, v in g.items():
        if k.startswith("ledger_leased_bytes"):
            sig["ledger_leased_bytes"] = sig.get("ledger_leased_bytes",
                                                 0.0) + _num(v)
    if "ledger_available_bytes" in g:
        sig["ledger_available_bytes"] = g["ledger_available_bytes"]
    p99s = [_num(v) for k, v in g.items()
            if k.startswith("watchdog_p99_")]
    if p99s:
        sig["watchdog_p99_ms_max"] = max(p99s)
    try:
        from ..ops.planner import active_ledger
        lg = active_ledger()
        if lg is not None:
            sig["ledger_lease_table"] = lg.table()
    except Exception:  # noqa: BLE001 — forensics only
        pass
    # bench journal stages refine / supply the workload-scale numbers
    stages = stages or {}
    full = None
    for key, st in stages.items():
        if key == "full" or str(key).startswith("full@"):
            full = st
    full = full or stages.get("smoke")
    if isinstance(full, dict):
        sig.setdefault("sec_per_tree", _num(full.get("sec_per_tree")))
        sig.setdefault("trees", _num(full.get("trees")))
        sig.setdefault("compile_seconds",
                       _num(full.get("compile_seconds")))
        sig.setdefault("train_seconds", _num(full.get("value")))
        cc = full.get("compile_cache")
        if isinstance(cc, dict):
            sig.setdefault("compile_cache_warm",
                           1.0 if cc.get("warm_start") else 0.0)
        mm = full.get("mfu_measured")
        if isinstance(mm, dict):
            best = max((v.get("mfu", 0.0) for v in mm.values()
                        if isinstance(v, dict)), default=0.0)
            if best:
                sig.setdefault("mfu_measured_best", best)
        sig.setdefault("bin_seconds", _num(full.get("bin_seconds")))
        sig.setdefault("bin_rows_per_sec",
                       _num(full.get("bin_rows_per_sec")))
    ip = stages.get("ingest_probe")
    if isinstance(ip, dict):
        sig.setdefault("ingest_kernel_speedup",
                       _num(ip.get("kernel_speedup_vs_host")))
    sp = stages.get("stream_probe")
    if isinstance(sp, dict):
        sig.setdefault("overlap_efficiency",
                       _num(sp.get("overlap_efficiency"), 1.0))
    cp = stages.get("collective_probe")
    if isinstance(cp, dict):
        sig.setdefault("train_ici_payload_bytes", _num(cp.get("ici_bytes")))
        sig.setdefault("train_dcn_payload_bytes", _num(cp.get("dcn_bytes")))
    # planner link speeds (the model the DCN rule prices bytes with)
    try:
        from ..ops.planner import (DEFAULT_DCN_GBPS, DEFAULT_ICI_GBPS,
                                   _env_gbps)
        sig.setdefault("ici_gbps",
                       _env_gbps("LGBM_TPU_ICI_GBPS", DEFAULT_ICI_GBPS))
        sig.setdefault("dcn_gbps",
                       _env_gbps("LGBM_TPU_DCN_GBPS", DEFAULT_DCN_GBPS))
    except Exception:  # noqa: BLE001
        sig.setdefault("ici_gbps", 100.0)
        sig.setdefault("dcn_gbps", 6.25)
    # the autotuner's most recent election: the kernel-underutilized
    # verdict names the measured-best variant as its concrete cure
    try:
        from ..ops.planner import autotune_last
        al = autotune_last()
        if al:
            sig["autotune_last"] = al
    except Exception:  # noqa: BLE001
        pass
    # the ingest election's last outcome (ops/ingest.py): the input-bound
    # verdict names whether binning ran on the kernel or fell back + why
    try:
        from ..ops.ingest import ingest_last
        il = ingest_last()
        if il:
            sig["ingest_last"] = il
    except Exception:  # noqa: BLE001
        pass
    return sig


def diagnose(signals: dict) -> List[Verdict]:
    """Rank every verdict whose rule fires; ``healthy`` alone when none
    do.  Pure function of the signal dict — the whole test surface."""
    out: List[Verdict] = []
    s = signals

    # --- dcn-bound: price the DCN payload with the per-tier link model
    dcn_bytes = _num(s.get("train_dcn_payload_bytes"))
    num_slices = _num(s.get("train_num_slices"), 1.0)
    iter_s = _num(s.get("train_iter_seconds")) or \
        _num(s.get("sec_per_tree"))
    if dcn_bytes > 0 and num_slices > 1 and iter_s > 0:
        dcn_s = dcn_bytes / (_num(s.get("dcn_gbps"), 6.25) * 1e9)
        frac = dcn_s / iter_s
        if frac >= DCN_FRACTION_MATERIAL:
            out.append(Verdict(
                "dcn-bound", min(frac, 1.0),
                f"DCN wire time ~{frac:.0%} of each iteration "
                f"({dcn_bytes / 1e6:.1f} MB/sync at "
                f"{_num(s.get('dcn_gbps'), 6.25):g} GB/s across "
                f"{int(num_slices)} slices) — elect voting-parallel or "
                "shrink the cross-slice payload",
                {"dcn_payload_bytes": dcn_bytes,
                 "dcn_gbps": _num(s.get("dcn_gbps"), 6.25),
                 "dcn_seconds_per_sync": dcn_s,
                 "iter_seconds": iter_s, "fraction": round(frac, 4),
                 "num_slices": int(num_slices),
                 "hier_reduce": bool(_num(s.get("train_hier_reduce")))}))

    # --- compile-bound: one-time XLA compile vs the steady-state train
    comp = _num(s.get("compile_seconds"))
    train = _num(s.get("train_seconds"))
    if comp > 0 and (comp + train) > 0:
        frac = comp / (comp + train)
        warm = bool(_num(s.get("compile_cache_warm")))
        if frac >= COMPILE_FRACTION_MATERIAL:
            out.append(Verdict(
                "compile-bound", min(frac, 1.0),
                f"XLA compilation is {frac:.0%} of wall-clock "
                f"({comp:.1f}s compile vs {train:.1f}s train); compile "
                f"cache {'WARM — shapes are churning' if warm else 'COLD'}"
                " — set LGBM_TPU_COMPILE_CACHE / stop varying shapes",
                {"compile_seconds": comp, "train_seconds": train,
                 "fraction": round(frac, 4),
                 "compile_cache_warm": warm}))

    # --- input/stream-bound: the pump isn't hiding host->device puts
    streaming = _num(s.get("stream_blocks_total")) > 0 or \
        "overlap_efficiency" in s
    if streaming and "overlap_efficiency" in s:
        eff = _num(s.get("overlap_efficiency"), 1.0)
        if eff < OVERLAP_EFFICIENCY_FLOOR:
            score = min(max((OVERLAP_EFFICIENCY_FLOOR - eff) * 4 + 0.4,
                            0.0), 1.0)
            out.append(Verdict(
                "input-bound", score,
                f"block pump overlap efficiency {eff:.2f} (< "
                f"{OVERLAP_EFFICIENCY_FLOOR}): device compute is waiting "
                "on host reads/puts — deepen prefetch, grow blocks, or "
                "speed the spill store",
                {"overlap_efficiency": eff,
                 "stream_blocks_total":
                     int(_num(s.get("stream_blocks_total"))),
                 "floor": OVERLAP_EFFICIENCY_FLOOR}))

    # --- input-bound (ingest flavor): binning dominates training wall
    # clock — the verdict names its cure: whether the device ingest
    # kernel (ops/ingest.py) was elected or fell back, and why
    bin_s = _num(s.get("bin_seconds"))
    train_s = _num(s.get("train_seconds"))
    if bin_s > 0 and train_s > 0:
        frac = bin_s / train_s
        if frac >= BIN_FRACTION_MATERIAL:
            il = s.get("ingest_last")
            ev = {"bin_seconds": bin_s, "train_seconds": train_s,
                  "fraction": round(frac, 4),
                  "threshold": BIN_FRACTION_MATERIAL}
            if _num(s.get("bin_rows_per_sec")):
                ev["bin_rows_per_sec"] = _num(s.get("bin_rows_per_sec"))
            cure = ("route construction through the device ingest kernel "
                    "(ops/ingest.py)")
            if isinstance(il, dict) and il:
                ev["ingest_path"] = il.get("path")
                if il.get("path") == "kernel":
                    ev["ingest_elected_by"] = il.get("elected_by")
                    cure = ("the ingest kernel DID run (elected_by="
                            f"{il.get('elected_by')}) and binning still "
                            "dominates: grow the chunk "
                            "(LGBM_TPU_INGEST_CHUNK) or check H2D "
                            "bandwidth (ingest.block_put spans)")
                else:
                    ev["ingest_fallback_reason"] = il.get("reason")
                    cure = ("ingest fell back to host NumPy binning ("
                            f"{il.get('reason', 'no election ran')}) — "
                            "fix that, or pin LGBM_TPU_INGEST_KERNEL to "
                            "bisect the election")
            out.append(Verdict(
                "input-bound", min(0.3 + 0.4 * frac, 1.0),
                f"Dataset binning took {bin_s:.1f}s against {train_s:.1f}"
                f"s of training ({frac:.0%}): construction is the "
                f"bottleneck — {cure}",
                ev))

    # --- straggler: one slice materially slower than its peers
    skew = _num(s.get("pod_straggler_skew"), 1.0)
    if skew >= STRAGGLER_SKEW_MATERIAL:
        slice_k = int(_num(s.get("pod_straggler_slice")))
        out.append(Verdict(
            "straggler", min((skew - 1.0), 1.0),
            f"slice {slice_k} runs {skew:.2f}x slower than the fastest "
            "slice — check its hosts (thermal, neighbors, failing "
            "links); elastic shrink-rejoin can drop it",
            {"straggler_slice": slice_k, "straggler_skew": skew,
             "threshold": STRAGGLER_SKEW_MATERIAL}))

    # --- contention: co-resident planes fighting over the same devices
    thr = _num(s.get("coresident_throttle_total"))
    pauses = _num(s.get("coresident_pause_total"))
    if thr + pauses >= CONTENTION_EVENTS_MATERIAL:
        ev = {"coresident_throttle_total": int(thr),
              "coresident_pause_total": int(pauses)}
        for k in ("ledger_leased_bytes", "ledger_available_bytes",
                  "watchdog_p99_ms_max"):
            if k in s:
                ev[k] = s[k]
        table = s.get("ledger_lease_table")
        if isinstance(table, list):
            ev["ledger_lease_table"] = table
        # pauses weigh double: a pause means the brownout persisted past
        # throttling — deeper contention than a transient spike
        out.append(Verdict(
            "contention",
            min(0.4 + 0.05 * (thr + 2.0 * pauses), 0.9),
            f"co-resident training was throttled {int(thr)}x and paused "
            f"{int(pauses)}x by serving brownout signals — train and "
            "serve are contending for the same devices; shrink the "
            "training chunk cap / lease, move the refresh off-peak, or "
            "give serving its own devices",
            ev))

    # --- kernel-underutilized: nothing specific, chip still idle
    mfu = s.get("mfu_measured_best")
    if mfu is not None and _num(mfu) < MFU_HEALTHY_FLOOR and not out:
        mfu = _num(mfu)
        ev = {"mfu_measured_best": mfu, "floor": MFU_HEALTHY_FLOOR}
        cure = ("batch boosters over a model axis or widen the fused "
                "frontier")
        al = s.get("autotune_last")
        if isinstance(al, dict) and al.get("measured_variant"):
            # the autotuner already knows the concrete cure: the variant
            # its stopwatch ranked fastest for this shape-bucket
            ev["measured_best_variant"] = al["measured_variant"]
            ev["elected_variant"] = al.get("elected_variant")
            ev["autotune_key"] = al.get("key")
            if al["measured_variant"] != al.get("elected_variant"):
                cure = (f"run the measured-best kernel variant "
                        f"{al['measured_variant']!r} (autotuner store, "
                        f"bucket {al.get('key')}) — the election "
                        f"declined it, so fix the context that blocked "
                        "it (VMEM budget / hist_method force / "
                        "LGBM_TPU_FUSED)")
        out.append(Verdict(
            "kernel-underutilized",
            min(0.3 + (MFU_HEALTHY_FLOOR - mfu) / MFU_HEALTHY_FLOOR * 0.4,
                0.7),
            f"best measured kernel MFU {mfu:.5f} (< {MFU_HEALTHY_FLOOR})"
            " with no specific bottleneck: per-level work is too small "
            f"for the MXU — {cure}",
            ev))

    if not out:
        return [Verdict("healthy", 1.0,
                        "no rule fired: no dominant bottleneck in the "
                        "measured signals", {})]
    out.sort(key=lambda v: v.score, reverse=True)
    return out


def diagnosis_summary(verdicts: List[Verdict],
                      signals: Optional[dict] = None) -> dict:
    """JSON-ready report (the bench stage / CLI last-line shape)."""
    out = {
        "top_verdict": verdicts[0].name if verdicts else "healthy",
        "verdicts": [v.to_dict() for v in verdicts],
    }
    if signals is not None:
        out["signals"] = {k: v for k, v in sorted(signals.items())
                          if isinstance(v, (int, float, str, bool))}
    return out


def run_doctor(registry=None, stages: Optional[dict] = None) -> dict:
    """collect -> diagnose -> summarize in one call (bench stage +
    tools/obs_doctor.py entry point)."""
    signals = collect_signals(registry=registry, stages=stages)
    return diagnosis_summary(diagnose(signals), signals)
