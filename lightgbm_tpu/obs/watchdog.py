"""SLO watchdog: a heartbeat-based stall/SLO sentry over the long-lived
loops (engine iterations, the streaming block pump, collective attempts,
the serving batcher).

The passive plane (spans, metrics) records what happened; this module
WATCHES it happen and raises the alarm when it stops or degrades:

- **heartbeats** — instrumented loops call ``beat(name[, count])`` (one
  dict store, always cheap).  A heartbeat registered for watching
  (``watch_heartbeat``) that goes stale past its threshold is a
  ``stall:<name>`` breach — the 3am "training wedged silently" case.
  Registration is scoped to the activity: the engine registers its beat
  on loop entry and unregisters on exit, so a heartbeat that stopped
  because training FINISHED never breaches.
- **rate floors** — a counted heartbeat (``beat(name, count=...)``)
  checked against a floor (trees/sec SLO): the watchdog differentiates
  the count between checks, so a loop that still beats but crawls
  breaches ``slo:<name>``.
- **latency ceilings** — ``watch_histogram_p99`` holds a latency
  histogram's estimated p99 (from its cumulative buckets) to a ceiling:
  the serving-p99 SLO.
- **model freshness** — ``watch_freshness``/``mark_fresh`` hold a
  deployed model's age (seconds since its last promotion,
  ``model_age_seconds`` gauge) to a ceiling
  (``LIGHTGBM_TPU_SLO_MODEL_AGE_S``): the lifecycle's "never serve a
  stale model" SLO (docs/LIFECYCLE.md).
- **availability** — ``watch_availability`` holds a served model's
  windowed availability (completed / (completed + non-typed failed)
  between sweeps, sampled from the pod fleet's per-model outcome
  counters; typed shed/expired are NOT failures) to a floor
  (``LIGHTGBM_TPU_SLO_AVAILABILITY``): a fleet that starts failing
  requests breaches ``availability:<model>`` and dumps a forensic
  bundle, mirroring the p99-ceiling pattern (docs/RESILIENCE.md).

Every breach increments ``slo_breach_total{slo=...}`` on the process
registry, logs loudly, and — on the rising edge only, so a persistent
breach cannot dump-storm — triggers a flight-recorder forensic bundle
(obs/flight.py).

The sentry thread is OPT-IN (``start()``, or env
``LIGHTGBM_TPU_WATCHDOG=1`` / any ``LIGHTGBM_TPU_SLO_*`` knob via
``maybe_start_from_env``, checked at engine/server init); ``check_once``
runs one synchronous sweep for tests and CLIs.  Stdlib-only.
"""

from __future__ import annotations

import math
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

_WATCHDOG_ENV = "LIGHTGBM_TPU_WATCHDOG"
_SLO_TPS_ENV = "LIGHTGBM_TPU_SLO_TREES_PER_SEC"
_SLO_P99_ENV = "LIGHTGBM_TPU_SLO_SERVING_P99_MS"
_SLO_STALE_ENV = "LIGHTGBM_TPU_SLO_HEARTBEAT_S"
_SLO_AGE_ENV = "LIGHTGBM_TPU_SLO_MODEL_AGE_S"
_SLO_AVAIL_ENV = "LIGHTGBM_TPU_SLO_AVAILABILITY"
_INTERVAL_ENV = "LIGHTGBM_TPU_WATCHDOG_INTERVAL_S"


def _env_float(name: str) -> Optional[float]:
    v = os.environ.get(name, "").strip()
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


@dataclass
class SLOConfig:
    """The service-level objectives the sentry enforces.  ``None``
    disables that check; the heartbeat staleness default is deliberately
    generous — a compile can legitimately take minutes."""

    heartbeat_stale_s: float = 300.0
    trees_per_sec_floor: Optional[float] = None
    serving_p99_ms: Optional[float] = None
    model_age_max_s: Optional[float] = None
    availability_floor: Optional[float] = None
    check_interval_s: float = 5.0

    @classmethod
    def from_env(cls) -> "SLOConfig":
        cfg = cls()
        v = _env_float(_SLO_STALE_ENV)
        if v is not None:
            cfg.heartbeat_stale_s = v
        cfg.trees_per_sec_floor = _env_float(_SLO_TPS_ENV)
        cfg.serving_p99_ms = _env_float(_SLO_P99_ENV)
        cfg.model_age_max_s = _env_float(_SLO_AGE_ENV)
        cfg.availability_floor = _env_float(_SLO_AVAIL_ENV)
        v = _env_float(_INTERVAL_ENV)
        if v is not None and v > 0:
            cfg.check_interval_s = v
        return cfg


def histogram_p99_ms(hist) -> Optional[float]:
    """Upper-bound p99 estimate from a metrics Histogram's cumulative
    buckets (the smallest bound covering >= 99% of observations; the
    histogram max when that bound is +inf).  None with no samples."""
    cum, _total, count = hist.cumulative()
    if count == 0:
        return None
    target = 0.99 * count
    for bound, c in cum:
        if c >= target:
            if math.isinf(bound):
                snap = hist.snapshot()
                return float(snap.get("max", 0.0))
            return float(bound)
    return None


class Watchdog:
    """Heartbeat registry + SLO sentry; one instance per process
    (``global_watchdog``), scratch instances for tests."""

    def __init__(self, config: Optional[SLOConfig] = None,
                 registry=None, flight=None):
        self.config = config or SLOConfig()
        self._registry = registry
        self._flight = flight
        self._beats: dict = {}        # name -> (monotonic ts, count|None)
        self._watched: dict = {}      # name -> stale threshold seconds
        self._floors: dict = {}       # name -> rate floor (units/sec)
        self._rate_state: dict = {}   # guarded-by: _lock (ts, count)/name
        self._hists: dict = {}        # name -> (Histogram, ceiling_ms,
        #                               windowed)
        self._hist_state: dict = {}   # guarded-by: _lock — windowed p99:
        #                               name -> (bucket counts, count)
        self._fresh: dict = {}        # guarded-by: _lock
        #                               name -> (fresh_ts, max_age_s|None)
        self._avail: dict = {}        # guarded-by: _lock
        #                               name -> (sample_fn, floor|None)
        self._avail_state: dict = {}  # guarded-by: _lock
        #                               name -> (completed, failed) last sweep
        self._breached: set = set()   # guarded-by: _lock (edge detection)
        self._listeners: list = []    # guarded-by: _lock (breach hooks)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _reg(self):
        if self._registry is None:
            from .metrics import global_registry
            self._registry = global_registry
        return self._registry

    def _fl(self):
        if self._flight is None:
            from .flight import global_flight
            self._flight = global_flight
        return self._flight

    # ----------------------------------------------------------- heartbeats

    def beat(self, name: str, count: Optional[float] = None) -> None:
        """Record liveness (and optionally progress) of ``name``.  One
        dict store — safe on any hot loop, watched or not."""
        self._beats[name] = (time.monotonic(), count)

    def beat_age(self, name: str,
                 now: Optional[float] = None) -> Optional[float]:
        """Seconds since ``name`` last beat, or None when it never has —
        the pod router's per-replica staleness input (fleet/router.py):
        a replica whose batcher stops beating is wedged, whatever its
        queue says."""
        ts_count = self._beats.get(name)
        if ts_count is None:
            return None
        return (time.monotonic() if now is None else now) - ts_count[0]

    def watch_heartbeat(self, name: str, stale_s: Optional[float] = None,
                        floor: Optional[float] = None) -> None:
        """Arm staleness (and optionally rate-floor) checking for
        ``name``.  Call on activity START; ``unwatch`` on clean exit."""
        with self._lock:
            self._watched[name] = (stale_s if stale_s is not None
                                   else self.config.heartbeat_stale_s)
            if floor is not None:
                self._floors[name] = floor
            self._rate_state.pop(name, None)
        self.beat(name)       # arming is itself proof of life

    def unwatch(self, name: str) -> None:
        with self._lock:
            self._watched.pop(name, None)
            self._floors.pop(name, None)
            self._rate_state.pop(name, None)
            self._breached = {b for b in self._breached
                              if not b.endswith(":" + name)}

    def watch_histogram_p99(self, name: str, hist,
                            ceiling_ms: Optional[float] = None,
                            windowed: bool = False) -> None:
        """Hold ``hist``'s estimated p99 to ``ceiling_ms`` (defaults to
        the config's serving_p99_ms; never breaches while both are
        None).

        ``windowed=True`` estimates the p99 over the samples observed
        SINCE THE LAST SWEEP (differencing the cumulative buckets, like
        the availability watch) instead of over the histogram's whole
        cumulative history.  A cumulative p99 is sticky — one latency
        spike breaches it for the process lifetime — so windowed is the
        mode brownout controllers use: the breach clears once the
        current traffic is back under the ceiling
        (coresident/scheduler.py)."""
        with self._lock:
            self._hists[name] = (hist, ceiling_ms, bool(windowed))
            self._hist_state.pop(name, None)

    def unwatch_histogram(self, name: str) -> None:
        with self._lock:
            self._hists.pop(name, None)
            self._hist_state.pop(name, None)
            # a re-registered same-name watch must get a fresh rising
            # edge (its dump would otherwise be suppressed forever)
            self._breached.discard(f"slo:{name}")

    # ----------------------------------------------------------- freshness

    def watch_freshness(self, name: str,
                        max_age_s: Optional[float] = None) -> None:
        """Hold ``name``'s model age (seconds since the last
        ``mark_fresh``) to ``max_age_s`` (default: the config's
        ``model_age_max_s``; never breaches while both are None).  The
        age is published as ``model_age_seconds{model=...}`` either way
        — freshness is a first-class SLO of the model lifecycle
        (docs/LIFECYCLE.md): a deployment that stops refreshing breaches
        ``freshness:<name>`` and dumps a forensic bundle."""
        with self._lock:
            prev = self._fresh.get(name)
            self._fresh[name] = (prev[0] if prev is not None
                                 else time.monotonic(), max_age_s)

    def mark_fresh(self, name: str) -> None:
        """Reset ``name``'s model age to zero (called at promotion)."""
        with self._lock:
            entry = self._fresh.get(name)
            self._fresh[name] = (time.monotonic(),
                                 entry[1] if entry is not None else None)

    def unwatch_freshness(self, name: str) -> None:
        with self._lock:
            self._fresh.pop(name, None)
            self._breached.discard(f"freshness:{name}")

    def model_age_s(self, name: str) -> Optional[float]:
        with self._lock:
            entry = self._fresh.get(name)
        return None if entry is None else time.monotonic() - entry[0]

    # --------------------------------------------------------- availability

    def watch_availability(self, name: str, sample_fn,
                           floor: Optional[float] = None) -> None:
        """Hold ``name``'s windowed availability to ``floor`` (default:
        the config's ``availability_floor``, i.e.
        ``LIGHTGBM_TPU_SLO_AVAILABILITY``; never breaches while both are
        None).  ``sample_fn() -> (completed, failed)`` returns CUMULATIVE
        per-model outcome counts (typed shed/expired excluded from both
        — they are correct overload behavior, not unavailability); each
        sweep differentiates the window exactly like the rate floors, so
        one bad minute breaches even after a long clean run.  Breaches
        count ``slo_breach_total{slo="availability:<name>"}`` and
        flight-dump on the rising edge, mirroring the p99 ceiling."""
        with self._lock:
            self._avail[name] = (sample_fn, floor)
            self._avail_state.pop(name, None)

    def unwatch_availability(self, name: str) -> None:
        with self._lock:
            self._avail.pop(name, None)
            self._avail_state.pop(name, None)
            self._breached.discard(f"availability:{name}")

    # -------------------------------------------------------------- checks

    def active_breaches(self) -> list:
        """Sorted snapshot of the currently UN-RECOVERED breach names —
        what /healthz reports as degraded (obs/http.py) and what a
        brownout controller polls between sweeps."""
        with self._lock:
            return sorted(self._breached)

    def add_breach_listener(self, fn) -> None:
        """Register ``fn(slo, evidence, rising)`` to be called on EVERY
        breach occurrence (not just the rising edge — a throttle
        controller needs the repeat signal to know the brownout
        persists).  Exceptions from listeners are swallowed: a broken
        hook must never kill the sentry sweep."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_breach_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def _windowed_p99(self, name: str, hist) -> Optional[float]:
        """p99 estimate over the samples since the LAST sweep (delta of
        the cumulative buckets).  None on the arming sweep or an empty
        window."""
        cum, _total, count = hist.cumulative()
        counts = [c for _b, c in cum]
        with self._lock:
            prev = self._hist_state.get(name)
            self._hist_state[name] = (counts, count)
        if prev is None:
            return None
        dcount = count - prev[1]
        if dcount <= 0:
            return None
        target = 0.99 * dcount
        for (bound, c), pc in zip(cum, prev[0]):
            if c - pc >= target:
                if math.isinf(bound):
                    snap = hist.snapshot()
                    return float(snap.get("max", 0.0))
                return float(bound)
        return None

    def _breach(self, slo: str, evidence: dict) -> None:
        # the sentry thread and a caller's unwatch() both touch the
        # breach set; the rising-edge read must pair with the add, and a
        # breach computed from a pre-unwatch snapshot must not re-enter
        # the set after unwatch cleared it (that would both alarm for an
        # activity that exited cleanly and suppress the NEXT watch's
        # rising-edge dump)
        name = slo.split(":", 1)[-1]
        with self._lock:
            if name not in self._watched and name not in self._floors \
                    and name not in self._hists \
                    and name not in self._fresh \
                    and name not in self._avail:
                return
            rising = slo not in self._breached
            self._breached.add(slo)
        try:
            self._reg().counter("slo_breach_total",
                                labels={"slo": slo}).inc()
        except Exception:  # noqa: BLE001
            pass
        from ..utils.log import log_warning
        log_warning(f"watchdog: SLO breach [{slo}] {evidence}")
        if rising:
            # rising edge only: a persistent breach must not dump-storm
            self._fl().dump(f"watchdog:{slo}", extra=evidence)
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(slo, evidence, rising)
            except Exception:  # noqa: BLE001 — hooks never kill the sweep
                pass

    def _clear(self, slo: str) -> None:
        with self._lock:
            self._breached.discard(slo)

    def check_once(self, now: Optional[float] = None) -> list:
        """One synchronous sweep; returns the list of (slo, evidence)
        breaches found THIS sweep (tests drive this without the thread)."""
        now = time.monotonic() if now is None else now
        breaches = []
        with self._lock:
            watched = dict(self._watched)
            floors = dict(self._floors)
            hists = dict(self._hists)
            fresh = dict(self._fresh)
            avail = dict(self._avail)
        for name, stale_s in watched.items():
            ts_count = self._beats.get(name)
            if ts_count is None:
                continue
            age = now - ts_count[0]
            if age > stale_s:
                breaches.append((f"stall:{name}", {
                    "heartbeat_age_s": round(age, 3),
                    "stale_threshold_s": stale_s}))
            else:
                self._clear(f"stall:{name}")
        for name, floor in floors.items():
            ts_count = self._beats.get(name)
            if ts_count is None or ts_count[1] is None:
                continue
            ts, count = ts_count
            with self._lock:    # watch/unwatch reset this concurrently
                prev = self._rate_state.get(name)
                self._rate_state[name] = (ts, count)
            if prev is None or ts <= prev[0]:
                continue
            rate = (count - prev[1]) / (ts - prev[0])
            self._reg().gauge(f"watchdog_rate_{name}").set(round(rate, 4))
            if rate < floor:
                breaches.append((f"slo:{name}", {
                    "rate": round(rate, 4), "floor": floor}))
            else:
                self._clear(f"slo:{name}")
        for name, (hist, ceiling, windowed) in hists.items():
            if ceiling is None:
                ceiling = self.config.serving_p99_ms
            if ceiling is None:
                continue
            p99 = (self._windowed_p99(name, hist) if windowed
                   else histogram_p99_ms(hist))
            if p99 is None:
                continue
            self._reg().gauge(f"watchdog_p99_{name}").set(p99)
            if p99 > ceiling:
                breaches.append((f"slo:{name}", {
                    "p99_ms": p99, "ceiling_ms": ceiling}))
            else:
                self._clear(f"slo:{name}")
        for name, (fresh_ts, max_age) in fresh.items():
            age = now - fresh_ts
            self._reg().gauge("model_age_seconds",
                              labels={"model": name}).set(round(age, 3))
            if max_age is None:
                max_age = self.config.model_age_max_s
            if max_age is None:
                continue
            if age > max_age:
                breaches.append((f"freshness:{name}", {
                    "model_age_s": round(age, 3),
                    "max_age_s": max_age}))
            else:
                self._clear(f"freshness:{name}")
        for name, (sample_fn, floor) in avail.items():
            if floor is None:
                floor = self.config.availability_floor
            try:
                completed, failed = sample_fn()
            except Exception:  # noqa: BLE001 — a dead sampler never kills
                continue       # the sweep (the fleet may be closing)
            with self._lock:    # watch/unwatch reset this concurrently
                prev = self._avail_state.get(name)
                self._avail_state[name] = (completed, failed)
            if prev is None:
                continue
            dc, df = completed - prev[0], failed - prev[1]
            if dc + df <= 0:
                continue
            a = dc / (dc + df)
            self._reg().gauge("fleet_availability",
                              labels={"model": name}).set(round(a, 6))
            if floor is None:
                continue
            if a < floor:
                breaches.append((f"availability:{name}", {
                    "availability": round(a, 6), "floor": floor,
                    "window_completed": dc, "window_failed": df}))
            else:
                self._clear(f"availability:{name}")
        for slo, evidence in breaches:
            self._breach(slo, evidence)
        return breaches

    # -------------------------------------------------------------- sentry

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.config.check_interval_s):
                try:
                    self.check_once()
                except Exception:  # noqa: BLE001 — the sentry never dies
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="lgbt-slo-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None


global_watchdog = Watchdog()


def beat(name: str, count: Optional[float] = None) -> None:
    """Module-level heartbeat against the process watchdog."""
    global_watchdog._beats[name] = (time.monotonic(), count)


def maybe_start_from_env() -> bool:
    """Start the process watchdog when env opts in
    (``LIGHTGBM_TPU_WATCHDOG=1`` or any ``LIGHTGBM_TPU_SLO_*`` set);
    idempotent.  Returns whether the sentry is running."""
    if global_watchdog.running:
        return True
    opted = os.environ.get(_WATCHDOG_ENV, "") not in ("", "0")
    cfg = SLOConfig.from_env()
    if not opted and cfg.trees_per_sec_floor is None \
            and cfg.serving_p99_ms is None \
            and cfg.model_age_max_s is None \
            and cfg.availability_floor is None \
            and _env_float(_SLO_STALE_ENV) is None:
        return False
    global_watchdog.config = cfg
    global_watchdog.start()
    return True
