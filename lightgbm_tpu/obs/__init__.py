"""Unified observability plane: structured tracing, measured device
profiling, and the single process metrics registry (docs/OBSERVABILITY.md).

Three pillars, shared by training, serving, resilience and the bench:

- ``obs.trace`` — thread-safe span recorder (``span("grow_tree")``
  context managers through the hot seams) emitting Chrome trace-event /
  Perfetto-compatible JSON; gated by ``LIGHTGBM_TPU_TRACE`` to one
  attribute check when disabled;
- ``obs.devprof`` — measured per-program MFU / HBM-bandwidth utilization
  from ``Compiled.cost_analysis()`` (the compiler's own FLOP/byte
  counts), plus optional ``jax.profiler`` capture;
- ``obs.metrics`` — the ``MetricsRegistry`` promoted from serving as the
  process-wide instrument registry (``global_registry``), with JSON
  snapshots and Prometheus text exposition.

The ACTIVE layer on top (docs/OBSERVABILITY.md):

- ``obs.flight`` — always-on bounded ring-buffer flight recorder
  dumping atomic forensic bundles on failure triggers;
- ``obs.watchdog`` — heartbeat/SLO sentry (stalls, trees/sec floor,
  serving-p99 ceiling) breaching into ``slo_breach_total`` + flight
  dumps;
- ``obs.aggregate`` — pod-level telemetry vectors gathered through the
  resilient collective plane (straggler skew, per-tier byte sums);
- ``obs.diagnose`` — ranked bottleneck verdicts joining measured vs
  planner-predicted signals (``tools/obs_doctor.py`` CLI);
- ``obs.http`` — opt-in stdlib HTTP exposition of the process registry.

``trace``/``metrics``/``flight``/``watchdog``/``http`` are stdlib-only;
``devprof`` imports jax lazily.
"""

from .metrics import (LATENCY_BUCKETS_MS, RATIO_BUCKETS, Counter, Gauge,
                      Histogram, MetricsRegistry, get_registry,
                      global_registry)
from .trace import (Tracer, global_tracer, instant, span, span_coverage,
                    trace_enabled, trace_path)
# importing flight installs the tracer's ring tee (set_flight_sink)
from .flight import FlightRecorder, global_flight
from .watchdog import SLOConfig, Watchdog, global_watchdog

__all__ = [
    "span", "instant", "trace_enabled", "trace_path", "span_coverage",
    "Tracer", "global_tracer",
    "MetricsRegistry", "global_registry", "get_registry",
    "Counter", "Gauge", "Histogram",
    "LATENCY_BUCKETS_MS", "RATIO_BUCKETS",
    "FlightRecorder", "global_flight",
    "Watchdog", "SLOConfig", "global_watchdog",
]
