"""Pod-level telemetry: fixed-layout per-rank metric vectors gathered
through the PR 10 collective plane at eval boundaries.

Each rank packs a FIXED layout (``METRIC_LAYOUT``) of float64 slots read
from its local process registry; the vectors travel through
``resilient_allgather`` (CRC framing + rank-consistent verdict — a
telemetry round can never wedge training and never mixes rounds), and
every rank derives the same pod view:

- **straggler gauge** — per-slice mean iteration seconds (slice = rank
  // devices_per_slice in the hybrid mesh's row-major rank order), skew
  = slowest slice / fastest slice, plus WHICH slice is the straggler;
- **summed ICI/DCN payload bytes** — the pod's actual per-tier wire
  load, not one rank's share;
- **pod-wide MFU** — mean of per-rank measured MFU (the chips are
  identical; the mean is what capacity planning wants).

The derived values land as ``pod_*`` gauges on the local registry, emit
a ``pod.telemetry`` trace instant (which also feeds the flight ring),
and return as a ``PodTelemetry`` for programmatic use — the diagnoser
(obs/diagnose.py) reads ``straggler_skew``/``straggler_slice`` from
exactly these gauges.

The engine gathers at eval boundaries only when a pod transport is
registered (``register_pod_transport``, e.g. from the launcher that owns
``jax_allgather_bytes``) — single-host training never pays a round.
Vector layout is versioned: a rank running older code is detected by the
header, not silently mis-decoded.
"""

from __future__ import annotations

import struct
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

# fixed vector layout, one float64 per slot.  APPEND-ONLY: reordering or
# removing slots breaks cross-version pods; the header version bumps on
# any layout change.
METRIC_LAYOUT = (
    "iter_seconds",          # last engine step wall-clock / iteration
    "trees_per_sec",         # live training rate
    "ici_payload_bytes",     # per-sync ICI tier bytes (planner model)
    "dcn_payload_bytes",     # per-sync DCN tier bytes (planner model)
    "mfu",                   # measured MFU (devprof), 0 when unmeasured
    "host_rss_peak_bytes",   # streaming host watermark
    "compile_cache_warm",    # 0/1
    "slo_breach_total",      # watchdog breaches seen by this rank
)

_MAGIC = b"LGPM"
_VERSION = 1
_HEAD = struct.Struct("<4sBI")            # magic, version, rank


def pack_rank_vector(values: dict, rank: int) -> bytes:
    """Serialize ``values`` (missing slots -> 0.0) into the fixed wire
    layout."""
    vec = [float(values.get(k, 0.0) or 0.0) for k in METRIC_LAYOUT]
    return (_HEAD.pack(_MAGIC, _VERSION, int(rank))
            + struct.pack(f"<{len(METRIC_LAYOUT)}d", *vec))


def unpack_rank_vector(blob: bytes) -> "tuple[int, dict]":
    """(rank, {slot: value}); raises ValueError on a foreign payload."""
    if len(blob) < _HEAD.size:
        raise ValueError(f"short pod-metric frame ({len(blob)} bytes)")
    magic, ver, rank = _HEAD.unpack(blob[:_HEAD.size])
    if magic != _MAGIC:
        raise ValueError("bad pod-metric magic")
    if ver != _VERSION:
        raise ValueError(f"pod-metric layout version {ver} != {_VERSION}")
    body = blob[_HEAD.size:]
    n = len(body) // 8
    vals = struct.unpack(f"<{n}d", body[:n * 8])
    return int(rank), dict(zip(METRIC_LAYOUT, vals))


def local_vector(registry=None) -> dict:
    """This rank's slot values, read off the process registry's gauges
    and counters (all optional; absent instruments report 0)."""
    if registry is None:
        from .metrics import global_registry as registry
    d = registry.to_dict()
    g, c = d.get("gauges", {}), d.get("counters", {})

    def num(v):
        return float(v) if isinstance(v, (int, float)) \
            and not isinstance(v, bool) else 0.0

    breaches = sum(v for k, v in c.items()
                   if k.startswith("slo_breach_total"))
    return {
        "iter_seconds": num(g.get("train_iter_seconds", 0.0)),
        "trees_per_sec": num(g.get("train_trees_per_sec_live",
                                   g.get("train_trees_per_sec", 0.0))),
        "ici_payload_bytes": num(g.get("train_ici_payload_bytes", 0.0)),
        "dcn_payload_bytes": num(g.get("train_dcn_payload_bytes", 0.0)),
        "mfu": num(g.get("mfu_measured_best", 0.0)),
        "host_rss_peak_bytes": num(g.get("host_rss_peak_bytes", 0.0)),
        "compile_cache_warm": num(g.get("compile_cache_warm", 0.0)),
        "slo_breach_total": float(breaches),
    }


@dataclass
class PodTelemetry:
    """The derived pod view every rank computes identically."""

    world: int
    num_slices: int
    devices_per_slice: int
    per_rank: List[dict]                 # rank-ordered slot dicts
    slice_iter_seconds: List[float]      # per-slice mean iteration time
    straggler_slice: int
    straggler_skew: float                # slowest / fastest slice
    pod_ici_payload_bytes: float
    pod_dcn_payload_bytes: float
    pod_mfu: float

    def summary(self) -> dict:
        return {
            "world": self.world,
            "num_slices": self.num_slices,
            "devices_per_slice": self.devices_per_slice,
            "slice_iter_seconds": [round(s, 6)
                                   for s in self.slice_iter_seconds],
            "straggler_slice": self.straggler_slice,
            "straggler_skew": round(self.straggler_skew, 4),
            "pod_ici_payload_bytes": int(self.pod_ici_payload_bytes),
            "pod_dcn_payload_bytes": int(self.pod_dcn_payload_bytes),
            "pod_mfu": round(self.pod_mfu, 6),
        }


def derive_pod_view(per_rank: List[dict], num_slices: int) -> PodTelemetry:
    """Pure reduction of rank-ordered vectors into the pod view (shared
    by the live gather and the tests)."""
    world = len(per_rank)
    s = max(int(num_slices), 1)
    dps = max(world // s, 1)
    slice_iters = []
    for k in range(s):
        members = per_rank[k * dps:(k + 1) * dps]
        vals = [m.get("iter_seconds", 0.0) for m in members] or [0.0]
        slice_iters.append(sum(vals) / len(vals))
    fastest = min((v for v in slice_iters if v > 0), default=0.0)
    slowest = max(slice_iters, default=0.0)
    skew = (slowest / fastest) if fastest > 0 else 1.0
    straggler = (slice_iters.index(slowest) if slice_iters else 0)
    mfus = [m.get("mfu", 0.0) for m in per_rank]
    return PodTelemetry(
        world=world, num_slices=s, devices_per_slice=dps,
        per_rank=per_rank, slice_iter_seconds=slice_iters,
        straggler_slice=straggler, straggler_skew=skew,
        pod_ici_payload_bytes=sum(m.get("ici_payload_bytes", 0.0)
                                  for m in per_rank),
        pod_dcn_payload_bytes=sum(m.get("dcn_payload_bytes", 0.0)
                                  for m in per_rank),
        pod_mfu=(sum(mfus) / len(mfus)) if mfus else 0.0)


def _publish(view: PodTelemetry, registry=None) -> None:
    if registry is None:
        from .metrics import global_registry as registry
    registry.gauge("pod_straggler_skew").set(round(view.straggler_skew, 4))
    registry.gauge("pod_straggler_slice").set(view.straggler_slice)
    registry.gauge("pod_ici_payload_bytes").set(
        int(view.pod_ici_payload_bytes))
    registry.gauge("pod_dcn_payload_bytes").set(
        int(view.pod_dcn_payload_bytes))
    registry.gauge("pod_mfu").set(round(view.pod_mfu, 6))
    registry.gauge("pod_world").set(view.world)
    from .trace import instant
    instant("pod.telemetry", **view.summary())


def gather_pod_metrics(allgather_bytes: Callable[[bytes], List[bytes]],
                       *, world: int, rank: int, num_slices: int = 1,
                       registry=None, config=None,
                       values: Optional[dict] = None) -> PodTelemetry:
    """One pod telemetry round: pack the local vector, allgather it
    resiliently, derive + publish the pod view.  Raises CollectiveError
    only when the collective plane itself is down (the caller treats it
    as it treats any training collective failure)."""
    from ..resilience.retry import ResilienceConfig, resilient_allgather
    cfg = config or ResilienceConfig(deadline_s=10.0, max_retries=2)
    payload = pack_rank_vector(
        values if values is not None else local_vector(registry), rank)
    # flight_dump=False: a failed telemetry round is logged-and-survived
    # by the caller — it must not spend the bounded forensic dump budget
    parts = resilient_allgather(payload, allgather_bytes, world=world,
                                rank=rank, config=cfg,
                                label="pod_telemetry", metrics=registry,
                                flight_dump=False)
    decoded = sorted((unpack_rank_vector(p) for p in parts),
                     key=lambda rv: rv[0])
    view = derive_pod_view([v for _r, v in decoded], num_slices)
    _publish(view, registry)
    return view


# ---------------------------------------------------------------- engine seam

_transport_lock = threading.Lock()
_transport: Optional[dict] = None


def register_pod_transport(allgather_bytes: Callable[[bytes], List[bytes]],
                           *, world: int, rank: int,
                           num_slices: int = 1) -> None:
    """Install the process's pod telemetry transport (the launcher that
    owns the cross-host allgather calls this once); the engine then
    gathers at every eval boundary.  ``None``-able via
    ``clear_pod_transport``."""
    global _transport
    with _transport_lock:
        _transport = {"fn": allgather_bytes, "world": int(world),
                      "rank": int(rank), "num_slices": int(num_slices)}


def clear_pod_transport() -> None:
    global _transport
    with _transport_lock:
        _transport = None


def maybe_gather_at_eval(registry=None) -> Optional[PodTelemetry]:
    """The engine's eval-boundary hook: a no-op (None) unless a pod
    transport is registered; telemetry failures are logged, never raised
    into the training loop."""
    with _transport_lock:
        t = dict(_transport) if _transport else None
    if t is None:
        return None
    t0 = time.perf_counter()
    try:
        view = gather_pod_metrics(
            t["fn"], world=t["world"], rank=t["rank"],
            num_slices=t["num_slices"], registry=registry)
    except Exception as e:  # noqa: BLE001 — telemetry must not kill training
        from ..utils.log import log_warning
        log_warning(f"pod telemetry round failed ({e!r}); continuing")
        return None
    if registry is None:
        from .metrics import global_registry as registry
    registry.histogram("pod_telemetry_round_ms").observe(
        (time.perf_counter() - t0) * 1e3)
    return view
