"""Structured tracing: a thread-safe span recorder emitting Chrome
trace-event / Perfetto-compatible JSON.

The timer subsystem (utils/timer.py) answers "how much total time went
into section X"; this module answers "WHEN did each occurrence run, on
which thread, nested under what" — the difference between a table and a
timeline.  Spans are recorded through the hot seams of the whole stack
(engine iteration loop, macro-chunk dispatch + host fetch, grower trace
construction, checkpoint save/load, ``resilient_allgather`` attempts,
serving batcher admission -> dispatch -> completion) and dump as one JSON
file that chrome://tracing or ui.perfetto.dev loads directly.

Gate: ``LIGHTGBM_TPU_TRACE`` — unset/"0" disables (a disabled call site
costs one attribute check and returns a shared null context manager, the
same contract as ``global_timer``); "1" enables recording; any other
value enables AND names the file the trace is dumped to at interpreter
exit.  ``global_tracer.dump(path)`` dumps on demand.

Event format (Chrome trace-event "JSON object format"): complete events
``{"name", "ph": "X", "ts", "dur", "pid", "tid", "args"}`` with ``ts``/
``dur`` in microseconds since the tracer's epoch, plus instant events
(``"ph": "i"``) for point-in-time facts (planner verdicts, measured HBM
peaks, request admissions).  Events are timestamp-sorted at dump time.

Because device work is asynchronous under jit, spans measure HOST time:
dispatch cost lands in the dispatch span and device time surfaces in
whichever span first blocks on a result (the same decomposition
``global_timer`` reports, now with per-occurrence timing).  This module
is dependency-free (stdlib only) and never imports jax.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import List, Optional

_TRACE_ENV = "LIGHTGBM_TPU_TRACE"
_MAX_EVENTS_ENV = "LIGHTGBM_TPU_TRACE_MAX_EVENTS"
# generous default: ~1M events is hundreds of MB of JSON before a long
# pod run would ever hit it, but it IS a bound — the in-process span
# list can no longer grow without limit (drops are counted, never silent)
_DEFAULT_MAX_EVENTS = 1_000_000

# the flight recorder's ring sink (obs/flight.py installs itself via
# set_flight_sink at import).  Kept as a module global so trace.py never
# imports flight.py (no cycle); None = no recorder armed.
_flight_sink = None


def set_flight_sink(sink) -> None:
    """Install (or clear, with None) the flight-recorder ring that tees
    recorded span/instant events.  Called by obs/flight.py."""
    global _flight_sink
    _flight_sink = sink


def _max_events_env() -> int:
    try:
        v = int(os.environ.get(_MAX_EVENTS_ENV, _DEFAULT_MAX_EVENTS))
    except ValueError:
        return _DEFAULT_MAX_EVENTS
    return v if v > 0 else _DEFAULT_MAX_EVENTS


class _NullSpan:
    """Shared no-op context manager for the disabled path (one instance
    for the whole process: disabled tracing never allocates)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span; records a complete event on ``__exit__`` (always —
    an exception inside the span closes it and tags ``args["error"]``,
    so span trees stay well-nested under raises)."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def set(self, **args) -> "_Span":
        """Attach attributes mid-span (e.g. a result size known late)."""
        self.args.update(args)
        return self

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        self._tracer._record(self.name, self._t0, t1, self.args)
        return False


class Tracer:
    """Thread-safe span/instant recorder with Chrome-trace export."""

    def __init__(self, enabled: Optional[bool] = None,
                 max_events: Optional[int] = None):
        if enabled is None:
            v = os.environ.get(_TRACE_ENV, "")
            enabled = bool(v) and v != "0"
        self.enabled = enabled
        # bounded in-process event list (LIGHTGBM_TPU_TRACE_MAX_EVENTS):
        # beyond the cap new events are DROPPED and counted, so a long
        # pod run cannot grow the span list without bound
        self.max_events = (int(max_events) if max_events is not None
                           else _max_events_env())
        self.dropped = 0
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._epoch = time.perf_counter()
        # only the process tracer tees into the flight ring (scratch
        # tracers in tests must not pollute the process forensics)
        self._flight_tee = False

    # ------------------------------------------------------------- control

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    # ----------------------------------------------------------- recording

    def span(self, name: str, **args):
        """``with tracer.span("grow_tree", leaves=255): ...`` — returns
        the shared null context manager when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def instant(self, name: str, **args) -> None:
        """Point-in-time event (Chrome "i" phase, thread scope)."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "i", "s": "t", "pid": self._pid,
              "tid": threading.get_ident(),
              "ts": (time.perf_counter() - self._epoch) * 1e6}
        if args:
            ev["args"] = args
        self._append(ev)

    def _record(self, name: str, t0: float, t1: float, args: dict) -> None:
        ev = {"name": name, "ph": "X", "pid": self._pid,
              "tid": threading.get_ident(),
              "ts": (t0 - self._epoch) * 1e6,
              "dur": (t1 - t0) * 1e6}
        if args:
            ev["args"] = args
        self._append(ev)

    def _append(self, ev: dict) -> None:
        dropped_now = None
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                dropped_now = self.dropped
            else:
                self._events.append(ev)
        sink = _flight_sink
        if sink is not None and self._flight_tee:
            # the flight ring is bounded by construction, so it still
            # sees events the capped span list dropped
            sink.feed(ev)
        if dropped_now is not None:
            # visible both process-wide (gauge) and in the trace dump
            # (an instant is appended at export, see to_chrome_trace)
            from .metrics import global_registry
            global_registry.gauge("trace_events_dropped").set(dropped_now)

    # -------------------------------------------------------------- export

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def to_chrome_trace(self, events: Optional[List[dict]] = None) -> dict:
        """Loadable-by-chrome://tracing dict: timestamp-sorted events plus
        a process-name metadata record.  ``events`` restricts the export
        to a subset (e.g. one bench stage's slice of a shared tracer)."""
        evs = sorted(self.events() if events is None else events,
                     key=lambda e: e.get("ts", 0.0))
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "ts": 0.0,
                 "args": {"name": "lightgbm-tpu"}}]
        if self.dropped:
            evs = evs + [{
                "name": "trace_events_dropped", "ph": "i", "s": "p",
                "pid": self._pid, "tid": 0,
                "ts": (evs[-1]["ts"] if evs else 0.0),
                "args": {"dropped": self.dropped,
                         "max_events": self.max_events}}]
        return {"traceEvents": meta + evs, "displayTimeUnit": "ms"}

    def dump(self, path: str, events: Optional[List[dict]] = None) -> str:
        """Write the Chrome-trace JSON to ``path`` (atomic); returns it."""
        from ..utils.file_io import write_atomic
        write_atomic(path, json.dumps(self.to_chrome_trace(events)))
        return str(path)

    def mark(self) -> int:
        """Current event count — pass the returned mark to ``since`` to
        slice later events (per-stage export from a shared tracer)."""
        with self._lock:
            return len(self._events)

    def since(self, mark: int) -> List[dict]:
        with self._lock:
            return list(self._events[mark:])


global_tracer = Tracer()
global_tracer._flight_tee = True


def span(name: str, **args):
    """Module-level span against the process tracer — the instrumentation
    entry point: ``with span("engine.step", i=i): ...``."""
    if not global_tracer.enabled:
        return _NULL_SPAN
    return _Span(global_tracer, name, args)


def instant(name: str, **args) -> None:
    global_tracer.instant(name, **args)
    if not global_tracer.enabled and _flight_sink is not None:
        # instants are rare (planner verdicts, HBM peaks, admissions) and
        # exactly the point-in-time facts a forensic bundle needs — keep
        # feeding the always-on flight ring with tracing off
        _flight_sink.note_instant(name, args)


def trace_enabled() -> bool:
    return global_tracer.enabled


def trace_path() -> Optional[str]:
    """The exit-dump path named by ``LIGHTGBM_TPU_TRACE``, if any."""
    v = os.environ.get(_TRACE_ENV, "")
    if v and v.lower() not in ("0", "1", "on", "true"):
        return v
    return None


def span_coverage(events: List[dict], root_name: str) -> Optional[float]:
    """Fraction of the longest ``root_name`` span's wall-clock covered by
    the union of every other span overlapping it — the "does the span
    tree account for the stage?" number the bench reports."""
    roots = [e for e in events
             if e.get("name") == root_name and e.get("ph") == "X"]
    if not roots:
        return None
    root = max(roots, key=lambda e: e.get("dur", 0.0))
    lo, hi = root["ts"], root["ts"] + root["dur"]
    if hi <= lo:
        return None
    ivals = []
    for e in events:
        if e is root or e.get("ph") != "X":
            continue
        s = max(e["ts"], lo)
        t = min(e["ts"] + e.get("dur", 0.0), hi)
        if t > s:
            ivals.append((s, t))
    ivals.sort()
    covered, cur_s, cur_t = 0.0, None, None
    for s, t in ivals:
        if cur_t is None or s > cur_t:
            if cur_t is not None:
                covered += cur_t - cur_s
            cur_s, cur_t = s, t
        else:
            cur_t = max(cur_t, t)
    if cur_t is not None:
        covered += cur_t - cur_s
    return covered / (hi - lo)


@atexit.register
def _dump_at_exit() -> None:
    p = trace_path()
    if p and global_tracer.enabled and global_tracer.events():
        try:
            global_tracer.dump(p)
        except OSError:
            pass
