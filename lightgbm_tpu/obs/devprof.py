"""Measured device profiling: per-program FLOPs/bytes from XLA's
``Compiled.cost_analysis()`` -> measured MFU and HBM-bandwidth
utilization.

``bench.py``'s ``mfu_histogram_lower_bound`` hand-counts only the
histogram-matmul FLOPs and divides by a wall-clock that smears compile
and host time in — a lower bound good for trendlines, useless for
finding where the other 99.9% of the chip went.  This module asks the
compiler instead: ``jit(f).lower(*args).compile().cost_analysis()``
reports the FLOPs and bytes the COMPILED program actually executes
(post-fusion, post-DCE), so

    mfu      = flops / seconds / peak_flops
    hbm_util = bytes_accessed / seconds / peak_hbm_bandwidth

are measured per program variant, not estimated per formula.  Caveats
(docs/OBSERVABILITY.md): under async dispatch ``seconds`` must come from
a host-blocking sync (callers pass the same ``dsync`` trick bench.py
uses — ``block_until_ready`` is a no-op on the tunneled backend), and
``cost_analysis`` availability varies by backend/jax version — every
helper degrades to ``{}``/partial results instead of raising.

``jax.profiler`` trace capture (the XLA-level timeline, complementary to
obs/trace.py's host spans) is wrapped behind ``profiler_trace`` with the
same degrade-gracefully contract.  jax imports are lazy: importing this
module never initializes a backend.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Callable, Optional

# peak dense compute per chip (bf16/int8 systolic, conservative) — shared
# with bench.py's lower-bound estimate
PEAK_FLOPS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,
}
DEFAULT_PEAK_FLOPS = 197e12

# peak HBM bandwidth per chip, bytes/s (public spec sheets)
PEAK_HBM_BW = {
    "v5 lite": 819e9,
    "v5e": 819e9,
    "v4": 1228e9,
    "v5p": 2765e9,
    "v6": 1640e9,
}
DEFAULT_PEAK_HBM_BW = 819e9


def _device_kind(device=None) -> str:
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:
            return ""
    return str(getattr(device, "device_kind", "")).lower()


def peak_flops_for(device=None) -> float:
    kind = _device_kind(device)
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return DEFAULT_PEAK_FLOPS


def peak_hbm_bw_for(device=None) -> float:
    kind = _device_kind(device)
    for key, val in PEAK_HBM_BW.items():
        if key in kind:
            return val
    return DEFAULT_PEAK_HBM_BW


def normalize_cost(ca) -> dict:
    """Flatten a ``cost_analysis()`` result (dict, or list-of-dict on
    older jax) into {str: float}; {} when unavailable."""
    if ca is None:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    out = {}
    try:
        items = dict(ca).items()
    except Exception:
        return {}
    for k, v in items:
        try:
            out[str(k)] = float(v)
        except (TypeError, ValueError):
            continue
    return out


def program_cost(fn: Callable, *args) -> dict:
    """{"flops", "bytes_accessed"} of the compiled program for ``fn`` at
    ``args``'s shapes ({} when the backend reports no cost model).

    ``fn`` may be a plain callable or an already-``jax.jit``-wrapped one;
    the AOT path (``lower().compile()``) hits the persistent compile
    cache, so asking for the cost of an already-trained program is cheap.
    """
    try:
        import jax
        jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = jfn.lower(*args).compile()
        ca = normalize_cost(compiled.cost_analysis())
    except Exception:
        return {}
    if not ca:
        return {}
    out = {}
    if "flops" in ca:
        out["flops"] = ca["flops"]
    ba = ca.get("bytes accessed", ca.get("bytes_accessed"))
    if ba is not None:
        out["bytes_accessed"] = ba
    return out


def _default_sync(out) -> None:
    """Block until device work behind ``out`` is done.  On the tunneled
    axon backend ``block_until_ready`` is a no-op (measured, bench.py
    ``dsync``), so pull a tiny reduction of every array leaf instead."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "astype"):
            np.asarray(jnp.sum(leaf.astype(jnp.float32)))


def measure_program(fn: Callable, args: tuple, reps: int = 3,
                    sync: Optional[Callable] = None,
                    device=None) -> dict:
    """Compile ``fn(*args)``, read its cost analysis, time ``reps``
    executions, and report measured utilization::

        {"flops", "bytes_accessed",            # from cost_analysis
         "seconds_per_call", "mfu", "hbm_gbps", "hbm_util",
         "peak_flops", "peak_hbm_bw"}

    Cost keys are absent when the backend has no cost model; timing keys
    are always present.  ``sync`` defaults to a host-pulling reduction
    (see ``_default_sync``).
    """
    import jax
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    sync = sync or _default_sync
    # ONE compile: the AOT executable serves both the cost analysis and
    # the timed runs (jit'ing again would pay a second, discarded compile
    # for every variant — compile time dominates bench stages)
    out = {}
    runner = jfn
    try:
        compiled = jfn.lower(*args).compile()
        ca = normalize_cost(compiled.cost_analysis())
        if "flops" in ca:
            out["flops"] = ca["flops"]
        ba = ca.get("bytes accessed", ca.get("bytes_accessed"))
        if ba is not None:
            out["bytes_accessed"] = ba
        compiled(*args)                  # callable-executable probe
        runner = compiled
    except Exception:
        runner = jfn                     # backend without AOT/cost model
    sync(runner(*args))                  # warm outside the clock
    t0 = time.perf_counter()
    for _ in range(max(reps, 1)):
        sync(runner(*args))
    sec = (time.perf_counter() - t0) / max(reps, 1)
    out["seconds_per_call"] = sec
    pf = peak_flops_for(device)
    pb = peak_hbm_bw_for(device)
    out["peak_flops"] = pf
    out["peak_hbm_bw"] = pb
    if "flops" in out and sec > 0:
        out["mfu"] = out["flops"] / sec / pf
    if "bytes_accessed" in out and sec > 0:
        out["hbm_gbps"] = out["bytes_accessed"] / sec / 1e9
        out["hbm_util"] = out["bytes_accessed"] / sec / pb
    return out


@contextmanager
def profiler_trace(logdir: str):
    """Optional ``jax.profiler`` capture around a block; yields True when
    the profiler started (False = unavailable on this backend — the block
    still runs)."""
    started = False
    try:
        import jax
        jax.profiler.start_trace(logdir)
        started = True
    except Exception:
        pass
    try:
        yield started
    finally:
        if started:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass


def histogram_utilization_table(rows: int = 200_000, features: int = 28,
                                num_bins: int = 64, slots: int = 8,
                                reps: int = 2, tile_rows: Optional[int] = None,
                                seed: int = 0, quant: bool = True) -> dict:
    """Measured per-kernel-variant utilization table for the histogram
    family: {matmul, matmul_f32, scatter, pallas, sorted, expanded,
    fused} x {f32, quant} x {untiled, tiled} -> ``measure_program``
    dicts.

    This replaces the bench's hand-derived MFU lower bound with the
    compiler's own FLOP/byte counts per compiled variant — the numbers
    the Pallas-megakernel work (ROADMAP item 2) is steered by; the
    ``*/fused`` rows are that megakernel itself (ops/fused.py: histogram
    build + in-VMEM split scan in one program — the acceptance figure is
    its MFU against the staged rows at the same shape); the
    ``*/fused_sharded_{flat,hier}`` rows are its collective-seam form —
    accumulate-only kernel, data-axis psum (identity off-mesh), sibling
    derive + scan kernel — the program pair the data-parallel growers
    actually run.  The
    ``f32/scatter_batched8`` row is the model-axis plane
    (lightgbm_tpu/multi/): the same scatter build vmapped over 8
    lane-stacked gradient vectors against ONE shared binned matrix —
    its MFU against ``f32/scatter`` at the same shape is the per-kernel
    evidence behind the batched sweep stage (tools/sweep_probe.py).  A
    variant unsupported on the backend reports ``{"error": ...}``
    instead of failing the table.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import fused as FU
    from ..ops import histogram as H
    from ..ops.split import SplitHyperparams
    from ..parallel import collectives as PC

    rng = np.random.RandomState(seed)
    n, F, B = int(rows), int(features), int(num_bins)
    binned = jnp.asarray(
        rng.randint(0, B, (F, n), dtype=np.int64), jnp.uint8)
    grad = jnp.asarray(rng.randn(n), jnp.float32)
    hess = jnp.abs(grad) + 0.1
    mask = jnp.ones((n,), jnp.float32)
    slot = jnp.asarray(rng.randint(0, slots, n, dtype=np.int64), jnp.int32)
    gq = jnp.asarray(rng.randint(-8, 8, n, dtype=np.int64), jnp.int8)
    hq = jnp.asarray(rng.randint(0, 8, n, dtype=np.int64), jnp.int8)
    member = jnp.ones((n,), bool)
    # model-axis fixtures: 8 heterogeneous gradient lanes over the ONE
    # shared binned matrix (lane scaling defeats cross-lane CSE)
    lanes = 8
    gradB = jnp.stack([grad * (1.0 + 0.01 * i) for i in range(lanes)])
    hessB = jnp.stack([hess * (1.0 + 0.01 * i) for i in range(lanes)])

    if tile_rows is None:
        tile_rows = 1 << max((n // 4).bit_length() - 1, 10)
    tile_rows = max(min(int(tile_rows), n), 1)

    # fused-megakernel fixtures: per-slot totals + trivially-valid meta
    hp = SplitHyperparams(min_data_in_leaf=1)
    nb_v = jnp.full((F,), B, jnp.int32)
    z_v = jnp.zeros((F,), jnp.int32)
    oh_slot = (slot[None, :] == jnp.arange(slots)[:, None])
    slot_sums = jnp.stack([
        jnp.sum(jnp.where(oh_slot, grad[None, :], 0.0), axis=1),
        jnp.sum(jnp.where(oh_slot, hess[None, :], 0.0), axis=1),
        jnp.sum(oh_slot.astype(jnp.float32), axis=1)])

    def fam(tile):
        ms = {
            "f32/matmul": lambda b, g, h, m: H.build_histogram(
                b, g, h, m, B, method="matmul", tile_rows=tile),
            "f32/matmul_f32": lambda b, g, h, m: H.build_histogram(
                b, g, h, m, B, method="matmul_f32", tile_rows=tile),
            "f32/scatter": lambda b, g, h, m: H.build_histogram(
                b, g, h, m, B, method="scatter", tile_rows=tile),
            "f32/scatter_batched8": lambda b, g, h, m: jax.vmap(
                lambda gg, hh: H.build_histogram(
                    b, gg, hh, m, B, method="scatter", tile_rows=tile)
            )(gradB, hessB),
            "f32/pallas": lambda b, g, h, m: H.build_histogram(
                b, g, h, m, B, method="pallas", tile_rows=tile),
            "f32/sorted": lambda b, g, h, m: H.segment_histogram_sorted(
                b, g, h, m, slot, slots, B, tile_rows=tile),
            "f32/expanded": lambda b, g, h, m: H.segment_histogram_expanded(
                b, g, h, m, slot, B, tile_rows=tile),
            "f32/fused": lambda b, g, h, m: FU.fused_segment_splits(
                b, H._vals_t(g, h, m), slot, slots, B, slot_sums,
                nb_v, z_v, z_v, hp, tile_rows=tile),
            # sharded-seam rows (ops/fused.py collective seam): fused
            # accumulate -> data-axis psum -> fused sibling scan.  Off a
            # mesh the psum is identity, so these measure the two kernel
            # halves the sharded path actually runs; flat vs hierarchical
            # differ only in the reduction routing a real mesh would take
            # (parallel/collectives.py), kept as separate rows so on-mesh
            # captures land in distinct keys.
            "f32/fused_sharded_flat": lambda b, g, h, m:
                FU.fused_sibling_scan(
                    PC.psum_tiered(FU.fused_frontier_accumulate(
                        b, H._vals_t(g, h, m), slot, slots, B,
                        tile_rows=tile), None),
                    slot_sums, nb_v, z_v, z_v, hp),
            "f32/fused_sharded_hier": lambda b, g, h, m:
                FU.fused_sibling_scan(
                    PC.psum_tiered(FU.fused_frontier_accumulate(
                        b, H._vals_t(g, h, m), slot, slots, B,
                        tile_rows=tile), None, hierarchical=True),
                    slot_sums, nb_v, z_v, z_v, hp),
        }
        if quant:
            ms.update({
                "quant/matmul_int8": lambda b, g, h, m: H.build_histogram_int(
                    b, gq, hq, member, B, method="matmul_int8",
                    tile_rows=tile),
                "quant/scatter_int": lambda b, g, h, m: H.build_histogram_int(
                    b, gq, hq, member, B, method="scatter_int",
                    tile_rows=tile),
                "quant/sorted": lambda b, g, h, m:
                    H.segment_histogram_sorted_int(
                        b, gq, hq, slot, slots, B, tile_rows=tile),
                "quant/expanded": lambda b, g, h, m:
                    H.segment_histogram_expanded_int(
                        b, gq, hq, member, slot, B, tile_rows=tile),
                "quant/fused": lambda b, g, h, m:
                    FU.fused_segment_splits(
                        b, H._vals_t_int(gq, hq, member), slot, slots, B,
                        slot_sums, nb_v, z_v, z_v, hp,
                        quant_scales=(jnp.float32(0.25), jnp.float32(0.5)),
                        tile_rows=tile),
                "quant/fused_sharded_flat": lambda b, g, h, m:
                    FU.fused_sibling_scan(
                        H.psum_quant_hist(FU.fused_frontier_accumulate(
                            b, H._vals_t_int(gq, hq, member), slot, slots,
                            B, tile_rows=tile), None, n, B),
                        slot_sums, nb_v, z_v, z_v, hp,
                        quant_scales=(jnp.float32(0.25), jnp.float32(0.5))),
                "quant/fused_sharded_hier": lambda b, g, h, m:
                    FU.fused_sibling_scan(
                        H.psum_quant_hist(FU.fused_frontier_accumulate(
                            b, H._vals_t_int(gq, hq, member), slot, slots,
                            B, tile_rows=tile), None, n, B,
                            hierarchical=True),
                        slot_sums, nb_v, z_v, z_v, hp,
                        quant_scales=(jnp.float32(0.25), jnp.float32(0.5))),
            })
        return ms

    device = None
    try:
        device = jax.devices()[0]
    except Exception:
        pass
    out = {"rows": n, "features": F, "num_bins": B, "slots": slots,
           "tile_rows": tile_rows}
    for tile_label, tile in (("untiled", None), ("tiled", tile_rows)):
        for name, fn in fam(tile).items():
            key = f"{name}/{tile_label}"
            try:
                out[key] = measure_program(
                    jax.jit(fn), (binned, grad, hess, mask),
                    reps=reps, device=device)
            except Exception as e:  # unsupported variant on this backend
                out[key] = {"error": str(e)[:160]}
    return out


def predict_utilization_table(device_forest, rows: int = 200_000,
                              reps: int = 2, num_class: int = 1,
                              seed: int = 0) -> dict:
    """Measured per-traversal-variant utilization table for the predict
    family (ops/predict_kernels.py): {while, fori, fused[, fused_scores]}
    -> ``measure_program`` dicts over one synthetic ``[rows, F]`` batch.

    The histogram table above steers the training-kernel war; this is
    its inference twin — the compiler-counted FLOPs/bytes behind the
    ``predict_probe`` bench stage's sec/Mrow trendline.  ``device_forest``
    is a ``predict.DeviceForest`` (any precision — the variants all read
    its quantized planes); ``fused_scores`` adds the in-kernel leaf-sum
    epilogue row when the forest carries leaf values and the tree count
    divides by ``num_class``.  A variant unsupported on the backend
    reports ``{"error": ...}`` instead of failing the table.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import predict_kernels as PK

    f = device_forest.forest
    F = int(np.asarray(f.split_feature).max(initial=0)) + 1
    rng = np.random.RandomState(seed)
    X = jnp.asarray(rng.randn(int(rows), F), jnp.float32)
    tile = int(getattr(device_forest, "tile_rows", 512)) or 512
    K = max(int(num_class), 1)

    variants = {
        "while": lambda x: PK.leaves_while(device_forest, x),
        "fori": lambda x: PK.leaves_fori(device_forest, x),
        "fused": lambda x: PK.fused_traverse(device_forest, x, tile),
    }
    if (device_forest.leaf_value is not None
            and int(f.num_trees) % K == 0):
        variants["fused_scores"] = lambda x: PK.fused_traverse(
            device_forest, x, tile, K, emit_scores=True)

    device = None
    try:
        device = jax.devices()[0]
    except Exception:
        pass
    out = {"rows": int(rows), "features": F,
           "num_trees": int(f.num_trees), "tile_rows": tile,
           "elected_variant": getattr(device_forest, "variant", "while")}
    for name, fn in variants.items():
        try:
            out[name] = measure_program(jax.jit(fn), (X,), reps=reps,
                                        device=device)
        except Exception as e:  # unsupported variant on this backend
            out[name] = {"error": str(e)[:160]}
    return out


def ingest_utilization_table(dataset, raw: "np.ndarray", reps: int = 2,
                             tile_rows: Optional[int] = None) -> dict:
    """Measured utilization table for the ingest family (ops/ingest.py):
    the bucketize+pack kernel per tile-ladder rung -> ``measure_program``
    dicts over one real raw block, plus a wall-clock ``host`` row (the
    NumPy ``_bin_block`` oracle at the same shape) so the kernel-vs-host
    speedup is read straight off the table — the number behind the
    ``ingest_probe`` bench stage and the ``bin_rows_per_sec`` telemetry
    gauge.  ``dataset`` must be constructed (or sample-fitted) so its
    bin mappers and EFB layout exist; a rung unsupported on the backend
    reports ``{"error": ...}`` instead of failing the table.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import ingest as ING
    from ..ops.planner import INGEST_TILES

    tables = ING.build_ingest_tables(dataset)
    X = np.ascontiguousarray(np.asarray(raw), dtype=np.float32)
    n = int(X.shape[0])
    device = None
    try:
        device = jax.devices()[0]
    except Exception:
        pass
    out = {"rows": n, "features": int(tables.num_features),
           "num_groups": int(tables.num_groups),
           "out_dtype": str(tables.out_dtype)}
    ladder = ((int(tile_rows),) if tile_rows else INGEST_TILES)
    Xd = jnp.asarray(X)
    for tile in ladder:
        binner = ING.DeviceBinner(tables, tile)
        try:
            out[f"kernel/t{tile}"] = measure_program(
                binner._call, (Xd,), reps=reps, device=device)
        except Exception as e:  # unsupported rung on this backend
            out[f"kernel/t{tile}"] = {"error": str(e)[:160]}
    # the host oracle at the same shape: wall clock only (no compiler
    # cost model exists for NumPy) — the denominator of the speedup
    ref = np.zeros((n, tables.num_groups), tables.out_dtype)
    dataset._bin_block(X.astype(np.float64), None, ref)   # warm caches
    t0 = time.perf_counter()
    for _ in range(max(reps, 1)):
        dataset._bin_block(X.astype(np.float64), None, ref)
    sec = (time.perf_counter() - t0) / max(reps, 1)
    out["host"] = {"seconds_per_call": sec}
    best = min((v["seconds_per_call"] for k, v in out.items()
                if k.startswith("kernel/") and isinstance(v, dict)
                and "seconds_per_call" in v), default=None)
    if best:
        out["best_kernel_seconds_per_call"] = best
        out["kernel_speedup_vs_host"] = round(sec / max(best, 1e-12), 3)
        out["bin_rows_per_sec"] = round(n / max(best, 1e-12), 1)
    return out
