"""Flight recorder: an always-on, bounded ring buffer of recent
observability events that dumps an atomic forensic bundle when a run
dies (docs/OBSERVABILITY.md).

Tracing (obs/trace.py) answers "show me the timeline I asked for";
the flight recorder answers "what were the last N things that happened
before the 3am crash" — WITHOUT anyone having asked in advance.  It is
armed by default (``LIGHTGBM_TPU_FLIGHT=0`` disables) and costs one
bounded ``deque.append`` per fed event:

- **ring** — a ``collections.deque(maxlen=...)`` of Chrome-trace-shaped
  events: every span/instant the tracer records is teed in when tracing
  is enabled, and the instrumented seams (engine step boundary,
  ``resilient_allgather`` attempts, serving batches, planner verdict
  instants) feed it DIRECTLY via ``note``/``note_instant`` even with
  tracing off, so the ring is never empty when it matters.  O(1)
  memory, no growth, no numerics touched — recorder-on training is
  byte-identical by construction.
- **metric marks** — a small deque of periodic counter/gauge snapshots
  (``sample_metrics``) so a bundle can show metric DELTAS across the
  final minutes, not just the terminal values.
- **dump triggers** — an unhandled engine-loop exception,
  ``CollectiveError``, ``SliceLostError``, ``SwapQuarantined`` /
  ``LowPrecisionQuarantined``, or a watchdog SLO breach each call
  ``on_exception``/``dump``, writing ONE atomic JSON bundle:
  the ring as a loadable Chrome trace, a full metrics snapshot +
  deltas, and a config/env/mesh fingerprint.  Dumping never raises
  into the failing caller and is rate-limited (``max_dumps``) so a
  crash loop cannot fill a disk.

Env knobs: ``LIGHTGBM_TPU_FLIGHT`` (unset/1 = armed, 0 = off),
``LIGHTGBM_TPU_FLIGHT_EVENTS`` (ring capacity, default 2048),
``LIGHTGBM_TPU_FLIGHT_DIR`` (bundle directory, default cwd),
``LIGHTGBM_TPU_FLIGHT_MAX_DUMPS`` (default 8 per process).
Stdlib-only; jax is only READ from ``sys.modules`` (a bundle never
initializes a backend).
"""

from __future__ import annotations

import collections
import json
import os
import platform as _platform
import sys
import threading
import time
import traceback
from typing import Optional

from . import trace as _trace

_FLIGHT_ENV = "LIGHTGBM_TPU_FLIGHT"
_EVENTS_ENV = "LIGHTGBM_TPU_FLIGHT_EVENTS"
_DIR_ENV = "LIGHTGBM_TPU_FLIGHT_DIR"
_MAX_DUMPS_ENV = "LIGHTGBM_TPU_FLIGHT_MAX_DUMPS"
_DEFAULT_RING = 2048
BUNDLE_VERSION = 1

# env prefixes worth fingerprinting in a bundle (the knobs that decide
# planner verdicts, mesh shapes, chunking, streaming, compile caching)
_ENV_PREFIXES = ("LGBM_TPU", "LIGHTGBM_TPU", "JAX_", "XLA_", "BENCH_")


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    return v if v > 0 else default


def _json_safe(v, depth: int = 0):
    """Clamp arbitrary note args into JSON-serializable primitives —
    a forensic bundle that fails to serialize is worse than a lossy one."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if depth >= 3:
        return repr(v)[:200]
    if isinstance(v, dict):
        return {str(k)[:80]: _json_safe(x, depth + 1)
                for k, x in list(v.items())[:64]}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_json_safe(x, depth + 1) for x in list(v)[:64]]
    try:
        return float(v)          # numpy scalars and friends
    except (TypeError, ValueError):
        return repr(v)[:200]


class FlightRecorder:
    """Bounded ring of recent events + atomic forensic bundle dumps."""

    def __init__(self, max_events: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 out_dir: Optional[str] = None,
                 max_dumps: Optional[int] = None):
        if enabled is None:
            enabled = os.environ.get(_FLIGHT_ENV, "1") != "0"
        self.enabled = enabled
        cap = (int(max_events) if max_events is not None
               else _env_int(_EVENTS_ENV, _DEFAULT_RING))
        self._ring: "collections.deque" = collections.deque(maxlen=cap)
        # (ts_unix, counters+numeric gauges) marks for delta reporting
        self._marks: "collections.deque" = collections.deque(maxlen=8)
        self._lock = threading.Lock()
        self._out_dir = out_dir
        self.max_dumps = (int(max_dumps) if max_dumps is not None
                          else _env_int(_MAX_DUMPS_ENV, 8))
        self.dumps = 0
        self._seq = 0
        self._last_sample = 0.0
        self._context: dict = {}
        self._pid = os.getpid()

    # ------------------------------------------------------------- feeding

    def feed(self, ev: dict) -> None:
        """Tee one already-formatted trace event into the ring (called by
        the tracer on every recorded span/instant)."""
        if self.enabled:
            self._ring.append(ev)

    def note(self, name: str, **args) -> None:
        """Record a complete-style event directly (instrumented seams:
        engine step, allgather attempt, serving batch).  Cheap: one dict
        build + one bounded append; a no-op when disarmed."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "X", "pid": self._pid,
              "tid": threading.get_ident(),
              "ts": (time.perf_counter() - _trace.global_tracer._epoch)
              * 1e6,
              "dur": float(args.pop("dur_us", 0.0))}
        if args:
            ev["args"] = args
        self._ring.append(ev)

    def note_instant(self, name: str, args: dict) -> None:
        """Point-in-time twin of ``note`` (trace.instant tees here when
        tracing is disabled, so planner verdicts always reach the ring)."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "i", "s": "t", "pid": self._pid,
              "tid": threading.get_ident(),
              "ts": (time.perf_counter() - _trace.global_tracer._epoch)
              * 1e6}
        if args:
            ev["args"] = dict(args)
        self._ring.append(ev)

    def set_context(self, **ctx) -> None:
        """Attach run context (training params, serving config, mesh
        summary) included verbatim in every bundle's fingerprint."""
        with self._lock:
            self._context.update(
                {k: _json_safe(v) for k, v in ctx.items()})

    def sample_metrics(self, registry=None,
                       min_interval_s: float = 5.0) -> None:
        """Snapshot counters + numeric gauges into the bounded marks
        deque (rate-limited); bundles report first-vs-last deltas."""
        if not self.enabled:
            return
        now = time.monotonic()
        if now - self._last_sample < min_interval_s and self._marks:
            return
        self._last_sample = now
        try:
            if registry is None:
                from .metrics import global_registry as registry
            d = registry.to_dict()
            nums = dict(d.get("counters", {}))
            nums.update({k: v for k, v in d.get("gauges", {}).items()
                         if isinstance(v, (int, float))
                         and not isinstance(v, bool)})
            self._marks.append((time.time(), nums))
        except Exception:  # noqa: BLE001 — telemetry never breaks callers
            pass

    # ------------------------------------------------------------- dumping

    def ring_events(self) -> list:
        return list(self._ring)

    def _metric_deltas(self) -> dict:
        if len(self._marks) < 2:
            return {}
        (t0, a), (t1, b) = self._marks[0], self._marks[-1]
        out = {}
        for k, v in b.items():
            d = v - a.get(k, 0)
            if d:
                out[k] = d
        return {"window_s": round(t1 - t0, 3), "deltas": out}

    def fingerprint(self) -> dict:
        """Config/env/mesh identity of THIS process: enough to answer
        "what exact setup died" without a live debugger."""
        fp = {
            "pid": self._pid,
            "time_unix": time.time(),
            "argv": [str(a)[:200] for a in sys.argv[:8]],
            "python": sys.version.split()[0],
            "platform": _platform.platform(),
            "env": {k: os.environ[k] for k in sorted(os.environ)
                    if k.startswith(_ENV_PREFIXES)},
            "context": dict(self._context),
        }
        jax = sys.modules.get("jax")
        if jax is not None:       # never initializes a backend here
            try:
                fp["jax_version"] = getattr(jax, "__version__", "")
                # jax.devices() INITIALIZES the default backend when none
                # exists — multi-second TPU init from a crash path; only
                # report device facts a live backend already knows
                from jax._src import xla_bridge
                if getattr(xla_bridge, "_backends", None):
                    devs = jax.devices()
                    fp["backend"] = devs[0].platform
                    fp["device_kind"] = getattr(devs[0], "device_kind", "")
                    fp["n_devices"] = len(devs)
                    fp["process_index"] = jax.process_index()
                    fp["process_count"] = jax.process_count()
            except Exception:  # noqa: BLE001 — uninitialized backend
                pass
        try:
            from .metrics import global_registry
            g = global_registry.to_dict().get("gauges", {})
            fp["mesh"] = {k: g[k] for k in (
                "train_num_slices", "train_hier_reduce",
                "train_ici_payload_bytes", "train_dcn_payload_bytes",
                "train_hist_method", "train_tile_rows") if k in g}
        except Exception:  # noqa: BLE001
            pass
        return fp

    def bundle(self, trigger: str, exc: Optional[BaseException] = None,
               extra: Optional[dict] = None) -> dict:
        """The forensic bundle dict (``dump`` writes it atomically)."""
        evs = sorted(self.ring_events(), key=lambda e: e.get("ts", 0.0))
        ring = {"traceEvents": [
            {"name": "process_name", "ph": "M", "pid": self._pid,
             "tid": 0, "ts": 0.0,
             "args": {"name": f"lightgbm-tpu flight [{trigger}]"}}] + evs,
            "displayTimeUnit": "ms"}
        out = {
            "flight_bundle": BUNDLE_VERSION,
            "trigger": trigger,
            "ring": ring,
            "ring_events": len(evs),
            "metric_deltas": self._metric_deltas(),
            "fingerprint": self.fingerprint(),
        }
        if exc is not None:
            out["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc)[:2000],
                "traceback_tail": "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__))[-4000:],
            }
        try:
            from .metrics import global_registry
            out["metrics"] = global_registry.to_dict()
        except Exception:  # noqa: BLE001
            out["metrics"] = {}
        if extra:
            out["extra"] = _json_safe(extra)
        return out

    def out_dir(self) -> str:
        return (self._out_dir or os.environ.get(_DIR_ENV) or os.getcwd())

    def dump(self, trigger: str, exc: Optional[BaseException] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write one atomic forensic bundle; returns its path, or None
        (disarmed / rate-limited / write failed).  NEVER raises — the
        recorder must not turn a failing run into a failing-worse run."""
        if not self.enabled:
            return None
        with self._lock:
            if self.dumps >= self.max_dumps:
                return None
            self.dumps += 1
            self._seq += 1
            seq = self._seq
        try:
            safe = "".join(c if c.isalnum() or c in "-_." else "_"
                           for c in trigger)[:60] or "trigger"
            path = os.path.join(
                self.out_dir(),
                f"flight_{safe}_{self._pid}_{seq}.json")
            payload = json.dumps(self.bundle(trigger, exc=exc, extra=extra),
                                 default=lambda v: _json_safe(v))
            from ..utils.file_io import write_atomic
            write_atomic(path, payload)
        except Exception as e:  # noqa: BLE001 — forensics must not crash
            try:
                from ..utils.log import log_warning
                log_warning(f"flight recorder: bundle write failed ({e!r})")
            except Exception:  # noqa: BLE001
                pass
            return None
        try:
            from .metrics import global_registry
            global_registry.counter(
                "flight_dumps_total", labels={"trigger": safe}).inc()
            from ..utils.log import log_warning
            log_warning(f"flight recorder: forensic bundle -> {path} "
                        f"(trigger={trigger})")
        except Exception:  # noqa: BLE001
            pass
        return path

    def on_exception(self, site: str,
                     exc: BaseException) -> Optional[str]:
        """Dump with a ``<site>:<ExcType>`` trigger — the one-liner the
        raise sites (engine loop, collectives, elastic, serving swap)
        call on their way out."""
        return self.dump(f"{site}:{type(exc).__name__}", exc=exc)


# THE process flight recorder: armed unless LIGHTGBM_TPU_FLIGHT=0.
global_flight = FlightRecorder()

# tee tracer-recorded events into the ring (trace.py holds only a weak
# seam — no import cycle)
_trace.set_flight_sink(global_flight)


def note(name: str, **args) -> None:
    """Module-level ``global_flight.note`` (instrumentation entry)."""
    global_flight.note(name, **args)
