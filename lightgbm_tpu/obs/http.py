"""Metrics-over-HTTP: an opt-in stdlib endpoint serving the unified
process registry for LIVE scraping of training and serving processes.

``to_prometheus()``/``to_dict()`` already render the registry; this
module puts them on a socket so an operator (or a Prometheus scraper)
can watch a RUNNING train/serve process instead of waiting for exit
dumps.  Endpoints:

- ``GET /metrics``       — Prometheus text exposition (version 0.0.4)
- ``GET /metrics.json``  — the ``to_dict()`` JSON snapshot
- ``GET /healthz``       — ``ok`` (200) while the global watchdog has no
  un-recovered SLO breach; 503 with a JSON breach list otherwise, so an
  orchestrator's readiness probe sheds traffic from a browned-out pod
  instead of reading "alive" as "healthy"

Opt-in only: ``LIGHTGBM_TPU_METRICS_PORT=<port>`` makes the engine and
every ``Server`` call ``maybe_start_from_env`` (idempotent, one server
per process); port ``0`` binds an ephemeral port (tests).  The server is
a daemon ``ThreadingHTTPServer`` bound to localhost by default
(``LIGHTGBM_TPU_METRICS_HOST`` overrides — exposing beyond localhost is
the operator's explicit choice).  Serving a scrape never touches device
state: both renderers only read instrument values under the registry
lock.  Stdlib-only.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_PORT_ENV = "LIGHTGBM_TPU_METRICS_PORT"
_HOST_ENV = "LIGHTGBM_TPU_METRICS_HOST"

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    """One registry on one port; ``start()`` returns the bound port."""

    def __init__(self, registry=None, port: int = 0,
                 host: Optional[str] = None):
        if registry is None:
            from .metrics import global_registry as registry
        self.registry = registry
        self.host = host or os.environ.get(_HOST_ENV, "127.0.0.1")
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        if self._httpd is not None:
            return self.port
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(200,
                                   registry.to_prometheus().encode(),
                                   PROM_CONTENT_TYPE)
                    elif path == "/metrics.json":
                        self._send(200,
                                   json.dumps(registry.to_dict(),
                                              sort_keys=True).encode(),
                                   "application/json")
                    elif path == "/healthz":
                        from .watchdog import global_watchdog
                        breaches = global_watchdog.active_breaches()
                        if breaches:
                            self._send(503, json.dumps(
                                {"status": "degraded",
                                 "breaches": breaches},
                                sort_keys=True).encode(),
                                "application/json")
                        else:
                            self._send(200, b"ok\n", "text/plain")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as e:  # noqa: BLE001 — scrape never kills
                    try:
                        self._send(500, repr(e).encode(), "text/plain")
                    except Exception:  # noqa: BLE001
                        pass

            def log_message(self, *a):     # no stderr chatter per scrape
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="lgbt-metrics-http")
        self._thread.start()
        from ..utils.log import log_info
        log_info(f"metrics HTTP exposition on "
                 f"http://{self.host}:{self.port}/metrics")
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


_lock = threading.Lock()
_server: Optional[MetricsHTTPServer] = None


def maybe_start_from_env() -> Optional[MetricsHTTPServer]:
    """Start the process metrics endpoint when
    ``LIGHTGBM_TPU_METRICS_PORT`` is set (idempotent; "" disables, "0"
    binds ephemeral).  Returns the live server or None."""
    global _server
    v = os.environ.get(_PORT_ENV, "").strip()
    if not v:
        return _server
    with _lock:
        if _server is None:
            try:
                srv = MetricsHTTPServer(port=int(v))
                srv.start()
                _server = srv
            except (ValueError, OSError) as e:
                from ..utils.log import log_warning
                log_warning(
                    f"metrics HTTP endpoint failed to start on "
                    f"{_PORT_ENV}={v!r}: {e}")
                return None
        return _server


def stop_process_server() -> None:
    """Tear down the env-started endpoint (tests)."""
    global _server
    with _lock:
        if _server is not None:
            _server.stop()
            _server = None
