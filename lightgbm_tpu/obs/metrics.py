"""The single process metrics registry: counters, gauges, histograms.

Promoted from ``serving/metrics.py`` (which now re-exports from here) so
training, serving, resilience and the bench all report through ONE
instrument model:

- serving keeps per-``Server`` registries (tests assert per-server
  counters) but each server ATTACHES its registry to the process
  registry as a named component, so a process-wide snapshot sees it;
- training-side gauges/counters (trees/sec, resolved histogram variant,
  planner verdicts, compile-cache warmth, psum payload bytes, checkpoint
  durations, macro chunk sizes) land directly on ``global_registry``;
- ``resilient_allgather`` defaults its collective counters here when no
  registry is passed.

Two export formats: ``to_dict()`` (the historical JSON layout —
``counters``/``gauges``/``histograms``, unchanged key schema, plus a
``components`` section when children are attached) and
``to_prometheus()`` (text exposition format, cumulative buckets), so an
operator can scrape the same numbers the tests assert on.

Instruments are deliberately simple — a histogram is fixed upper-bound
buckets plus count/sum/min/max, not a quantile sketch: the consumers here
are tests and benchmark JSON, where exact bucket counts beat approximate
percentiles.  Every mutation takes the owning registry's single lock;
mutation rates (one batch / boosting iteration every few ms) are far
below where lock sharding would matter.  Dependency-free: stdlib only,
never imports jax.
"""

from __future__ import annotations

import json
import math
import re
import threading
from typing import Dict, List, Optional, Sequence

# default latency bucket upper bounds, milliseconds (log-ish ladder)
LATENCY_BUCKETS_MS = (0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0,
                      200.0, 500.0, 1000.0, 2000.0, 5000.0, math.inf)
# fill-ratio buckets: deciles of rows / bucket_capacity
RATIO_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)


class Counter:
    """Monotonic counter."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-set value (numeric or short string, e.g. a model digest)."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with count/sum/min/max.

    ``buckets`` are inclusive upper bounds in ascending order; the last
    bound may be +inf (it is reported as the string "inf" in JSON).
    """

    def __init__(self, lock: threading.Lock,
                 buckets: Sequence[float] = LATENCY_BUCKETS_MS):
        self._lock = lock
        self.bounds: List[float] = list(buckets)
        if self.bounds[-1] != math.inf:
            self.bounds.append(math.inf)
        self._counts = [0] * len(self.bounds)
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    break

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def snapshot(self) -> dict:
        with self._lock:
            if self._count == 0:
                return {"count": 0, "sum": 0.0}
            return {
                "count": self._count,
                "sum": round(self._sum, 6),
                "mean": round(self._sum / self._count, 6),
                "min": round(self._min, 6),
                "max": round(self._max, 6),
                "buckets": {
                    ("inf" if math.isinf(b) else repr(b)): c
                    for b, c in zip(self.bounds, self._counts) if c
                },
            }

    def cumulative(self) -> tuple:
        """(list of (upper_bound, cumulative_count), sum, count) — the
        Prometheus exposition shape (buckets are cumulative there)."""
        with self._lock:
            out, running = [], 0
            for b, c in zip(self.bounds, self._counts):
                running += c
                out.append((b, running))
            return out, self._sum, self._count


def _prom_name(name: str, prefix: str = "") -> str:
    """Sanitize an instrument name into a legal Prometheus metric name."""
    s = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if prefix:
        s = f"{prefix}_{s}"
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _esc_label(v) -> str:
    # Prometheus text format: backslash, quote AND line feed must be
    # escaped in label values or one bad value splits the sample across
    # lines and the scraper rejects the whole exposition
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels_suffix(labels: Optional[dict]) -> str:
    """Canonical ``{k="v",...}`` series suffix (sorted keys) — also the
    instrument-key suffix, so the same (name, labels) pair always
    resolves to the same instrument."""
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _series(name: str, labels: Optional[dict],
            extra: Optional[dict] = None) -> str:
    """One exposition sample name: metric name + merged label set
    (instrument labels first, then per-sample ones like ``le``)."""
    merged = dict(labels or {})
    if extra:
        merged.update(extra)
    return name + _labels_suffix(merged)


class MetricsRegistry:
    """Named instrument registry; ``counter``/``gauge``/``histogram`` are
    get-or-create so call sites never race on registration.  Child
    registries (``attach_child``) appear in snapshots as components.

    ``labels={"model": "ranker"}`` creates a LABELLED series of the same
    metric (the serving fleet's per-model instruments): distinct label
    values are distinct instruments, keyed ``name{k="v"}``.  Unlabelled
    instruments keep their exact historical keys in ``to_dict`` — the
    labelled series appear ADDITIVELY under their suffixed keys — and
    ``to_prometheus`` emits proper label sets (one # TYPE line per
    metric name, per-sample labels like ``le`` merged in)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._reg_lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._children: Dict[str, "MetricsRegistry"] = {}
        # key -> (bare name, labels dict) for labelled series only
        self._meta: Dict[str, tuple] = {}

    def _key(self, name: str, labels: Optional[dict]) -> str:
        if not labels:
            return name
        key = name + _labels_suffix(labels)
        self._meta.setdefault(key, (name, dict(labels)))
        return key

    def counter(self, name: str, labels: Optional[dict] = None) -> Counter:
        with self._reg_lock:
            key = self._key(name, labels)
            if key not in self._counters:
                self._counters[key] = Counter(self._lock)
            return self._counters[key]

    def gauge(self, name: str, labels: Optional[dict] = None) -> Gauge:
        with self._reg_lock:
            key = self._key(name, labels)
            if key not in self._gauges:
                self._gauges[key] = Gauge(self._lock)
            return self._gauges[key]

    def histogram(self, name: str,
                  buckets: Sequence[float] = LATENCY_BUCKETS_MS,
                  labels: Optional[dict] = None) -> Histogram:
        with self._reg_lock:
            key = self._key(name, labels)
            if key not in self._histograms:
                self._histograms[key] = Histogram(self._lock, buckets)
            return self._histograms[key]

    # ----------------------------------------------------------- components

    def attach_child(self, name: str, child: "MetricsRegistry") -> str:
        """Register a component registry (e.g. one serving Server) under
        ``name``; a taken name gets a numeric suffix.  Returns the name
        actually used (pass it to ``detach_child``)."""
        with self._reg_lock:
            key, i = name, 1
            while key in self._children:
                i += 1
                key = f"{name}_{i}"
            self._children[key] = child
            return key

    def detach_child(self, name: str) -> None:
        with self._reg_lock:
            self._children.pop(name, None)

    def children(self) -> Dict[str, "MetricsRegistry"]:
        with self._reg_lock:
            return dict(self._children)

    # -------------------------------------------------------------- export

    def to_dict(self) -> dict:
        """JSON-ready snapshot (schema: docs/SERVING.md; unchanged from
        the serving-era layout — ``components`` appears only when child
        registries are attached)."""
        with self._reg_lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
            children = dict(self._children)
        out = {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: g.value for k, g in sorted(gauges.items())},
            "histograms": {k: h.snapshot() for k, h in sorted(hists.items())},
        }
        if children:
            out["components"] = {k: c.to_dict()
                                 for k, c in sorted(children.items())}
        return out

    def dump_json(self, path: Optional[str] = None, indent: int = 1) -> str:
        s = json.dumps(self.to_dict(), indent=indent, sort_keys=True)
        if path is not None:
            # bench stages and operators read these snapshots back; the
            # atomic seam means a scrape never sees a half-written one
            from ..utils.file_io import write_atomic
            write_atomic(path, s)
        return s

    def to_prometheus(self, prefix: str = "lgbt") -> str:
        """Prometheus text exposition (version 0.0.4) of every instrument,
        children included (component name joins the prefix).  Non-numeric
        gauges (model digests) export as ``<name>_info{value="..."} 1``.
        """
        with self._reg_lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
            children = dict(self._children)
            meta = dict(self._meta)
        lines: List[str] = []
        typed: set = set()      # one # TYPE line per metric name

        def head(key):
            name, labels = meta.get(key, (key, None))
            return _prom_name(name, prefix), labels

        def declare(n, kind):
            if n not in typed:
                typed.add(n)
                lines.append(f"# TYPE {n} {kind}")

        for k, c in sorted(counters.items()):
            n, labels = head(k)
            declare(n, "counter")
            lines.append(f"{_series(n, labels)} {c.value}")
        for k, g in sorted(gauges.items()):
            n, labels = head(k)
            v = g.value
            if isinstance(v, bool):
                v = int(v)
            if isinstance(v, (int, float)) and math.isfinite(v):
                declare(n, "gauge")
                lines.append(f"{_series(n, labels)} {v}")
            else:
                declare(f"{n}_info", "gauge")
                lines.append(
                    f"{_series(n + '_info', labels, {'value': v})} 1")
        for k, h in sorted(hists.items()):
            n, labels = head(k)
            cum, total, count = h.cumulative()
            declare(n, "histogram")
            for bound, c in cum:
                le = "+Inf" if math.isinf(bound) else repr(float(bound))
                lines.append(
                    f"{_series(n + '_bucket', labels, {'le': le})} {c}")
            lines.append(f"{_series(n + '_sum', labels)} {total}")
            lines.append(f"{_series(n + '_count', labels)} {count}")
        for name, child in sorted(children.items()):
            lines.append(child.to_prometheus(
                prefix=_prom_name(name, prefix)).rstrip("\n"))
        return "\n".join(lines) + "\n"


# THE process registry: training/resilience instruments land here and
# serving Servers attach their per-server registries as components.
global_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return global_registry
