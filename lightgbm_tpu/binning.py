"""Host-side feature binning (quantization).

TPU-native re-design of LightGBM's BinMapper (reference:
include/LightGBM/bin.h:61-219, src/io/bin.cpp:54-534).  Binning is a
host-side, one-shot preprocessing step: the TPU only ever sees the binned
uint8/uint16 matrix, so this module is plain NumPy.  The binning *algorithm*
reproduces the reference semantics exactly (GreedyFindBin,
FindBinWithZeroAsOneBin, categorical count-sort, missing types) so that
split thresholds and model text are cross-compatible.
"""

from __future__ import annotations

import ctypes
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

# reference: include/LightGBM/meta.h:53
K_ZERO_THRESHOLD = 1e-35
# reference: include/LightGBM/bin.h:39
K_SPARSE_THRESHOLD = 0.7


class MissingType:
    NONE = 0
    ZERO = 1
    NAN = 2

    _NAMES = {0: "None", 1: "Zero", 2: "NaN"}

    @staticmethod
    def to_str(v: int) -> str:
        return MissingType._NAMES[v]

    @staticmethod
    def from_str(s: str) -> int:
        for k, v in MissingType._NAMES.items():
            if v.lower() == s.lower():
                return k
        raise ValueError(f"unknown missing type {s!r}")


class BinType:
    NUMERICAL = 0
    CATEGORICAL = 1


def _next_after_up(a: float) -> float:
    """reference: Common::GetDoubleUpperBound (utils/common.h:894)."""
    return math.nextafter(a, math.inf)


def _check_double_equal_ordered(a: float, b: float) -> bool:
    """reference: Common::CheckDoubleEqualOrdered (utils/common.h:889)."""
    return b <= math.nextafter(a, math.inf)


def _greedy_find_bin_native(distinct_values, counts, max_bin, total_cnt,
                            min_data_in_bin):
    """Native GreedyFindBin (native/findbin.cpp); None if lib unavailable."""
    from .native.build import load_native_lib
    lib = load_native_lib()
    if lib is None or not hasattr(lib, "lgbt_greedy_find_bin"):
        return None
    dv = np.ascontiguousarray(distinct_values, dtype=np.float64)
    ct = np.ascontiguousarray(counts, dtype=np.int64)
    out = np.empty(max(max_bin, 1), np.float64)
    n = lib.lgbt_greedy_find_bin(
        dv.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ct.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(len(dv)), ctypes.c_int(int(max_bin)),
        ctypes.c_int64(int(total_cnt)), ctypes.c_int(int(min_data_in_bin)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out[:n].tolist()


def greedy_find_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_cnt: int,
    min_data_in_bin: int,
) -> List[float]:
    """Equal-ish-frequency bin boundaries over sorted distinct values.

    reference: GreedyFindBin (src/io/bin.cpp:77-155).  Returns the list of
    bin upper bounds, last element is +inf.  The greedy scan is
    sequential over up to the sampled distinct-value count; the native
    implementation (native/findbin.cpp, identical float semantics) does
    it at C speed, with this Python body as the fallback and the
    equivalence pinned by tests/test_binning.py.
    """
    if len(distinct_values) > 512 and max_bin > 0:
        native = _greedy_find_bin_native(distinct_values, counts, max_bin,
                                         total_cnt, min_data_in_bin)
        if native is not None:
            return native
    num_distinct_values = len(distinct_values)
    bin_upper_bound: List[float] = []
    assert max_bin > 0
    if num_distinct_values == 0:
        return [math.inf]
    if num_distinct_values <= max_bin:
        cur_cnt_inbin = 0
        for i in range(num_distinct_values - 1):
            cur_cnt_inbin += int(counts[i])
            if cur_cnt_inbin >= min_data_in_bin:
                val = _next_after_up((float(distinct_values[i]) + float(distinct_values[i + 1])) / 2.0)
                if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
                    bin_upper_bound.append(val)
                    cur_cnt_inbin = 0
        bin_upper_bound.append(math.inf)
        return bin_upper_bound

    if min_data_in_bin > 0:
        max_bin = min(max_bin, total_cnt // min_data_in_bin)
        max_bin = max(max_bin, 1)
    mean_bin_size = total_cnt / max_bin

    rest_bin_cnt = max_bin
    rest_sample_cnt = total_cnt
    is_big_count_value = counts >= mean_bin_size
    rest_bin_cnt -= int(is_big_count_value.sum())
    rest_sample_cnt -= int(counts[is_big_count_value].sum())
    mean_bin_size = rest_sample_cnt / rest_bin_cnt if rest_bin_cnt > 0 else math.inf

    upper_bounds = [math.inf] * max_bin
    lower_bounds = [math.inf] * max_bin

    bin_cnt = 0
    lower_bounds[bin_cnt] = float(distinct_values[0])
    cur_cnt_inbin = 0
    counts_l = counts.tolist()
    big_l = is_big_count_value.tolist()
    vals_l = distinct_values.tolist()
    for i in range(num_distinct_values - 1):
        if not big_l[i]:
            rest_sample_cnt -= counts_l[i]
        cur_cnt_inbin += counts_l[i]
        # need a new bin: the reference's `std::max(1.0, mean_bin_size *
        # 0.5f)` promotes to DOUBLE (double * float -> double), so the
        # half-mean trigger compares at double precision (ADVICE.md r5)
        if big_l[i] or cur_cnt_inbin >= mean_bin_size or (
            big_l[i + 1] and cur_cnt_inbin >= max(1.0, mean_bin_size * 0.5)
        ):
            upper_bounds[bin_cnt] = vals_l[i]
            bin_cnt += 1
            lower_bounds[bin_cnt] = vals_l[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt_inbin = 0
            if not big_l[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / rest_bin_cnt if rest_bin_cnt > 0 else math.inf
    bin_cnt += 1
    bin_upper_bound = []
    for i in range(bin_cnt - 1):
        val = _next_after_up((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
        if not bin_upper_bound or not _check_double_equal_ordered(bin_upper_bound[-1], val):
            bin_upper_bound.append(val)
    bin_upper_bound.append(math.inf)
    return bin_upper_bound


def _find_bin_with_zero_as_one_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_sample_cnt: int,
    min_data_in_bin: int,
) -> List[float]:
    """reference: FindBinWithZeroAsOneBin (src/io/bin.cpp:255-312)."""
    num_distinct_values = len(distinct_values)
    left_mask = distinct_values <= -K_ZERO_THRESHOLD
    right_mask = distinct_values > K_ZERO_THRESHOLD
    left_cnt_data = int(counts[left_mask].sum())
    right_cnt_data = int(counts[right_mask].sum())
    cnt_zero = total_sample_cnt - left_cnt_data - right_cnt_data

    nonleft = np.nonzero(~left_mask)[0]
    left_cnt = int(nonleft[0]) if len(nonleft) else num_distinct_values

    bin_upper_bound: List[float] = []
    if left_cnt > 0 and max_bin > 1:
        denom = total_sample_cnt - cnt_zero
        left_max_bin = int(left_cnt_data / denom * (max_bin - 1)) if denom > 0 else 1
        left_max_bin = max(1, left_max_bin)
        bin_upper_bound = greedy_find_bin(
            distinct_values[:left_cnt], counts[:left_cnt], left_max_bin, left_cnt_data, min_data_in_bin
        )
        if bin_upper_bound:
            bin_upper_bound[-1] = -K_ZERO_THRESHOLD

    rights = np.nonzero(right_mask[left_cnt:])[0]
    right_start = left_cnt + int(rights[0]) if len(rights) else -1

    right_max_bin = max_bin - 1 - len(bin_upper_bound)
    if right_start >= 0 and right_max_bin > 0:
        right_bounds = greedy_find_bin(
            distinct_values[right_start:], counts[right_start:], right_max_bin, right_cnt_data, min_data_in_bin
        )
        bin_upper_bound.append(K_ZERO_THRESHOLD)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(math.inf)
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def _find_bin_with_predefined_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_sample_cnt: int,
    min_data_in_bin: int,
    forced_upper_bounds: Sequence[float],
) -> List[float]:
    """reference: FindBinWithPredefinedBin (src/io/bin.cpp:157-253)."""
    num_distinct_values = len(distinct_values)
    left_mask = distinct_values <= -K_ZERO_THRESHOLD
    right_mask = distinct_values > K_ZERO_THRESHOLD
    nonleft = np.nonzero(~left_mask)[0]
    left_cnt = int(nonleft[0]) if len(nonleft) else num_distinct_values
    rights = np.nonzero(right_mask[left_cnt:])[0]
    right_start = left_cnt + int(rights[0]) if len(rights) else -1

    bin_upper_bound: List[float] = []
    if max_bin == 2:
        bin_upper_bound.append(K_ZERO_THRESHOLD if left_cnt == 0 else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            bin_upper_bound.append(-K_ZERO_THRESHOLD)
        if right_start >= 0:
            bin_upper_bound.append(K_ZERO_THRESHOLD)
    bin_upper_bound.append(math.inf)

    max_to_insert = max_bin - len(bin_upper_bound)
    num_inserted = 0
    for b in forced_upper_bounds:
        if num_inserted >= max_to_insert:
            break
        if abs(b) > K_ZERO_THRESHOLD:
            bin_upper_bound.append(float(b))
            num_inserted += 1
    bin_upper_bound.sort()

    free_bins = max_bin - len(bin_upper_bound)
    bounds_to_add: List[float] = []
    value_ind = 0
    n_fixed = len(bin_upper_bound)
    for i in range(n_fixed):
        cnt_in_bin = 0
        distinct_cnt_in_bin = 0
        bin_start = value_ind
        while value_ind < num_distinct_values and distinct_values[value_ind] < bin_upper_bound[i]:
            cnt_in_bin += int(counts[value_ind])
            distinct_cnt_in_bin += 1
            value_ind += 1
        bins_remaining = max_bin - n_fixed - len(bounds_to_add)
        num_sub_bins = int(round(cnt_in_bin * free_bins / total_sample_cnt))
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == n_fixed - 1:
            num_sub_bins = bins_remaining + 1
        new_ub = greedy_find_bin(
            distinct_values[bin_start:bin_start + distinct_cnt_in_bin],
            counts[bin_start:bin_start + distinct_cnt_in_bin],
            num_sub_bins, cnt_in_bin, min_data_in_bin,
        )
        bounds_to_add.extend(new_ub[:-1])  # last bound is inf
    bin_upper_bound.extend(bounds_to_add)
    bin_upper_bound.sort()
    assert len(bin_upper_bound) <= max_bin
    return bin_upper_bound


def _need_filter(cnt_in_bin: List[int], total_cnt: int, filter_cnt: int, bin_type: int) -> bool:
    """reference: NeedFilter (src/io/bin.cpp:54-75)."""
    if bin_type == BinType.NUMERICAL:
        sum_left = 0
        for i in range(len(cnt_in_bin) - 1):
            sum_left += cnt_in_bin[i]
            if sum_left >= filter_cnt and total_cnt - sum_left >= filter_cnt:
                return False
        return True
    else:
        if len(cnt_in_bin) <= 2:
            for i in range(len(cnt_in_bin) - 1):
                if cnt_in_bin[i] >= filter_cnt and total_cnt - cnt_in_bin[i] >= filter_cnt:
                    return False
            return True
        return False


@dataclass
class BinMapper:
    """Per-feature value→bin quantizer.  reference: include/LightGBM/bin.h:61."""

    num_bin: int = 1
    missing_type: int = MissingType.NONE
    is_trivial: bool = True
    sparse_rate: float = 1.0
    bin_type: int = BinType.NUMERICAL
    bin_upper_bound: np.ndarray = field(default_factory=lambda: np.array([np.inf]))
    bin_2_categorical: List[int] = field(default_factory=list)
    categorical_2_bin: Dict[int, int] = field(default_factory=dict)
    min_val: float = 0.0
    max_val: float = 0.0
    default_bin: int = 0
    most_freq_bin: int = 0

    def find_bin(
        self,
        values: np.ndarray,
        total_sample_cnt: int,
        max_bin: int,
        min_data_in_bin: int = 3,
        min_split_data: int = 0,
        pre_filter: bool = False,
        bin_type: int = BinType.NUMERICAL,
        use_missing: bool = True,
        zero_as_missing: bool = False,
        forced_upper_bounds: Sequence[float] = (),
    ) -> None:
        """Fit bin boundaries from sampled values.

        ``values`` are the sampled *non-zero* (or all) values of one feature;
        ``total_sample_cnt`` is the number of sampled rows, so
        ``total_sample_cnt - len(values)`` rows are implicit zeros.
        reference: BinMapper::FindBin (src/io/bin.cpp:327-534).
        """
        values = np.asarray(values, dtype=np.float64)
        num_sample_values = len(values)
        values = values[~np.isnan(values)]
        na_cnt = num_sample_values - len(values)
        if not use_missing:
            self.missing_type = MissingType.NONE
        elif zero_as_missing:
            self.missing_type = MissingType.ZERO
        else:
            self.missing_type = MissingType.NONE if na_cnt == 0 else MissingType.NAN
        if self.missing_type != MissingType.NAN:
            na_cnt = 0  # NaNs fold into the zero bin (reference: bin.cpp:332-347)

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - len(values) - na_cnt)

        # distinct values with zero spliced at its sorted position; ties within
        # nextafter() of each other collapse to the larger value
        # (reference: src/io/bin.cpp:358-390).  Vectorized: the loop's
        # CheckDoubleEqualOrdered(prev, cur) compares CONSECUTIVE raw
        # values, so group boundaries are exactly where cur > nextafter(
        # prev, inf); each group's representative is its LAST (largest)
        # member — a chained "collapse to cur" lands there too.  (This was
        # a ~12 s pure-Python loop per 28-feature construct at the default
        # 200k sample.)
        values = np.sort(values, kind="stable")
        if len(values):
            newgrp = values[1:] > np.nextafter(values[:-1], np.inf)
            ends = np.append(np.nonzero(newgrp)[0], len(values) - 1)
            dv = values[ends]                           # last member of group
            ct = np.diff(np.append(-1, ends)).astype(np.int64)
            # splice the implicit-zeros group at its sorted position,
            # mirroring the scalar loop exactly: before everything only
            # when zero_cnt > 0; BETWEEN a negative and a positive group
            # unconditionally (the loop inserts a zero-count group there
            # too); after everything only when zero_cnt > 0.  Sampled
            # values have |v| > kZeroThreshold, so no group spans zero.
            # (arrays end to end — the former .tolist()/.insert round-trip
            # of 200k-element vectors was a measured ~40% of find_bin)
            zpos = None
            if values[0] > 0.0:
                if zero_cnt > 0:
                    zpos = 0
            elif values[-1] < 0.0:
                if zero_cnt > 0:
                    zpos = len(dv)
            elif dv[0] < 0.0 and dv[-1] > 0.0:
                zpos = int(np.searchsorted(dv, 0.0))
            if zpos is not None:
                dv = np.insert(dv, zpos, 0.0)
                ct = np.insert(ct, zpos, zero_cnt)
        else:
            dv = np.array([0.0], np.float64)
            ct = np.array([zero_cnt], np.int64)

        # dv is never empty here: the grouped branch always yields at
        # least one group and the empty-values branch builds the zero
        # group explicitly
        self.min_val = float(dv[0])
        self.max_val = float(dv[-1])
        num_distinct_values = len(dv)
        cnt_in_bin: List[int] = []

        if bin_type == BinType.NUMERICAL:
            forced = sorted(forced_upper_bounds) if len(forced_upper_bounds) else []
            if self.missing_type == MissingType.ZERO:
                ub = self._find_bin_inner(dv, ct, max_bin, total_sample_cnt, min_data_in_bin, forced)
                if len(ub) == 2:
                    self.missing_type = MissingType.NONE
            elif self.missing_type == MissingType.NONE:
                ub = self._find_bin_inner(dv, ct, max_bin, total_sample_cnt, min_data_in_bin, forced)
            else:
                ub = self._find_bin_inner(dv, ct, max_bin - 1, total_sample_cnt - na_cnt, min_data_in_bin, forced)
                ub = ub + [math.nan]
            self.bin_upper_bound = np.asarray(ub, dtype=np.float64)
            self.num_bin = len(ub)
            # count per bin for filtering / most_freq.  The reference
            # loop advances i_bin at most ONCE per distinct value
            # (bin.cpp cnt_in_bin accumulation), which LAGS behind the
            # true bin when forced bounds create consecutive empty bins —
            # that lag is observable (NeedFilter prefix sums,
            # most_freq_bin) and must be mirrored.  Closed form of the
            # recurrence i_bin_i = min(true_i, i_bin_{i-1} + 1) with
            # i_bin_{-1} = 0:  min(i + 1, i + running_min(true_j - j)).
            nb_real = (self.num_bin - 1
                       if self.missing_type == MissingType.NAN
                       else self.num_bin)       # exclude the NaN sentinel
            true_idx = np.minimum(
                np.searchsorted(self.bin_upper_bound[:nb_real], dv,
                                side="left"), nb_real - 1)
            lag = np.arange(len(dv))
            i_bin = np.minimum(
                lag + 1, lag + np.minimum.accumulate(true_idx - lag))
            cnt_vec = np.bincount(i_bin, weights=ct,
                                  minlength=self.num_bin)
            cnt_in_bin = [int(v) for v in cnt_vec]
            if self.missing_type == MissingType.NAN:
                cnt_in_bin[self.num_bin - 1] = na_cnt
            assert self.num_bin <= max_bin
        else:
            # categorical: count-sorted category→bin (src/io/bin.cpp:425-497)
            dvi: List[int] = []
            cti: List[int] = []
            for i in range(num_distinct_values):
                val = int(dv[i])
                if val < 0:
                    na_cnt += int(ct[i])
                else:
                    if not dvi or val != dvi[-1]:
                        dvi.append(val)
                        cti.append(int(ct[i]))
                    else:
                        cti[-1] += int(ct[i])
            self.num_bin = 0
            rest_cnt = total_sample_cnt - na_cnt
            if rest_cnt > 0:
                # sort descending by count, stable (SortForPair)
                order = np.argsort(-np.asarray(cti), kind="stable")
                cti = [cti[j] for j in order]
                dvi = [dvi[j] for j in order]
                if dvi and dvi[0] == 0:
                    if len(cti) == 1:
                        cti.append(0)
                        dvi.append(dvi[0] + 1)
                    cti[0], cti[1] = cti[1], cti[0]
                    dvi[0], dvi[1] = dvi[1], dvi[0]
                cut_cnt = int((total_sample_cnt - na_cnt) * 0.99)
                cur_cat = 0
                self.categorical_2_bin = {}
                self.bin_2_categorical = []
                used_cnt = 0
                max_bin_c = min(len(dvi), max_bin)
                cnt_in_bin = []
                while cur_cat < len(dvi) and (used_cnt < cut_cnt or self.num_bin < max_bin_c):
                    if cti[cur_cat] < min_data_in_bin and cur_cat > 1:
                        break
                    self.bin_2_categorical.append(dvi[cur_cat])
                    self.categorical_2_bin[dvi[cur_cat]] = self.num_bin
                    used_cnt += cti[cur_cat]
                    cnt_in_bin.append(cti[cur_cat])
                    self.num_bin += 1
                    cur_cat += 1
                if cur_cat == len(dvi) and na_cnt > 0:
                    self.bin_2_categorical.append(-1)
                    self.categorical_2_bin[-1] = self.num_bin
                    cnt_in_bin.append(0)
                    self.num_bin += 1
                if cur_cat == len(dvi) and na_cnt == 0:
                    self.missing_type = MissingType.NONE
                else:
                    self.missing_type = MissingType.NAN
                if cnt_in_bin:
                    cnt_in_bin[-1] += total_sample_cnt - used_cnt

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and pre_filter and _need_filter(
            cnt_in_bin, total_sample_cnt, min_split_data, self.bin_type
        ):
            self.is_trivial = True

        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(np.array([0.0]))[0])
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            if self.bin_type == BinType.CATEGORICAL and self.most_freq_bin == 0:
                self.most_freq_bin = 1
            max_sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
            if self.most_freq_bin != self.default_bin and max_sparse_rate < K_SPARSE_THRESHOLD:
                self.most_freq_bin = self.default_bin
            self.sparse_rate = cnt_in_bin[self.most_freq_bin] / total_sample_cnt
        else:
            self.sparse_rate = 1.0

    @staticmethod
    def _find_bin_inner(dv, ct, max_bin, total_cnt, min_data_in_bin, forced) -> List[float]:
        if forced:
            return _find_bin_with_predefined_bin(dv, ct, max_bin, total_cnt, min_data_in_bin, forced)
        return _find_bin_with_zero_as_one_bin(dv, ct, max_bin, total_cnt, min_data_in_bin)

    # ---- application -------------------------------------------------------

    def value_to_bin(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value→bin (reference: BinMapper::ValueToBin bin.h:457-493)."""
        values = np.asarray(values, dtype=np.float64)
        if self.bin_type == BinType.NUMERICAL:
            nan_mask = np.isnan(values)
            v = np.where(nan_mask, 0.0, values)
            r = self.num_bin - 1
            if self.missing_type == MissingType.NAN:
                r -= 1
            # first index i in [0, r) with v <= ub[i], else r
            bins = np.searchsorted(self.bin_upper_bound[:r], v, side="left").astype(np.int32)
            if self.missing_type == MissingType.NAN:
                bins = np.where(nan_mask, self.num_bin - 1, bins)
            return bins
        else:
            nan_bin = self.num_bin - 1
            out = np.full(values.shape, nan_bin, dtype=np.int32)
            iv = np.where(np.isnan(values), -1, values).astype(np.int64)
            cats = np.asarray(self.bin_2_categorical, dtype=np.int64)
            bins_for_cat = np.arange(len(cats), dtype=np.int32)
            order = np.argsort(cats)
            sorted_cats = cats[order]
            pos = np.searchsorted(sorted_cats, iv)
            pos_c = np.clip(pos, 0, len(cats) - 1)
            found = (sorted_cats[pos_c] == iv) & (iv >= 0)
            out[found] = bins_for_cat[order][pos_c[found]]
            return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative threshold for serialization (upper bound of bin)."""
        if self.bin_type == BinType.NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])

    # ---- (de)serialization for model text / binary cache -------------------

    def to_dict(self) -> dict:
        d = {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
            "most_freq_bin": self.most_freq_bin,
        }
        if self.bin_type == BinType.NUMERICAL:
            d["bin_upper_bound"] = [float(x) for x in self.bin_upper_bound]
        else:
            d["bin_2_categorical"] = list(self.bin_2_categorical)
        return d

    @staticmethod
    def from_dict(d: dict) -> "BinMapper":
        m = BinMapper(
            num_bin=d["num_bin"],
            missing_type=d["missing_type"],
            is_trivial=d["is_trivial"],
            sparse_rate=d["sparse_rate"],
            bin_type=d["bin_type"],
            min_val=d["min_val"],
            max_val=d["max_val"],
            default_bin=d["default_bin"],
            most_freq_bin=d["most_freq_bin"],
        )
        if m.bin_type == BinType.NUMERICAL:
            m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        else:
            m.bin_2_categorical = list(d["bin_2_categorical"])
            m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        return m
