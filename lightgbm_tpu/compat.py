"""Optional-dependency detection flags.

reference: python-package/lightgbm/compat.py — the same probe-and-flag
pattern (PANDAS_INSTALLED etc.) so downstream code and the reference's own
test suite can gate on what is available.
"""

try:
    import pandas as _pd                           # noqa: F401
    from pandas import DataFrame, Series           # noqa: F401
    PANDAS_INSTALLED = True
except ImportError:
    PANDAS_INSTALLED = False

    class DataFrame:                               # noqa: D401
        """Dummy DataFrame when pandas is absent."""

    class Series:
        """Dummy Series when pandas is absent."""

try:
    import matplotlib                              # noqa: F401
    MATPLOTLIB_INSTALLED = True
except ImportError:
    MATPLOTLIB_INSTALLED = False

try:
    import graphviz                                # noqa: F401
    GRAPHVIZ_INSTALLED = True
except ImportError:
    GRAPHVIZ_INSTALLED = False

try:
    import datatable                               # noqa: F401
    DATATABLE_INSTALLED = True
except ImportError:
    DATATABLE_INSTALLED = False

try:
    import sklearn                                 # noqa: F401
    SKLEARN_INSTALLED = True
except ImportError:
    SKLEARN_INSTALLED = False


if SKLEARN_INSTALLED:
    from sklearn.base import (BaseEstimator as _LGBMModelBase,          # noqa: F401
                              ClassifierMixin as _LGBMClassifierBase,
                              RegressorMixin as _LGBMRegressorBase)
    from sklearn.exceptions import NotFittedError as _SKNotFittedError

    class LGBMNotFittedError(_SKNotFittedError):
        """Raised when predicting with an unfitted estimator (reference
        compat.py LGBMNotFittedError; subclasses sklearn's NotFittedError
        so sklearn's estimator checks recognize it)."""
else:
    class _LGBMModelBase:                          # noqa: D401
        """Dummy base when scikit-learn is absent."""

    class _LGBMClassifierBase:
        pass

    class _LGBMRegressorBase:
        pass

    class LGBMNotFittedError(ValueError, AttributeError):
        """Raised when predicting with an unfitted estimator.

        Also an AttributeError so hasattr(est, "n_features_in_") is False
        before fit (matching sklearn's NotFittedError MRO)."""
