"""Optional-dependency detection flags.

reference: python-package/lightgbm/compat.py — the same probe-and-flag
pattern (PANDAS_INSTALLED etc.) so downstream code and the reference's own
test suite can gate on what is available.
"""

try:
    import pandas as _pd                           # noqa: F401
    from pandas import DataFrame, Series           # noqa: F401
    PANDAS_INSTALLED = True
except ImportError:
    PANDAS_INSTALLED = False

    class DataFrame:                               # noqa: D401
        """Dummy DataFrame when pandas is absent."""

    class Series:
        """Dummy Series when pandas is absent."""

try:
    import matplotlib                              # noqa: F401
    MATPLOTLIB_INSTALLED = True
except ImportError:
    MATPLOTLIB_INSTALLED = False

try:
    import graphviz                                # noqa: F401
    GRAPHVIZ_INSTALLED = True
except ImportError:
    GRAPHVIZ_INSTALLED = False

try:
    import datatable                               # noqa: F401
    DATATABLE_INSTALLED = True
except ImportError:
    DATATABLE_INSTALLED = False

try:
    import sklearn                                 # noqa: F401
    SKLEARN_INSTALLED = True
except ImportError:
    SKLEARN_INSTALLED = False
