"""Quickstart: binary classification end to end.

Run: python examples/quickstart.py   (CPU or TPU; auto-detected)
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import lightgbm_tpu as lgb


def main():
    rng = np.random.RandomState(0)
    n = 20_000
    X = rng.rand(n, 12).astype(np.float32)
    y = ((X[:, 0] + X[:, 1] * X[:, 2] - X[:, 3] ** 2
          + 0.2 * rng.randn(n)) > 0.4).astype(np.float32)
    Xt, yt, Xv, yv = X[:16_000], y[:16_000], X[16_000:], y[16_000:]

    train = lgb.Dataset(Xt, label=yt)
    valid = train.create_valid(Xv, label=yv)

    evals = {}
    booster = lgb.train(
        {"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
         "metric": ["auc", "binary_logloss"], "verbosity": -1},
        train, num_boost_round=50,
        valid_sets=[valid], valid_names=["valid"],
        callbacks=[lgb.record_evaluation(evals),
                   lgb.early_stopping(10, verbose=False)])

    print(f"best iteration: {booster.best_iteration}")
    print(f"valid AUC: {evals['valid']['auc'][booster.best_iteration - 1]:.4f}")

    pred = booster.predict(Xv)
    print(f"holdout accuracy: {((pred > 0.5) == yv).mean():.4f}")

    booster.save_model("quickstart_model.txt")
    reloaded = lgb.Booster(model_file="quickstart_model.txt")
    assert np.allclose(reloaded.predict(Xv), pred)
    print("model round-trip OK -> quickstart_model.txt")

    imp = booster.feature_importance("gain")
    print("top features by gain:", np.argsort(-imp)[:3].tolist())


if __name__ == "__main__":
    main()
