"""Driver benchmark: HIGGS-scale GBDT training wall-clock on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — always,
even on failure (structured error fields, value 0.0).

Workload mirrors the reference's headline experiment (docs/Experiments.rst:
500 trees, 255 leaves, lr=0.1; GPU-comparable max_bin=63 per
docs/GPU-Performance.rst guidance) on a synthetic dataset with HIGGS's shape
(11M x 28 dense float features, binary labels).  HIGGS itself cannot be
downloaded in this environment (zero egress), so the data is synthetic with
label structure (linear + pairwise signal, 20% noise) to keep trees growing
to the leaf budget as on real data.

Baseline: 130.094 s — LightGBM CPU on 2x Xeon E5-2690 v4
(docs/Experiments.rst:114).  vs_baseline = baseline_seconds / our_seconds
(>1 means faster than the reference).

Timing excludes binning/dataset construction (as does the reference's
experiment, which times the training phase) and excludes the one-time XLA
compile: the clock starts after iteration 1 and the total is rescaled by
T/(T-1).

Robustness (round-3 hardening; the r1/r2 benches died at backend init and at
train iteration 1 respectively):
  * every stage that touches the accelerator runs in a KILLABLE SUBPROCESS
    with a timeout — a wedged TPU tunnel cannot hang the driver;
  * pipeline: probe backend -> small on-device smoke run -> full run;
  * any stage failure re-probes and retries (BENCH_TRAIN_TRIES, default 2);
  * if the TPU never recovers the bench re-runs itself on a clean-env CPU
    backend with a scaled-down workload so the driver still gets a real
    measured number, clearly labelled (reachable from train-time failures
    too, not just probe-time — the r2 gap).

Extra emitted fields: sec_per_tree, compile/bin seconds, holdout AUC, an MFU
estimate for the histogram matmuls, device peak-HBM, and a measured
matmul-vs-scatter kernel probe (reference analogue: the col-vs-row timing
probe in src/io/dataset.cpp:589-684).

Env overrides: BENCH_ROWS, BENCH_TREES, BENCH_LEAVES, BENCH_BIN,
BENCH_FORCE_CPU=1 (skip TPU probe), BENCH_PROFILE=1 (write a jax.profiler
trace to ./bench_trace), BENCH_PROBE_TRIES / BENCH_PROBE_TIMEOUT,
BENCH_TRAIN_TRIES / BENCH_TRAIN_TIMEOUT / BENCH_SMOKE_TIMEOUT,
BENCH_SKIP_SMOKE=1, BENCH_SKIP_KERNEL_PROBE=1.
"""
import json
import os
import subprocess
import sys
import time
import traceback

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

BASELINE_SECONDS = 130.094

N = int(os.environ.get("BENCH_ROWS", 11_000_000))
F = 28
TREES = int(os.environ.get("BENCH_TREES", 500))
LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_BIN", 63))

# CPU-fallback workload (per-core CPU is ~2 orders slower than one TPU chip)
CPU_N = int(os.environ.get("BENCH_CPU_ROWS", 200_000))
CPU_TREES = int(os.environ.get("BENCH_CPU_TREES", 50))

# smoke-run workload: big enough to exercise the real compiled program
# shape-wise, small enough to finish in ~a minute
SMOKE_N = int(os.environ.get("BENCH_SMOKE_ROWS", 500_000))
SMOKE_TREES = int(os.environ.get("BENCH_SMOKE_TREES", 5))

# peak dense compute per chip, used for the MFU estimate.  Keyed by
# device_kind substring; conservative bf16 numbers.
PEAK_FLOPS = {
    "v5 lite": 197e12,   # v5e
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,        # trillium
}
DEFAULT_PEAK = 197e12


def emit(d):
    print(json.dumps(d), flush=True)


def error_line(stage, err, extra=None):
    d = {
        "metric": f"bench-error at {stage}",
        "value": 0.0,
        "unit": "seconds",
        "vs_baseline": 0.0,
        "error": str(err)[-1500:],
    }
    if extra:
        d.update(extra)
    return d


def make_higgs_like(n, f, seed=0):
    # the label concept (w) is drawn from a FIXED rng so train (seed=0) and
    # holdout (seed=1) share one distribution; `seed` varies only the draw
    w = np.random.RandomState(12345).randn(f).astype(np.float32)
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    signal = X @ w
    signal += 2.0 * X[:, 0] * X[:, 1] - 1.5 * (X[:, 2] > 0.5) * X[:, 3]
    signal += rng.randn(n).astype(np.float32) * 0.2 * signal.std()
    y = (signal > np.median(signal)).astype(np.float32)
    return X, y


def holdout_auc(booster, f, seed=1):
    Xh, yh = make_higgs_like(200_000, f, seed=seed)
    pred = booster.predict(Xh, device=True)   # forest traversal on-device
    order = np.argsort(pred)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(pred) + 1)
    npos = yh.sum()
    return (ranks[yh > 0].sum() - npos * (npos + 1) / 2) / (
        npos * (len(yh) - npos))


def peak_flops_for(device):
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return DEFAULT_PEAK


def device_memory_stats():
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        return {
            "peak_hbm_bytes": int(stats.get("peak_bytes_in_use", 0)),
            "hbm_limit_bytes": int(stats.get("bytes_limit", 0)),
        }
    except Exception:
        return {}


def kernel_probe(n_rows=1_000_000, f=F, max_bin=MAX_BIN, reps=3):
    """Time the histogram kernel variants on the live backend.

    Reference analogue: GetShareStates times col-wise vs row-wise histogram
    construction at startup and picks the winner (src/io/dataset.cpp:589-684).
    """
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops import histogram as H

    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, max_bin, (n_rows, f), dtype=np.int64),
                         jnp.uint8)
    grad = jnp.asarray(rng.randn(n_rows), jnp.float32)
    hess = jnp.abs(grad) + 0.1
    mask = jnp.ones((n_rows,), jnp.float32)
    B = max_bin + 1
    out = {}
    for method in ("matmul", "matmul_f32", "scatter"):
        fn = jax.jit(lambda b, g, h, m, _m=method: H.build_histogram(
            b, g, h, m, B, method=_m))
        try:
            fn(binned, grad, hess, mask).block_until_ready()  # compile
            t0 = time.perf_counter()
            for _ in range(reps):
                fn(binned, grad, hess, mask).block_until_ready()
            out[method] = round((time.perf_counter() - t0) / reps * 1e3, 2)
        except Exception as e:  # a variant may be unsupported on a backend
            out[method] = f"error: {str(e)[:120]}"
    timed = {k: v for k, v in out.items() if isinstance(v, float)}
    if timed:
        out["winner"] = min(timed, key=timed.get)
    return out


def mfu_estimate(n, f, max_bin, leaves, sec_per_tree, peak):
    """Lower-bound MFU of the histogram matmuls.

    Per histogram pass over R rows: [3, R] @ [R, F*B] = 2*3*R*F*B FLOPs.
    Per tree, the bucketed compaction processes ~n rows per frontier level
    and there are ~log2(leaves) levels, so R_total ≈ n * log2(leaves).
    This counts ONLY histogram matmul FLOPs (the MXU work) — split scans,
    partitioning and score updates ride along — so it is a lower bound.
    """
    levels = max(1.0, np.log2(leaves))
    flops_per_tree = 2.0 * 3.0 * n * levels * f * (max_bin + 1)
    return flops_per_tree / max(sec_per_tree, 1e-9) / peak


def run_bench(n, trees, leaves, max_bin, tag=""):
    """Train in-process on whatever backend is active; return result dict."""
    import jax

    import lightgbm_tpu as lgb

    device = jax.devices()[0]
    platform = device.platform

    X, y = make_higgs_like(n, F)
    params = {
        "objective": "binary",
        "num_leaves": leaves,
        "learning_rate": 0.1,
        "max_bin": max_bin,
        "metric": "None",
        "verbosity": -1,
    }
    train_set = lgb.Dataset(X, label=y)
    t_bin0 = time.perf_counter()
    train_set.construct()          # binning happens here, outside the clock
    bin_seconds = time.perf_counter() - t_bin0
    del X

    booster = lgb.Booster(params=params, train_set=train_set)
    t_c0 = time.perf_counter()
    booster.update()               # iteration 1: triggers XLA compile
    jax.block_until_ready(booster.boosting.train_score)
    compile_seconds = time.perf_counter() - t_c0

    profile = os.environ.get("BENCH_PROFILE") == "1"
    if profile:
        jax.profiler.start_trace(os.path.join(REPO, "bench_trace"))

    t0 = time.perf_counter()
    for _ in range(trees - 1):
        booster.update()
    jax.block_until_ready(booster.boosting.train_score)
    elapsed = (time.perf_counter() - t0) * trees / max(trees - 1, 1)

    if profile:
        jax.profiler.stop_trace()

    sec_per_tree = elapsed / trees
    auc = holdout_auc(booster, F)
    result = {
        "metric": f"synthetic-HIGGS {n}x{F} train wall-clock, "
                  f"{trees} trees x {leaves} leaves, max_bin={max_bin} "
                  f"[{platform}{tag}] (holdout AUC {auc:.4f})",
        "value": round(elapsed, 3),
        "unit": "seconds",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 3),
        "platform": platform,
        "device_kind": getattr(device, "device_kind", ""),
        "sec_per_tree": round(sec_per_tree, 4),
        "compile_seconds": round(compile_seconds, 2),
        "bin_seconds": round(bin_seconds, 2),
        "holdout_auc": round(float(auc), 5),
    }
    peak = peak_flops_for(device)
    result["mfu_histogram_lower_bound"] = round(
        mfu_estimate(n, F, max_bin, leaves, sec_per_tree, peak), 4)
    result["peak_flops_assumed"] = peak
    result.update(device_memory_stats())
    if os.environ.get("BENCH_SKIP_KERNEL_PROBE") != "1":
        try:
            result["hist_kernel_probe_ms"] = kernel_probe(
                min(n, 1_000_000), F, max_bin)
        except Exception as e:
            result["hist_kernel_probe_ms"] = {"error": str(e)[:200]}
    return result


def probe_backend(timeout):
    """Check in a subprocess (killable) that the default backend comes up."""
    code = ("import jax; d = jax.devices(); "
            "import jax.numpy as jnp; "
            "jnp.ones((8, 8)).sum().block_until_ready(); "
            "print('PLATFORM=' + d[0].platform)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout, cwd=REPO)
    except subprocess.TimeoutExpired:
        return None, f"backend probe timed out after {timeout}s"
    if proc.returncode != 0:
        return None, proc.stderr.strip()[-800:]
    for line in proc.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], None
    return None, "probe produced no platform line"


def _last_json_line(text):
    for ln in reversed(text.strip().splitlines()):
        try:
            obj = json.loads(ln)
        except ValueError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def run_stage_subprocess(stage_env, timeout):
    """Re-invoke this script with BENCH_STAGE=run in a killable subprocess.

    Returns (result_dict_or_None, error_string_or_None).
    """
    env = dict(os.environ)
    env.update(stage_env)
    env["BENCH_STAGE"] = "run"
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              capture_output=True, text=True,
                              timeout=timeout, env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        return None, f"stage timed out after {timeout}s"
    line = _last_json_line(proc.stdout)
    if line is None:
        return None, (proc.stderr.strip()[-800:] or "no JSON output")
    if proc.returncode != 0 or "error" in line:
        parts = [line.get("error", ""), line.get("traceback_tail", ""),
                 proc.stderr.strip()[-800:]]
        return None, " | ".join(p for p in parts if p)
    return line, None


def cpu_fallback(reason):
    """Re-run this script on a clean-env CPU backend, scaled down."""
    from lightgbm_tpu.utils.platform import clean_cpu_env
    env = clean_cpu_env(1)
    env["BENCH_STAGE"] = "run"
    env["BENCH_ROWS"] = str(CPU_N)
    env["BENCH_TREES"] = str(CPU_TREES)
    env["BENCH_LEAVES"] = str(LEAVES)
    env["BENCH_BIN"] = str(MAX_BIN)
    env["BENCH_TAG"] = "-fallback"
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              capture_output=True, text=True,
                              timeout=3000, env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        emit(error_line("cpu-fallback", f"timed out; tpu was: {reason}"))
        return 1
    line = _last_json_line(proc.stdout)
    if line is None:
        emit(error_line("cpu-fallback", proc.stderr.strip()[-800:],
                        {"tpu_error": reason}))
        return 1
    line["metric"] += f" CPU-FALLBACK (tpu unavailable: {reason[:200]})"
    line["vs_baseline"] = 0.0  # scaled-down CPU run is not comparable
    emit(line)
    return 0 if proc.returncode == 0 and "error" not in line else 1


def reprobe(tries, probe_timeout):
    platform, err = None, "no probe attempted"
    for attempt in range(tries):
        platform, err = probe_backend(probe_timeout)
        if platform:
            break
        print(f"[bench] probe attempt {attempt + 1}/{tries} failed: {err}",
              file=sys.stderr, flush=True)
        if attempt + 1 < tries:
            time.sleep(15 * (attempt + 1))
    return platform, err


def main():
    if os.environ.get("BENCH_STAGE") == "run" or \
            os.environ.get("BENCH_FORCE_CPU") == "1":
        # worker mode: train in-process on whatever backend is active
        try:
            emit(run_bench(N, TREES, LEAVES, MAX_BIN,
                           tag=os.environ.get("BENCH_TAG", "")))
            return 0
        except Exception as e:
            emit(error_line("train", f"{e}",
                            {"traceback_tail": traceback.format_exc()[-1200:]}))
            return 1

    tries = int(os.environ.get("BENCH_PROBE_TRIES", 3))
    probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", 300))
    train_tries = int(os.environ.get("BENCH_TRAIN_TRIES", 2))
    train_timeout = int(os.environ.get("BENCH_TRAIN_TIMEOUT", 5400))
    smoke_timeout = int(os.environ.get("BENCH_SMOKE_TIMEOUT", 900))

    platform, err = reprobe(tries, probe_timeout)
    if platform is None:
        return cpu_fallback(err or "unknown")
    if platform == "cpu":
        # No accelerator on this host: full 11M x 500 on CPU would run for
        # hours; use the scaled-down workload so one JSON line still lands.
        return cpu_fallback("probe found only a CPU backend")

    last_err = None
    for attempt in range(train_tries):
        if attempt > 0:
            # the backend died mid-run last attempt: re-probe before retrying
            platform, err = reprobe(tries, probe_timeout)
            if platform is None or platform == "cpu":
                return cpu_fallback(
                    f"backend lost after train failure: {last_err}")

        if os.environ.get("BENCH_SKIP_SMOKE") != "1":
            smoke, err = run_stage_subprocess(
                {"BENCH_ROWS": str(min(SMOKE_N, N)),
                 "BENCH_TREES": str(min(SMOKE_TREES, TREES)),
                 "BENCH_TAG": "-smoke", "BENCH_SKIP_KERNEL_PROBE": "1"},
                smoke_timeout)
            if smoke is None:
                last_err = f"smoke run failed: {err}"
                print(f"[bench] {last_err}", file=sys.stderr, flush=True)
                continue
            print(f"[bench] smoke ok: {smoke.get('sec_per_tree')} s/tree "
                  f"on {smoke.get('platform')}", file=sys.stderr, flush=True)

        result, err = run_stage_subprocess({}, train_timeout)
        if result is not None:
            emit(result)
            return 0
        last_err = f"full run failed: {err}"
        print(f"[bench] {last_err}", file=sys.stderr, flush=True)

    return cpu_fallback(last_err or "unknown train failure")


if __name__ == "__main__":
    sys.exit(main())
