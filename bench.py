"""Driver benchmark: HIGGS-scale GBDT training wall-clock on one TPU chip.

Prints JSON lines; the LAST line is the result the driver records:
{"metric", "value", "unit", "vs_baseline", ...}.

Workload mirrors the reference's headline experiment (docs/Experiments.rst:
500 trees, 255 leaves, lr=0.1; GPU-comparable max_bin=63 per
docs/GPU-Performance.rst guidance) on a synthetic dataset with HIGGS's shape
(11M x 28 dense float features, binary labels).  HIGGS itself cannot be
downloaded in this environment (zero egress), so the data is synthetic with
label structure (linear + pairwise signal, 20% noise) to keep trees growing
to the leaf budget as on real data.

Baseline: 130.094 s — LightGBM CPU on 2x Xeon E5-2690 v4
(docs/Experiments.rst:114).  vs_baseline = baseline_seconds / our_seconds
(>1 means faster than the reference).

Timing excludes binning/dataset construction (as does the reference's
experiment) and the one-time XLA compile: the clock starts after iteration 1
and the total is rescaled by T/(T-1).

Orchestration (round-4 redesign, updated for the measured single-tenant
tunnel).  Round-4 root-cause finding: the axon tunnel admits ONE client
process.  A second concurrent client BLOCKS in backend init with no error;
killing a client leaves a server-side claim that makes subsequent inits
block ~25+ minutes and then fail UNAVAILABLE — which is exactly the
rounds-1..3 "probe timed out" failure signature.  When the tunnel is free,
init takes ~8 s.  Therefore:
  * the TPU path runs in ONE warmed worker subprocess — init, kernel probe,
    smoke, full run all in the same process, so a successful backend init
    is never thrown away;
  * a worker blocked in INIT is never killed on a timer: a blocked init
    usually means a lingering claim that will expire, and killing the
    worker starts a fresh ~25-minute wedge.  The remote-compile service
    (PALLAS_AXON_REMOTE_COMPILE) stays in the env for every attempt —
    round-5 measurement: every env-stripped run blocked in init
    indefinitely, so the service is REQUIRED for init — but it hung >100
    minutes compiling the 11M-row program (1M compiled in 40 s), so a
    worker that inited and then goes BENCH_STALL_TIMEOUT without a stage
    line is killed and retried at HALF the row count, banking a real TPU
    number at the largest scale the service can compile;
  * the worker emits a JSON "stage" line after every stage; whatever it
    produced before dying is folded into the final emission as partial
    TPU telemetry;
  * the CPU-fallback measurement runs CONCURRENTLY in a clean-env CPU
    subprocess (the env strip keeps it off the tunnel) and its result line
    is emitted the moment it is ready — insurance against the driver
    killing the bench at any point;
  * the persistent XLA compile cache is enabled for every stage.

Env overrides: BENCH_ROWS, BENCH_TREES, BENCH_LEAVES, BENCH_BIN,
BENCH_FORCE_CPU=1 (skip TPU entirely), BENCH_PROFILE=1 (jax.profiler trace
to ./bench_trace), BENCH_TOTAL_BUDGET (s, default 6600),
BENCH_CPU_ROWS / BENCH_CPU_TREES, BENCH_SMOKE_ROWS / BENCH_SMOKE_TREES,
BENCH_SKIP_SMOKE=1, BENCH_SKIP_KERNEL_PROBE=1, BENCH_SKIP_HIST_PROBE=1,
BENCH_SKIP_OBS=1 (skip the obs_dump + obs_doctor stages AND the measured
per-variant MFU table; obs_doctor — tools/obs_doctor.py over
lightgbm_tpu/obs/diagnose.py — runs LAST and journals ranked bottleneck
verdicts ("dcn-bound", "compile-bound", "input-bound", "straggler",
"contention", "kernel-underutilized") derived from the banked stages, so every bench
round self-reports its bottleneck; the measured MFU table is the
lightgbm_tpu/obs/devprof.py cost_analysis numbers that
otherwise ride in the full/fallback run_bench results as "mfu_measured",
banked under their own journal key so retries replay them; the table
now includes the */fused rows — the Pallas histogram→split megakernel,
ops/fused.py — whose MFU against the staged rows at the same shape is
the fusion acceptance figure, and the hist_probe stage journals the
fused-vs-staged sec/level + HBM bytes_accessed drop per level).
Observability: LIGHTGBM_TPU_TRACE=1 records structured spans through
every stage (bench phases, engine loop, dispatch/fetch, serving) and
each run_bench stage dumps a Chrome-trace JSON (bench_trace_<stage>.json)
plus a unified metrics-registry snapshot (bench_obs_metrics.json) under
./bench_out/ (gitignored); "obs" in the stage JSON carries the file + a span-tree
wall-clock coverage figure (docs/OBSERVABILITY.md).
Memory/caching: LGBM_TPU_TILE_ROWS / LGBM_TPU_HBM_BYTES steer the HBM
budget planner (ops/planner.py; the >=10M-row stage is gated on its
feasibility verdict and degrades to smaller row tiles instead of
crashing — the decision is journaled as the "hbm_plan" stage);
BENCH_SKIP_COLLECTIVE_PROBE=1 skips the per-tier collective micro-bench
(tools/collective_probe.py: flat vs hierarchical vs voting reduction
latency + the ops/planner.plan_collectives per-tier byte accounting over
a simulated 2-slice hybrid ("dcn","ici") mesh — the journaled acceptance
signal is voting's DCN bytes strictly below data-parallel's at equal
trees; LGBM_TPU_NUM_SLICES / LGBM_TPU_HIER_REDUCE / LGBM_TPU_ICI_GBPS /
LGBM_TPU_DCN_GBPS steer the pod-scale election itself);
out-of-core streaming (lightgbm_tpu/data/): BENCH_SKIP_STREAM_PROBE=1
skips the block-pump micro-bench (tools/stream_probe.py),
BENCH_SKIP_STREAM=1 skips the graduated 100M-row streamed stage
(BENCH_STREAM_ROWS / BENCH_STREAM_TREES size it; its two-level
host+HBM verdict banks as the "stream_plan" stage and the run
journals planner-predicted vs measured peaks on BOTH memories;
LGBM_TPU_STREAM / LGBM_TPU_STREAM_BLOCK_ROWS / LGBM_TPU_HOST_BYTES
steer the election);
inference kernels (ops/predict_kernels.py): BENCH_SKIP_PREDICT_PROBE=1
skips the traversal micro-bench (tools/predict_probe.py: while vs fori
vs fused sec/Mrow + measured MFU/BW, the plan_predict election cold and
warm against the autotune store's "p-..." family, serving bit-parity;
accelerators raise below the 3x-vs-while bar at 1M rows),
BENCH_SKIP_BULK_SCORE=1 skips the bulk offline-scoring stage
(tools/bulk_score.py: a BENCH_BULK_ROWS-row — default 10M — synthetic
blockstore streamed through the AOT bulk bucket with per-block score
commits and a resume-after-kill byte-identity drill;
LGBM_TPU_PREDICT_KERNEL / LGBM_TPU_PREDICT_CHUNK /
LGBM_TPU_PREDICT_EPILOGUE steer the predict election itself);
BENCH_SKIP_SWEEP=1 skips the batched model-axis sweep micro-bench
(tools/sweep_probe.py: the SAME macro-chunk body solo vs vmapped at
B in {2,4,8} heterogeneous lanes over one shared binned matrix —
per-dispatch latency, aggregate boosting iters/sec and measured MFU
per batch width, plus ops/planner.plan_model_batch's lane-chunk
verdict; on accelerators the journaled acceptance bar is B=8
aggregate iters/sec >= 4x B=1, and a missed bar raises so failed
sweep runs are never journaled; LGBM_TPU_MODEL_BATCH caps the
production lane chunk itself);
BENCH_SKIP_FLEET=1 skips the serving-fleet stage (lightgbm_tpu/fleet/:
N-model registry under a shared-HBM residency plan — measured eviction
with every model still servable, AOT zero-compile replica restart, and
the opt-in bf16/int8 accuracy deltas via tools/fleet_smoke.py; a missed
acceptance bar raises so failed fleet runs are never journaled) AND the
fleet_failover stage (kill one device of a BENCH_FLEET_DEVICES-wide
replicated PodFleet under load: zero non-typed failures, availability
>= 0.999, recovery within one replan tick);
BENCH_SKIP_LIFECYCLE=1 skips the guarded model-lifecycle stage
(lightgbm_tpu/lifecycle/: continual refresh -> shadow/canary promotion
under loadgen traffic -> forced drift rollback with the fleet's output
byte-identical to the pre-promotion model, via
tools/lifecycle_smoke.py; a missed bar raises so failed lifecycle runs
are never journaled);
BENCH_SKIP_CORESIDENT=1 skips the co-resident train+serve stage
(lightgbm_tpu/coresident/: loadgen traffic AND a residency-ledger-
budgeted refresh on the SAME device set, via tools/coresident_smoke.py;
the bars — zero non-typed failures with p99 within SLO, model age
drops, the brownout throttle counter moved — raise when missed so
failed co-residency runs are never journaled);
LGBM_TPU_VMEM_BYTES steers the fused-megakernel VMEM arena election and
LGBM_TPU_FUSED=0 drops the fused arm entirely (staged family only);
LGBM_TPU_COMPILE_CACHE=<dir> wires the persistent XLA compile cache
(cold-vs-warm compile_seconds recorded per stage under "compile_cache").

Stage journal: every completed worker stage persists its result to
BENCH_JOURNAL (default ./bench_journal.json, atomic writes) under a
workload fingerprint; a rerun after a mid-run crash replays the banked
stages and executes only the missing ones.  BENCH_ONLY=<stage[,stage]>
selects exactly those worker stages.  BENCH_JOURNAL=0 disables.
"""
import json
import os
import subprocess
import sys
import threading
import time
import traceback

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# all per-run observability artifacts (Chrome traces, metrics snapshots)
# land here, NOT in the repo root — gitignored so bench runs stop
# churning the working tree
BENCH_OUT = os.path.join(REPO, "bench_out")

BASELINE_SECONDS = 130.094

N = int(os.environ.get("BENCH_ROWS", 11_000_000))
F = 28
TREES = int(os.environ.get("BENCH_TREES", 500))
LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_BIN", 63))

# CPU-fallback workload (per-core CPU is ~2 orders slower than one TPU chip)
CPU_N = int(os.environ.get("BENCH_CPU_ROWS", 200_000))
CPU_TREES = int(os.environ.get("BENCH_CPU_TREES", 50))

SMOKE_N = int(os.environ.get("BENCH_SMOKE_ROWS", 500_000))
SMOKE_TREES = int(os.environ.get("BENCH_SMOKE_TREES", 3))

# MSLR-shaped ranking stage (BASELINE.md: MS LTR 70.417 s / 500 trees CPU)
RANK_QUERIES = int(os.environ.get("BENCH_RANK_QUERIES", 12_000))
RANK_DOCS = int(os.environ.get("BENCH_RANK_DOCS", 100))
RANK_TREES = int(os.environ.get("BENCH_RANK_TREES", 100))

TOTAL_BUDGET = float(os.environ.get("BENCH_TOTAL_BUDGET", 6600))

# peak dense compute per chip for the MFU estimate (bf16, conservative) —
# ONE table, shared with the measured-MFU path (obs/devprof.py) so the
# lower bound and the cost_analysis numbers use the same denominator
from lightgbm_tpu.obs.devprof import (DEFAULT_PEAK_FLOPS as DEFAULT_PEAK,
                                      PEAK_FLOPS, peak_flops_for)

START = time.time()


def dsync(x):
    """Force completion of device work hanging off ``x``.

    jax's block_until_ready is a NO-OP on the tunneled axon backend
    (measured round 5: 0.04 ms "sync" vs 70 ms real via a device->host
    copy), so every timing in this file syncs by pulling a tiny reduction
    of the dependent array to the host instead.
    """
    import jax.numpy as jnp
    return float(np.asarray(jnp.sum(x.astype(jnp.float32))))



def remaining_budget():
    return TOTAL_BUDGET - (time.time() - START)


def emit(d):
    print(json.dumps(d), flush=True)


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def error_line(stage, err, extra=None):
    d = {
        "metric": f"bench-error at {stage}",
        "value": 0.0,
        "unit": "seconds",
        "vs_baseline": 0.0,
        "error": str(err)[-1500:],
    }
    if extra:
        d.update(extra)
    return d


def make_mslr_like(n_queries, docs_per_query, f, seed=0):
    """Synthetic MSLR-WEB30K-shaped ranking data: graded 0-4 relevance from
    a noisy nonlinear score (the real set is not downloadable here; shape
    and metric protocol follow docs/Experiments.rst:55-60 / BASELINE.md)."""
    rng = np.random.RandomState(seed)
    n = n_queries * docs_per_query
    w = np.random.RandomState(777).randn(f).astype(np.float32)
    X = rng.rand(n, f).astype(np.float32)
    s = X @ w + 1.5 * X[:, 0] * X[:, 1] - X[:, 2] * (X[:, 3] > 0.5)
    s += rng.randn(n).astype(np.float32) * 0.3 * s.std()
    # per-query relevance grades: quintile buckets of the score
    s = s.reshape(n_queries, docs_per_query)
    order = np.argsort(np.argsort(s, axis=1), axis=1)
    grade = (order * 5 // docs_per_query).astype(np.float32)
    group = np.full(n_queries, docs_per_query, np.int32)
    return X, grade.reshape(-1), group


def ndcg_at_k(scores, labels, docs_per_query, k=10):
    """NDCG@k averaged over equal-size queries (DCGCalculator semantics:
    gain 2^label-1, log2 position discount)."""
    s = scores.reshape(-1, docs_per_query)
    l = labels.reshape(-1, docs_per_query)
    idx = np.argsort(-s, axis=1)[:, :k]
    top = np.take_along_axis(l, idx, axis=1)
    disc = 1.0 / np.log2(np.arange(2, k + 2))
    dcg = ((2.0 ** top - 1) * disc).sum(axis=1)
    ideal = np.sort(l, axis=1)[:, ::-1][:, :k]
    idcg = ((2.0 ** ideal - 1) * disc).sum(axis=1)
    return float((dcg / np.maximum(idcg, 1e-12)).mean())


def run_ranking_bench(n_queries, docs_per_query, trees, leaves, max_bin):
    """Lambdarank wall-clock + NDCG@10 (the MSLR-side benchmark)."""
    import jax

    import lightgbm_tpu as lgb

    F = 136                           # MSLR feature count
    X, y, group = make_mslr_like(n_queries, docs_per_query, F)
    params = {
        "objective": "lambdarank",
        "num_leaves": leaves,
        "learning_rate": 0.1,
        "max_bin": max_bin,
        "metric": "None",
        "verbosity": -1,
        "tpu_tree_growth": "fast",      # see run_bench
    }
    extra = os.environ.get("BENCH_EXTRA_PARAMS")
    if extra:
        params.update(json.loads(extra))
    # params at creation time: constructing first and handing differing
    # dataset params to the Booster is a LightGBMError (reference
    # DatasetUpdateParamChecking semantics) — the round-4 CPU-fallback bug
    ds = lgb.Dataset(X, label=y, group=group, params=params)
    t0 = time.perf_counter()
    ds.construct()
    bin_seconds = time.perf_counter() - t0
    booster = lgb.Booster(params=params, train_set=ds)
    t0 = time.perf_counter()
    booster.update()
    dsync(booster.boosting.train_score)
    compile_seconds = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(trees - 1):
        booster.update()
    dsync(booster.boosting.train_score)
    elapsed = (time.perf_counter() - t0) * trees / max(trees - 1, 1)
    Xh, yh, _ = make_mslr_like(2000, docs_per_query, F, seed=9)
    pred = booster.predict(Xh, device=True)
    return {
        "rows": n_queries * docs_per_query,
        "queries": n_queries,
        "features": F,
        "trees": trees,
        "train_seconds": round(elapsed, 3),
        "sec_per_tree": round(elapsed / trees, 4),
        "compile_seconds": round(compile_seconds, 2),
        "bin_seconds": round(bin_seconds, 2),
        "holdout_ndcg@10": round(ndcg_at_k(pred, yh, docs_per_query), 5),
    }


def higgs_like_chunks(n, f, chunk_rows, seed0=0):
    """The synthetic-HIGGS source, generated chunk by chunk so the raw
    float matrix need never be resident (the out-of-core stage's data
    source; ``make_higgs_like`` is the single-chunk special case — ONE
    signal formula for train, holdout and streamed stages).

    The label concept (w) is drawn from a FIXED rng so train (seed 0)
    and holdout (seed 1) share one distribution; the label threshold is
    calibrated on the first chunk (~the global median — chunks are
    i.i.d. draws), which IS the global median in the single-chunk case.
    """
    w = np.random.RandomState(12345).randn(f).astype(np.float32)
    thresh = None
    lo = 0
    ci = 0
    while lo < n:
        rows = min(chunk_rows, n - lo)
        rng = np.random.RandomState(seed0 + 7919 * ci)
        X = rng.rand(rows, f).astype(np.float32)
        signal = X @ w
        signal += 2.0 * X[:, 0] * X[:, 1] - 1.5 * (X[:, 2] > 0.5) * X[:, 3]
        signal += rng.randn(rows).astype(np.float32) * 0.2 * signal.std()
        if thresh is None:
            thresh = float(np.median(signal))
        yield lo, X, (signal > thresh).astype(np.float32)
        lo += rows
        ci += 1


def make_higgs_like(n, f, seed=0):
    _lo, X, y = next(higgs_like_chunks(n, f, n, seed0=seed))
    return X, y


def holdout_auc(booster, f, seed=1):
    Xh, yh = make_higgs_like(200_000, f, seed=seed)
    pred = booster.predict(Xh, device=True)   # forest traversal on-device
    order = np.argsort(pred)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(pred) + 1)
    npos = yh.sum()
    return (ranks[yh > 0].sum() - npos * (npos + 1) / 2) / (
        npos * (len(yh) - npos))


def device_memory_stats():
    """peak/limit HBM from the device allocator; planner fallback.

    r5 shipped ``peak_hbm_bytes``/``hbm_limit_bytes`` as constant 0 —
    the axon plugin returns no ``memory_stats()``.  Try every key the
    PJRT allocators use, and when the device reports nothing, fall back
    to the planner's limit model (``hbm_limit_source`` says which)."""
    import jax
    out = {}
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        stats = {}
    peak = 0
    for k in ("peak_bytes_in_use", "peak_bytes", "bytes_in_use"):
        if int(stats.get(k, 0)) > 0:
            peak = int(stats[k])
            break
    limit = int(stats.get("bytes_limit", 0) or stats.get("bytes_limit_in_use", 0))
    if peak:
        out["peak_hbm_bytes"] = peak
    if limit:
        out["hbm_limit_bytes"] = limit
        out["hbm_limit_source"] = "memory_stats"
    else:
        from lightgbm_tpu.ops.planner import hbm_limit_bytes
        lim, src = hbm_limit_bytes()
        out["hbm_limit_bytes"] = lim
        out["hbm_limit_source"] = src
    return out


def kernel_probe(n_rows=1_000_000, f=F, max_bin=MAX_BIN, reps=3):
    """Time the histogram kernel variants on the live backend.

    Reference analogue: GetShareStates times col-wise vs row-wise histogram
    construction at startup and picks the winner (src/io/dataset.cpp:589-684).
    """
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.ops import histogram as H

    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, max_bin, (f, n_rows), dtype=np.int64),
                         jnp.uint8)          # feature-major [F, n]
    grad = jnp.asarray(rng.randn(n_rows), jnp.float32)
    hess = jnp.abs(grad) + 0.1
    mask = jnp.ones((n_rows,), jnp.float32)
    B = max_bin + 1
    out = {}
    for method in ("matmul", "matmul_f32", "scatter", "pallas"):
        fn = jax.jit(lambda b, g, h, m, _m=method: H.build_histogram(
            b, g, h, m, B, method=_m))
        try:
            dsync(fn(binned, grad, hess, mask))  # compile
            t0 = time.perf_counter()
            for _ in range(reps):
                dsync(fn(binned, grad, hess, mask))
            out[method] = round((time.perf_counter() - t0) / reps * 1e3, 2)
        except Exception as e:  # a variant may be unsupported on a backend
            out[method] = f"error: {str(e)[:120]}"
    timed = {k: v for k, v in out.items() if isinstance(v, float)}
    if timed:
        out["winner"] = min(timed, key=timed.get)
    return out


def mfu_estimate(n, f, max_bin, leaves, sec_per_tree, peak):
    """Lower-bound MFU of the histogram matmuls.

    Per histogram pass over R rows: [3, R] @ [R, F*B] = 2*3*R*F*B FLOPs.
    Per tree, the bucketed compaction processes ~n rows per frontier level
    and there are ~log2(leaves) levels, so R_total ~ n * log2(leaves).
    Counts ONLY histogram matmul FLOPs (the MXU work) — a lower bound.
    The MEASURED per-variant numbers (compiler cost_analysis, not this
    formula) ride alongside as ``mfu_measured`` (obs/devprof.py).
    """
    if peak <= 0:          # a device the flops table doesn't know
        return 0.0
    levels = max(1.0, np.log2(leaves))
    flops_per_tree = 2.0 * 3.0 * n * levels * f * (max_bin + 1)
    return flops_per_tree / max(sec_per_tree, 1e-9) / peak


def run_bench(n, trees, leaves, max_bin, tag="", cancel=None,
              compile_done=None):
    """Train in-process on whatever backend is active; return result dict.

    ``cancel`` (threading.Event): checked right after the compile sync —
    an abandoned hung-compile attempt (tools/tpu_measure.py guard_ladder)
    whose compile eventually unblocks must NOT proceed to the timed run,
    which would race the ladder's replacement attempt on the single-tenant
    chip.  ``compile_done`` (threading.Event): set right after the compile
    sync so the ladder's hung-compile patience can watch the COMPILE alone
    (the timed run may legitimately exceed any compile patience)."""
    import jax

    import lightgbm_tpu as lgb

    device = jax.devices()[0]
    platform = device.platform

    # HBM budget verdict BEFORE any allocation: the >=10M-row stage died
    # in compile in r5 (157.7 GB requested vs 17.2 GB HBM); the planner
    # now degrades to a smaller row tile instead, and the decision is
    # journaled with the stage result.  An infeasible verdict aborts the
    # stage up front (cheap, retriable) rather than wedging the chip.
    from lightgbm_tpu.ops.planner import plan_histograms
    plan = plan_histograms(rows=n, features=F, num_bins=max_bin + 1,
                           num_leaves=leaves)
    if not plan.feasible:
        raise RuntimeError(
            f"HBM planner: {n} rows infeasible on this device even at "
            f"tile_rows={plan.tile_rows} (predicted "
            f"{plan.predicted_peak_bytes / 1e9:.1f} GB vs budget "
            f"{plan.budget_bytes / 1e9:.1f} GB)")
    if plan.degraded:
        log(f"hbm planner degraded to tile_rows={plan.tile_rows} "
            f"(untiled predicted {plan.untiled_peak_bytes / 1e9:.1f} GB "
            f"> budget {plan.budget_bytes / 1e9:.1f} GB)")

    from lightgbm_tpu.utils.platform import (
        compile_cache_entries, compile_cache_entries_by_family,
        enable_compile_cache)
    # the reported dir must be the one the entries are counted in: with
    # LGBM_TPU_COMPILE_CACHE unset, the worker's JAX_COMPILATION_CACHE_DIR
    # default is still an active cache.  family="train" scopes the
    # warm-start verdict to TRAINING programs (JIT blobs only) — serving
    # AOT exports in the same store no longer fake a warm training start
    cache_dir = (enable_compile_cache(family="train")
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR") or None)
    cache_before = compile_cache_entries(cache_dir)
    cache_fam_before = compile_cache_entries_by_family()

    # structured tracing (lightgbm_tpu/obs/): with LIGHTGBM_TPU_TRACE set
    # the whole stage records phase spans (+ the engine/grower/serving
    # spans underneath) and dumps a Chrome-trace JSON next to the journal
    from lightgbm_tpu.obs.trace import global_tracer, instant as obs_instant
    from lightgbm_tpu.obs.trace import span as obs_span, span_coverage
    # stages share one process tracer: mark here so this stage's dump and
    # coverage cover ONLY its own slice of events
    trace_mark = global_tracer.mark()
    root_span = obs_span("bench.run", rows=n, trees=trees, tag=tag)
    root_span.__enter__()
    try:

        with obs_span("bench.make_data", rows=n):
            X, y = make_higgs_like(n, F)
        params = {
            "objective": "binary",
            "num_leaves": leaves,
            "learning_rate": 0.1,
            "max_bin": max_bin,
            "metric": "None",
            "verbosity": -1,
            # relaxed batched-frontier growth: ~8 rounds per 255-leaf tree vs
            # 17 for the exact-prefix mode (measured, docs/PERFORMANCE.md);
            # tree-shape deviation class = the reference's own CPU-vs-GPU
            # difference, and the holdout AUC printed in the metric line is
            # the quality check.  BENCH_EXTRA_PARAMS can override.
            "tpu_tree_growth": "fast",
        }
        # measurement experiments: BENCH_EXTRA_PARAMS='{"tpu_tree_growth":
        # "fast", ...}' merges into the training params
        extra = os.environ.get("BENCH_EXTRA_PARAMS")
        if extra:
            params.update(json.loads(extra))
        train_set = lgb.Dataset(X, label=y, params=params)
        t_bin0 = time.perf_counter()
        with obs_span("bench.construct"):
            train_set.construct()      # binning happens here, outside the clock
        bin_seconds = time.perf_counter() - t_bin0
        del X

        with obs_span("bench.build_booster"):
            booster = lgb.Booster(params=params, train_set=train_set)
        t_c0 = time.perf_counter()
        with obs_span("bench.compile"):
            booster.update()           # iteration 1: triggers XLA compile
            dsync(booster.boosting.train_score)
        compile_seconds = time.perf_counter() - t_c0
        if compile_done is not None:
            compile_done.set()
        if cancel is not None and cancel.is_set():
            root_span.set(cancelled=True)
            return {"cancelled_after_compile": True,
                    "compile_seconds": round(compile_seconds, 2)}

        profile = os.environ.get("BENCH_PROFILE") == "1"
        if profile:
            os.makedirs(BENCH_OUT, exist_ok=True)
            jax.profiler.start_trace(os.path.join(BENCH_OUT, "bench_trace"))

        t0 = time.perf_counter()
        with obs_span("bench.train_loop", trees=trees - 1):
            for _ in range(trees - 1):
                booster.update()
            dsync(booster.boosting.train_score)
        elapsed = (time.perf_counter() - t0) * trees / max(trees - 1, 1)

        if profile:
            jax.profiler.stop_trace()

        sec_per_tree = elapsed / trees
        with obs_span("bench.holdout_auc"):
            auc = holdout_auc(booster, F)  # metric BEFORE the chunked segment
        # extends the model, so the reported AUC stays comparable to baselines

        # fused macro-steps (lightgbm_tpu/boosting/macro.py): continue the
        # SAME booster with update_chunk so training compute matches and only
        # the dispatch count changes; LGBM_TPU_CHUNK=0 (the compile-variant
        # ladder's chunk-off rung) skips this segment
        from lightgbm_tpu.boosting.macro import chunk_cap, pow2_chunk
        chunk_result = None
        cap = chunk_cap()
        with obs_span("bench.chunked"):
            if cap > 1 and booster.boosting.chunk_supported():
                # whole chunks only: each distinct chunk size is a separate
                # compiled shape, so a ragged tail step would put an XLA compile
                # inside the clock and corrupt iters_per_sec_chunked
                c = pow2_chunk(trees, cap)
                n_chunks = max(trees // c, 1)
                chunk_iters = n_chunks * c
                booster.update_chunk(c)            # chunk program compile
                dsync(booster.boosting.train_score)
                t0 = time.perf_counter()
                for _ in range(n_chunks):
                    booster.update_chunk(c)
                dsync(booster.boosting.train_score)
                chunk_s = time.perf_counter() - t0
                chunk_result = {
                    "chunk_size": c,
                    "chunk_iters": chunk_iters,
                    "iters_per_sec_chunked": round(chunk_iters / chunk_s, 3),
                    "sec_per_tree_chunked": round(chunk_s / chunk_iters, 4),
                }
    except BaseException as e:
        root_span.set(error=type(e).__name__)
        raise
    finally:
        root_span.__exit__(None, None, None)

    result = {
        "metric": f"synthetic-HIGGS {n}x{F} train wall-clock, "
                  f"{trees} trees x {leaves} leaves, max_bin={max_bin} "
                  f"[{platform}{tag}] (holdout AUC {auc:.4f})",
        "value": round(elapsed, 3),
        "unit": "seconds",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 3),
        "platform": platform,
        "device_kind": getattr(device, "device_kind", ""),
        # sec_per_tree is TRAIN-ONLY (clock starts after iteration 1);
        # _total folds the one-time compile back in — r5's 7.77 s/tree
        # headline was the total being read as the train rate
        "sec_per_tree": round(sec_per_tree, 4),
        "sec_per_tree_train": round(sec_per_tree, 4),
        "sec_per_tree_total": round((elapsed + compile_seconds) / trees, 4),
        "iters_per_sec": round(1.0 / max(sec_per_tree, 1e-9), 3),
        "compile_seconds": round(compile_seconds, 2),
        "compile_cache": {
            "dir": cache_dir,
            # entries/warm_start are the TRAIN family's (the dir above is
            # the family subdir); by_family breaks the whole store down
            "entries_before": cache_before,
            "entries_after": compile_cache_entries(cache_dir),
            "warm_start": bool(cache_dir) and cache_before > 0,
            "entries_by_family_before": cache_fam_before,
            "entries_by_family_after": compile_cache_entries_by_family(),
            "warm_start_by_family": {
                k: v > 0 for k, v in cache_fam_before.items()},
        },
        "bin_seconds": round(bin_seconds, 2),
        "bin_rows_per_sec": round(n / max(bin_seconds, 1e-9), 1),
        "holdout_auc": round(float(auc), 5),
        "rows": n,
        "trees": trees,
        "hbm_plan": plan.summary(),
    }
    train_plan = getattr(booster.boosting, "hist_plan", None)
    if train_plan is not None:
        result["hbm_plan"] = train_plan.summary()
    if chunk_result is not None:
        result.update(chunk_result)
    try:
        from lightgbm_tpu.ops.ingest import ingest_last
        il = ingest_last()
        if il:
            result["ingest"] = il
    except Exception:
        pass
    peak = peak_flops_for(device)
    result["mfu_histogram_lower_bound"] = round(
        mfu_estimate(n, F, max_bin, leaves, sec_per_tree, peak), 4)
    result["peak_flops_assumed"] = peak
    mem = device_memory_stats()
    result.update(mem)

    # planner predicted-vs-measured peak bytes as a first-class event +
    # result field (docs/OBSERVABILITY.md): the number that says whether
    # the HBM model (ops/planner.py) is still honest on this backend
    eff_plan = getattr(booster.boosting, "hist_plan", None) or plan
    measured_peak = int(mem.get("peak_hbm_bytes", 0))
    pvm = {
        "predicted_peak_bytes": int(eff_plan.predicted_peak_bytes),
        "measured_peak_bytes": measured_peak,
        "ratio": (round(measured_peak / eff_plan.predicted_peak_bytes, 3)
                  if measured_peak and eff_plan.predicted_peak_bytes
                  else None),
    }
    result["hbm_predicted_vs_measured"] = pvm
    obs_instant("hbm.peak", **pvm)
    from lightgbm_tpu.obs.metrics import global_registry as obs_registry
    obs_registry.gauge("hbm_measured_peak_bytes").set(measured_peak)

    # MEASURED per-variant MFU / HBM-bandwidth utilization from the
    # compiler's own cost model (obs/devprof.py) — the number the
    # lower-bound estimate above only brackets.  Not in the smoke stage
    # (18 variant compiles would dwarf the canary it rides on) and banked
    # under its own journal key so a full-stage retry replays it instead
    # of paying the compiles again.  BENCH_SKIP_OBS=1 skips.
    if os.environ.get("BENCH_SKIP_OBS") != "1" and tag != "-smoke":
        mfu_rows = min(n, 1_000_000)
        mfu_key = f"mfu_measured@{mfu_rows}"
        # the journal belongs to the TPU worker: the CPU-fallback process
        # has a different workload fingerprint, and a journal_put from it
        # would atomically REWRITE the file and wipe every banked TPU stage
        in_worker = os.environ.get("BENCH_STAGE") == "tpu-worker"

        def _table_ok(t):
            return any(isinstance(v, dict) and "seconds_per_call" in v
                       for v in t.values())

        if not in_worker:
            # the CPU-fallback/pipeline path cannot bank (different
            # journal fingerprint): keep its un-replayable table cheap
            mfu_rows = min(mfu_rows, 200_000)
        banked = journal_stages().get(mfu_key) if in_worker else None
        if banked is not None and _table_ok(banked):
            result["mfu_measured"] = banked
        else:
            try:
                from lightgbm_tpu.obs.devprof import \
                    histogram_utilization_table
                with obs_span("bench.mfu_measured"):
                    result["mfu_measured"] = histogram_utilization_table(
                        rows=mfu_rows, features=F,
                        num_bins=max_bin + 1,
                        reps=2 if in_worker else 1)
                # best measured MFU as a gauge: the obs_doctor stage and
                # pod telemetry vectors read it (docs/OBSERVABILITY.md)
                best_mfu = max(
                    (v.get("mfu", 0.0)
                     for v in result["mfu_measured"].values()
                     if isinstance(v, dict)), default=0.0)
                if best_mfu:
                    obs_registry.gauge("mfu_measured_best").set(
                        round(best_mfu, 6))
                # bank only a table with at least one real measurement —
                # an all-error table must retry next run (the journal's
                # errors-never-banked rule)
                if in_worker and _table_ok(result["mfu_measured"]):
                    journal_put(mfu_key, result["mfu_measured"])
            except Exception as e:  # never fail the stage for telemetry
                result["mfu_measured"] = {"error": str(e)[-200:]}

    # trace file + unified-registry snapshot alongside the journal entry
    from lightgbm_tpu.utils.timer import global_timer
    if global_timer.enabled:
        global_timer.publish(obs_registry)
    if global_tracer.enabled:
        safe_tag = (tag or "-full").strip("-").replace("/", "_") or "full"
        evs = global_tracer.since(trace_mark)   # THIS stage's slice only
        try:
            os.makedirs(BENCH_OUT, exist_ok=True)
            result["obs"] = {
                "trace_file": global_tracer.dump(
                    os.path.join(BENCH_OUT, f"bench_trace_{safe_tag}.json"),
                    events=evs),
                "trace_events": len(evs),
                "trace_coverage": round(
                    span_coverage(evs, "bench.run") or 0.0, 4),
            }
        except OSError as e:
            result["obs"] = {"error": str(e)[-200:]}
    try:
        from lightgbm_tpu.utils.file_io import write_atomic
        os.makedirs(BENCH_OUT, exist_ok=True)
        snap_path = os.path.join(BENCH_OUT, "bench_obs_metrics.json")
        write_atomic(snap_path, obs_registry.dump_json())
        result["obs_metrics_file"] = snap_path
    except OSError:
        pass
    return result


def run_stream_bench(n, trees, leaves, max_bin, features=None):
    """The graduated out-of-core stage (lightgbm_tpu/data/): build a
    spill-store dataset of ``n`` rows CHUNK BY CHUNK (the binned matrix
    is never resident on host or device), train ``trees`` streamed
    trees, and journal the planner's predicted peaks on BOTH memories
    next to the measured ones (host VmHWM delta, device allocator
    peak).  LGBM_TPU_STREAM=1 is pinned for the stage — its claim is
    out-of-core execution, not a residency election."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.data.stream import (host_rss_bytes,
                                          host_rss_peak_bytes)
    from lightgbm_tpu.dataset import Dataset
    from lightgbm_tpu.ops.planner import plan_stream

    f = features or F
    trees = max(int(trees), 2)      # the clock starts after iteration 1;
    #                                 one tree would journal a ~0 s value
    plan = plan_stream(rows=n, features=f, num_bins=max_bin + 1,
                       num_leaves=leaves)
    if plan.stream and not plan.feasible:
        raise RuntimeError(
            f"stream planner: {n} rows infeasible even at block_rows="
            f"{plan.block_rows} (predicted device "
            f"{plan.predicted_device_peak_bytes / 1e9:.1f} GB / host "
            f"{plan.predicted_host_peak_bytes / 1e9:.1f} GB)")
    rss_peak0 = host_rss_peak_bytes()
    from lightgbm_tpu.obs.metrics import global_registry as _reg
    blocks0 = int(_reg.counter("stream_blocks_total").value)
    params = {"objective": "binary", "num_leaves": leaves,
              "learning_rate": 0.1, "max_bin": max_bin,
              "metric": "None", "verbosity": -1}
    prev_stream = os.environ.get("LGBM_TPU_STREAM")
    os.environ["LGBM_TPU_STREAM"] = "1"
    try:
        block_rows = plan.block_rows or min(n, 1 << 20)
        chunk_rows = min(block_rows, 1 << 20)
        t0 = time.perf_counter()
        gen = higgs_like_chunks(n, f, chunk_rows)
        lo0, X0, y0 = next(gen)
        ds = Dataset.from_sample(X0[:200_000], n, params=params,
                                 spill=True, spill_block_rows=block_rows)
        labels = np.empty(n, np.float32)
        ds.push_rows(X0)
        labels[lo0:lo0 + len(y0)] = y0
        del X0
        for lo, X, y in gen:
            ds.push_rows(X)
            labels[lo:lo + len(y)] = y
        ds.set_label(labels)
        spill_seconds = time.perf_counter() - t0
        store = ds._block_store

        t0 = time.perf_counter()
        booster = lgb.Booster(params=params, train_set=ds)
        if booster.boosting._stream is None:
            raise RuntimeError("stream stage trained RESIDENT — the "
                               "out-of-core claim would be false")
        booster.update()                      # compiles the block programs
        dsync(booster.boosting.train_score)
        compile_seconds = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(max(trees - 1, 0)):
            booster.update()
        dsync(booster.boosting.train_score)
        train_seconds = (time.perf_counter() - t0) * trees / max(trees - 1,
                                                                 1)
        auc = holdout_auc(booster, f)
        mem = device_memory_stats()
        measured_host_peak = host_rss_peak_bytes()
        result = {
            "metric": f"out-of-core streamed train {n}x{f}, {trees} trees"
                      f" x {leaves} leaves (holdout AUC {auc:.4f})",
            "value": round(train_seconds, 3),
            "unit": "seconds",
            "rows": n,
            "trees": trees,
            "sec_per_tree": round(train_seconds / max(trees, 1), 4),
            "spill_seconds": round(spill_seconds, 2),
            "compile_seconds": round(compile_seconds, 2),
            "holdout_auc": round(float(auc), 5),
            "store_bytes": store.nbytes(),
            "num_blocks": store.num_blocks,
            "block_rows": store.block_rows,
            # this STAGE's pumped blocks (the counter is process-wide
            # and the stream_probe stage pumps the same instrument)
            "blocks_streamed": int(
                _reg.counter("stream_blocks_total").value) - blocks0,
            "stream_plan": plan.summary(),
            "host_predicted_vs_measured": {
                "predicted_peak_bytes": plan.predicted_host_peak_bytes,
                "measured_rss_bytes": host_rss_bytes(),
                "measured_peak_bytes": measured_host_peak,
                "measured_peak_delta_bytes":
                    measured_host_peak - rss_peak0,
            },
            "hbm_predicted_vs_measured": {
                "predicted_peak_bytes": plan.predicted_device_peak_bytes,
                "measured_peak_bytes": int(mem.get("peak_hbm_bytes", 0)),
            },
        }
        result.update(mem)
        return result
    finally:
        if prev_stream is None:
            os.environ.pop("LGBM_TPU_STREAM", None)
        else:
            os.environ["LGBM_TPU_STREAM"] = prev_stream


def run_ingest_11m_bench(n, features=None, max_bin=None):
    """The resurrected higgs_11m ingest stage (ops/ingest.py): construct
    an ``n``-row Dataset chunk by chunk through the streamed device-ingest
    pump — raw f32 rows reach the device in planner-elected chunks and
    come back as binned bytes, so nothing close to r5's single 157 GB
    ``device_put`` ever exists.  Construction ONLY (the full stage trains
    the same scale): the banked claim is that full-scale ingest completes
    within device HBM, with the measured push rows/sec and the ingest
    story (kernel vs host fallback and why) next to the memory peaks."""
    from lightgbm_tpu.dataset import Dataset
    from lightgbm_tpu.ops.ingest import ingest_last

    f = features or F
    mb = max_bin or MAX_BIN
    params = {"objective": "binary", "num_leaves": LEAVES,
              "learning_rate": 0.1, "max_bin": mb,
              "metric": "None", "verbosity": -1}
    chunk_rows = 1 << 20
    t_all0 = time.perf_counter()
    gen = higgs_like_chunks(n, f, chunk_rows)
    lo0, X0, y0 = next(gen)
    ds = Dataset.from_sample(X0[:200_000], n, params=params)
    labels = np.empty(n, np.float32)
    push_seconds = 0.0
    t0 = time.perf_counter()
    ds.push_rows(X0)                 # chunk generation stays OFF the bin
    push_seconds += time.perf_counter() - t0   # clock: push time only
    labels[lo0:lo0 + len(y0)] = y0
    del X0, y0
    for lo, X, y in gen:
        t0 = time.perf_counter()
        ds.push_rows(X)
        push_seconds += time.perf_counter() - t0
        labels[lo:lo + len(y)] = y
    ds.set_label(labels)
    total_seconds = time.perf_counter() - t_all0
    story = ingest_last()
    mem = device_memory_stats()
    result = {
        "metric": f"streamed device ingest {n}x{f}, max_bin={mb} "
                  "(construction only)",
        "value": round(push_seconds, 3),
        "unit": "seconds",
        "rows": n,
        "features": f,
        "bin_seconds": round(push_seconds, 2),
        "bin_rows_per_sec": round(n / max(push_seconds, 1e-9), 1),
        "construct_total_seconds": round(total_seconds, 2),
        "binned_bytes": int(ds.binned.nbytes),
        "ingest": story or {"path": "host", "reason": "no story recorded"},
    }
    result.update(mem)
    return result


def run_serving_bench(n_train=100_000, trees=50, leaves=63, max_bin=63,
                      n_requests=600, n_threads=8, max_request_rows=700,
                      max_batch_rows=1024):
    """Serving-throughput metric: train a small booster, stand up the
    in-process server (lightgbm_tpu/serving/), fire mixed-shape requests
    from concurrent threads, report rows/s + latency + batching telemetry.

    Emitted alongside the training numbers: the ROADMAP north star is
    "serves heavy traffic", and this is the request-path half of it —
    micro-batched, shape-bucketed DeviceForest inference, so after
    warmup the accelerator sees only pre-compiled bucket shapes.
    """
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving.loadgen import fire_requests

    rng = np.random.RandomState(0)
    f = F
    X = rng.randn(n_train, f).astype(np.float32).astype(np.float64)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    booster = lgb.train(
        {"objective": "binary", "verbosity": -1, "num_leaves": leaves,
         "max_bin": max_bin},
        lgb.Dataset(X, label=y), num_boost_round=trees, verbose_eval=False)
    del X

    server = booster.serve(max_batch_rows=max_batch_rows,
                           batch_window_ms=2.0)
    # warmup: compile every bucket before the clock starts — off the
    # request path, so the latency/batch metrics report steady-state
    # serving only (no compile-time traffic)
    server.warm()
    storm = fire_requests(server, n_requests, n_threads,
                          max_request_rows, f)
    m = server.metrics_dict()
    server.close()
    lat = m["histograms"].get("request_latency_ms", {})
    fill = m["histograms"].get("batch_fill_ratio", {})
    c = m["counters"]
    wall = storm["wall_seconds"]
    out = {
        "requests": storm["requests"],
        "rows": storm["rows"],
        "trees": trees,
        "wall_seconds": round(wall, 3),
        "rows_per_second": round(storm["rows"] / wall, 1),
        "request_latency_ms_mean": lat.get("mean"),
        "request_latency_ms_max": lat.get("max"),
        "batch_fill_ratio_mean": fill.get("mean"),
        "batches": c.get("batches_total"),
        "multi_submitter_batches": c.get("multi_submitter_batches"),
        "compile_events": c.get("compile_events"),
        "bucket_hits": c.get("bucket_hits"),
    }
    if storm["errors"]:
        out["worker_errors"] = storm["errors"]
    return out


def run_fleet_bench(n_models=3, rows=20_000, trees=16, requests=300,
                    threads=6):
    """Serving-fleet metric (lightgbm_tpu/fleet/): N models behind one
    weighted front door under a shared-HBM residency plan — measured
    eviction with every model still servable (no OOM, no serve failure),
    an AOT-restored replica whose first request completes with ZERO
    compile events, and the opt-in bf16/int8 accuracy deltas, all via
    tools/fleet_smoke.py's phased run.  Raises on any missed acceptance
    bar so a failed fleet run is never journaled (PR 4 convention)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from fleet_smoke import run_smoke
    summary = run_smoke(n_models=n_models, rows=rows, trees=trees,
                        requests=requests, threads=threads)
    if summary.get("failed"):
        raise RuntimeError(
            f"fleet smoke failed phases: "
            f"{[k for k, ok in summary['phase_ok'].items() if not ok]}")
    return summary


def run_fleet_failover_bench(devices=None, n_models=2, rows=20_000,
                             trees=16, requests=600, threads=6):
    """Pod-scale availability metric (lightgbm_tpu/fleet/router.py): a
    replicated multi-device PodFleet serves a threaded traffic storm
    while chaos VANISHES one device mid-run.  Acceptance bars (raised on
    a miss so a failed drill is never journaled, PR 4 convention): zero
    non-typed request failures, availability >= 0.999, every response
    bit-identical to Booster.predict(raw_score=True), and every model's
    replica coverage restored within ONE replan tick.  Device count:
    BENCH_FLEET_DEVICES (default 3)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from fleet_smoke import run_failover_smoke
    if devices is None:
        devices = int(os.environ.get("BENCH_FLEET_DEVICES", "") or 3)
    summary = run_failover_smoke(devices=devices, n_models=n_models,
                                 rows=rows, trees=trees,
                                 requests=requests, threads=threads)
    if summary.get("failed"):
        raise RuntimeError(
            f"fleet failover drill missed its bars: "
            f"availability={summary.get('availability')} "
            f"outcomes={summary.get('outcomes')} "
            f"recovered={summary.get('recovered_within_one_tick')}")
    return summary


def run_lifecycle_bench(rows=20_000, trees=12, refresh_trees=4,
                        requests=120, threads=4):
    """Guarded model-lifecycle metric (lightgbm_tpu/lifecycle/): a full
    train -> continual refresh -> shadow/canary promotion -> forced
    drift rollback cycle under threaded loadgen traffic, via
    tools/lifecycle_smoke.py's phased run.  The acceptance bars: a
    clean promotion serves the candidate bit-identically with
    ``model_age_seconds`` reset, and the forced rollback leaves the
    fleet byte-identical to the pre-promotion model with a
    flight-recorder bundle naming the breached gate.  Raises on any
    missed bar so a failed lifecycle run is never journaled (PR 4
    convention)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from lifecycle_smoke import run_smoke
    summary = run_smoke(rows=rows, trees=trees,
                        refresh_trees=refresh_trees, requests=requests,
                        threads=threads)
    if summary.get("failed"):
        raise RuntimeError(
            f"lifecycle smoke failed phases: "
            f"{[k for k, ok in summary['phase_ok'].items() if not ok]}")
    return summary


def run_coresident_bench(rows=12_000, trees=10, refresh_trees=6,
                         requests=120, threads=4):
    """Co-residency metric (lightgbm_tpu/coresident/): loadgen traffic
    AND a continual refresh on the SAME device set behind the shared
    residency ledger, via tools/coresident_smoke.py's phased run.  The
    acceptance bars: zero non-typed serving failures with overall p99
    within the serving SLO, ``model_age_seconds`` drops across the
    refresh, and the brownout throttle counter moved (training yielded
    to serving through the pause_control seam at least once during the
    injected device-delay window).  Raises on any missed bar so a
    failed co-residency run is never journaled (PR 4 convention)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from coresident_smoke import run_smoke
    summary = run_smoke(rows=rows, trees=trees,
                        refresh_trees=refresh_trees, requests=requests,
                        threads=threads)
    if summary.get("failed"):
        raise RuntimeError(
            f"coresident smoke failed phases: "
            f"{[k for k, ok in summary['phase_ok'].items() if not ok]}")
    return summary


def run_resilience_bench(n_train=50_000, trees=24, leaves=63, max_bin=63,
                         snapshot_freq=8):
    """Fault-tolerance overhead metric: checkpoint-bundle save/load
    latency and resume bit-parity at bench scale (docs/RESILIENCE.md).

    Reports what periodic checkpointing costs the training loop
    (save_seconds covers state capture incl. the device->host score
    fetch, sha256 manifest, atomic write) and proves the resume path on
    THIS backend: a run killed after a bundle and resumed must produce a
    byte-identical model.
    """
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.resilience import CheckpointManager, load_checkpoint

    rng = np.random.RandomState(0)
    X = rng.rand(n_train, F)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0.8).astype(np.float32)
    P = {"objective": "binary", "verbosity": -1, "num_leaves": leaves,
         "max_bin": max_bin, "bagging_fraction": 0.8, "bagging_freq": 2}

    with tempfile.TemporaryDirectory() as td:
        t0 = time.time()
        full = lgb.train(P, lgb.Dataset(X, label=y), trees,
                         verbose_eval=False)
        plain_s = time.time() - t0
        full.save_model(f"{td}/full.txt")

        t0 = time.time()
        lgb.train(P, lgb.Dataset(X, label=y), trees, verbose_eval=False,
                  snapshot_freq=snapshot_freq,
                  snapshot_out=f"{td}/ck.txt")
        ckpt_s = time.time() - t0
        n_saves = trees // snapshot_freq

        mgr = CheckpointManager(f"{td}/ck.txt.ckpt")
        newest = mgr.bundles()[-1]
        t0 = time.time()
        ck = load_checkpoint(f"{td}/ck.txt.ckpt/{newest}")
        load_s = time.time() - t0

        die_at = max(snapshot_freq, trees // 2)
        lgb.train(P, lgb.Dataset(X, label=y), die_at, verbose_eval=False,
                  snapshot_freq=snapshot_freq,
                  snapshot_out=f"{td}/part.txt")
        t0 = time.time()
        res = lgb.train(P, lgb.Dataset(X, label=y), trees,
                        verbose_eval=False,
                        resume_from=f"{td}/part.txt.ckpt")
        resume_s = time.time() - t0
        res.save_model(f"{td}/res.txt")
        identical = (open(f"{td}/full.txt", "rb").read()
                     == open(f"{td}/res.txt", "rb").read())
        bundle_bytes = os.path.getsize(f"{td}/ck.txt.ckpt/{newest}")

    return {
        "trees": trees,
        "rows": n_train,
        "checkpoint_saves": n_saves,
        "save_seconds_each": round(max(0.0, ckpt_s - plain_s)
                                   / max(n_saves, 1), 4),
        "bundle_load_verify_seconds": round(load_s, 4),
        "bundle_bytes": bundle_bytes,
        "bundle_iteration": ck.iteration,
        "resume_wall_seconds": round(resume_s, 3),
        "resume_bit_identical": bool(identical),
    }


# the descending program-variant ladder for hung remote compiles: each
# entry is an env-gate set the growers read at TRACE time (grower_rounds
# .py use_pack, ops/histogram.py compacted_segment_histogram).  SINGLE
# SOURCE — tools/tpu_measure.py and tools/tpu_bisect.py import this list.
# Every entry FULLY specifies every gate (as tpu_bisect's merged dict
# does): the ladder is applied with os.environ.update, so a partial v0
# after a stripped variant would silently inherit the stripped gates and
# mislabel the banked result (ADVICE.md round 5).  Non-stripped slots are
# seeded from the operator's environment at startup, so an explicit
# `LGBM_TPU_PACK=0 python bench.py` is honored from attempt 0 instead of
# being clobbered back to the default.
_VARIANT_LADDER = [
    {"LGBM_TPU_SMALL_ROUNDS": os.environ.get("LGBM_TPU_SMALL_ROUNDS", "1"),
     "LGBM_TPU_PACK": os.environ.get("LGBM_TPU_PACK", "1"),
     "LGBM_TPU_CHUNK": os.environ.get("LGBM_TPU_CHUNK", "")},  # full default
    {"LGBM_TPU_SMALL_ROUNDS": "0",
     "LGBM_TPU_PACK": os.environ.get("LGBM_TPU_PACK", "1"),
     "LGBM_TPU_CHUNK": os.environ.get("LGBM_TPU_CHUNK", "")},
    # chunk-off rung: fused macro-steps disabled, legacy one-program-per-
    # round dispatch — isolates scan-program compiles from the hang hunt
    # and doubles as the bisection gate for macro-step regressions
    {"LGBM_TPU_SMALL_ROUNDS": "0", "LGBM_TPU_PACK": "0",
     "LGBM_TPU_CHUNK": os.environ.get("LGBM_TPU_CHUNK", "")},
    {"LGBM_TPU_SMALL_ROUNDS": "0", "LGBM_TPU_PACK": "0",
     "LGBM_TPU_CHUNK": "0"},                                 # most stripped
]
# a pre-stripped operator env can make adjacent rungs identical; dedupe
# so a hung compile never burns a stall_timeout retrying the same program
COMPILE_VARIANT_ENVS = [e for i, e in enumerate(_VARIANT_LADDER)
                        if i == 0 or e != _VARIANT_LADDER[i - 1]]


# --------------------------------------------------------------- TPU worker

# ---- stage journal ------------------------------------------------------
# Every completed worker stage persists its result JSON incrementally
# (atomic via file_io.write_atomic), keyed under a workload fingerprint.
# A rerun — or a retry attempt after a TPU kernel fault killed the worker
# mid-run (round 5: ranking and epsilon crashed and were never retried) —
# re-emits the banked results and executes ONLY the missing stages.
# Errors are emitted but never journaled, so failed stages retry.
# BENCH_JOURNAL=<path> overrides the location (default
# ./bench_journal.json next to this file); BENCH_JOURNAL=0 disables.
# BENCH_ONLY=<stage[,stage]> runs exactly those worker stages (budget
# gates are bypassed for explicitly selected stages).


def _journal_path():
    p = os.environ.get("BENCH_JOURNAL",
                       os.path.join(REPO, "bench_journal.json"))
    return None if str(p).strip().lower() in ("", "0", "off", "none") else p


_JOURNAL_FP_EXTRA = None


def _journal_fingerprint():
    """Workload shape + BACKEND + code revision: a banked result must
    never replay for a different platform (CPU-allowed CI run masking a
    later TPU bench) or after the kernels changed underneath it."""
    global _JOURNAL_FP_EXTRA
    if _JOURNAL_FP_EXTRA is None:
        plat = "unknown"
        try:
            import jax
            plat = jax.default_backend()   # journal use is post-init only
        except Exception:
            pass
        rev = ""
        try:
            r = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                               cwd=REPO, capture_output=True, text=True,
                               timeout=10)
            rev = r.stdout.strip()
        except Exception:
            pass
        _JOURNAL_FP_EXTRA = {"platform": plat, "code": rev}
    return {"rows": N, "trees": TREES, "leaves": LEAVES, "max_bin": MAX_BIN,
            "extra_params": os.environ.get("BENCH_EXTRA_PARAMS", ""),
            **_JOURNAL_FP_EXTRA}


def journal_stages() -> dict:
    """Banked stage results for THIS workload fingerprint ({} otherwise)."""
    path = _journal_path()
    if not path:
        return {}
    try:
        with open(path) as fh:
            d = json.load(fh)
    except (OSError, ValueError):
        return {}
    if d.get("fingerprint") != _journal_fingerprint():
        return {}
    stages = d.get("stages", {})
    return stages if isinstance(stages, dict) else {}


def journal_put(key, result) -> None:
    path = _journal_path()
    if not path:
        return
    from lightgbm_tpu.utils.file_io import write_atomic
    payload = {"fingerprint": _journal_fingerprint(),
               "stages": dict(journal_stages(), **{key: result})}
    try:
        write_atomic(path, json.dumps(payload, indent=1))
    except OSError as e:
        log(f"journal write failed ({e}); continuing without journal")


def bench_only():
    v = os.environ.get("BENCH_ONLY", "").strip()
    if not v:
        return None
    return {s.strip() for s in v.split(",") if s.strip()} or None


def run_stage(name, fn, key=None, budget_floor=0.0):
    """Run one worker stage through the journal + BENCH_ONLY selector.

    Returns the stage dict (fresh or journal-replayed), ``None`` when the
    stage was skipped (deselected / budget floor / skip env), or a dict
    with ``"error"`` when it raised (emitted, not journaled)."""
    only = bench_only()
    if only is not None and name not in only:
        return None
    key = key or name
    saved = journal_stages().get(key)
    if saved is not None and "error" not in saved:
        emit(dict(saved, stage=name, journal=True))
        return saved
    if only is None and budget_floor and remaining_budget() <= budget_floor:
        return None
    t1 = time.time()
    try:
        r = dict(fn())
    except Exception as e:
        err = {"stage": name, "error": str(e)[-800:],
               "traceback_tail": traceback.format_exc()[-800:]}
        emit(err)
        return err
    r["stage"] = name
    r["elapsed"] = round(time.time() - t1, 1)
    journal_put(key, r)
    emit(r)
    return r


def tpu_worker():
    """One warmed process: backend init -> probes -> smoke -> full ->
    telemetry stages, each routed through the stage journal above.

    Emits a JSON line per stage so the parent banks partial telemetry even
    if a later stage wedges or the process dies.  Exit codes: 0 full run
    done, 3 backend init failed, 4 init ok but a later stage failed.
    """
    from lightgbm_tpu.utils.platform import _cache_dir, enable_compile_cache
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
    enable_compile_cache()          # LGBM_TPU_COMPILE_CACHE=<dir> honored
    t0 = time.time()
    try:
        import jax
        devs = jax.devices()
        import jax.numpy as jnp
        jnp.ones((8, 8)).sum().block_until_ready()
    except Exception as e:
        emit({"stage": "init", "ok": False, "elapsed": round(time.time() - t0, 1),
              "error": str(e)[-800:]})
        return 3
    d = devs[0]
    emit({"stage": "init", "ok": True, "elapsed": round(time.time() - t0, 1),
          "platform": d.platform, "device_kind": getattr(d, "device_kind", ""),
          "n_devices": len(devs)})
    if d.platform == "cpu" and os.environ.get("BENCH_WORKER_ALLOW_CPU") != "1":
        # plugin resolved to CPU: not a TPU result; parent falls back
        # (BENCH_WORKER_ALLOW_CPU=1 lets CI exercise the full worker
        # pipeline without a TPU)
        return 3

    if os.environ.get("BENCH_SKIP_KERNEL_PROBE") != "1":
        run_stage("kernel_probe",
                  lambda: kernel_probe(min(N, 1_000_000), F, MAX_BIN))

    if os.environ.get("BENCH_SKIP_DISPATCH_PROBE") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))

        def _dispatch():
            from dispatch_probe import run_probe
            return run_probe(rows=min(N, 100_000), iters=12, chunks=(8, 32))
        run_stage("dispatch_probe", _dispatch)

    # f32-vs-quantized histogram throughput + psum payload accounting
    # (tools/hist_probe.py) — cheap, banked before the long stages
    if os.environ.get("BENCH_SKIP_HIST_PROBE") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))

        def _hist():
            from hist_probe import run_probe as hist_run
            return hist_run(rows=min(N, 1_000_000), features=F,
                            max_bin=MAX_BIN, leaves=LEAVES)
        run_stage("hist_probe", _hist)

    # inference-kernel micro-bench (tools/predict_probe.py): while vs
    # fori vs fused traversal sec/Mrow + measured MFU/BW, the planner's
    # variant election cold/warm against the "p-..." autotune family,
    # and the serving bit-parity check; on accelerators the probe raises
    # below the 3x-vs-while bar at 1M rows, and errors are never
    # journaled so a failed probe retries
    if os.environ.get("BENCH_SKIP_PREDICT_PROBE") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))

        def _predict_probe():
            from predict_probe import run_probe as predict_run
            return predict_run(rows=min(N, 1_000_000), features=F)
        run_stage("predict_probe", _predict_probe)

    # device-ingest binning micro-bench (tools/ingest_probe.py): the
    # full parity matrix (NaN / zero-as-bin / categorical / uint16)
    # device-vs-host byte identity, the "i-..." autotune election
    # cold/warm, and measured bin rows/sec + HBM BW per tile rung next
    # to the host oracle; on accelerators the probe raises below the
    # 5x-vs-host bar at 1M rows, and errors are never journaled so a
    # failed probe retries
    if os.environ.get("BENCH_SKIP_INGEST_PROBE") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))

        def _ingest_probe():
            from ingest_probe import run_probe as ingest_run
            return ingest_run(rows=min(N, 1_000_000), features=F,
                              max_bin=MAX_BIN)
        run_stage("ingest_probe", _ingest_probe)

    # out-of-core block-pump micro-bench (tools/stream_probe.py):
    # blocks/sec, device_put overlap efficiency, host-RSS peak vs the
    # two-level planner's prediction — cheap, banked early; errors are
    # never journaled so a failed probe retries
    if os.environ.get("BENCH_SKIP_STREAM_PROBE") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))

        def _stream_probe():
            from stream_probe import run_probe as stream_run
            return stream_run(rows=min(N, 2_000_000), features=F)
        run_stage("stream_probe", _stream_probe)

    # per-tier collective micro-bench (tools/collective_probe.py): flat
    # vs hierarchical vs voting reduction latency over a simulated
    # 2-slice hybrid ("dcn","ici") mesh + the planner's per-tier byte
    # accounting (the acceptance signal: voting's DCN bytes strictly
    # below data-parallel's at equal trees) — cheap, banked early;
    # errors are never journaled so a failed probe retries
    if os.environ.get("BENCH_SKIP_COLLECTIVE_PROBE") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))

        def _coll_probe():
            from collective_probe import run_probe as coll_run
            return coll_run(rows=min(N, 1_000_000), features=F,
                            max_bin=MAX_BIN, leaves=LEAVES, trees=TREES)
        run_stage("collective_probe", _coll_probe)

    # batched model-axis sweep micro-bench (tools/sweep_probe.py): the
    # same chunk body solo vs vmapped at B in {2,4,8} lanes over one
    # shared binned matrix — aggregate iters/sec + measured MFU per
    # batch width next to plan_model_batch's lane-chunk verdict; on
    # accelerators the probe raises below the 4x-at-B=8 bar, and errors
    # are never journaled so a failed sweep retries
    if os.environ.get("BENCH_SKIP_SWEEP") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))

        def _sweep():
            from sweep_probe import run_probe as sweep_run
            return sweep_run(rows=min(N, 200_000), features=F,
                             max_bin=MAX_BIN, leaves=LEAVES)
        run_stage("sweep", _sweep)

    # tpulint (tools/lint.py, docs/LINTING.md): the static-analysis
    # suite runs as a journaled stage so every bench round records that
    # the tree it measured was invariant-clean; violations raise, and
    # errors are never journaled (run_stage), so a dirty tree re-lints
    # on the next round instead of banking a stale verdict
    if os.environ.get("BENCH_SKIP_LINT") != "1":
        def _lint():
            if REPO not in sys.path:
                sys.path.insert(0, REPO)
            from tools.lint import load_project, run_lint
            project = load_project(root=REPO)
            violations = run_lint(project)
            if violations:
                raise RuntimeError(
                    f"tpulint: {len(violations)} violation(s), first: "
                    + violations[0].render())
            return {"ok": True, "files": len(project.files),
                    "violations": 0}
        run_stage("lint", _lint)

    # whole-plane observability smoke (tools/obs_dump.py): a tiny
    # instrumented train+serve cycle dumping trace/metrics/prometheus
    # artifacts — cheap, banked before the long stages; errors are never
    # journaled (run_stage), so a failed dump retries on the next run
    if os.environ.get("BENCH_SKIP_OBS") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))

        def _obs():
            from obs_dump import run_dump
            return run_dump(out_dir=REPO, rows=20_000, trees=8)
        run_stage("obs_dump", _obs)

    if os.environ.get("BENCH_SKIP_SMOKE") != "1":
        smoke = run_stage(
            "smoke", lambda: run_bench(min(SMOKE_N, N),
                                       min(SMOKE_TREES, TREES),
                                       LEAVES, MAX_BIN, tag="-smoke"))
        if smoke is not None and "error" in smoke:
            return 4

    n_full = int(os.environ.get("BENCH_WORKER_ROWS", N))

    # HBM budget verdict for the >=10M-row stage, banked as its own stage
    # so the planner's tile/feasibility decision is journaled even if the
    # run itself later dies.  The stage is restored (not skipped): an
    # infeasible verdict aborts cheaply; a degraded one RUNS with the
    # smaller tile instead of crashing in compile as in r5.
    def _plan():
        from lightgbm_tpu.ops.planner import plan_histograms
        return plan_histograms(rows=n_full, features=F,
                               num_bins=MAX_BIN + 1,
                               num_leaves=LEAVES).summary()
    run_stage("hbm_plan", _plan, key=f"hbm_plan@{n_full}")

    def _full():
        r = run_bench(n_full, TREES, LEAVES, MAX_BIN,
                      tag="" if n_full == N else "-reduced")
        if n_full != N:
            r["note"] = (f"row count reduced from {N} to {n_full}: the "
                         "remote compile service hung on the full-size "
                         "program (largest compilable scale banked)")
        return r

    # journal key carries the row count: retry attempts at halved rows
    # must not replay a different scale's banked result
    full = run_stage("full", _full, key=f"full@{n_full}")
    if full is not None and "error" in full:
        return 4

    # the resurrected higgs_11m ingest stage (ops/ingest.py): full-scale
    # construction through the streamed device-ingest pump, journaled so
    # the "11M rows bin within HBM, no 157 GB device_put" claim is a
    # banked number (rows/sec + ingest story + memory peaks), not a
    # side effect buried inside the full stage
    if os.environ.get("BENCH_SKIP_INGEST_11M") != "1":
        run_stage("ingest_11m",
                  lambda: run_ingest_11m_bench(n_full),
                  key=f"ingest_11m@{n_full}", budget_floor=600)

    # the >=10M stage, GRADUATED (lightgbm_tpu/data/): a journaled
    # 100M-row streamed run whose binned matrix never resides whole on
    # host or HBM, with planner-predicted vs measured peaks on BOTH
    # memories.  The two-level verdict banks as its own stage first so
    # the decision survives even if the run dies.
    stream_n = int(os.environ.get("BENCH_STREAM_ROWS", 100_000_000))

    def _stream_plan():
        from lightgbm_tpu.ops.planner import plan_stream
        return plan_stream(rows=stream_n, features=F,
                           num_bins=MAX_BIN + 1,
                           num_leaves=min(LEAVES, 63)).summary()
    run_stage("stream_plan", _stream_plan, key=f"stream_plan@{stream_n}")
    if os.environ.get("BENCH_SKIP_STREAM") != "1":
        run_stage(
            "stream",
            lambda: run_stream_bench(
                stream_n,
                trees=int(os.environ.get("BENCH_STREAM_TREES", 3)),
                leaves=min(LEAVES, 63), max_bin=MAX_BIN),
            key=f"stream@{stream_n}", budget_floor=1500)

    # bulk offline scoring (data/score.py via tools/bulk_score.py): the
    # blockstore pump pointed at inference — a >=10M-row synthetic set
    # streamed through the one AOT bulk bucket, scores banked with
    # per-block manifest commits, plus the crash drill (partial run,
    # resume, byte-identical blocks).  The drill raises on any miss, so
    # failed runs are never journaled; rows/sec/device and the
    # predicted-vs-measured peaks on both memories are the banked
    # numbers bench_diff gates on.
    if os.environ.get("BENCH_SKIP_BULK_SCORE") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))
        bulk_n = int(os.environ.get("BENCH_BULK_ROWS", 10_000_000))

        def _bulk():
            from bulk_score import run_bulk
            return run_bulk(rows=bulk_n, features=F)
        run_stage("bulk_score", _bulk, key=f"bulk_score@{bulk_n}",
                  budget_floor=900)

    # MSLR-side benchmark (lambdarank + NDCG@10, BASELINE.md) with the
    # leftover budget — strictly after the headline number is banked
    if os.environ.get("BENCH_SKIP_RANKING") != "1":
        run_stage("ranking",
                  lambda: run_ranking_bench(RANK_QUERIES, RANK_DOCS,
                                            RANK_TREES, LEAVES, MAX_BIN),
                  budget_floor=900)

    # serving-throughput metric (lightgbm_tpu/serving/): the request-path
    # half of the north star, after every training number is banked
    if os.environ.get("BENCH_SKIP_SERVING") != "1":
        run_stage("serving", run_serving_bench, budget_floor=300)

    # serving-fleet stage (lightgbm_tpu/fleet/): N-model registry under a
    # shared-HBM plan — measured eviction, AOT zero-compile restart,
    # opt-in low-precision deltas
    if os.environ.get("BENCH_SKIP_FLEET") != "1":
        run_stage("fleet", run_fleet_bench, budget_floor=240)

    # pod-scale failover drill (fleet/topology.py + fleet/router.py):
    # kill one replicated device under load — zero non-typed failures,
    # availability >= 0.999, recovery within one replan tick
    if os.environ.get("BENCH_SKIP_FLEET") != "1":
        run_stage("fleet_failover", run_fleet_failover_bench,
                  budget_floor=180)

    # fault-tolerance overhead (lightgbm_tpu/resilience/): checkpoint
    # save/load cost + resume bit-parity on the live backend
    if os.environ.get("BENCH_SKIP_RESILIENCE") != "1":
        run_stage("resilience", run_resilience_bench, budget_floor=240)

    # guarded model lifecycle (lightgbm_tpu/lifecycle/): continual
    # refresh -> shadow/canary promotion -> forced rollback under load;
    # errors raise so a failed cycle is never journaled
    if os.environ.get("BENCH_SKIP_LIFECYCLE") != "1":
        run_stage("lifecycle", run_lifecycle_bench, budget_floor=240)

    # co-resident train+serve (lightgbm_tpu/coresident/): traffic and a
    # ledger-budgeted refresh share one device set; brownout must
    # throttle training while p99 stays within SLO; errors raise so a
    # failed co-residency cycle is never journaled
    if os.environ.get("BENCH_SKIP_CORESIDENT") != "1":
        run_stage("coresident", run_coresident_bench, budget_floor=240)

    # automated bottleneck diagnosis (lightgbm_tpu/obs/diagnose.py):
    # joins THIS run's banked stages (mfu_measured, compile_cache,
    # stream_probe, collective_probe) + live registry gauges into ranked
    # verdicts, journaled LAST so every bench round self-reports its
    # bottleneck next to the numbers; errors are never journaled
    # (run_stage) so a failed diagnosis retries
    if os.environ.get("BENCH_SKIP_OBS") != "1":
        sys.path.insert(0, os.path.join(REPO, "tools"))

        def _doctor():
            from obs_doctor import run_doctor
            return run_doctor(stages=journal_stages())
        run_stage("obs_doctor", _doctor)
    return 0


class LineReader(threading.Thread):
    """Drain a subprocess stdout into a list of parsed JSON dicts."""

    def __init__(self, pipe):
        super().__init__(daemon=True)
        self.pipe = pipe
        self.lines = []
        self.start()

    def run(self):
        try:
            for line in self.pipe:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                    if isinstance(obj, dict):
                        self.lines.append(obj)
                        continue
                except ValueError:
                    pass
                log(f"worker: {line[:300]}")
        except Exception:
            pass


def launch_tpu_worker(env_variant):
    env = dict(os.environ)
    env["BENCH_STAGE"] = "tpu-worker"
    from lightgbm_tpu.utils.platform import _cache_dir
    env.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
    if env_variant == "no-remote-compile":
        env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL,
                            text=True, env=env, cwd=REPO)
    return proc, LineReader(proc.stdout)


def launch_cpu_fallback():
    from lightgbm_tpu.utils.platform import clean_cpu_env
    env = clean_cpu_env(1)
    env["BENCH_STAGE"] = "cpu-worker"
    env["BENCH_ROWS"] = str(CPU_N)
    env["BENCH_TREES"] = str(CPU_TREES)
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL,
                            text=True, env=env, cwd=REPO)
    return proc, LineReader(proc.stdout)


def cpu_worker():
    try:
        res = run_bench(N, TREES, LEAVES, MAX_BIN, tag="-fallback")
        res["stage"] = "cpu"
        # emit the moment it is ready (round-4 insurance against the
        # driver dying mid-run), THEN re-emit with serving telemetry —
        # the driver's collect() keeps the last "cpu" line
        emit(res)
        if os.environ.get("BENCH_SKIP_SERVING") != "1":
            try:
                res["serving"] = run_serving_bench(
                    n_train=50_000, trees=30, n_requests=400, n_threads=4)
            except Exception as e:
                res["serving"] = {"error": str(e)[-300:]}
            emit(res)
        if os.environ.get("BENCH_SKIP_FLEET") != "1":
            try:
                res["fleet"] = run_fleet_bench(
                    rows=10_000, trees=10, requests=200, threads=4)
            except Exception as e:
                res["fleet"] = {"error": str(e)[-300:]}
            emit(res)
            try:
                res["fleet_failover"] = run_fleet_failover_bench(
                    rows=10_000, trees=10, requests=300, threads=4)
            except Exception as e:
                res["fleet_failover"] = {"error": str(e)[-300:]}
            emit(res)
        if os.environ.get("BENCH_SKIP_RESILIENCE") != "1":
            try:
                res["resilience"] = run_resilience_bench(
                    n_train=20_000, trees=16, snapshot_freq=4)
            except Exception as e:
                res["resilience"] = {"error": str(e)[-300:]}
            emit(res)
        if os.environ.get("BENCH_SKIP_LIFECYCLE") != "1":
            try:
                res["lifecycle"] = run_lifecycle_bench(
                    rows=10_000, trees=8, refresh_trees=3,
                    requests=80, threads=4)
            except Exception as e:
                res["lifecycle"] = {"error": str(e)[-300:]}
            emit(res)
        if os.environ.get("BENCH_SKIP_CORESIDENT") != "1":
            try:
                res["coresident"] = run_coresident_bench(
                    rows=6_000, trees=8, refresh_trees=4,
                    requests=80, threads=4)
            except Exception as e:
                res["coresident"] = {"error": str(e)[-300:]}
            emit(res)
        return 0
    except Exception as e:
        emit({"stage": "cpu", "error": str(e)[-800:],
              "traceback_tail": traceback.format_exc()[-1000:]})
        return 1


def collect(stages_list, key):
    """LAST stage dict for ``key`` (stages accumulate across worker retry
    attempts; the latest attempt's telemetry wins)."""
    out = None
    for obj in stages_list:
        if obj.get("stage") == key:
            out = obj
    return out


def collect_ok(stages_list, key):
    """LAST error-free stage dict for ``key`` — an errored attempt must
    never mask a later successful retry."""
    out = None
    for obj in stages_list:
        if obj.get("stage") == key and "error" not in obj:
            out = obj
    return out


def _annotate(line, tpu_stages, cpu_result):
    """Attach telemetry (probe/init/ranking/cpu reference) to a result."""
    probe = collect_ok(tpu_stages, "kernel_probe")
    if probe:
        line["hist_kernel_probe_ms"] = {
            k: v for k, v in probe.items() if k not in ("stage", "elapsed")}
    hp = collect_ok(tpu_stages, "hist_probe")
    if hp:
        line["hist_probe"] = {k: v for k, v in hp.items()
                              if k not in ("stage", "elapsed")}
    cp = collect_ok(tpu_stages, "collective_probe")
    if cp:
        line["collective_probe"] = {k: v for k, v in cp.items()
                                    if k not in ("stage", "elapsed")}
    planl = collect_ok(tpu_stages, "hbm_plan")
    if planl and "hbm_plan" not in line:
        line["hbm_plan"] = {k: v for k, v in planl.items()
                            if k not in ("stage", "elapsed")}
    init = collect_ok(tpu_stages, "init")
    if init:
        line["backend_init_seconds"] = init.get("elapsed")
    rank = collect_ok(tpu_stages, "ranking")
    if rank:
        line["ranking"] = {k: v for k, v in rank.items()
                           if k not in ("stage", "elapsed")}
    serv = collect_ok(tpu_stages, "serving")
    if serv:
        line["serving"] = {k: v for k, v in serv.items()
                           if k not in ("stage", "elapsed")}
    if "serving" not in line and cpu_result and \
            isinstance(cpu_result.get("serving"), dict) and \
            "error" not in cpu_result["serving"]:
        line["serving"] = dict(cpu_result["serving"],
                               note="cpu-fallback serving numbers")
    fl = collect_ok(tpu_stages, "fleet")
    if fl:
        line["fleet"] = {k: v for k, v in fl.items()
                         if k not in ("stage", "elapsed")}
    if "fleet" not in line and cpu_result and \
            isinstance(cpu_result.get("fleet"), dict) and \
            "error" not in cpu_result["fleet"]:
        line["fleet"] = dict(cpu_result["fleet"],
                             note="cpu-fallback fleet numbers")
    resil = collect_ok(tpu_stages, "resilience")
    if resil:
        line["resilience"] = {k: v for k, v in resil.items()
                              if k not in ("stage", "elapsed")}
    if "resilience" not in line and cpu_result and \
            isinstance(cpu_result.get("resilience"), dict) and \
            "error" not in cpu_result["resilience"]:
        line["resilience"] = dict(cpu_result["resilience"],
                                  note="cpu-fallback resilience numbers")
    lc = collect_ok(tpu_stages, "lifecycle")
    if lc:
        line["lifecycle"] = {k: v for k, v in lc.items()
                             if k not in ("stage", "elapsed")}
    if "lifecycle" not in line and cpu_result and \
            isinstance(cpu_result.get("lifecycle"), dict) and \
            "error" not in cpu_result["lifecycle"]:
        line["lifecycle"] = dict(cpu_result["lifecycle"],
                                 note="cpu-fallback lifecycle numbers")
    co = collect_ok(tpu_stages, "coresident")
    if co:
        line["coresident"] = {k: v for k, v in co.items()
                              if k not in ("stage", "elapsed")}
    if "coresident" not in line and cpu_result and \
            isinstance(cpu_result.get("coresident"), dict) and \
            "error" not in cpu_result["coresident"]:
        line["coresident"] = dict(cpu_result["coresident"],
                                  note="cpu-fallback coresident numbers")
    if cpu_result and "error" not in cpu_result:
        line["cpu_reference"] = {
            "sec_per_tree": cpu_result.get("sec_per_tree"),
            "rows": cpu_result.get("rows"),
            "holdout_auc": cpu_result.get("holdout_auc"),
        }
    return line


def build_best_line(tpu_stages, cpu_result, note):
    """The best driver-parseable result line available RIGHT NOW.

    Priority: TPU full > TPU smoke (partial) > CPU fallback > placeholder.
    The driver records the LAST stdout JSON line, so the parent re-emits
    this at every state change — any kill point leaves a valid line.
    """
    full = collect_ok(tpu_stages, "full")
    if full:
        line = {k: v for k, v in full.items() if k != "stage"}
        return _annotate(line, tpu_stages, cpu_result), True
    smoke = collect_ok(tpu_stages, "smoke")
    if smoke:
        line = {k: v for k, v in smoke.items() if k != "stage"}
        line["metric"] += f" PARTIAL-SMOKE ({note})"
        line["vs_baseline"] = 0.0      # scaled-down run, not comparable
        return _annotate(line, tpu_stages, cpu_result), False
    if cpu_result and "error" not in cpu_result:
        line = {k: v for k, v in cpu_result.items() if k != "stage"}
        line["metric"] += f" CPU-FALLBACK ({note})"
        line["vs_baseline"] = 0.0
        partial = {k: collect(tpu_stages, k)
                   for k in ("init", "kernel_probe", "smoke")}
        line["tpu_partial"] = {k: v for k, v in partial.items() if v}
        return line, False
    err = (cpu_result or {}).get("error", "no result yet")
    line = error_line("train", err)
    partial = {k: collect(tpu_stages, k)
               for k in ("init", "kernel_probe", "smoke")}
    line["tpu_partial"] = {k: v for k, v in partial.items() if v}
    line["note"] = note
    return line, False


def main():
    if os.environ.get("BENCH_STAGE") == "tpu-worker":
        return tpu_worker()
    if os.environ.get("BENCH_STAGE") == "cpu-worker":
        return cpu_worker()

    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"

    from lightgbm_tpu.utils.platform import tpu_plugin_active
    try_tpu = (not force_cpu) and tpu_plugin_active()
    if not try_tpu:
        log("no TPU plugin in env (or BENCH_FORCE_CPU): CPU measurement only")

    # a valid (placeholder) result line lands FIRST — rc=124 at any later
    # point still leaves the driver a parseable last line
    emit(error_line("startup", "bench started; no measurement banked yet",
                    {"note": "placeholder — superseded by later lines"}))

    cpu_proc, cpu_reader = launch_cpu_fallback()
    log(f"cpu fallback started ({CPU_N} rows x {CPU_TREES} trees)")

    tpu_stages = []        # all stage dicts from every worker attempt
    attempt = 0
    proc, reader = (None, None)
    cpu_result = None
    emitted_state = None   # dedup: (n tpu stages, cpu done?, note)

    abandon_reason = None   # set when TPU attempts are abandoned mid-run

    def note_now():
        if not try_tpu:
            if abandon_reason:
                return abandon_reason
            return ("BENCH_FORCE_CPU=1" if force_cpu
                    else "no TPU plugin in environment")
        exhausted = remaining_budget() <= 120
        init = collect(tpu_stages, "init")
        if init and init.get("ok") is False:
            why = f"tpu init failed: {init.get('error', '')[:200]}"
        elif collect(tpu_stages, "smoke") or collect(tpu_stages, "full"):
            why = "tpu run in progress"
        else:
            why = "tpu pending"
        if exhausted:
            why = f"tpu attempts exhausted within budget; last state: {why}"
        return why

    def refresh_emission(force=False):
        """Re-emit the best-available line when state changed."""
        nonlocal emitted_state
        state = (len(tpu_stages),
                 tuple(s.get("stage") for s in tpu_stages),
                 cpu_result is not None, note_now())
        if state == emitted_state and not force:
            return
        line, is_full = build_best_line(tpu_stages, cpu_result, note_now())
        emit(line)
        emitted_state = state
        return is_full

    def poll_cpu():
        nonlocal cpu_result
        if cpu_result is None and cpu_proc.poll() is not None:
            cpu_reader.join(timeout=10)
            cpu_result = collect(cpu_reader.lines, "cpu")
            if cpu_result is None:
                cpu_result = {"error": "cpu worker produced no result line "
                                       f"(rc={cpu_proc.returncode})"}
            log(f"cpu fallback done: {cpu_result.get('sec_per_tree')} s/tree"
                f" (error={cpu_result.get('error', 'none')[:200]})")

    def have_full():
        return collect_ok(tpu_stages, "full") is not None

    # runs until the worker exits (even after "full" lands — the ranking
    # stage follows it) or the budget floor is hit
    stall_timeout = float(os.environ.get("BENCH_STALL_TIMEOUT", 2400))
    last_progress = time.time()
    full_rows = N
    # on a hung compile the first fallback lever is a SMALLER PROGRAM
    # (the env-gated variants the grower reads at trace time), and only
    # then fewer rows — a hang is a compiler pathology more often than a
    # size problem (round-5 bisect evidence)
    variant_envs = COMPILE_VARIANT_ENVS
    variant_idx = 0
    while try_tpu and remaining_budget() > 120:
        if proc is None:
            # measured round 5: the remote-compile service
            # (PALLAS_AXON_REMOTE_COMPILE) is REQUIRED for backend init
            # (every env-stripped run blocked in init indefinitely) but
            # hung >100 min compiling the 11M-row program, while the same
            # program at 1M compiled in 40 s.  So every attempt keeps the
            # service, and a post-init stall (hung compile) halves the
            # row count for the next attempt — banking a real TPU number
            # at the largest scale the service can compile.
            variant = f"program-v{variant_idx}"
            os.environ.update(variant_envs[variant_idx])
            attempt += 1
            log(f"tpu worker attempt {attempt} (rows={full_rows}, "
                f"budget left={int(remaining_budget())}s); a worker blocked "
                "in INIT is never killed (single-tenant tunnel: the "
                "lingering claim expires on its own; killing starts a "
                "fresh ~25 min wedge), but a worker that has inited and "
                f"then goes {int(stall_timeout)}s without a stage line is "
                "assumed hung in compile and is restarted at half the rows")
            os.environ["BENCH_WORKER_ROWS"] = str(full_rows)
            proc, reader = launch_tpu_worker(variant)
            seen_lines = 0
            last_progress = time.time()
        # drain worker stage lines AS THEY ARRIVE: a smoke result banked
        # mid-run becomes the driver-visible line even if we die later
        new = reader.lines[seen_lines:]
        if new:
            tpu_stages.extend(new)
            seen_lines += len(new)
            last_progress = time.time()
        inited = any(s.get("stage") == "init" and s.get("ok")
                     for s in reader.lines)
        if (inited and time.time() - last_progress > stall_timeout
                and remaining_budget() > 600):
            if have_full():
                # the hang is in a post-full telemetry stage (ranking /
                # serving): the training number is banked, so never
                # relaunch hours of training for it — and never kill a
                # post-init worker (single-tenant tunnel wedge); leave it
                # to wind down when the parent exits
                log(f"worker stalled {int(time.time() - last_progress)}s "
                    "post-full (telemetry stage); stopping retries")
                break
            if variant_idx < len(variant_envs) - 1:
                variant_idx += 1
            else:
                # ladder exhausted: halve the rows and retry from the FULL
                # default program — the round-5 evidence is that compile
                # cost is size-sensitive, so the smaller problem deserves
                # the fastest program, not the most-stripped one
                full_rows = max(1_000_000, full_rows // 2)
                variant_idx = 0
            log(f"worker stalled {int(time.time() - last_progress)}s "
                f"post-init (hung compile); killing and retrying with "
                f"program-v{variant_idx} at {full_rows} rows")
            proc.kill()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
            reader.join(timeout=10)
            tpu_stages.extend(reader.lines[seen_lines:])
            proc, reader = None, None
            refresh_emission()
            continue
        rc = proc.poll()
        if rc is not None:
            reader.join(timeout=10)   # let the drain thread parse the tail
            new = reader.lines[seen_lines:]
            tpu_stages.extend(new)
            seen_lines += len(new)
            if have_full():
                break
            init = collect(reader.lines, "init")
            log(f"tpu worker attempt {attempt} exited rc={rc}; "
                f"init={json.dumps(init)[:300] if init else None}")
            proc, reader = None, None
            if init and init.get("ok") and init.get("platform") == "cpu":
                # plugin resolved to a CPU backend: deterministic, not a
                # transient tunnel failure — stop burning budget on retries
                log("plugin resolved to CPU backend; abandoning TPU attempts")
                try_tpu = False
                abandon_reason = ("tpu plugin present but backend resolved "
                                  "to CPU (tunnel did not yield a TPU)")
                refresh_emission()
                break
            if remaining_budget() < 300:
                break
            refresh_emission()
            time.sleep(20)
            continue
        poll_cpu()
        refresh_emission()
        time.sleep(2)

    if proc is not None:
        # budget exhausted with the worker still alive.  With a full result
        # in hand, leave it running (it is finishing the ranking stage; the
        # parent's exit closes the pipe and it winds down on its own — an
        # external kill would wedge the single-tenant tunnel).  Without one
        # there is nothing more to wait for either way; collect what it
        # printed and move on.
        reader.join(timeout=5)
        tpu_stages.extend(reader.lines[seen_lines:])

    # without a TPU full result, wait for the CPU insurance number; with
    # one in hand never block on the CPU worker
    if cpu_result is None and not have_full():
        try:
            budget = max(60, min(3000, remaining_budget()))
            cpu_proc.wait(timeout=budget)
        except subprocess.TimeoutExpired:
            cpu_proc.kill()
        poll_cpu()
        if cpu_result is None:
            cpu_result = {"error": "cpu worker produced no result"}
    if cpu_proc.poll() is None:
        cpu_proc.kill()
        try:
            cpu_proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
    # collect the insurance line the worker may have emitted before the
    # kill (cpu_worker emits "cpu" the moment training lands, then
    # re-emits with serving telemetry — either line counts)
    poll_cpu()

    refresh_emission(force=True)
    full_ok = have_full()
    cpu_ok = cpu_result is not None and "error" not in cpu_result
    return 0 if (full_ok or cpu_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
