"""Driver benchmark: HIGGS-scale GBDT training wall-clock on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload mirrors the reference's headline experiment (docs/Experiments.rst:
500 trees, 255 leaves, lr=0.1; GPU-comparable max_bin=63 per
docs/GPU-Performance.rst guidance) on a synthetic dataset with HIGGS's shape
(11M x 28 dense float features, binary labels).  HIGGS itself cannot be
downloaded in this environment (zero egress), so the data is synthetic with
label structure (linear + pairwise signal, 20% noise) to keep trees growing
to the leaf budget as on real data.

Baseline: 130.094 s — LightGBM CPU on 2x Xeon E5-2690 v4
(docs/Experiments.rst:114).  vs_baseline = baseline_seconds / our_seconds
(>1 means faster than the reference).

Timing excludes binning/dataset construction (as does the reference's
experiment, which times the training phase) and excludes the one-time XLA
compile: the clock starts after iteration 1 and the total is rescaled by
T/(T-1).

Env overrides for local/quick runs: BENCH_ROWS, BENCH_TREES, BENCH_LEAVES,
BENCH_BIN.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_SECONDS = 130.094

N = int(os.environ.get("BENCH_ROWS", 11_000_000))
F = 28
TREES = int(os.environ.get("BENCH_TREES", 500))
LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_BIN", 63))


def make_higgs_like(n, f, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    w = rng.randn(f).astype(np.float32)
    signal = X @ w
    signal += 2.0 * X[:, 0] * X[:, 1] - 1.5 * (X[:, 2] > 0.5) * X[:, 3]
    signal += rng.randn(n).astype(np.float32) * 0.2 * signal.std()
    y = (signal > np.median(signal)).astype(np.float32)
    return X, y


def main():
    import lightgbm_tpu as lgb

    X, y = make_higgs_like(N, F)
    params = {
        "objective": "binary",
        "num_leaves": LEAVES,
        "learning_rate": 0.1,
        "max_bin": MAX_BIN,
        "metric": "None",
        "verbosity": -1,
    }
    train_set = lgb.Dataset(X, label=y)
    train_set.construct()          # binning happens here, outside the clock
    del X

    booster = lgb.Booster(params=params, train_set=train_set)
    booster.update()               # iteration 1: triggers XLA compile
    import jax
    jax.block_until_ready(booster.boosting.train_score)

    t0 = time.perf_counter()
    for _ in range(TREES - 1):
        booster.update()
    jax.block_until_ready(booster.boosting.train_score)
    elapsed = (time.perf_counter() - t0) * TREES / max(TREES - 1, 1)

    # sanity: training must actually have learned something
    Xh, yh = make_higgs_like(200_000, F, seed=1)
    pred = booster.predict(Xh)
    order = np.argsort(pred)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(pred) + 1)
    npos = yh.sum()
    auc = (ranks[yh > 0].sum() - npos * (npos + 1) / 2) / (npos * (len(yh) - npos))

    result = {
        "metric": f"synthetic-HIGGS {N}x{F} train wall-clock, "
                  f"{TREES} trees x {LEAVES} leaves, max_bin={MAX_BIN} "
                  f"(holdout AUC {auc:.4f})",
        "value": round(elapsed, 3),
        "unit": "seconds",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
