"""Driver benchmark: HIGGS-scale GBDT training wall-clock on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — always,
even on failure (structured error fields, value 0.0).

Workload mirrors the reference's headline experiment (docs/Experiments.rst:
500 trees, 255 leaves, lr=0.1; GPU-comparable max_bin=63 per
docs/GPU-Performance.rst guidance) on a synthetic dataset with HIGGS's shape
(11M x 28 dense float features, binary labels).  HIGGS itself cannot be
downloaded in this environment (zero egress), so the data is synthetic with
label structure (linear + pairwise signal, 20% noise) to keep trees growing
to the leaf budget as on real data.

Baseline: 130.094 s — LightGBM CPU on 2x Xeon E5-2690 v4
(docs/Experiments.rst:114).  vs_baseline = baseline_seconds / our_seconds
(>1 means faster than the reference).

Timing excludes binning/dataset construction (as does the reference's
experiment, which times the training phase) and excludes the one-time XLA
compile: the clock starts after iteration 1 and the total is rescaled by
T/(T-1).

Robustness: TPU backend availability is probed in a *subprocess* with a
timeout (backend init can block indefinitely on a wedged tunnel — it cannot
be interrupted in-process), retried with backoff.  If the TPU never comes
up, the bench re-runs itself on a clean-env CPU backend with a scaled-down
workload so the driver still gets a real measured number, clearly labelled.

Env overrides: BENCH_ROWS, BENCH_TREES, BENCH_LEAVES, BENCH_BIN,
BENCH_FORCE_CPU=1 (skip TPU probe), BENCH_PROFILE=1 (write a jax.profiler
trace to ./bench_trace), BENCH_PROBE_TRIES / BENCH_PROBE_TIMEOUT.
"""
import json
import os
import subprocess
import sys
import time
import traceback

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

BASELINE_SECONDS = 130.094

N = int(os.environ.get("BENCH_ROWS", 11_000_000))
F = 28
TREES = int(os.environ.get("BENCH_TREES", 500))
LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_BIN", 63))

# CPU-fallback workload (per-core CPU is ~2 orders slower than one TPU chip)
CPU_N = int(os.environ.get("BENCH_CPU_ROWS", 200_000))
CPU_TREES = int(os.environ.get("BENCH_CPU_TREES", 50))


def emit(d):
    print(json.dumps(d), flush=True)


def error_line(stage, err, extra=None):
    d = {
        "metric": f"bench-error at {stage}",
        "value": 0.0,
        "unit": "seconds",
        "vs_baseline": 0.0,
        "error": str(err)[-1500:],
    }
    if extra:
        d.update(extra)
    return d


def make_higgs_like(n, f, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, f).astype(np.float32)
    w = rng.randn(f).astype(np.float32)
    signal = X @ w
    signal += 2.0 * X[:, 0] * X[:, 1] - 1.5 * (X[:, 2] > 0.5) * X[:, 3]
    signal += rng.randn(n).astype(np.float32) * 0.2 * signal.std()
    y = (signal > np.median(signal)).astype(np.float32)
    return X, y


def holdout_auc(booster, f, seed=1):
    Xh, yh = make_higgs_like(200_000, f, seed=seed)
    pred = booster.predict(Xh)
    order = np.argsort(pred)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(pred) + 1)
    npos = yh.sum()
    return (ranks[yh > 0].sum() - npos * (npos + 1) / 2) / (
        npos * (len(yh) - npos))


def run_bench(n, trees, leaves, max_bin, tag=""):
    """Train in-process on whatever backend is active; return result dict."""
    import jax

    import lightgbm_tpu as lgb

    platform = jax.devices()[0].platform

    X, y = make_higgs_like(n, F)
    params = {
        "objective": "binary",
        "num_leaves": leaves,
        "learning_rate": 0.1,
        "max_bin": max_bin,
        "metric": "None",
        "verbosity": -1,
    }
    train_set = lgb.Dataset(X, label=y)
    t_bin0 = time.perf_counter()
    train_set.construct()          # binning happens here, outside the clock
    bin_seconds = time.perf_counter() - t_bin0
    del X

    booster = lgb.Booster(params=params, train_set=train_set)
    t_c0 = time.perf_counter()
    booster.update()               # iteration 1: triggers XLA compile
    jax.block_until_ready(booster.boosting.train_score)
    compile_seconds = time.perf_counter() - t_c0

    profile = os.environ.get("BENCH_PROFILE") == "1"
    if profile:
        jax.profiler.start_trace(os.path.join(REPO, "bench_trace"))

    t0 = time.perf_counter()
    for _ in range(trees - 1):
        booster.update()
    jax.block_until_ready(booster.boosting.train_score)
    elapsed = (time.perf_counter() - t0) * trees / max(trees - 1, 1)

    if profile:
        jax.profiler.stop_trace()

    auc = holdout_auc(booster, F)
    return {
        "metric": f"synthetic-HIGGS {n}x{F} train wall-clock, "
                  f"{trees} trees x {leaves} leaves, max_bin={max_bin} "
                  f"[{platform}{tag}] (holdout AUC {auc:.4f})",
        "value": round(elapsed, 3),
        "unit": "seconds",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 3),
        "platform": platform,
        "sec_per_tree": round(elapsed / trees, 4),
        "compile_seconds": round(compile_seconds, 2),
        "bin_seconds": round(bin_seconds, 2),
        "holdout_auc": round(float(auc), 5),
    }


def probe_backend(timeout):
    """Check in a subprocess (killable) that the default backend comes up."""
    code = ("import jax; d = jax.devices(); "
            "import jax.numpy as jnp; "
            "jnp.ones((8, 8)).sum().block_until_ready(); "
            "print('PLATFORM=' + d[0].platform)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout, cwd=REPO)
    except subprocess.TimeoutExpired:
        return None, f"backend probe timed out after {timeout}s"
    if proc.returncode != 0:
        return None, proc.stderr.strip()[-800:]
    for line in proc.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1], None
    return None, "probe produced no platform line"


def cpu_fallback(reason):
    """Re-run this script on a clean-env CPU backend, scaled down."""
    from lightgbm_tpu.utils.platform import clean_cpu_env
    env = clean_cpu_env(1)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_ROWS"] = str(CPU_N)
    env["BENCH_TREES"] = str(CPU_TREES)
    env["BENCH_LEAVES"] = str(LEAVES)
    env["BENCH_BIN"] = str(MAX_BIN)
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              capture_output=True, text=True,
                              timeout=3000, env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        emit(error_line("cpu-fallback", f"timed out; tpu was: {reason}"))
        return 1
    line = None
    for ln in reversed(proc.stdout.strip().splitlines()):
        try:
            line = json.loads(ln)
            break
        except ValueError:
            continue
    if line is None:
        emit(error_line("cpu-fallback", proc.stderr.strip()[-800:],
                        {"tpu_error": reason}))
        return 1
    line["metric"] += f" CPU-FALLBACK (tpu unavailable: {reason[:200]})"
    line["vs_baseline"] = 0.0  # scaled-down CPU run is not comparable
    emit(line)
    return 0 if proc.returncode == 0 and "error" not in line else 1


def main():
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        try:
            emit(run_bench(N, TREES, LEAVES, MAX_BIN, tag="-fallback"))
            return 0
        except Exception as e:
            emit(error_line("cpu-train", f"{e}\n{traceback.format_exc()}"))
            return 1

    tries = int(os.environ.get("BENCH_PROBE_TRIES", 3))
    probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", 300))
    platform, err = None, "no probe attempted"
    for attempt in range(tries):
        platform, err = probe_backend(probe_timeout)
        if platform:
            break
        print(f"[bench] probe attempt {attempt + 1}/{tries} failed: {err}",
              file=sys.stderr, flush=True)
        if attempt + 1 < tries:
            time.sleep(15 * (attempt + 1))

    if platform is None:
        return cpu_fallback(err or "unknown")
    if platform == "cpu":
        # No accelerator on this host: full 11M x 500 on CPU would run for
        # hours; use the scaled-down workload so one JSON line still lands.
        return cpu_fallback("probe found only a CPU backend")

    try:
        emit(run_bench(N, TREES, LEAVES, MAX_BIN))
        return 0
    except Exception as e:
        tb = traceback.format_exc()
        print(tb, file=sys.stderr, flush=True)
        emit(error_line("train", f"{e}", {"traceback_tail": tb[-1200:]}))
        return 1


if __name__ == "__main__":
    sys.exit(main())
