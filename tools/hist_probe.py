#!/usr/bin/env python
"""Histogram-pipeline micro-bench: f32 vs quantized-gradient throughput
plus per-round psum payload accounting (use_quantized_grad).

Measures, on the live backend:

- ``f32``: the resolved f32 histogram kernel (matmul/bf16 on
  accelerators, scatter on CPU) over a synthetic [F, n] binned matrix;
- ``quant``: gradient discretization (``quantize_gradients``) + the
  resolved integer kernel (int8 one-hot matmul with int32 accumulation
  on accelerators — ``matmul_int8`` — packed scatter on CPU);
- payload accounting per histogram psum for both modes
  (``hist_payload_bytes``: 3 x f32 channels vs 2 integer channels,
  int16-narrowed when the static rows x level bound allows) and the
  per-tree estimate (one masked pass per frontier level,
  ~log2(leaves) levels);
- a rescale sanity check: the integer histogram rescaled by the
  quantization scales must track the f32 histogram within the
  discretization step.

Tile-sweep mode (``--tile-sweep``, or the default small sweep inside
``run_probe``): for each row-tile size, report the HBM planner's
PREDICTED peak bytes (ops/planner.py memory model) next to the MEASURED
per-pass time (and measured peak where the device allocator reports
``memory_stats``) — the predicted-vs-measured table that validates the
planner's model at bench time.

Fused column (``--fused``, default on; ``--no-fused`` skips): the
histogram→split megakernel (ops/fused.py) vs the staged pipeline
(``build_histogram`` + ``feature_best_splits``) at one frontier level —
sec/level, HBM ``bytes_accessed`` from the compiler's cost model
(``obs/devprof.measure_program``), measured MFU for both, and the
accounting drop (``hist_scan_traffic_bytes``: the [ch, F, B] scan
re-read + sibling write/read the fused kernel never performs).

Autotune column (``--autotune``, default on; ``--no-autotune`` skips;
needs the fused column): banks the measured staged/fused sec-per-level
into the planner's timing store (ops/planner.py autotuner), then runs
the kernel election cold and warm so the journal shows the
analytic-elected vs measured-elected variant side by side with the
sec/level backing each, names the winner, and reports
``autotune_{hits,misses,flips}`` for bench_diff's election-quality gate.
Reports ``skipped`` when no store dir is configured
(``LGBM_TPU_AUTOTUNE_DIR`` / ``LGBM_TPU_COMPILE_CACHE``).

The LAST stdout line is a single JSON object so bench.py's worker can
bank it as a stage (``stage: hist_probe``, wired next to
``dispatch_probe``; ``BENCH_SKIP_HIST_PROBE=1`` skips the stage).

Usage:
    JAX_PLATFORMS=cpu python tools/hist_probe.py \
        [--rows 1000000] [--features 28] [--max-bin 63] \
        [--quant-bins 4] [--leaves 255] [--reps 5] \
        [--tile-sweep 0,262144,65536]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _measured_peak():
    """Allocator peak bytes, 0 when the backend reports none."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        return int(stats.get("peak_bytes_in_use", 0))
    except Exception:
        return 0


def tile_sweep(binned_t, grad, hess, ones, B, tiles, reps, sync,
               leaves=255) -> list:
    """Predicted-vs-measured table per row-tile size (see module doc).

    The allocator's ``peak_bytes_in_use`` is a process-lifetime
    HIGH-WATER mark that cannot be reset, so the sweep runs in ASCENDING
    predicted-peak order (smallest tile first, untiled last): each
    config's high-water then reflects its own pass rather than an
    earlier larger one's.  The field is named
    ``measured_peak_bytes_highwater`` to say exactly that.
    """
    import jax

    from lightgbm_tpu.ops import histogram as H
    from lightgbm_tpu.ops import planner as P

    F, n = binned_t.shape
    variant = H.resolve_hist_method("auto")

    def predicted(t):
        return P.predict_peak_bytes(n, F, B, num_leaves=leaves,
                                    variant=variant, tile_rows=t,
                                    use_pack=(t == 0))[0]

    out = []
    for t in sorted(set(tiles), key=predicted):
        fn = jax.jit(lambda b, g, h, m, _t=t: H.build_histogram(
            b, g, h, m, B, tile_rows=(_t or None)))
        sync(fn(binned_t, grad, hess, ones))            # compile
        t0 = time.perf_counter()
        for _ in range(reps):
            sync(fn(binned_t, grad, hess, ones))
        ms = (time.perf_counter() - t0) / reps * 1e3
        row = {"tile_rows": t,
               "ms_per_pass": round(ms, 2),
               "iters_per_sec": round(1e3 / max(ms, 1e-9), 2),
               "predicted_peak_bytes": predicted(t)}
        measured = _measured_peak()
        if measured:
            row["measured_peak_bytes_highwater"] = measured
        out.append(row)
    return out


def fused_probe(binned_t, grad, hess, ones, B, reps, leaves=255,
                slots=None) -> dict:
    """Fused megakernel vs staged pipeline at one frontier level.

    Staged = per-slot segment histogram + per-slot
    ``feature_best_splits`` scan (TWO stages with the [S, ch, F, B]
    histogram materialized between them); fused = ONE
    ``fused_segment_splits`` program.  Reports measured sec/level and
    MFU for both (``obs/devprof.measure_program``) plus the compiler's
    ``bytes_accessed`` so the per-level HBM-traffic drop is a measured
    number next to the ``hist_scan_traffic_bytes`` accounting term.
    """
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.obs.devprof import measure_program
    from lightgbm_tpu.ops import fused as FU
    from lightgbm_tpu.ops import histogram as H
    from lightgbm_tpu.ops.split import SplitHyperparams, feature_best_splits

    F, n = binned_t.shape
    # frontier width: one level of a `leaves`-leaf tree, capped at the
    # 8-candidate slice that keeps the staged comparator cheap
    S = int(slots) if slots else max(1, min(8, int(leaves) - 1))
    hp = SplitHyperparams(min_data_in_leaf=1)
    nb = jnp.full((F,), B, jnp.int32)
    zz = jnp.zeros((F,), jnp.int32)
    slot = jnp.asarray(np.random.RandomState(5).randint(0, S, n), jnp.int32)
    oh = slot[None, :] == jnp.arange(S)[:, None]
    sums = jnp.stack([jnp.sum(jnp.where(oh, grad[None, :], 0.0), axis=1),
                      jnp.sum(jnp.where(oh, hess[None, :], 0.0), axis=1),
                      jnp.sum(oh.astype(jnp.float32), axis=1)])
    iscat = jnp.zeros((F,), bool)

    def staged(b, g, h, m):
        seg = H.segment_histogram_sorted(b, g, h, m, slot, S, B,
                                         f32_vals=True) \
            if H.use_sorted_seghist() else \
            H.segment_histogram(b, g, h, m, slot, S, B)
        return jax.vmap(
            lambda hs, sg, sh, cnt: feature_best_splits(
                hs, sg, sh, cnt, nb, zz, zz, iscat, hp).gain
        )(seg, sums[0], sums[1], sums[2])

    def fused(b, g, h, m):
        _, best = FU.fused_segment_splits(
            b, H._vals_t(g, h, m), slot, S, B, sums, nb, zz, zz, hp)
        return best.gain

    args = (binned_t, grad, hess, ones)
    out = {"slots": S}
    for name, fn in (("staged", staged), ("fused", fused)):
        try:
            m = measure_program(jax.jit(fn), args, reps=reps)
            out[name] = {
                "sec_per_level": round(m["seconds_per_call"], 5),
                "mfu_measured": round(m.get("mfu", 0.0), 6),
                "hbm_bytes_accessed": int(m.get("bytes_accessed", 0)),
                "hbm_util": round(m.get("hbm_util", 0.0), 6),
            }
        except Exception as e:      # a variant may not lower here
            out[name] = {"error": str(e)[:160]}
    if "error" not in out.get("staged", {}) and \
            "error" not in out.get("fused", {}):
        out["speedup_vs_staged"] = round(
            out["staged"]["sec_per_level"]
            / max(out["fused"]["sec_per_level"], 1e-12), 3)
        sb = out["staged"]["hbm_bytes_accessed"]
        fb = out["fused"]["hbm_bytes_accessed"]
        if sb and fb:
            out["hbm_bytes_dropped"] = sb - fb
    # accounting twin: the scan re-read + sibling write/read the fused
    # arm deletes per level of S candidates (tests pin this formula)
    out["hist_scan_traffic_bytes"] = FU.hist_scan_traffic_bytes(S, F, B)
    from lightgbm_tpu.parallel.learners import fused_best_payload_bytes
    out["best_tuple_payload_bytes"] = fused_best_payload_bytes(F)
    return out


def autotune_probe(fused_result, rows, features, B, leaves) -> dict:
    """--autotune column: analytic-elected vs measured-elected variant.

    Feeds the fused column's measured staged/fused sec-per-level into
    the planner's persistent timing store (``record_timing``), running
    the election BEFORE the write (cold start or a prior run's
    measurements) and AFTER it (guaranteed warm), so the probe reports
    what the analytic model picks, what the stopwatch picks, the
    sec/level behind each, and the hit/miss/flip counters the bench
    stage journals for ``bench_diff``'s election-quality gate.
    """
    from lightgbm_tpu.ops import planner as P

    out = {"enabled": P.autotune_enabled(), "store_dir": P.autotune_dir()}
    if not (P.autotune_enabled() and P.autotune_dir()):
        out["skipped"] = ("no autotune store configured: set "
                          "LGBM_TPU_AUTOTUNE_DIR or LGBM_TPU_COMPILE_CACHE")
        return out
    staged = fused_result.get("staged", {})
    fus = fused_result.get("fused", {})
    if "error" in staged or "error" in fus:
        out["skipped"] = "staged or fused arm did not run"
        return out
    P.autotune_counters(reset=True)
    cold = P.plan_histograms(rows, features, B, num_leaves=leaves,
                             method="auto", fused_ok=True)
    P.record_timing(rows, features, B, False, 128, "staged",
                    staged["sec_per_level"])
    P.record_timing(rows, features, B, False, 128, "fused",
                    fus["sec_per_level"],
                    params={"feat_tile": cold.fused_feat_tile,
                            "block_rows": cold.fused_block_rows}
                    if cold.fused else None)
    warm = P.plan_histograms(rows, features, B, num_leaves=leaves,
                             method="auto", fused_ok=True)
    last = P.autotune_last()
    counters = P.autotune_counters()
    sec = {"staged": staged["sec_per_level"], "fused": fus["sec_per_level"]}
    out.update({
        "shape_bucket": warm.autotune_key,
        "analytic_variant": last.get("analytic_variant"),
        "measured_variant": last.get("measured_variant"),
        "elected_by": warm.elected_by,
        "elected_variant": last.get("elected_variant"),
        "winner": min(sec, key=sec.get),
        "sec_per_level": sec,
        "autotune_hits": counters["hits"],
        "autotune_misses": counters["misses"],
        "autotune_flips": counters["flips"],
    })
    return out


def run_probe(rows=1_000_000, features=28, max_bin=63, quant_bins=4,
              leaves=255, reps=5, tiles=None, fused=True,
              autotune=True) -> dict:
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops import histogram as H

    B = max_bin + 1
    rng = np.random.RandomState(0)
    binned_t = jnp.asarray(
        rng.randint(0, max_bin, (features, rows), dtype=np.int64), jnp.uint8)
    grad = jnp.asarray(rng.randn(rows), jnp.float32)
    hess = jnp.abs(grad) + 0.1
    ones = jnp.ones((rows,), jnp.float32)
    member = jnp.ones((rows,), bool)

    def sync(x):
        # block_until_ready is a no-op on the tunneled axon backend
        # (docs/PERFORMANCE.md): sync via a dependent host copy instead
        return float(np.asarray(jnp.sum(x.astype(jnp.float32))))

    out = {
        "rows": rows, "features": features, "max_bin": max_bin,
        "quant_bins": quant_bins,
        "platform": jax.devices()[0].platform,
        "f32_method": H.resolve_hist_method("auto"),
        "quant_method": H.resolve_hist_method("auto", quantized=True),
    }

    # ---- f32 pipeline -------------------------------------------------
    f32_fn = jax.jit(lambda b, g, h, m: H.build_histogram(b, g, h, m, B))
    sync(f32_fn(binned_t, grad, hess, ones))            # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        sync(f32_fn(binned_t, grad, hess, ones))
    f32_ms = (time.perf_counter() - t0) / reps * 1e3

    # ---- quantized pipeline (discretize + integer histogram) ----------
    levels = H.quant_levels(quant_bins)
    key = jax.random.PRNGKey(0)

    def quant_pass(b, g, h, w):
        gq, hq, gs, hs = H.quantize_gradients(g, h, w, quant_bins, key)
        hist = H.build_histogram_int(b, gq, hq, w > 0, B, levels=levels)
        return hist, gs, hs

    q_fn = jax.jit(quant_pass)
    hist_i, gs, hs = q_fn(binned_t, grad, hess, ones)
    sync(hist_i)                                        # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        sync(q_fn(binned_t, grad, hess, ones)[0])
    quant_ms = (time.perf_counter() - t0) / reps * 1e3

    # ---- rescale sanity: int sums * scale tracks the f32 sums ---------
    ref = np.asarray(f32_fn(binned_t, grad, hess, ones))
    hi = np.asarray(hist_i)
    g_err = np.abs(hi[0] * float(gs) - ref[0]).max()
    h_err = np.abs(hi[1] * float(hs) - ref[1]).max()
    # stochastic rounding error per row is < 1 level; per bin it grows
    # ~sqrt(rows_in_bin) — bound loosely by a few levels * sqrt(n/B)
    tol = 8.0 * max(float(gs), float(hs)) * max((rows / B) ** 0.5, 1.0)

    # ---- payload accounting -------------------------------------------
    f32_payload = H.hist_payload_bytes(features, B)
    quant_payload = H.hist_payload_bytes(features, B, rows, quant_bins)
    levels_per_tree = max(1.0, float(np.log2(leaves)))
    # ---- tile sweep: planner predicted-vs-measured per tile size ------
    if tiles is None:
        # default small sweep: untiled plus two power-of-two tiles
        p2 = 1 << max((rows // 4).bit_length() - 1, 10)
        tiles = [0, p2, max(p2 // 4, 1024)]
    sweep = tile_sweep(binned_t, grad, hess, ones, B, tiles, reps, sync,
                       leaves=leaves)

    # ---- fused megakernel vs staged pipeline (--fused column) ---------
    if fused:
        # interpret-mode emulation off-accelerator is slow at probe
        # scale: cap the fused comparison shape there (the on-device
        # bench worker runs the full size)
        if H.on_accelerator() or rows <= 200_000:
            fb, fg, fh, fo = binned_t, grad, hess, ones
        else:
            fb = binned_t[:, :200_000]
            fg, fh, fo = grad[:200_000], hess[:200_000], ones[:200_000]
        out["fused"] = fused_probe(fb, fg, fh, fo, B, reps, leaves=leaves)
        # the autotune column keys the store by the shape the stopwatch
        # actually measured (the capped one off-accelerator)
        out["fused"]["rows_measured"] = int(fb.shape[1])
        if autotune:
            out["autotune"] = autotune_probe(
                out["fused"], int(fb.shape[1]), features, B, leaves)

    out.update({
        "reps": reps,
        "tile_sweep": sweep,
        "f32": {"ms_per_pass": round(f32_ms, 2),
                "psum_payload_bytes": f32_payload,
                "psum_payload_bytes_per_tree":
                    int(f32_payload * levels_per_tree)},
        "quant": {"ms_per_pass": round(quant_ms, 2),
                  "psum_payload_bytes": quant_payload,
                  "psum_payload_bytes_per_tree":
                      int(quant_payload * levels_per_tree),
                  "psum_narrowed_int16":
                      H.quant_psum_narrow(rows, quant_bins),
                  "g_scale": float(gs), "h_scale": float(hs)},
        "payload_shrink": round(f32_payload / max(quant_payload, 1), 3),
        "speedup_vs_f32": round(f32_ms / max(quant_ms, 1e-9), 3),
        "rescale_abs_err": {"grad": round(float(g_err), 6),
                            "hess": round(float(h_err), 6),
                            "tol": round(tol, 6),
                            "ok": bool(g_err <= tol and h_err <= tol)},
    })
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--max-bin", type=int, default=63)
    ap.add_argument("--quant-bins", type=int, default=4)
    ap.add_argument("--leaves", type=int, default=255)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--tile-sweep", type=str, default=None,
                    help="comma-separated row-tile sizes (0 = untiled); "
                         "default: a small automatic sweep")
    ap.add_argument("--fused", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="fused megakernel vs staged column (default on; "
                         "--no-fused skips)")
    ap.add_argument("--autotune", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="measured-vs-analytic election column (default "
                         "on; needs --fused and a configured timing "
                         "store; --no-autotune skips)")
    args = ap.parse_args()
    tiles = None
    if args.tile_sweep:
        tiles = [max(int(v), 0) for v in args.tile_sweep.split(",") if v]
    out = run_probe(args.rows, args.features, args.max_bin, args.quant_bins,
                    args.leaves, args.reps, tiles=tiles, fused=args.fused,
                    autotune=args.autotune)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
