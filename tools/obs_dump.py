#!/usr/bin/env python
"""Observability dump: run one small fully-instrumented train + serve
cycle and emit every obs artifact — the smoke test for the whole
observability plane (docs/OBSERVABILITY.md).

Enables tracing + timers, trains a small booster (with a checkpoint
snapshot so ``checkpoint.save`` spans appear), serves a few requests
through the in-process server (so the serving component joins the
process registry), then writes:

- ``obs_trace.json``      — Chrome trace-event / Perfetto-loadable spans
- ``obs_metrics.json``    — unified registry snapshot (training gauges,
  timer mirrors, serving component)
- ``obs_metrics.prom``    — the same registry in Prometheus text format

The LAST stdout line is one JSON summary (span names, coverage, artifact
paths).  Smoke-invoked by bench.py as the ``obs_dump`` stage
(``BENCH_SKIP_OBS=1`` skips; errors are never journaled so reruns retry).

Usage:
    JAX_PLATFORMS=cpu python tools/obs_dump.py \
        [--out-dir .] [--rows 20000] [--trees 8]
"""

import argparse
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_dump(out_dir=".", rows=20_000, features=10, trees=8, leaves=15,
             requests=4):
    """One instrumented train+serve cycle; returns the JSON summary."""
    from lightgbm_tpu.obs.metrics import global_registry
    from lightgbm_tpu.obs.trace import global_tracer, span_coverage
    from lightgbm_tpu.utils.timer import global_timer

    trace_was_on = global_tracer.enabled
    timer_was_on = global_timer.enabled
    global_tracer.enable()
    global_timer.enable()
    try:
        import lightgbm_tpu as lgb

        rng = np.random.RandomState(0)
        X = rng.rand(rows, features)
        y = (X[:, 0] + X[:, 1] * X[:, 2] > 0.8).astype(np.float32)
        with tempfile.TemporaryDirectory() as td:
            booster = lgb.train(
                {"objective": "binary", "verbosity": -1,
                 "num_leaves": leaves},
                lgb.Dataset(X, label=y), num_boost_round=trees,
                verbose_eval=False,
                snapshot_freq=max(trees // 2, 1),
                snapshot_out=os.path.join(td, "ck.txt"))
        # snapshot INSIDE the serve block: close() detaches the serving
        # component from the process registry, and the artifacts exist to
        # show training + serving in ONE snapshot
        with booster.serve(max_batch_rows=256) as server:
            for _ in range(requests):
                server.predict(X[:32])
            global_timer.publish(global_registry)
            os.makedirs(out_dir, exist_ok=True)
            trace_file = os.path.join(out_dir, "obs_trace.json")
            metrics_file = os.path.join(out_dir, "obs_metrics.json")
            prom_file = os.path.join(out_dir, "obs_metrics.prom")
            global_registry.dump_json(metrics_file)
            from lightgbm_tpu.utils.file_io import write_atomic
            write_atomic(prom_file, global_registry.to_prometheus())
            snap = global_registry.to_dict()
        global_tracer.dump(trace_file)   # after close: drain spans included

        events = global_tracer.events()
        return {
            "trace_file": trace_file,
            "metrics_file": metrics_file,
            "prometheus_file": prom_file,
            "trace_events": len(events),
            "span_names": sorted({e["name"] for e in events})[:40],
            "train_coverage": span_coverage(events, "engine.train"),
            "gauges": {k: v for k, v in snap["gauges"].items()
                       if not k.startswith("timer.")},
            "counters": snap["counters"],
            "components": sorted(snap.get("components", {})),
            "timer_sections": sum(1 for k in snap["gauges"]
                                  if k.startswith("timer.")),
        }
    finally:
        if not trace_was_on:
            global_tracer.disable()
            global_tracer.reset()
        if not timer_was_on:
            global_timer.disable()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default=".")
    ap.add_argument("--rows", type=int, default=20_000)
    ap.add_argument("--features", type=int, default=10)
    ap.add_argument("--trees", type=int, default=8)
    ap.add_argument("--leaves", type=int, default=15)
    args = ap.parse_args()
    result = run_dump(out_dir=args.out_dir, rows=args.rows,
                      features=args.features, trees=args.trees,
                      leaves=args.leaves)
    print(json.dumps(result, indent=1, sort_keys=True))
    ok = result["trace_events"] > 0 and result["train_coverage"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
