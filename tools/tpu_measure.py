"""One-process TPU measurement session for the rounds grower.

Single-tenant tunnel doctrine (docs/PERFORMANCE.md): exactly ONE process
may hold the axon backend; this script does init -> all measurements ->
clean exit in one process, banking partial results to a JSON file after
every stage so a wedge/crash still leaves data on disk.

Run ALONE (no concurrent TPU process):  python tools/tpu_measure.py out.json

Stages (gate with TM_SKIP_<STAGE>=1):
  init        backend init time
  higgs_1m    rounds grower, 1M x 28, 20 trees        (quick validation)
  higgs_11m   rounds grower, 11M x 28, 500 trees      (the headline number;
              auto-shrunk to 60 trees if the 1M sec/tree looks pathological)
  ranking     lambdarank MSLR-shaped 1.2M docs, 100 trees
Shapes match bench.py exactly so this run warms the persistent XLA
compile cache for the driver's bench run.
"""
import json
import os
import sys
import threading
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.utils.platform import _cache_dir  # noqa: E402

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

OUT = sys.argv[1] if len(sys.argv) > 1 else os.path.join(REPO, "tpu_measure.json")
T0 = time.time()
DATA = {"started_utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
        "stages": []}


def bank(stage, **kw):
    kw["stage"] = stage
    kw["t_elapsed"] = round(time.time() - T0, 1)
    DATA["stages"].append(kw)
    tmp = OUT + ".tmp"
    # manual tmp+os.replace below; stdlib-only probe must stay
    # importable before jax/package init
    with open(tmp, "w") as f:  # tpulint: disable=atomic-write
        json.dump(DATA, f, indent=1, default=str)
    os.replace(tmp, OUT)
    print(f"[tpu_measure] {stage}: {json.dumps(kw, default=str)[:500]}",
          flush=True)


# program-variant ladder for a hung remote compile (round-5 evidence:
# the compile service blocked >25 min on the full default program while a
# close cousin compiled in 40 s).  Entries are env gates read at trace
# time (ops/histogram.py / grower_rounds.py); the winning variant's env
# persists for later stages.  The hung attempt's thread is abandoned —
# killing the process would wedge backend init ~25 min (single-tenant
# tunnel), an abandoned RPC just idles.
COMPILE_PATIENCE = float(os.environ.get("TM_COMPILE_PATIENCE", 600))


def _variant_ladder():
    import bench
    return bench.COMPILE_VARIANT_ENVS


def guard(stage, fn, *a, **kw):
    if os.environ.get(f"TM_SKIP_{stage.upper()}") == "1":
        bank(stage, skipped=True)
        return None
    t1 = time.time()
    try:
        r = fn(*a, **kw)
        out = dict(r) if isinstance(r, dict) else {"result": r}
        out["stage_seconds"] = round(time.time() - t1, 1)
        bank(stage, **out)
        return r
    except Exception as e:
        bank(stage, error=str(e)[-600:], tb=traceback.format_exc()[-1500:])
        return None


def guard_ladder(stage, fn, *a, **kw):
    """guard() with the compile-hang variant ladder: each variant runs in
    a worker thread; if no result lands within COMPILE_PATIENCE, the next
    (smaller) program variant is tried.  First success wins and its env
    stays for subsequent stages."""
    if os.environ.get(f"TM_SKIP_{stage.upper()}") == "1":
        bank(stage, skipped=True)
        return None
    for i, env in enumerate(_variant_ladder()):
        os.environ.update(env)
        box = {}
        done = threading.Event()
        cancel = threading.Event()
        compile_done = threading.Event()

        def attempt(box=box, done=done, cancel=cancel, cd=compile_done):
            t1 = time.time()
            try:
                r = fn(*a, cancel=cancel, compile_done=cd, **kw)
                out = dict(r) if isinstance(r, dict) else {"result": r}
                out["stage_seconds"] = round(time.time() - t1, 1)
                box["out"] = out
                box["r"] = r
            except Exception as e:
                box["out"] = {"error": str(e)[-600:],
                              "tb": traceback.format_exc()[-1500:]}
            finally:
                done.set()

        th = threading.Thread(target=attempt, daemon=True)
        th.start()
        # the patience clock watches the COMPILE only — the timed run may
        # legitimately run far past it (500 trees at 11M rows); once the
        # compile lands, wait for the stage without a deadline.  A
        # pre-compile failure (data-gen OOM, construct error) fires
        # ``done`` without ``compile_done`` and banks its real error
        # instead of masquerading as a hung compile.
        deadline = time.time() + COMPILE_PATIENCE
        while not done.is_set() and not compile_done.is_set() \
                and time.time() < deadline:
            done.wait(5)
        if not done.is_set() and not compile_done.is_set():
            # the zombie's post-compile guard (bench.run_bench cancel)
            # keeps it from racing the next attempt's timed run if its
            # compile ever unblocks
            cancel.set()
            bank(f"{stage}_hung", variant=i, env=env,
                 patience_s=COMPILE_PATIENCE)
            continue
        done.wait()
        out = box["out"]
        if i:
            out["variant"] = i
            out["variant_env"] = env
        bank(stage, **out)
        return box.get("r")
    bank(stage, error="all program variants hung in compile")
    return None


def main():
    t = time.time()
    try:
        import jax
        devs = jax.devices()
        import jax.numpy as jnp
        jnp.ones((8, 8)).sum().block_until_ready()
    except Exception as e:
        bank("init", error=str(e)[-600:])
        return 3
    d = devs[0]
    bank("init", seconds=round(time.time() - t, 1), platform=d.platform,
         kind=getattr(d, "device_kind", ""))
    if d.platform == "cpu" and os.environ.get("TM_ALLOW_CPU") != "1":
        bank("abort", reason="backend resolved to cpu")
        return 3

    import bench

    r1 = guard_ladder("higgs_1m",
                      bench.run_bench, 1_000_000, 20, 255, 63, tag="-1m")

    trees_11m = int(os.environ.get("TM_TREES_11M", 0)) or None
    if trees_11m is None:
        spt = (r1 or {}).get("sec_per_tree")
        trees_11m = 500 if (spt is not None and spt < 0.6) else 60
    guard_ladder("higgs_11m",
                 bench.run_bench, 11_000_000, trees_11m, 255, 63)

    guard("ranking",
          bench.run_ranking_bench, 12_000, 100, 100, 255, 63)

    # sparse-story shapes (BASELINE.md GPU table): Epsilon-like 400k x 2000
    # dense and Bosch-like 1.2M x 968 ~80% sparse must train without OOM
    # on one chip; peak HBM is banked via run's device_memory_stats
    guard("epsilon_like", _wide_dense_bench, 400_000, 2000, 30)
    guard("bosch_like", _sparse_bench, 1_200_000, 968, 30)

    bank("done", total_seconds=round(time.time() - T0, 1))
    return 0


def _wide_dense_bench(n, f, trees):
    """Epsilon-shaped: wide dense float features (no EFB possible)."""
    import numpy as np
    rng = np.random.RandomState(0)
    X = rng.rand(n, f).astype(np.float32)
    w = np.random.RandomState(7).randn(f).astype(np.float32) / np.sqrt(f)
    y = ((X @ w + 0.1 * rng.randn(n)) > 0).astype(np.float32)
    return _train_timed(X, y, trees, max_bin=63, leaves=255)


def _sparse_bench(n, f, trees, density=0.2):
    """Bosch-shaped: ~80% of entries missing (NaN); EFB + NaN missing-type
    handling carry the memory story."""
    import numpy as np
    rng = np.random.RandomState(0)
    X = np.full((n, f), np.nan, np.float32)
    # each row gets a random ~density subset of features (int32 indices and
    # chunked label math keep transient host memory ~bounded by X itself)
    nz = int(f * density)
    cols = rng.randint(0, f, size=(n, nz)).astype(np.int32)
    vals = rng.rand(n, nz).astype(np.float32)
    np.put_along_axis(X, cols, vals, axis=1)
    w = np.random.RandomState(7).randn(f).astype(np.float32)
    sig = np.empty(n, np.float32)
    step = 100_000
    for i in range(0, n, step):
        sig[i:i + step] = np.nansum(X[i:i + step] * w[None, :], axis=1)
    y = (sig > np.median(sig)).astype(np.float32)
    del cols, vals, sig
    return _train_timed(X, y, trees, max_bin=63, leaves=255)


def _train_timed(X, y, trees, max_bin, leaves):
    """bench.py's timing protocol (params at Dataset creation, compile on
    iteration 1, steady-state rescaled by T/(T-1)) on an arbitrary matrix."""
    import bench
    import jax

    import lightgbm_tpu as lgb
    n, f = X.shape
    params = {"objective": "binary", "num_leaves": leaves,
              "learning_rate": 0.1, "max_bin": max_bin,
              "metric": "None", "verbosity": -1}
    ds = lgb.Dataset(X, label=y, params=params)
    t0 = time.perf_counter()
    ds.construct()
    bin_seconds = time.perf_counter() - t0
    groups = int(ds.binned.shape[1])
    booster = lgb.Booster(params=params, train_set=ds)
    t0 = time.perf_counter()
    booster.update()
    bench.dsync(booster.boosting.train_score)
    compile_seconds = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(trees - 1):
        booster.update()
    bench.dsync(booster.boosting.train_score)
    elapsed = (time.perf_counter() - t0) * trees / max(trees - 1, 1)
    out = {
        "rows": n, "features": f, "groups_after_efb": groups,
        "trees": trees,
        "device_matrix_mb": round(n * groups / 1e6, 1),
        "bin_seconds": round(bin_seconds, 2),
        "compile_seconds": round(compile_seconds, 2),
        "sec_per_tree": round(elapsed / trees, 4),
    }
    out.update(bench.device_memory_stats())
    return out


if __name__ == "__main__":
    sys.exit(main())
