"""tpulint CLI — run the project-native static-analysis suite.

Usage (``python tools/lint.py`` and ``python -m tools.lint`` are
equivalent)::

    python tools/lint.py                     # lint the default tree
    python tools/lint.py lightgbm_tpu/ops    # lint a path subset
    python tools/lint.py --only atomic-write,env-flag-registry
    python tools/lint.py --ignore lock-discipline
    python tools/lint.py --list-rules

Output: one human line per violation (``path:line: [rule] message``),
then a LAST-LINE JSON verdict (the same contract tools/bench_diff.py
and tools/obs_doctor.py follow)::

    {"tool": "tpulint", "files": N, "violations": M,
     "by_rule": {"atomic-write": 2, ...}, "ok": false}

Exit codes: 0 clean, 1 violations found, 2 unusable input (unknown
rule selector, missing path, unparseable file).  Rules, pragmas and the
how-to-add-a-checker recipe: docs/LINTING.md.
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import REPO, all_rules, load_project, run_lint, select_rules


def _csv(value):
    return [s.strip() for s in value.split(",") if s.strip()]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the repo tree)")
    ap.add_argument("--only", type=_csv, default=None,
                    help="comma-separated rule names to run exclusively")
    ap.add_argument("--ignore", type=_csv, default=None,
                    help="comma-separated rule names to skip")
    ap.add_argument("--root", default=REPO,
                    help="repo root for relative paths and docs lookups")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule names + one-line docs and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.name}: {r.doc}")
        return 0

    try:
        rules = select_rules(only=args.only, ignore=args.ignore)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    try:
        project = load_project(root=args.root,
                               paths=args.paths or None)
    # ValueError: null bytes in source (ast.parse); UnicodeDecodeError:
    # non-UTF-8 file — both are unusable input, not "violations found"
    except (OSError, SyntaxError, ValueError, UnicodeDecodeError) as e:
        print(f"cannot load tree: {e}", file=sys.stderr)
        return 2

    violations = run_lint(project, rules)
    for v in violations:
        print(v.render())
    by_rule = {}
    for v in violations:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    ok = not violations
    if ok:
        print(f"tpulint: {len(project.files)} files clean "
              f"({len(rules)} rules)")
    else:
        print(f"tpulint: {len(violations)} violation(s) in "
              f"{len(set(v.path for v in violations))} file(s)")
    print(json.dumps({"tool": "tpulint", "files": len(project.files),
                      "rules": sorted(r.name for r in rules),
                      "violations": len(violations),
                      "by_rule": dict(sorted(by_rule.items())),
                      "ok": ok}))
    return 0 if ok else 1
