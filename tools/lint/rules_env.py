"""env-flag-registry: every env gate must be declared and documented.

Three failure modes, each named after the offending flag:

1. a string literal matching the flag grammar
   (``LGBM_TPU_*`` / ``LIGHTGBM_TPU_*`` / ``LGBT_*`` / ``BENCH_*``)
   appears in scanned code but not in
   ``lightgbm_tpu/utils/envflags.FLAGS`` — an unregistered knob;
2. a registered flag's name is absent from its declared doc file — an
   undocumented knob;
3. (full-tree scans only) a registered flag appears nowhere in the
   scanned code — a stale registry entry.

Scanning LITERALS rather than only ``os.environ`` call expressions is
deliberate: it also catches flags routed through helper wrappers
(``_env_float("LGBM_TPU_ICI_GBPS")``), ladder dicts
(``{"LGBM_TPU_PACK": ...}``) and ``os.environ.update`` payloads —
anywhere a knob name is spelled, it must be a registered knob.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
import sys
from typing import Dict, List, Tuple

from .core import Project, Rule, Violation

_FLAG_RE = re.compile(
    r"^(LGBM_TPU_|LIGHTGBM_TPU_|LGBT_|BENCH_)[A-Z0-9_]+$")

# the registry itself spells every name; the lint package spells the
# prefixes and fixture names in rule docs/tests
_EXEMPT_RELS = ("lightgbm_tpu/utils/envflags.py",)
_EXEMPT_PREFIXES = ("tools/lint/", "tools/lint.py")


def load_registry(root: str) -> Dict[str, object]:
    """Load ``root``'s envflags registry BY PATH — never through the
    import cache, so linting another checkout (or a fixture tree) reads
    that tree's registry, not whichever one this process imported
    first.  envflags.py is stdlib-only with no package-relative imports
    by contract, which is what makes standalone execution safe."""
    path = os.path.join(root, "lightgbm_tpu", "utils", "envflags.py")
    if not os.path.exists(path):
        raise ImportError(f"no envflags registry at {path}")
    spec = importlib.util.spec_from_file_location("_tpulint_envflags",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves the class namespace through sys.modules at
    # definition time; a later load of a different root overwrites the
    # slot, which is exactly the per-root freshness we want
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return dict(mod.FLAGS)


class EnvFlagRegistryRule(Rule):
    name = "env-flag-registry"
    doc = ("every LGBM_TPU_*/LIGHTGBM_TPU_*/BENCH_* literal must be "
           "registered in lightgbm_tpu/utils/envflags.py and documented "
           "in its declared doc file")

    def check(self, project: Project) -> List[Violation]:
        try:
            flags = load_registry(project.root)
        except ImportError:
            # scanning a tree without the registry module: every
            # matching literal is by definition unregistered
            flags = {}
        out: List[Violation] = []
        seen: Dict[str, List[Tuple[str, int]]] = {}
        for f in project.files:
            if f.rel in _EXEMPT_RELS or \
                    f.rel.startswith(_EXEMPT_PREFIXES):
                continue
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)):
                    continue
                name = node.value
                if not _FLAG_RE.match(name):
                    continue
                seen.setdefault(name, []).append((f.rel, node.lineno))
                if name not in flags:
                    out.append(Violation(
                        self.name, f.rel, node.lineno,
                        f"env flag {name} is not registered in "
                        "lightgbm_tpu/utils/envflags.py (add an EnvFlag "
                        "entry with default, consumer and doc anchor)"))
        # registered but undocumented / stale.  Word-boundary match: a
        # short flag must not pass because a longer flag it prefixes
        # (BENCH_SKIP_STREAM vs BENCH_SKIP_STREAM_PROBE) is documented
        reg_file = "lightgbm_tpu/utils/envflags.py"
        doc_cache: Dict[str, str] = {}
        for name, flag in sorted(flags.items()):
            docfile = flag.docfile
            if docfile not in doc_cache:
                doc_cache[docfile] = project.read_doc(docfile)
            if not re.search(r"(?<![A-Z0-9_])" + re.escape(name)
                             + r"(?![A-Z0-9_])", doc_cache[docfile]):
                out.append(Violation(
                    self.name, reg_file, 1,
                    f"env flag {name} is registered but undocumented: "
                    f"its name does not appear in {docfile}"))
            if project.full_tree and name not in seen:
                out.append(Violation(
                    self.name, reg_file, 1,
                    f"env flag {name} is registered but read nowhere in "
                    "the tree — delete the stale entry"))
        return out
