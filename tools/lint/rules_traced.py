"""traced-purity: no host effects inside jit-traced code.

Byte-identical parity (quantized fused==staged, streamed==resident,
hierarchical==flat) depends on traced programs being PURE functions of
their inputs.  A ``time.time()``, ``np.random`` draw, ``os.environ``
read, host sync (``.item()`` / ``float(param)`` / ``np.asarray``), or a
Python ``if`` on a traced value inside a jitted function either fails at
trace time in the best case or — worse — bakes a trace-time host value
into the compiled program so reruns silently diverge.

Traced code is found three ways (all AST-local, no imports):

- functions decorated with ``jax.jit`` / ``jit`` / ``pjit`` (bare,
  called, or via ``partial(jax.jit, ...)``);
- local functions passed to a ``jax.jit(...)`` / ``pjit(...)`` call,
  directly or through ``functools.partial(fn, ...)`` (the dominant
  idiom here: ``self._step = jax.jit(step)``);
- kernel functions passed to ``pl.pallas_call``.

Parameters bound via ``static_argnums`` / ``static_argnames`` or by
``functools.partial`` are static at trace time and never flagged.
Lambdas passed to jit are skipped (no body scope to resolve).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Project, Rule, Violation, dotted_name

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "functools.pjit"}
_PALLAS_NAMES = {"pl.pallas_call", "pallas_call", "jax.experimental."
                 "pallas.pallas_call"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
_CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                "time.time_ns", "time.perf_counter_ns"}
_ENV_NAMES = {"os.environ", "os.getenv"}
_HOST_ARRAY_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                     "numpy.array"}
_CAST_CALLS = {"float", "int", "bool"}


def _param_names(fn: ast.FunctionDef):
    """Positional parameter names in call order (posonly first) and the
    keyword-only names — static_argnums indexes the former; kwargs and
    kwonly params are traced unless named in static_argnames."""
    positional = ([a.arg for a in fn.args.posonlyargs]
                  + [a.arg for a in fn.args.args])
    return positional, [a.arg for a in fn.args.kwonlyargs]


def _static_params(fn: ast.FunctionDef, call: Optional[ast.Call],
                   partial_call: Optional[ast.Call]) -> Set[str]:
    """Parameter names of ``fn`` that are static under this jit site.
    ``self`` is excluded from index mapping: bound-method jit sites
    (``jax.jit(self._leaves)``) never see it."""
    names, _kwonly = _param_names(fn)
    names = [n for n in names if n != "self"]
    static: Set[str] = set()
    if call is not None:
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for elt in ast.walk(kw.value):
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        static.add(elt.value)
            elif kw.arg == "static_argnums":
                for elt in ast.walk(kw.value):
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, int) \
                            and 0 <= elt.value < len(names):
                        static.add(names[elt.value])
    if partial_call is not None:
        # functools.partial(fn, a, b, k=v): leading positionals and every
        # keyword are bound at trace time -> static
        for i in range(1, len(partial_call.args)):
            if i - 1 < len(names):
                static.add(names[i - 1])
        for kw in partial_call.keywords:
            if kw.arg:
                static.add(kw.arg)
    return static


class _Scope(ast.NodeVisitor):
    """Collect (function def, enclosing-scope chain) pairs."""

    def __init__(self):
        self.defs: List[Tuple[ast.FunctionDef, Tuple[ast.AST, ...]]] = []
        self._stack: List[ast.AST] = []

    def _visit_scope(self, node):
        self.defs.append((node, tuple(self._stack)))
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_scope
    visit_AsyncFunctionDef = _visit_scope
    visit_ClassDef = _visit_scope
    visit_Lambda = lambda self, node: self.generic_visit(node)  # noqa: E731


def _fn_ref_name(node: ast.AST) -> Optional[str]:
    """The local function name a jit argument refers to: bare ``step``
    or bound ``self._leaves`` (methods resolve by bare name too)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _jit_target(call: ast.Call):
    """(target_name, jit_call, partial_call) for jit(X) / jit(partial(X,
    ...)) / pallas_call(X, ...) where X is a local function or a
    ``self.<method>``; (None, None, None) otherwise."""
    callee = dotted_name(call.func)
    if callee in _JIT_NAMES or callee in _PALLAS_NAMES:
        if not call.args:
            return None, None, None
        arg = call.args[0]
        name = _fn_ref_name(arg)
        if name is not None:
            return name, call, None
        if isinstance(arg, ast.Call) \
                and dotted_name(arg.func) in _PARTIAL_NAMES and arg.args:
            name = _fn_ref_name(arg.args[0])
            if name is not None:
                return name, call, arg
    return None, None, None


def _decorator_jit(fn: ast.FunctionDef):
    """The jit-ish decorator call of ``fn`` (or True for a bare one)."""
    for dec in fn.decorator_list:
        d = dec
        partial = None
        if isinstance(d, ast.Call):
            callee = dotted_name(d.func)
            if callee in _PARTIAL_NAMES and d.args \
                    and dotted_name(d.args[0]) in _JIT_NAMES:
                return d, partial
            if callee in _JIT_NAMES:
                return d, partial
            continue
        if dotted_name(d) in _JIT_NAMES:
            return True, partial
    return None, None


class TracedPurityRule(Rule):
    name = "traced-purity"
    doc = ("no host clocks, np.random, os.environ, host syncs "
           "(.item()/float(param)/np.asarray) or Python branches on "
           "traced params inside jit/pjit/pallas-traced functions")

    def check(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for f in project.files:
            out.extend(self._check_file(f))
        return out

    def _check_file(self, f) -> List[Violation]:
        scopes = _Scope()
        scopes.visit(f.tree)
        # name -> innermost defs (a name may repeat across scopes; flag
        # them all — jit sites and defs are matched per enclosing scope)
        defs_by_name: Dict[str, List[ast.FunctionDef]] = {}
        for fn, _chain in scopes.defs:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(fn.name, []).append(fn)

        traced: Dict[ast.FunctionDef, Set[str]] = {}

        def mark(fn: ast.FunctionDef, static: Set[str]):
            if fn in traced:
                traced[fn] |= static
            else:
                traced[fn] = set(static)

        for fn, _chain in scopes.defs:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            dec, partial = _decorator_jit(fn)
            if dec is not None:
                call = dec if isinstance(dec, ast.Call) else None
                mark(fn, _static_params(fn, call, partial))
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            target, jit_call, partial_call = _jit_target(node)
            if target is None:
                continue
            for fn in defs_by_name.get(target, []):
                mark(fn, _static_params(fn, jit_call, partial_call))

        out: List[Violation] = []
        for fn, static in traced.items():
            positional, kwonly = _param_names(fn)
            params = set(positional) | set(kwonly)
            params -= static | {"self"}
            out.extend(self._check_traced(f.rel, fn, params))
        return out

    def _check_traced(self, rel: str, fn: ast.FunctionDef,
                      traced_params: Set[str]) -> List[Violation]:
        out: List[Violation] = []

        def v(node, msg):
            out.append(Violation(self.name, rel, node.lineno,
                                 f"in traced function {fn.name!r}: {msg}"))

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                if callee in _CLOCK_CALLS:
                    v(node, f"host clock {callee}() — a trace-time "
                            "constant baked into the compiled program")
                elif callee in _HOST_ARRAY_CALLS:
                    v(node, f"{callee}() forces a device->host sync and "
                            "materializes a traced value on the host")
                elif callee in _CAST_CALLS and len(node.args) == 1 \
                        and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in traced_params:
                    v(node, f"{callee}({node.args[0].id}) host-syncs a "
                            "traced parameter")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" \
                        and not node.args:
                    v(node, ".item() host-syncs a traced value")
            elif isinstance(node, ast.Attribute):
                dn = dotted_name(node)
                if dn in _ENV_NAMES:
                    v(node, f"{dn} read — env state is a trace-time "
                            "constant; hoist it out of the kernel")
                elif dn is not None and (
                        dn.startswith("np.random.")
                        or dn.startswith("numpy.random.")):
                    v(node, f"{dn} — host RNG inside traced code; use "
                            "jax.random with an explicit key")
            elif isinstance(node, (ast.If, ast.While)):
                name = self._bare_traced_test(node.test, traced_params)
                if name:
                    v(node, f"Python {type(node).__name__.lower()} "
                            f"branches on traced parameter {name!r}; "
                            "use lax.cond/jnp.where or mark it static")
        return out

    @staticmethod
    def _bare_traced_test(test: ast.AST,
                          traced_params: Set[str]) -> Optional[str]:
        """The offending param name when ``test`` is built purely from
        bare names/constants and touches a traced param (``is``
        comparisons are static and exempt)."""

        def scan(node) -> Optional[str]:
            if isinstance(node, ast.Name):
                return node.id if node.id in traced_params else None
            if isinstance(node, ast.Constant):
                return None
            if isinstance(node, ast.UnaryOp) \
                    and isinstance(node.op, ast.Not):
                return scan(node.operand)
            if isinstance(node, ast.BoolOp):
                for sub in node.values:
                    hit = scan(sub)
                    if hit:
                        return hit
                return None
            if isinstance(node, ast.Compare):
                if any(isinstance(op, (ast.Is, ast.IsNot))
                       for op in node.ops):
                    return None
                for sub in [node.left] + list(node.comparators):
                    if not isinstance(sub, (ast.Name, ast.Constant)):
                        return None
                for sub in [node.left] + list(node.comparators):
                    hit = scan(sub)
                    if hit:
                        return hit
                return None
            return None

        return scan(test)
