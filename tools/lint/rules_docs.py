"""docs-sync: observable names match the docs that describe them.

Two checks:

1. every metric name registered in ``obs/`` code — a string-literal
   first argument to ``.counter(...)`` / ``.gauge(...)`` /
   ``.histogram(...)`` — and every span/instant name recorded there
   must appear verbatim in docs/OBSERVABILITY.md.  An operator staring
   at a Prometheus scrape or a flight bundle greps that file; a name it
   does not contain is an undocumented signal;
2. docs/Parameters.rst must be current against the ``Config``
   dataclass (the ``tools/gen_parameters_doc.py --check`` contract,
   folded in as a lint rule; full-tree scans only).
"""

from __future__ import annotations

import ast
import re
from typing import List

from .core import Project, Rule, Violation, dotted_name, str_const

_METRIC_METHODS = {"counter", "gauge", "histogram"}
_SPAN_CALLS = {"span", "instant", "_span", "_instant", "note",
               "note_instant"}
_DOC = "docs/OBSERVABILITY.md"


def _is_obs_file(rel: str) -> bool:
    return "/obs/" in "/" + rel.replace("\\", "/")


class DocsSyncRule(Rule):
    name = "docs-sync"
    doc = ("metric/span names registered in obs/ must appear in "
           "docs/OBSERVABILITY.md; docs/Parameters.rst must be current "
           "against the Config dataclass")

    def check(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        doc_text = project.read_doc(_DOC)
        for f in project.files:
            if not _is_obs_file(f.rel):
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                name = str_const(node.args[0])
                if name is None:
                    continue
                kind = None
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _METRIC_METHODS:
                    kind = node.func.attr
                else:
                    callee = (dotted_name(node.func) or "").split(".")[-1]
                    if callee in _SPAN_CALLS:
                        kind = "span"
                if kind is None:
                    continue
                # word-boundary match: a name must not pass because a
                # longer documented name contains it
                if not re.search(r"(?<![A-Za-z0-9_.])" + re.escape(name)
                                 + r"(?![A-Za-z0-9_.])", doc_text):
                    out.append(Violation(
                        self.name, f.rel, node.lineno,
                        f"{kind} name {name!r} registered in obs/ but "
                        f"absent from {_DOC} — document the signal "
                        "where operators will grep for it"))
        if project.full_tree \
                and project.file("lightgbm_tpu/config.py") is not None:
            out.extend(self._params_check(project.root))
        return out

    def _params_check(self, root: str) -> List[Violation]:
        import os  # noqa: PLC0415
        from . import params_doc  # noqa: PLC0415
        # Config is imported (not parsed), and a process that already
        # holds this repo's lightgbm_tpu cannot faithfully import
        # another checkout's — cross-root scans skip this sub-check
        # rather than judge foreign docs against the host's Config
        if os.path.realpath(root) != os.path.realpath(params_doc.REPO):
            return []
        try:
            code, messages = params_doc.check(root=root)
        except Exception as e:  # pragma: no cover - import breakage
            return [Violation(self.name, "docs/Parameters.rst", 1,
                              f"Parameters.rst check failed to run: {e}")]
        if code == 0:
            return []
        return [Violation(self.name, "docs/Parameters.rst", 1, m)
                for m in messages]
