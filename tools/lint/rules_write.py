"""atomic-write: durable writes route through ``file_io.write_atomic``.

Every persistence claim in the tree (checkpoint bundles, blockstore
manifests, AOT exports, the bench journal, flight bundles) rests on the
temp-sibling + fsync + ``os.replace`` discipline in
``lightgbm_tpu/utils/file_io.write_atomic`` — a reader never observes a
truncated file.  A raw ``open(path, "w")`` silently opts out of that
contract, so this rule flags every builtin ``open`` (and seam-routed
``open_file``) call whose mode writes (``w``/``a``/``x``, text or
binary) anywhere in the scanned tree.

Both seam spellings pass: ``write_atomic(path, data)`` for in-memory
payloads and the streaming ``with open_atomic(path, mode):`` for
payloads too large to assemble (binary caches, per-row output).
Genuinely non-durable writes (tmp probe output, lock sentinels) are
allowlisted per line with a justification::

    with open(tmp, "w") as f:  # tpulint: disable=atomic-write — tmp probe

``utils/file_io.py`` itself is exempt: it IS the seam.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import Project, Rule, Violation, dotted_name, str_const

_EXEMPT_RELS = ("lightgbm_tpu/utils/file_io.py",)
_OPENERS = {"open", "open_file", "io.open"}


def _write_mode(call: ast.Call) -> Optional[str]:
    """The mode string when this open()-style call writes, else None."""
    mode = None
    if len(call.args) >= 2:
        mode = str_const(call.args[1])
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = str_const(kw.value)
    if mode and any(c in mode for c in "wax"):
        return mode
    return None


class AtomicWriteRule(Rule):
    name = "atomic-write"
    doc = ("raw open(..., 'w'/'a'/'x') writes must route through "
           "utils.file_io.write_atomic (pragma-allowlist non-durable "
           "tmp output with a justification)")

    def check(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for f in project.files:
            if f.rel in _EXEMPT_RELS:
                continue
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func)
                if callee not in _OPENERS:
                    continue
                mode = _write_mode(node)
                if mode is None:
                    continue
                if "a" in mode and not any(c in mode for c in "wx"):
                    # appends have no atomic equivalent (the seam is
                    # whole-file replace); the remedy differs
                    out.append(Violation(
                        self.name, f.rel, node.lineno,
                        f"append-mode {callee}(..., {mode!r}) cannot "
                        "ride the atomic seam; restructure to "
                        "whole-file rewrites through write_atomic/"
                        "open_atomic, or pragma with a justification "
                        "if the log is genuinely non-durable"))
                    continue
                out.append(Violation(
                    self.name, f.rel, node.lineno,
                    f"raw {callee}(..., {mode!r}) write bypasses the "
                    "utils.file_io atomic seam (write_atomic for "
                    "in-memory payloads, open_atomic to stream); a "
                    "crash here can leave a truncated file behind"))
        return out
