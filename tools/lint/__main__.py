"""``python -m tools.lint`` — same CLI as ``python tools/lint.py``."""
import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
