"""docs/Parameters.rst generation + staleness check.

Moved here from ``tools/gen_parameters_doc.py`` (now a thin shim) so the
tpulint ``docs-sync`` rule and the standalone CLI share ONE
implementation.  reference: helpers/parameter_generator.py generates
config_auto.cpp AND docs/Parameters.rst from structured comments in
config.h; here the source of truth is the ``Config`` dataclass and
``_ALIASES`` dict in ``lightgbm_tpu/config.py``.
"""

from __future__ import annotations

import dataclasses
import io
import os
import re
import sys
from typing import List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
OUT = os.path.join(REPO, "docs", "Parameters.rst")


def _config(root: str = REPO):
    if root not in sys.path:
        sys.path.insert(0, root)
    from lightgbm_tpu.config import _ALIASES, Config  # noqa: PLC0415
    return Config, _ALIASES


def _sections(root: str = REPO):
    """(field name -> section title) from the explicit ``# section:
    <name>`` sentinels that structure the dataclass body — explicit, so
    an ordinary short comment can never silently spawn a garbage doc
    section."""
    src = open(os.path.join(root, "lightgbm_tpu", "config.py")).read()
    body = src.split("class Config:", 1)[1]
    section = "Core Parameters"
    out = {}
    for line in body.splitlines():
        m = re.match(r"\s*#\s*section:\s*(.+?)\s*$", line)
        if m:
            section = m.group(1).strip().title() + " Parameters"
            continue
        f = re.match(r"\s{4}(\w+)\s*:\s*\w", line)
        if f:
            out[f.group(1)] = section
    return out


def generate(root: str = REPO) -> str:
    Config, _ALIASES = _config(root)
    fields = dataclasses.fields(Config)
    sec_of = _sections(root)
    aliases_of = {}
    for alias, canon in _ALIASES.items():
        if alias != canon:
            aliases_of.setdefault(canon, []).append(alias)

    buf = io.StringIO()
    w = buf.write
    w("Parameters\n==========\n\n")
    w("Generated from ``lightgbm_tpu/config.py`` by "
      "``tools/gen_parameters_doc.py`` — do not edit by hand.\n"
      "The reference analogue is ``docs/Parameters.rst`` generated from "
      "``config.h`` by ``helpers/parameter_generator.py``.\n\n")
    current = None
    for f in fields:
        sec = sec_of.get(f.name, "Other Parameters")
        if sec != current:
            w(f"\n{sec}\n{'-' * len(sec)}\n\n")
            current = sec
        default = f.default
        if default is dataclasses.MISSING:
            default = (f.default_factory()
                       if f.default_factory is not dataclasses.MISSING
                       else "")
        typename = getattr(f.type, "__name__", str(f.type))
        w(f"- ``{f.name}``: {typename}, default ``{default!r}``")
        al = aliases_of.get(f.name)
        if al:
            w(f", aliases: {', '.join('``%s``' % a for a in sorted(al))}")
        w("\n")
    return buf.getvalue()


def check(out_path: Optional[str] = None,
          root: str = REPO) -> Tuple[int, List[str]]:
    """(exit code, messages) for the staleness check — 0 current, 1
    stale.  Missing Config fields are named FIRST: "stale" alone sends
    people diffing; a field added without regenerating should fail by
    name."""
    if out_path is None:
        out_path = os.path.join(root, "docs", "Parameters.rst")
    Config, _ = _config(root)
    text = generate(root)
    on_disk = open(out_path).read() if os.path.exists(out_path) else ""
    missing = [f.name for f in dataclasses.fields(Config)
               if f"``{f.name}``" not in on_disk]
    if missing:
        return 1, [f"{out_path} is missing Config fields: "
                   f"{', '.join(missing)}; regenerate with "
                   "python tools/gen_parameters_doc.py"]
    if on_disk != text:
        return 1, [f"{out_path} is stale: regenerate with "
                   "python tools/gen_parameters_doc.py"]
    return 0, [f"{out_path} is current"]
