"""tpulint core: file model, pragma handling, rule registry, runner.

tpulint is the project-native static-analysis suite: ~6 AST checkers
enforcing the invariants the codebase bets on but no generic linter
knows about (env-flag registry, atomic-write discipline, traced-code
purity, MXU parity conventions, lock discipline, docs/metrics sync).
``tools/lint.py`` is the CLI; ``tests/test_lint.py`` runs the suite over
the real tree in tier-1 so every PR is linted by default.

Suppression pragmas (docs/LINTING.md):

- ``# tpulint: disable=<rule>[,<rule>...]`` trailing on a line silences
  those rules for violations REPORTED on that line (``all`` silences
  every rule).  Allowlisting a real violation should come with a short
  justification in the same comment.
- ``# tpulint: disable-file=<rule>[,...]`` anywhere in a file silences
  the rules for the whole file.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence, Set

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_PRAGMA_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding: rule id, repo-relative path, 1-based line, text."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed Python file plus its pragma tables."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        self.line_disables: Dict[int, Set[str]] = {}
        self.file_disables: Set[str] = set()
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                self.file_disables |= rules
            else:
                self.line_disables.setdefault(i, set()).update(rules)

    def suppressed(self, v: Violation) -> bool:
        for s in (self.file_disables,
                  self.line_disables.get(v.line, ())):
            if v.rule in s or "all" in s:
                return True
        return False


class Project:
    """The scanned file set plus repo-level context for repo rules."""

    def __init__(self, files: Sequence[SourceFile], root: str = REPO,
                 full_tree: bool = False):
        self.files = list(files)
        self.root = root
        # full_tree: the default whole-repo scan — repo-level checks that
        # need the complete picture (stale registry entries, the
        # Parameters.rst sync) only run here, never on a path subset
        self.full_tree = full_tree
        self._by_rel = {f.rel: f for f in self.files}

    def file(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)

    def read_doc(self, rel: str) -> str:
        try:
            with open(os.path.join(self.root, rel)) as fh:
                return fh.read()
        except OSError:
            return ""


class Rule:
    """One checker.  Subclasses set ``name``/``doc`` and implement
    ``check(project) -> [Violation]`` (pragma filtering happens in the
    runner, not in rules)."""

    name: str = ""
    doc: str = ""

    def check(self, project: Project) -> List[Violation]:
        raise NotImplementedError


# ------------------------------------------------------------ file walking

# the default scan set: the library, the bench driver, the operator
# tools and the graft entry; tests/ seed env vars and raw writes on
# purpose and are excluded (pass paths explicitly to lint them)
DEFAULT_ROOTS = ("lightgbm_tpu", "tools", "bench.py", "__graft_entry__.py")
_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "dist"}


def iter_py_files(root: str, paths: Optional[Sequence[str]] = None):
    """Yield absolute paths of .py files under ``paths`` (default:
    DEFAULT_ROOTS) relative to ``root``."""
    rels = list(paths) if paths else list(DEFAULT_ROOTS)
    for rel in rels:
        p = rel if os.path.isabs(rel) else os.path.join(root, rel)
        if not os.path.exists(p):
            # a typo'd path must NOT come back "0 files clean, exit 0"
            raise OSError(f"no such path: {rel}")
        if os.path.isfile(p):
            if not p.endswith(".py"):
                raise OSError(f"not a Python file: {rel}")
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def load_project(root: str = REPO,
                 paths: Optional[Sequence[str]] = None) -> Project:
    files = []
    for p in iter_py_files(root, paths):
        rel = os.path.relpath(p, root)
        with open(p, encoding="utf-8") as fh:
            text = fh.read()
        files.append(SourceFile(p, rel, text))
    return Project(files, root=root, full_tree=not paths)


# ------------------------------------------------------------------ runner

def all_rules() -> List[Rule]:
    from . import rules_docs, rules_env, rules_locks  # noqa: PLC0415
    from . import rules_parity, rules_traced, rules_write
    return [rules_env.EnvFlagRegistryRule(),
            rules_write.AtomicWriteRule(),
            rules_traced.TracedPurityRule(),
            rules_parity.ParityHazardRule(),
            rules_locks.LockDisciplineRule(),
            rules_docs.DocsSyncRule()]


def select_rules(only: Optional[Sequence[str]] = None,
                 ignore: Optional[Sequence[str]] = None) -> List[Rule]:
    rules = all_rules()
    known = {r.name for r in rules}
    for sel in list(only or []) + list(ignore or []):
        if sel not in known:
            raise ValueError(
                f"unknown rule {sel!r}; known: {', '.join(sorted(known))}")
    if only:
        rules = [r for r in rules if r.name in set(only)]
    if ignore:
        rules = [r for r in rules if r.name not in set(ignore)]
    return rules


def run_lint(project: Project,
             rules: Optional[Sequence[Rule]] = None) -> List[Violation]:
    """Run ``rules`` (default: all) over ``project``; returns pragma-
    filtered violations sorted by (path, line, rule)."""
    out: Set[Violation] = set()
    for rule in (rules if rules is not None else all_rules()):
        for v in rule.check(project):
            f = project.file(v.path)
            if f is not None and f.suppressed(v):
                continue
            out.add(v)
    return sorted(out, key=lambda v: (v.path, v.line, v.rule, v.message))


# --------------------------------------------------------------- AST utils

def dotted_name(node: ast.AST) -> Optional[str]:
    """'os.environ.get' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_str_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments — lets checkers
    resolve ``os.environ.get(_TRACE_ENV)`` through the constant."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            s = str_const(node.value)
            if s is not None:
                out[node.targets[0].id] = s
    return out
