"""tpulint — the project-native static-analysis suite (docs/LINTING.md).

Checkers live in ``rules_*.py``; ``tools/lint.py`` is the CLI and
``tests/test_lint.py`` runs the suite over the real tree in tier-1.
"""

from .core import (DEFAULT_ROOTS, Project, Rule, SourceFile,  # noqa: F401
                   Violation, all_rules, load_project, run_lint,
                   select_rules)
