"""parity-hazard: the MXU numeric conventions byte-identity rests on.

Three checks, all scoped to the parity-critical modules:

1. every dot/matmul call in ``ops/`` must pin its accumulation type:
   integer one-hot matmuls pass ``preferred_element_type`` (i32
   accumulation, no bf16 mantissa loss — the quantized parity
   invariant) and f32 dots pass ``precision=HIGHEST`` (no TF32-style
   reassociation, see arXiv 1706.08359 / 1806.11248 for the GPU
   histogram-precision lineage).  A bare ``jnp.dot(a, b)`` inherits
   backend defaults that differ between CPU and TPU — exactly the
   silent divergence the parity tests exist to catch;
2. the ``@`` matmul operator is banned in ``ops/`` outright — it cannot
   carry either kwarg;
3. row-axis histogram folds (``jnp.sum(..., axis=0)``) in the
   histogram/fused/stream modules must live inside the blessed carry-in
   kernels (functions taking an ``init``/``carry`` accumulator
   parameter): the streamed==resident invariant holds only when block
   folds continue the SAME f32 accumulation sequence, which is what the
   carry-in seam guarantees.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from .core import Project, Rule, Violation, dotted_name

_DOT_CALLS = {"lax.dot", "lax.dot_general", "jax.lax.dot",
              "jax.lax.dot_general", "jnp.matmul", "jnp.dot",
              "jnp.einsum", "jnp.tensordot", "jax.numpy.matmul",
              "jax.numpy.dot"}
_PIN_KWARGS = {"preferred_element_type", "precision"}
_FOLD_BASENAMES = ("histogram", "fused", "stream")
_CARRY_PARAMS = {"init", "carry"}


def _in_ops(rel: str) -> bool:
    parts = rel.replace(os.sep, "/").split("/")
    return "ops" in parts


def _is_fold_module(rel: str) -> bool:
    base = os.path.basename(rel)
    return any(k in base for k in _FOLD_BASENAMES)


def _sum_axis(call: ast.Call) -> Optional[int]:
    axis = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        axis = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "axis" and isinstance(kw.value, ast.Constant):
            axis = kw.value.value
    return axis if isinstance(axis, int) else None


class ParityHazardRule(Rule):
    name = "parity-hazard"
    doc = ("ops/ dot/matmul calls must pin preferred_element_type or "
           "precision; '@' is banned in ops/; row-axis histogram folds "
           "belong inside carry-in kernels")

    def check(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for f in project.files:
            in_ops = _in_ops(f.rel)
            fold_mod = _is_fold_module(f.rel)
            if not (in_ops or fold_mod):
                continue
            # function stack so the sum check knows its enclosing defs
            out.extend(self._walk(f.rel, f.tree, in_ops, fold_mod, []))
        return out

    def _walk(self, rel: str, node: ast.AST, in_ops: bool,
              fold_mod: bool, fn_stack: List[ast.FunctionDef]):
        out: List[Violation] = []
        for child in ast.iter_child_nodes(node):
            push = isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
            if push:
                fn_stack.append(child)
            out.extend(self._visit(rel, child, in_ops, fold_mod,
                                   fn_stack))
            out.extend(self._walk(rel, child, in_ops, fold_mod,
                                  fn_stack))
            if push:
                fn_stack.pop()
        return out

    def _visit(self, rel, node, in_ops, fold_mod, fn_stack):
        out: List[Violation] = []
        if in_ops and isinstance(node, ast.BinOp) \
                and isinstance(node.op, ast.MatMult):
            out.append(Violation(
                self.name, rel, node.lineno,
                "'@' matmul cannot pin preferred_element_type/"
                "precision; use lax.dot with explicit accumulation"))
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if in_ops and callee in _DOT_CALLS:
                kwargs = {kw.arg for kw in node.keywords}
                if not (kwargs & _PIN_KWARGS):
                    out.append(Violation(
                        self.name, rel, node.lineno,
                        f"{callee}(...) without preferred_element_type/"
                        "precision: backend-default accumulation breaks "
                        "cross-platform bit parity (int matmuls need "
                        "preferred_element_type, f32 dots "
                        "precision=HIGHEST)"))
            elif fold_mod and callee in ("jnp.sum", "jax.numpy.sum") \
                    and _sum_axis(node) == 0:
                in_carry = any(
                    {a.arg for a in fn.args.args} & _CARRY_PARAMS
                    for fn in fn_stack)
                if not in_carry:
                    out.append(Violation(
                        self.name, rel, node.lineno,
                        "row-axis jnp.sum(..., axis=0) outside a "
                        "carry-in kernel (no enclosing function takes "
                        "init/carry): streamed==resident parity needs "
                        "folds to ride the blessed accumulation seam"))
        return out
