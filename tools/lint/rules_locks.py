"""lock-discipline: shared mutable state is declared and verified.

The serving batcher, the stream ``BlockPump``, the watchdog sentry and
the fleet replan tick all share instance attributes between a
``threading.Thread`` target and ordinary caller-side methods.  The
convention this rule enforces:

- an attribute mutated BOTH from thread-side code (a ``Thread`` target
  method or nested function, plus everything it reaches through
  ``self.m()`` calls) AND from caller-side methods must carry a
  ``# guarded-by: <lock>`` annotation on its ``__init__`` assignment::

      self._q = collections.deque()   # guarded-by: _lock

- every mutation of an annotated attribute (outside ``__init__``) must
  sit lexically inside ``with self.<lock>:`` — where ``<lock>`` is the
  annotated lock, or a ``threading.Condition(self.<lock>)`` alias
  created in ``__init__`` (holding the condition holds the lock);
- a helper whose CALLERS hold the lock declares it on its ``def`` line
  with ``# guarded-by-caller: <lock>``.

Mutations counted: attribute rebinds (``self.x = ...``, ``+=``), item
stores (``self.x[k] = ...``), and calls of known container mutators
(``self.x.append(...)``, ``popleft``, ``update``, ...).  Reads are not
tracked — the rule targets lost updates, the failure mode that actually
shipped races here (see fleet/registry.py's ``_admissions`` comment).
Deliberately lock-free single-store designs (GIL-atomic dict stores)
are allowlisted per line with a pragma + justification.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Project, Rule, Violation, dotted_name

_MUTATORS = {"append", "appendleft", "extend", "insert", "pop",
             "popleft", "remove", "clear", "update", "setdefault",
             "add", "discard", "__setitem__"}
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z0-9_,| ]+)")
_CALLER_RE = re.compile(r"#\s*guarded-by-caller:\s*([A-Za-z0-9_,| ]+)")
_INIT_NAMES = {"__init__", "__post_init__"}


def _locks_from(match) -> Set[str]:
    return {s.strip() for s in re.split(r"[,|]", match.group(1))
            if s.strip()}


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x``; None otherwise."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _mutations(fn: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(attr, node) pairs for every self-attribute mutation in ``fn``."""
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for el in ast.walk(t):
                    attr = _self_attr(el)
                    if attr is not None:
                        out.append((attr, node))
                    elif isinstance(el, ast.Subscript):
                        attr = _self_attr(el.value)
                        if attr is not None:
                            out.append((attr, node))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr is not None:
                out.append((attr, node))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is None and isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                if attr is not None:
                    out.append((attr, node))
    return out


def _thread_targets(scope: ast.AST) -> Tuple[Set[str], List[ast.AST]]:
    """(self-method names, nested function defs) passed as
    ``target=`` to a Thread(...) constructor inside ``scope``."""
    methods: Set[str] = set()
    nested_names: Set[str] = set()
    for node in ast.walk(scope):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        if not callee.endswith("Thread"):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            attr = _self_attr(kw.value)
            if attr is not None:
                methods.add(attr)
            elif isinstance(kw.value, ast.Name):
                nested_names.add(kw.value.id)
    nested_defs = [n for n in ast.walk(scope)
                   if isinstance(n, ast.FunctionDef)
                   and n.name in nested_names]
    return methods, nested_defs


def _self_calls(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            attr = _self_attr(node.func)
            if attr is not None:
                out.add(attr)
    return out


class _ClassInfo:
    def __init__(self, src_lines: List[str], cls: ast.ClassDef):
        self.cls = cls
        self.lines = src_lines
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # Condition/alias map: self.A = threading.Condition(self.B)
        # in __init__ means holding A holds B
        self.cond_alias: Dict[str, str] = {}
        # guarded-by annotations: attr -> (locks, lineno of declaration)
        self.guarded: Dict[str, Tuple[Set[str], int]] = {}
        for name in _INIT_NAMES:
            init = self.methods.get(name)
            if init is None:
                continue
            for node in ast.walk(init):
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    targets = [node.target]
                else:
                    continue
                attr = None
                for t in targets:
                    attr = attr or _self_attr(t)
                if attr is None or node.value is None:
                    continue
                if isinstance(node.value, ast.Call) \
                        and (dotted_name(node.value.func) or "").endswith(
                            "Condition") and node.value.args:
                    base = _self_attr(node.value.args[0])
                    if base is not None:
                        self.cond_alias[attr] = base
                line = self.lines[node.lineno - 1] \
                    if node.lineno - 1 < len(self.lines) else ""
                m = _GUARDED_RE.search(line)
                if m:
                    self.guarded[attr] = (_locks_from(m), node.lineno)

    def holds(self, held: Set[str], want: Set[str]) -> bool:
        """Does holding the locks in ``held`` satisfy one of ``want``?
        A Condition alias counts as its underlying lock."""
        expanded = set(held)
        for h in held:
            if h in self.cond_alias:
                expanded.add(self.cond_alias[h])
        for w in want:
            if w in expanded:
                return True
            # annotation may name the condition; holding its lock or
            # any sibling alias of the same lock also satisfies it
            if w in self.cond_alias and self.cond_alias[w] in expanded:
                return True
        return False


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    doc = ("attributes mutated from both a Thread target and caller "
           "methods need '# guarded-by: <lock>' on their __init__ "
           "assignment, and every mutation must sit under "
           "'with self.<lock>:'")

    def check(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for f in project.files:
            for node in ast.walk(f.tree):
                if isinstance(node, ast.ClassDef):
                    out.extend(self._check_class(f, node))
        return out

    def _check_class(self, f, cls: ast.ClassDef) -> List[Violation]:
        info = _ClassInfo(f.lines, cls)
        entry_methods, nested_defs = _thread_targets(cls)
        if not entry_methods and not nested_defs:
            return []

        # thread-reachable methods: closure over self.m() calls from the
        # entries (simple name-based reachability; cycles fine)
        reach: Set[str] = set()
        frontier = set(entry_methods)
        for nd in nested_defs:
            frontier |= _self_calls(nd)
        while frontier:
            m = frontier.pop()
            if m in reach or m not in info.methods:
                continue
            reach.add(m)
            frontier |= _self_calls(info.methods[m])

        thread_muts: Dict[str, List[ast.AST]] = {}
        for nd in nested_defs:
            for attr, node in _mutations(nd):
                thread_muts.setdefault(attr, []).append(node)
        for m in reach:
            for attr, node in _mutations(info.methods[m]):
                thread_muts.setdefault(attr, []).append(node)

        caller_muts: Dict[str, List[ast.AST]] = {}
        for name, fn in info.methods.items():
            if name in reach or name in _INIT_NAMES:
                continue
            # skip the thread code nested inside caller methods — those
            # mutations were already collected on the thread side
            skip = set()
            for nd in nested_defs:
                for sub in ast.walk(nd):
                    skip.add(id(sub))
            for attr, node in _mutations(fn):
                if id(node) not in skip:
                    caller_muts.setdefault(attr, []).append(node)

        shared = set(thread_muts) & set(caller_muts)
        out: List[Violation] = []
        for attr in sorted(shared):
            if attr not in info.guarded:
                line = min(n.lineno
                           for n in thread_muts[attr] + caller_muts[attr])
                out.append(Violation(
                    self.name, f.rel, line,
                    f"{cls.name}.{attr} is mutated from both a Thread "
                    "target and caller methods but its __init__ "
                    "assignment has no '# guarded-by: <lock>' "
                    "annotation"))
                continue
            want, _decl = info.guarded[attr]
            for name, fn in info.methods.items():
                if name in _INIT_NAMES:
                    continue
                out.extend(self._check_fn(f, cls, info, fn, attr, want))
            for nd in nested_defs:
                out.extend(self._check_fn(f, cls, info, nd, attr, want))
        return out

    def _check_fn(self, f, cls, info: _ClassInfo, fn: ast.AST,
                  attr: str, want: Set[str]) -> List[Violation]:
        base_held: Set[str] = set()
        def_line = f.lines[fn.lineno - 1] \
            if fn.lineno - 1 < len(f.lines) else ""
        m = _CALLER_RE.search(def_line)
        if m:
            base_held |= _locks_from(m)
        out: List[Violation] = []

        def visit(node: ast.AST, held: Set[str]):
            if isinstance(node, ast.With):
                got = set(held)
                for item in node.items:
                    a = _self_attr(item.context_expr)
                    if a is not None:
                        got.add(a)
                for sub in node.body:
                    visit(sub, got)
                return
            if isinstance(node, ast.FunctionDef) and node is not fn:
                # nested defs are visited as their own _check_fn pass
                # when they are thread targets; otherwise they inherit
                # the current held set (closures run where called — be
                # conservative and reset to base)
                for sub in ast.iter_child_nodes(node):
                    visit(sub, set(base_held))
                return
            hits = [(a, n) for a, n in _mutations(node)
                    if a == attr and n is node]
            for _a, n in hits:
                if not info.holds(held, want):
                    out.append(Violation(
                        self.name, f.rel, n.lineno,
                        f"{cls.name}.{attr} is guarded by "
                        f"{'/'.join(sorted(want))} but this mutation is "
                        "not under 'with self.<lock>:'"))
            for sub in ast.iter_child_nodes(node):
                visit(sub, held)

        for stmt in (fn.body if hasattr(fn, "body") else []):
            visit(stmt, set(base_held))
        return out
