#!/usr/bin/env python
"""bench_diff: stage-by-stage comparison of two bench journals — the
perf gate future PRs run before claiming "no regression".

Compares every stage the two journals share, metric by metric, against
per-metric regression thresholds with known polarity (sec_per_tree UP is
a regression, iters_per_sec DOWN is, holdout_auc has an absolute-delta
budget).  Metrics without a registered polarity are reported as info,
never gated — a new field can land without breaking the gate.

Inputs (either side): a bench journal (``bench_journal.json``:
``{"fingerprint", "stages": {...}}``; the fingerprint is informational
here — cross-shape comparisons print a warning, thresholds still apply),
a driver result file (``BENCH_r*.json``: the ``parsed`` record becomes
stage "full"), or a bare ``{stage: result}`` map.

Output: a human table (stage / metric / old / new / ratio / verdict) and
a LAST-LINE single JSON verdict; exit 0 = no regression, 1 = regression,
2 = unreadable input.

Usage:
    python tools/bench_diff.py OLD NEW \
        [--threshold sec_per_tree=1.10] [--stage full] [--json-only]
"""

import argparse
import json
import os
import sys

# metric -> (polarity, default threshold).  Polarities:
#   lower      — lower is better; regression when new/old > threshold
#   higher     — higher is better; regression when old/new > threshold
#   higher_abs — higher is better; regression when old - new > threshold
#                (absolute delta budget: quality metrics near 1.0)
THRESHOLDS = {
    "sec_per_tree": ("lower", 1.25),
    "sec_per_tree_train": ("lower", 1.25),
    "sec_per_tree_total": ("lower", 1.30),
    "sec_per_tree_chunked": ("lower", 1.25),
    "value": ("lower", 1.25),
    "elapsed": ("lower", 1.50),
    "compile_seconds": ("lower", 1.50),
    "bin_seconds": ("lower", 1.50),
    "iters_per_sec": ("higher", 1.25),
    "iters_per_sec_chunked": ("higher", 1.25),
    "trees_per_sec": ("higher", 1.25),
    "qps": ("higher", 1.25),
    "rows_per_sec": ("higher", 1.25),
    "blocks_per_sec": ("higher", 1.30),
    "overlap_efficiency": ("higher", 1.20),
    "p50_ms": ("lower", 1.50),
    "p90_ms": ("lower", 1.50),
    "p99_ms": ("lower", 1.50),
    "holdout_auc": ("higher_abs", 0.005),
    "auc": ("higher_abs", 0.005),
    "ndcg10": ("higher_abs", 0.005),
    "mfu_histogram_lower_bound": ("higher", 2.0),
    # autotuner election quality (hist_probe stage, ``autotune.*``):
    # fewer store hits or more misses/flips than the baseline run means
    # the measured-election path lost warmth or the analytic model and
    # the stopwatch started disagreeing — both worth failing loudly
    "autotune_hits": ("higher", 1.5),
    "autotune_misses": ("lower", 1.5),
    "autotune_flips": ("lower", 1.5),
    # inference-path numbers (predict_probe / bulk_score stages): the
    # elected traversal kernel's sec/Mrow and the bulk scorer's
    # per-device throughput are the perf-gate guards for ISSUE 19
    "predict_sec_per_mrow": ("lower", 1.25),
    "bulk_rows_per_sec_per_device": ("higher", 1.25),
    # ingest-path numbers (ingest_probe / ingest_11m / full stages):
    # device binning throughput and the kernel-vs-host margin are the
    # perf-gate guards for ISSUE 20 (bin_seconds rides the existing
    # lower-is-better rule above)
    "bin_rows_per_sec": ("higher", 1.25),
    "kernel_speedup_vs_host": ("higher", 1.25),
}
# a tiny absolute floor below which timing ratios are noise, not signal
ABS_FLOOR = {"compile_seconds": 0.5, "bin_seconds": 0.5, "elapsed": 1.0}


def load_stages(path):
    """Normalize any supported file shape to (fingerprint|None,
    {stage: result-dict})."""
    with open(path) as fh:
        d = json.load(fh)
    if not isinstance(d, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if isinstance(d.get("stages"), dict):
        return d.get("fingerprint"), {
            k: v for k, v in d["stages"].items() if isinstance(v, dict)}
    if isinstance(d.get("parsed"), dict):        # BENCH_r*.json driver file
        return None, {"full": d["parsed"]}
    if all(isinstance(v, dict) for v in d.values()) and d:
        return None, d
    # single bare stage result
    return None, {"full": d}


def _flat_metrics(stage_result, prefix=""):
    """Numeric leaves one level deep (``compile_cache.entries_after``
    style nested dicts flatten with a dotted key)."""
    out = {}
    for k, v in stage_result.items():
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict) and not prefix:       # one level only
            out.update(_flat_metrics(v, prefix=f"{k}."))
    return out


def _rule_for(metric, overrides):
    """(polarity, threshold) for a metric key; dotted keys match their
    leaf name (``client.p99_ms`` -> ``p99_ms``)."""
    leaf = metric.rsplit(".", 1)[-1]
    if metric in overrides:
        pol = THRESHOLDS.get(metric, THRESHOLDS.get(leaf, ("lower", 0)))[0]
        return pol, overrides[metric]
    if leaf in overrides:
        pol = THRESHOLDS.get(leaf, ("lower", 0))[0]
        return pol, overrides[leaf]
    if metric in THRESHOLDS:
        return THRESHOLDS[metric]
    if leaf in THRESHOLDS:
        return THRESHOLDS[leaf]
    return None, None


def compare(old_stages, new_stages, overrides=None, only_stage=None):
    """Row-per-metric comparison across shared stages.  Returns (rows,
    verdict-dict)."""
    overrides = overrides or {}
    rows, regressions = [], []
    shared = sorted(set(old_stages) & set(new_stages))
    if only_stage:
        shared = [s for s in shared if s == only_stage
                  or s.startswith(f"{only_stage}@")]
    for stage in shared:
        a = _flat_metrics(old_stages[stage])
        b = _flat_metrics(new_stages[stage])
        for metric in sorted(set(a) & set(b)):
            old, new = a[metric], b[metric]
            pol, thr = _rule_for(metric, overrides)
            row = {"stage": stage, "metric": metric,
                   "old": old, "new": new,
                   "ratio": round(new / old, 4) if old else None}
            if pol is None:
                row["status"] = "info"
            elif pol == "higher_abs":
                delta = old - new
                row["status"] = ("regression" if delta > thr else
                                 "improved" if -delta > thr else "ok")
                row["threshold"] = thr
            else:
                leaf = metric.rsplit(".", 1)[-1]
                floor = ABS_FLOOR.get(leaf, 0.0)
                if pol == "higher" and new <= 0 < old:
                    # a good-metric collapse to zero must never pass as
                    # "sub-noise-floor ok" (qps=0 IS the regression)
                    row["status"] = "regression"
                    row["threshold"] = thr
                elif max(abs(old), abs(new)) <= floor or old <= 0 or new <= 0:
                    row["status"] = "ok"        # sub-noise-floor values
                else:
                    worse = (new / old) if pol == "lower" else (old / new)
                    row["status"] = ("regression" if worse > thr else
                                     "improved" if worse < 1.0 / thr
                                     else "ok")
                    row["threshold"] = thr
            if row["status"] == "regression":
                regressions.append({k: row[k] for k in
                                    ("stage", "metric", "old", "new",
                                     "ratio", "threshold")})
            rows.append(row)
    verdict = {
        "ok": not regressions,
        "regressions": regressions,
        "stages_compared": len(shared),
        "metrics_compared": sum(1 for r in rows if r["status"] != "info"),
        "improvements": sum(1 for r in rows if r["status"] == "improved"),
    }
    return rows, verdict


def format_table(rows):
    if not rows:
        return "bench_diff: no shared stages/metrics to compare"
    w_stage = max(len(r["stage"]) for r in rows)
    w_metric = max(len(r["metric"]) for r in rows)
    lines = [f"{'stage':<{w_stage}}  {'metric':<{w_metric}}  "
             f"{'old':>12}  {'new':>12}  {'ratio':>7}  verdict"]
    for r in rows:
        if r["status"] == "info":
            continue
        ratio = f"{r['ratio']:.3f}" if r["ratio"] is not None else "-"
        mark = {"regression": "REGRESSION", "improved": "improved",
                "ok": "ok"}[r["status"]]
        lines.append(
            f"{r['stage']:<{w_stage}}  {r['metric']:<{w_metric}}  "
            f"{r['old']:>12.4f}  {r['new']:>12.4f}  {ratio:>7}  {mark}")
    return "\n".join(lines)


def parse_overrides(pairs):
    out = {}
    for p in pairs or []:
        k, _, v = p.partition("=")
        if not k or not v:
            raise ValueError(f"bad --threshold {p!r} (want metric=value)")
        out[k] = float(v)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("old", help="baseline journal / BENCH_r*.json")
    ap.add_argument("new", help="candidate journal / BENCH_r*.json")
    ap.add_argument("--threshold", action="append", default=[],
                    metavar="METRIC=RATIO",
                    help="override a per-metric threshold")
    ap.add_argument("--stage", default=None,
                    help="restrict the comparison to one stage")
    ap.add_argument("--json-only", action="store_true")
    args = ap.parse_args()
    try:
        fp_a, old_stages = load_stages(args.old)
        fp_b, new_stages = load_stages(args.new)
        overrides = parse_overrides(args.threshold)
    except (OSError, ValueError) as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 2
    if fp_a and fp_b and fp_a != fp_b and not args.json_only:
        print(f"bench_diff: WARNING — workload fingerprints differ "
              f"({fp_a!r} vs {fp_b!r}); comparing anyway", file=sys.stderr)
    rows, verdict = compare(old_stages, new_stages, overrides,
                            only_stage=args.stage)
    if not args.json_only:
        print(format_table(rows))
        print()
    print(json.dumps(verdict, sort_keys=True))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
