#!/usr/bin/env python
"""obs_doctor: automated bottleneck diagnosis over the banked bench
journal + a metrics snapshot (lightgbm_tpu/obs/diagnose.py,
docs/OBSERVABILITY.md verdict taxonomy).

Joins measured signals (devprof MFU tables, compile-cache warmth,
stream-probe overlap efficiency, straggler skew) with
planner-predicted ones (per-tier ICI/DCN payload bytes, link models)
and prints RANKED verdicts — "DCN-bound", "compile-bound",
"input-bound", "straggler slice k", "contention" (co-resident train vs
serve fighting over the same devices; evidence carries the residency
ledger's lease table + brownout throttle/pause counts), and
"kernel-underutilized" — each with the evidence behind it.  The LAST stdout line is one JSON summary (the
shape the bench journals as the ``obs_doctor`` stage).

Usage:
    python tools/obs_doctor.py \
        [--journal bench_journal.json]   # banked bench stages
        [--metrics bench_out/bench_obs_metrics.json]  # registry snapshot
        [--json-only]                    # machine consumers
Exit codes: 0 = diagnosed (whatever the verdict), 2 = input unreadable.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_journal_stages(path):
    """Banked stages from a bench journal ({} when absent); tolerant of
    both the fingerprint-wrapped layout and a bare stage map."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as fh:
        d = json.load(fh)
    if isinstance(d, dict) and isinstance(d.get("stages"), dict):
        return d["stages"]
    return d if isinstance(d, dict) else {}


def load_metrics_snapshot(path):
    """A dumped registry snapshot re-wrapped so ``collect_signals`` can
    read it like a live registry (duck-typed: only ``to_dict`` is
    consulted)."""
    if not path or not os.path.exists(path):
        return None
    with open(path) as fh:
        snap = json.load(fh)

    class _Snap:
        def to_dict(self):
            return snap

    return _Snap()


def run_doctor(stages=None, registry=None):
    """collect -> diagnose -> summary (the bench ``obs_doctor`` stage
    entry point; falls back to the live process registry)."""
    from lightgbm_tpu.obs.diagnose import run_doctor as _run
    return _run(registry=registry, stages=stages)


def format_human(report):
    lines = [f"obs_doctor: top verdict = {report['top_verdict']}", ""]
    for i, v in enumerate(report["verdicts"], 1):
        lines.append(f"{i}. [{v['name']}] score={v['score']:.2f}")
        lines.append(f"   {v['summary']}")
        if v["evidence"]:
            ev = ", ".join(f"{k}={v['evidence'][k]}"
                           for k in sorted(v["evidence"]))
            lines.append(f"   evidence: {ev}")
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--journal",
                    default=os.environ.get(
                        "BENCH_JOURNAL",
                        os.path.join(REPO, "bench_journal.json")))
    ap.add_argument("--metrics",
                    default=os.path.join(REPO, "bench_out",
                                         "bench_obs_metrics.json"))
    ap.add_argument("--json-only", action="store_true")
    args = ap.parse_args()
    try:
        stages = load_journal_stages(args.journal)
        registry = load_metrics_snapshot(args.metrics)
    except (OSError, ValueError) as e:
        print(json.dumps({"error": f"unreadable input: {e}"}))
        return 2
    report = run_doctor(stages=stages, registry=registry)
    if not args.json_only:
        print(format_human(report))
    print(json.dumps(report, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
