"""On-chip decomposition of the rounds grower's per-round cost.

Round-5 motivation: the first real TPU measurement of the rounds grower
(BENCH_MEASURED_r5.json higgs_1m) came in at 7.77 s/tree at 1M rows —
~450 ms per round — while the round-4 kernel probe claimed 0.04-0.09 ms
per full histogram pass.  Those probe numbers are physically impossible
(the one-hot matmul alone is ~1e13 FLOPs ≈ 55 ms at this chip's peak), so
either the probe's synchronization is broken on the tunnel backend or the
cost is elsewhere in the round body.  This script times every candidate
bottleneck individually with *device-to-host copies* as the sync barrier
(np.asarray of a small reduction of the result — cannot complete early),
banking results to JSON after each stage like tools/tpu_measure.py.

Run ALONE (single-tenant tunnel):  python tools/profile_rounds.py out.json

Stages:
  sync_check        block_until_ready vs D2H-copy timing of one matmul pass
  hist_full         full-pass histogram variants at 1M x 28 x 64
  hist_seg_scatter  segment_histogram (XLA scatter) at cap 512k, S=128
  seg_matmul_s16    segment hist as combined-onehot matmul, S=16 (FLOP wall)
  nonzero_compact   jnp.nonzero(size=cap) + row gather at several n
  sort_i32          jnp.sort / argsort of i32 keys at several n
  while_overhead    lax.while_loop step cost vs body size
  fori_hist         fori_loop of k compacted pallas histograms (design B)
  scatter_slices    scatter-add of nb [F*B*3] slices (grouped-block commit)
"""
import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.utils.platform import _cache_dir  # noqa: E402

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")

OUT = sys.argv[1] if len(sys.argv) > 1 else os.path.join(REPO, "profile_rounds.json")
T0 = time.time()
DATA = {"started_utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
        "stages": []}


def bank(stage, **kw):
    kw["stage"] = stage
    kw["t_elapsed"] = round(time.time() - T0, 1)
    DATA["stages"].append(kw)
    tmp = OUT + ".tmp"
    # manual tmp+os.replace below; stdlib-only probe must stay
    # importable before jax/package init
    with open(tmp, "w") as f:  # tpulint: disable=atomic-write
        json.dump(DATA, f, indent=1, default=str)
    os.replace(tmp, OUT)
    print(f"[profile] {stage}: {json.dumps(kw, default=str)[:400]}", flush=True)


def guard(stage, fn, *a, **kw):
    if os.environ.get(f"PR_SKIP_{stage.upper()}") == "1":
        bank(stage, skipped=True)
        return None
    t1 = time.time()
    try:
        r = fn(*a, **kw)
        out = dict(r) if isinstance(r, dict) else {"result": r}
        out["stage_seconds"] = round(time.time() - t1, 1)
        bank(stage, **out)
        return r
    except Exception as e:
        bank(stage, error=str(e)[-400:], tb=traceback.format_exc()[-1200:])
        return None


def d2h_time(fn, *args, reps=5):
    """Median wall time of fn(*args) synced by a D2H copy of a reduction.

    jnp.sum(out) adds negligible work; np.asarray cannot return before the
    whole computation has finished, unlike a possibly-lazy
    block_until_ready on this experimental backend.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    red = jax.jit(lambda *a: jnp.sum(
        jax.tree_util.tree_reduce(lambda x, y: jnp.sum(x) + jnp.sum(y),
                                  fn(*a), jnp.float32(0.0))))
    float(np.asarray(red(*args)))          # compile + warm
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(red(*args)))
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return round(ts[len(ts) // 2] * 1e3, 3)   # median ms


SMALL = os.environ.get("PR_SMALL") == "1"   # CPU smoke-test mode


def _scale(n):
    return max(4096, n // 64) if SMALL else n


def make_inputs(n, f=28, bins=64, seed=0):
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(seed)
    binned = jnp.asarray(rng.randint(0, bins - 1, (f, n), dtype=np.int64),
                         jnp.uint8)
    grad = jnp.asarray(rng.randn(n), jnp.float32)
    hess = jnp.abs(grad) + 0.1
    mask = jnp.ones((n,), jnp.float32)
    return binned, grad, hess, mask


def stage_sync_check():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from lightgbm_tpu.ops import histogram as H
    binned, grad, hess, mask = make_inputs(_scale(1_000_000))
    fn = jax.jit(lambda b, g, h, m: H.build_histogram(b, g, h, m, 64,
                                                      method="matmul"))
    out = fn(binned, grad, hess, mask)
    out.block_until_ready()
    # block_until_ready timing (the round-4 probe protocol)
    t0 = time.perf_counter()
    for _ in range(3):
        fn(binned, grad, hess, mask).block_until_ready()
    bur_ms = (time.perf_counter() - t0) / 3 * 1e3
    # D2H-synced timing
    d2h_ms = d2h_time(lambda b, g, h, m: H.build_histogram(
        b, g, h, m, 64, method="matmul"), binned, grad, hess, mask)
    return {"block_until_ready_ms": round(bur_ms, 3), "d2h_ms": d2h_ms,
            "suspect_lazy_sync": bool(d2h_ms > 4 * bur_ms + 1)}


def stage_hist_full():
    from lightgbm_tpu.ops import histogram as H
    binned, grad, hess, mask = make_inputs(_scale(1_000_000))
    out = {}
    for method in ("matmul", "matmul_f32", "scatter", "pallas"):
        try:
            out[f"{method}_ms"] = d2h_time(
                lambda b, g, h, m, _m=method: H.build_histogram(
                    b, g, h, m, 64, method=_m), binned, grad, hess, mask)
        except Exception as e:
            out[f"{method}_ms"] = f"error: {str(e)[:120]}"
    return out


def stage_hist_seg_scatter():
    import jax.numpy as jnp
    from lightgbm_tpu.ops import histogram as H
    out = {}
    for n, S in ((_scale(512 * 1024), 128), (_scale(512 * 1024), 16),
                 (_scale(65536), 128)):
        binned, grad, hess, mask = make_inputs(n)
        slot = (jnp.arange(n, dtype=jnp.int32) % S)
        try:
            out[f"n{n}_S{S}_ms"] = d2h_time(
                lambda b, g, h, m, s, _S=S: H.segment_histogram(
                    b, g, h, m, s, _S, 64), binned, grad, hess, mask, slot)
        except Exception as e:
            out[f"n{n}_S{S}_ms"] = f"error: {str(e)[:120]}"
    return out


def stage_seg_matmul_s16():
    """Combined (slot,bin) one-hot matmul — viable only for small S."""
    import jax.numpy as jnp
    from jax import lax

    def seg_mm(binned, grad, hess, mask, slot, S, B):
        F, n = binned.shape
        binned = binned.T
        vals = jnp.stack([grad, hess, jnp.ones_like(grad)], 1) * mask[:, None]
        C = 4096
        nb = n // C
        bb = binned.reshape(nb, C, F)
        sb = slot.reshape(nb, C)
        vb = vals.reshape(nb, C, 3)
        iota = jnp.arange(S * B, dtype=jnp.int32)

        def body(acc, blk):
            b, s, v = blk
            comb = s[:, None].astype(jnp.int32) * B + b.astype(jnp.int32)
            oh = (comb[:, :, None] == iota).astype(jnp.bfloat16)
            oh2 = oh.reshape(C, F * S * B)
            part = lax.dot(v.astype(jnp.bfloat16).T, oh2,
                           preferred_element_type=jnp.float32)
            return acc + part, None

        acc, _ = lax.scan(body, jnp.zeros((3, F * S * B), jnp.float32),
                          (bb, sb, vb))
        return acc

    n, S, B = _scale(512 * 1024), 16, 64
    binned, grad, hess, mask = make_inputs(n)
    slot = (jnp.arange(n, dtype=jnp.int32) % S)
    return {"n512k_S16_ms": d2h_time(
        lambda b, g, h, m, s: seg_mm(b, g, h, m, s, S, B),
        binned, grad, hess, mask, slot)}


def stage_nonzero_compact():
    import jax.numpy as jnp
    out = {}
    for n in (_scale(1_000_000), _scale(5_500_000), _scale(11_000_000)):
        binned, grad, hess, mask = make_inputs(n, seed=1)
        member = (grad > 0)
        cap = n // 2 + 65536

        def compact(b, mem, _cap=cap, _n=n):
            idx = jnp.nonzero(mem, size=_cap, fill_value=_n)[0]
            idxc = jnp.minimum(idx, _n - 1)
            return jnp.take(b, idxc, axis=1)

        try:
            out[f"n{n}_ms"] = d2h_time(compact, binned, member)
        except Exception as e:
            out[f"n{n}_ms"] = f"error: {str(e)[:120]}"
    return out


def stage_sort_i32():
    import jax.numpy as jnp
    import numpy as np
    out = {}
    for n in (_scale(512 * 1024), _scale(5_500_000)):
        keys = jnp.asarray(np.random.RandomState(0).randint(0, 128, n),
                           jnp.int32)
        try:
            out[f"sort_n{n}_ms"] = d2h_time(jnp.sort, keys)
            out[f"argsort_n{n}_ms"] = d2h_time(jnp.argsort, keys)
        except Exception as e:
            out[f"n{n}_ms"] = f"error: {str(e)[:120]}"
    return out


def stage_while_overhead():
    import jax.numpy as jnp
    from jax import lax
    out = {}
    for nops in (8, 64, 512):
        def body(c, _k=nops):
            i, x = c
            for _ in range(_k):
                x = x * 1.000001 + 1e-7
            return i + 1, x

        def run(x0):
            return lax.while_loop(lambda c: c[0] < 254,
                                  body, (jnp.int32(0), x0))[1]

        ms = d2h_time(run, jnp.ones((8, 128), jnp.float32))
        out[f"body{nops}ops_254steps_ms"] = ms
        out[f"body{nops}ops_per_step_us"] = round(ms / 254 * 1e3, 1)
    return out


def stage_fori_hist():
    """Design B prototype: k sequential compacted pallas histograms."""
    import jax.numpy as jnp
    from jax import lax
    from lightgbm_tpu.ops import histogram as H

    n, S, B = _scale(1_000_000), 14, 64
    binned, grad, hess, mask = make_inputs(n)
    slot = (jnp.arange(n, dtype=jnp.int32) % 137) % (S + 3)  # ~n/17 per slot
    caps = [n, n // 2, n // 4, n // 8, n // 16, n // 32]
    caps = [(c + 4095) // 4096 * 4096 for c in caps]

    def one(b, g, h, m, s):
        def body(i, acc):
            mem = (s == i) & (m > 0)
            cnt = jnp.sum(mem)

            def branch(cap):
                def run():
                    idx = jnp.nonzero(mem, size=cap, fill_value=n)[0]
                    idxc = jnp.minimum(idx, n - 1)
                    rows = jnp.take(b, idxc, axis=1)
                    w = jnp.where(idx < n, jnp.take(m, idxc), 0.0)
                    return H.build_histogram(rows, jnp.take(g, idxc),
                                             jnp.take(h, idxc), w, B,
                                             method="pallas")
                return run
            bucket = jnp.sum(jnp.asarray(caps, jnp.int32) >= cnt) - 1
            hist = lax.switch(bucket, [branch(c) for c in caps])
            return acc.at[i].set(hist)

        return lax.fori_loop(0, S, body,
                             jnp.zeros((S, 28, B, 3), jnp.float32))

    return {"k14_seq_compact_pallas_ms": d2h_time(
        one, binned, grad, hess, mask, slot)}


def stage_scatter_slices():
    """Scatter-add nb [F*B*3]-slices into S slots (grouped-block commit)."""
    import jax.numpy as jnp
    import numpy as np
    nb, S = 1024, 128
    F, B = 28, 64
    parts = jnp.asarray(np.random.RandomState(0).rand(nb, F * B * 3),
                        jnp.float32)
    sl = jnp.asarray(np.random.RandomState(1).randint(0, S, nb), jnp.int32)

    def commit(p, s):
        return jnp.zeros((S, F * B * 3), jnp.float32).at[s].add(p)

    return {"nb1024_slices_ms": d2h_time(commit, parts, sl)}


def main():
    t = time.time()
    try:
        import jax
        devs = jax.devices()
        import jax.numpy as jnp
        jnp.ones((8, 8)).sum().block_until_ready()
    except Exception as e:
        bank("init", error=str(e)[-400:])
        return 3
    d = devs[0]
    bank("init", seconds=round(time.time() - t, 1), platform=d.platform,
         kind=getattr(d, "device_kind", ""))
    if d.platform == "cpu" and os.environ.get("PR_ALLOW_CPU") != "1":
        bank("abort", reason="backend resolved to cpu")
        return 3

    guard("sync_check", stage_sync_check)
    guard("hist_full", stage_hist_full)
    guard("hist_seg_scatter", stage_hist_seg_scatter)
    guard("seg_matmul_s16", stage_seg_matmul_s16)
    guard("nonzero_compact", stage_nonzero_compact)
    guard("sort_i32", stage_sort_i32)
    guard("while_overhead", stage_while_overhead)
    guard("fori_hist", stage_fori_hist)
    guard("scatter_slices", stage_scatter_slices)
    bank("done", total_seconds=round(time.time() - T0, 1))
    return 0


if __name__ == "__main__":
    import jax.numpy as jnp  # noqa: F401  (stages assume jnp importable)
    sys.exit(main())
