#!/usr/bin/env python
"""Lifecycle smoke: train -> continual refresh -> guarded promotion ->
forced rollback — the CLI twin of tests/test_lifecycle.py, for eyeballs,
CI logs, and the bench ``lifecycle`` stage (bench.py imports
``run_smoke``).  The LAST stdout line is a single JSON object.

Phases (each banks its own sub-dict in the summary):

* ``train``    — train the deployed model, stand it up as the fleet's
  ``live`` entry.
* ``promote``  — warm-start a candidate over fresh rows on the deployed
  bin grid (lifecycle.refresh), bank the sha256 bundle, then drive the
  guarded rollout under threaded loadgen traffic (probe quarantine ->
  shadow mirror -> staged canary ramp -> probed cutover); the bar is a
  clean end-to-end promotion with the fleet serving the candidate
  bit-identically and ``model_age_seconds`` reset.
* ``rollback`` — refresh again, then promote under an impossible drift
  budget: the rollout must ROLL BACK, the fleet's output must be
  byte-identical to the pre-promotion model, and a flight-recorder
  bundle naming the ``drift`` gate must exist.
* ``shadow``   — serving/loadgen shadow mode against two standalone
  servers: mirrored count, measured drift, and honest live accounting.

Usage:
    JAX_PLATFORMS=cpu python tools/lifecycle_smoke.py \
        [--rows 6000] [--trees 10] [--refresh-trees 4] [--requests 96]
"""

import argparse
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_data(rng, rows, features):
    X = rng.randn(rows, features).astype(np.float32).astype(np.float64)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    return X, y


def _loadgen_traffic(requests, threads, rows):
    """A promote() traffic driver firing threaded mixed-size requests
    through the controller (serving/loadgen idiom)."""
    import threading

    def drive(controller, phase, fraction):
        def worker(tidx):
            r = np.random.RandomState(1000 + tidx)
            per = requests // threads
            for _ in range(per):
                m = int(r.randint(1, rows + 1))
                F = controller.fleet.entry(
                    controller.live_name).model.num_features
                Xr = r.randn(m, F).astype(np.float32).astype(np.float64)
                controller.predict(Xr, timeout=120)

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    return drive


def run_smoke(rows=6000, trees=10, refresh_trees=4, features=10,
              leaves=15, requests=96, threads=4, max_request_rows=64,
              directory=None) -> dict:
    """Run all phases; returns the JSON-ready summary dict.  ``failed``
    is True when any acceptance bar was missed."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.lifecycle import LifecycleConfig, LifecycleController
    from lightgbm_tpu.obs.watchdog import global_watchdog
    from lightgbm_tpu.serving.loadgen import fire_requests

    own_tmp = None
    if directory is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="lgbt_lifecycle_")
        directory = own_tmp.name

    summary = {"rows": rows, "trees": trees, "phases": {}}
    rng = np.random.RandomState(0)
    params = {"objective": "binary", "verbosity": -1,
              "num_leaves": leaves}

    # ----------------------------------------------------------- train
    X, y = _make_data(rng, rows, features)
    base_ds = lgb.Dataset(X, label=y, free_raw_data=False)
    deployed = lgb.train(params, base_ds, trees, verbose_eval=False)
    fleet = lgb.Fleet(max_batch_rows=256)
    fleet.add_model("live", deployed)
    fleet.warm()
    summary["phases"]["train"] = {
        "iterations": deployed.current_iteration(),
        "live_digest": fleet.entry("live").model.digest,
    }

    probe = X[:256]
    traffic = _loadgen_traffic(requests, threads, max_request_rows)

    # --------------------------------------------------------- promote
    ctl = LifecycleController(
        fleet, "live", directory=f"{directory}/ok",
        config=LifecycleConfig(drift_budget=50.0, mirror_fraction=0.5,
                               ramp=(0.25, 0.5)))
    Xf, yf = _make_data(rng, rows // 2, features)
    bundle, cand = ctl.refresh(Xf, yf, params=params,
                               num_boost_round=refresh_trees)
    res = ctl.promote(bundle, probe_X=probe, traffic=traffic)
    ref = cand.predict(probe, raw_score=True)
    served = fleet.predict("live", probe, timeout=120)
    age = global_watchdog.model_age_s("live")
    summary["phases"]["promote"] = {
        "status": res["status"],
        "candidate_iterations": cand.current_iteration(),
        "shadow": res["phases"].get("shadow"),
        "ramp": res["phases"].get("ramp"),
        "served_bit_equal_candidate": bool(np.array_equal(served, ref)),
        "model_age_seconds": round(age, 3) if age is not None else None,
    }
    promote_ok = (res["status"] == "promoted"
                  and summary["phases"]["promote"]
                  ["served_bit_equal_candidate"]
                  and age is not None and age < 300.0)

    # -------------------------------------------------------- rollback
    pre = fleet.predict("live", probe, timeout=120)
    from lightgbm_tpu.obs.flight import global_flight

    def _flight_listing():
        # the recorder creates its directory on first dump; a clean
        # process may not have one yet
        try:
            return set(os.listdir(global_flight.out_dir()))
        except OSError:
            return set()

    before_dumps = _flight_listing()
    ctl2 = LifecycleController(
        fleet, "live", directory=f"{directory}/bad",
        config=LifecycleConfig(drift_budget=1e-12, mirror_fraction=1.0))
    Xg, yg = _make_data(rng, rows // 2, features)
    bundle2, _ = ctl2.refresh(Xg, yg, params=params,
                              num_boost_round=refresh_trees,
                              base=base_ds)
    res2 = ctl2.promote(bundle2, probe_X=probe, traffic=traffic)
    post = fleet.predict("live", probe, timeout=120)
    new_dumps = [d for d in _flight_listing()
                 if d not in before_dumps and "lifecycle" in d]
    summary["phases"]["rollback"] = {
        "status": res2["status"],
        "gate": res2.get("gate"),
        "bit_identical_after_rollback": bool(np.array_equal(pre, post)),
        "flight_dumps": new_dumps,
    }
    rollback_ok = (res2["status"] == "rolled_back"
                   and res2.get("gate") == "drift"
                   and summary["phases"]["rollback"]
                   ["bit_identical_after_rollback"]
                   and any("drift" in d for d in new_dumps))

    # ---------------------------------------------------------- shadow
    live_srv = deployed.serve(max_batch_rows=256)
    cand_srv = cand.serve(max_batch_rows=256)
    storm = fire_requests(live_srv, requests, threads, max_request_rows,
                          features, timeout=120, shadow_server=cand_srv,
                          mirror_fraction=0.5)
    live_srv.close()
    cand_srv.close()
    fleet.close()
    sh = storm["shadow"]
    summary["phases"]["shadow"] = {
        "live_requests": storm["requests"],
        "mirrored": sh["mirrored"],
        "drift_max": sh["drift_max"],
        "latency_delta_ms_mean": sh["latency_delta_ms"].get("mean"),
        "errors": storm["errors"] + sh["errors"],
    }
    shadow_ok = (not storm["errors"] and not sh["errors"]
                 and storm["requests"] == storm["requests_planned"]
                 and sh["mirrored"] > 0 and sh["drift_max"] is not None)

    if own_tmp is not None:
        own_tmp.cleanup()
    summary["phase_ok"] = {"promote": promote_ok,
                           "rollback": rollback_ok, "shadow": shadow_ok}
    summary["failed"] = not (promote_ok and rollback_ok and shadow_ok)
    return summary


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=6000)
    ap.add_argument("--trees", type=int, default=10)
    ap.add_argument("--refresh-trees", type=int, default=4)
    ap.add_argument("--features", type=int, default=10)
    ap.add_argument("--requests", type=int, default=96)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--max-request-rows", type=int, default=64)
    ap.add_argument("--dir", default=None,
                    help="bundle/journal dir (default: a temp dir)")
    args = ap.parse_args()

    print(f"[lifecycle_smoke] {args.rows} rows, {args.trees}+"
          f"{args.refresh_trees} trees, {args.requests} requests",
          flush=True)
    summary = run_smoke(
        rows=args.rows, trees=args.trees,
        refresh_trees=args.refresh_trees, features=args.features,
        requests=args.requests, threads=args.threads,
        max_request_rows=args.max_request_rows, directory=args.dir)
    print(json.dumps(summary, indent=1, sort_keys=True))
    return 1 if summary["failed"] else 0


if __name__ == "__main__":
    sys.exit(main())
