#!/usr/bin/env python
"""Fleet smoke: N-model serve, planner-driven eviction, AOT restart,
and opt-in low-precision — the CLI twin of tests/test_fleet.py, for
eyeballs, CI logs, and the bench `fleet` stage (bench.py imports
``run_smoke``).  The LAST stdout line is a single JSON object.

Phases (each banks its own sub-dict in the summary):

* ``serve``   — train N boosters (one multiclass), register them with
  mixed weights/deadline classes, fire a weighted multi-model traffic
  mix (serving/loadgen.fire_fleet_requests), verify every f32 response
  bit-equal to ``StackedForest.predict_raw``.
* ``evict``   — replan against a faked HBM budget sized to the hottest
  model only: colder models must be EVICTED (device arrays + programs
  released) yet stay fully servable through the host path, still
  bit-equal.  No OOM, no serve failure is the acceptance bar.
* ``aot``     — export every resident bucket program (fleet/aot.py),
  stand up a FRESH fleet against the store, warm it, and serve first
  requests: zero ``compile_events``, only ``aot_program_loads``.
* ``lowprec`` — register bf16 and int8 twins of a model under a
  declared accuracy budget; journal the measured deltas; demonstrate
  the quarantine by offering an int8 model a budget of 0.
* ``failover`` (``--devices N``, N >= 2; the bench ``fleet_failover``
  stage) — stand up a replicated ``PodFleet`` over N simulated
  devices, fire a threaded traffic storm, KILL one device mid-run
  (chaos ``device`` site), and assert the acceptance bars: ZERO
  non-typed request failures, availability >= 0.999, every response
  bit-equal to ``Booster.predict(raw_score=True)``, and recovery
  (every model regains replica coverage) within ONE replan tick.

Usage:
    JAX_PLATFORMS=cpu python tools/fleet_smoke.py \
        [--models 3] [--requests 240] [--threads 6] [--rows 3000] \
        [--max-batch-rows 256] [--accuracy-budget 0.5] [--devices 2]
"""

import argparse
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _train_models(n_models, rows, trees, features, leaves):
    import lightgbm_tpu as lgb
    boosters = []
    for i in range(n_models):
        rng = np.random.RandomState(100 + i)
        X = rng.randn(rows, features).astype(np.float32).astype(np.float64)
        if i == n_models - 1 and n_models >= 2:
            params = {"objective": "multiclass", "num_class": 3,
                      "verbosity": -1, "num_leaves": leaves}
            y = rng.randint(0, 3, rows).astype(float)
        else:
            params = {"objective": "binary", "verbosity": -1,
                      "num_leaves": leaves}
            y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
        boosters.append(lgb.train(params, lgb.Dataset(X, label=y),
                                  num_boost_round=trees,
                                  verbose_eval=False))
    return boosters


def _verify_forests(boosters):
    out = {}
    for i, b in enumerate(boosters):
        n_iter = len(b.models) // b.num_tree_per_iteration
        out[f"m{i}"] = b._forest(0, n_iter)
    return out


def run_smoke(n_models=3, rows=3000, trees=10, features=10, leaves=15,
              requests=240, threads=6, max_request_rows=200,
              max_batch_rows=256, accuracy_budget=0.5,
              aot_dir=None) -> dict:
    """Run all four phases; returns the JSON-ready summary dict.
    ``failed`` is True when any acceptance bar was missed."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import LowPrecisionQuarantined
    from lightgbm_tpu.serving.loadgen import fire_fleet_requests

    summary = {"n_models": n_models, "phases": {}}
    boosters = _train_models(n_models, rows, trees, features, leaves)
    verify = _verify_forests(boosters)
    names = sorted(verify)

    # ----------------------------------------------------------- serve
    fleet = lgb.Fleet(max_batch_rows=max_batch_rows)
    weights = {}
    classes = sorted(fleet.config.deadline_classes)
    for i, b in enumerate(boosters):
        w = float(n_models - i)
        weights[f"m{i}"] = w
        fleet.add_model(f"m{i}", b, weight=w,
                        deadline_class=classes[i % len(classes)])
    fleet.warm()
    storm = fire_fleet_requests(fleet, weights, requests, threads,
                                max_request_rows, verify=verify,
                                timeout=120)
    summary["phases"]["serve"] = {
        "requests": storm["requests"],
        "requests_planned": storm["requests_planned"],
        "rows": storm["rows"],
        "shed": storm["shed"],
        "expired": storm["expired"],
        "failed": storm["failed"],
        "availability": storm["availability"],
        "mismatches": storm["mismatches"],
        "wall_seconds": round(storm["wall_seconds"], 3),
        "rows_per_second": round(
            storm["rows"] / max(storm["wall_seconds"], 1e-9), 1),
        "errors": storm["errors"],
        "models": storm["models"],
        "plan": fleet.plan.summary() if fleet.plan else None,
    }
    # failed requests are typed OUTCOMES now (loadgen no longer kills
    # the thread), so the bar must assert them zero EXPLICITLY — the
    # planned-request tally alone would also catch them, but a named
    # zero reads honestly in the journal
    serve_ok = (not storm["errors"] and storm["failed"] == 0
                and storm["mismatches"] == 0
                and storm["requests"] + storm["shed"] + storm["expired"]
                + storm["failed"] == storm["requests_planned"])

    # ----------------------------------------------------------- evict
    plan0 = fleet.replan()
    hottest = max(plan0.models, key=lambda m: m.priority)
    hot_cost = hottest.forest_bytes + hottest.program_bytes
    from lightgbm_tpu.ops.planner import HEADROOM
    fleet.config.hbm_budget_bytes = int((hot_cost + 1024) / HEADROOM)
    plan = fleet.replan()
    evict_storm = fire_fleet_requests(fleet, weights, requests // 2,
                                      threads, max_request_rows,
                                      verify=verify, timeout=120)
    md = fleet.metrics_dict()
    evictions = sum(v for k, v in md["counters"].items()
                    if k.startswith("fleet_evictions"))
    summary["phases"]["evict"] = {
        "budget_bytes": plan.budget_bytes,
        "evicted_models": list(plan.evicted),
        "evictions": evictions,
        "requests": evict_storm["requests"],
        "shed": evict_storm["shed"],
        "expired": evict_storm["expired"],
        "failed": evict_storm["failed"],
        "mismatches": evict_storm["mismatches"],
        "errors": evict_storm["errors"],
        "all_models_served": all(
            m["requests"] > 0 or m["shed"] > 0 or weights[n] == 0
            for n, m in evict_storm["models"].items()),
    }
    evict_ok = (len(plan.evicted) >= 1 and not evict_storm["errors"]
                and evict_storm["failed"] == 0
                and evict_storm["mismatches"] == 0
                and summary["phases"]["evict"]["all_models_served"])
    fleet.config.hbm_budget_bytes = None
    fleet.replan()

    # ------------------------------------------------------------- aot
    own_tmp = None
    if aot_dir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="lgbt_fleet_aot_")
        aot_dir = own_tmp.name
    n_exported = fleet.export_aot(aot_dir)
    fleet.close()
    replica = lgb.Fleet(max_batch_rows=max_batch_rows, aot_dir=aot_dir)
    for i, b in enumerate(boosters):
        replica.add_model(f"m{i}", b, weight=weights[f"m{i}"])
    replica.warm()
    rng = np.random.RandomState(7)
    first_ok = True
    for i, name in enumerate(names):
        X = rng.randn(32, features).astype(np.float32).astype(np.float64)
        out = replica.predict(name, X, timeout=60)
        K = replica.entry(name).model.num_class
        ref = verify[name].predict_raw(X, num_class=K)
        first_ok = first_ok and np.array_equal(out,
                                               ref[0] if K == 1 else ref.T)
    compiles = 0
    aot_loads = 0
    for name in names:
        c = replica.entry(name).server.metrics_dict()["counters"]
        compiles += c.get("compile_events", 0)
        aot_loads += c.get("aot_program_loads", 0)
    replica.close()
    if own_tmp is not None:
        own_tmp.cleanup()
    summary["phases"]["aot"] = {
        "exported_programs": n_exported,
        "replica_compile_events": compiles,
        "replica_aot_loads": aot_loads,
        "first_requests_bit_equal": first_ok,
    }
    aot_ok = compiles == 0 and aot_loads > 0 and first_ok

    # --------------------------------------------------------- lowprec
    lp = lgb.Fleet(max_batch_rows=max_batch_rows)
    lp.add_model("full", boosters[0])
    deltas = {}
    for prec in ("bf16", "int8"):
        e = lp.add_model(f"{prec}", boosters[0], precision=prec,
                         accuracy_budget=accuracy_budget)
        deltas[prec] = e.server.metrics.gauge(
            "lowprec_accuracy_delta").value
    X = np.random.RandomState(11).randn(64, features) \
        .astype(np.float32).astype(np.float64)
    ref = boosters[0].predict(X, raw_score=True)
    default_bit_equal = np.array_equal(lp.predict("full", X, timeout=60),
                                       ref)
    lp_served = {p: float(np.max(np.abs(
        lp.predict(p, X, timeout=60) - ref))) for p in ("bf16", "int8")}
    try:
        lp.add_model("int8_zero_budget", boosters[0], precision="int8",
                     accuracy_budget=0.0)
        quarantined = False
    except LowPrecisionQuarantined:
        quarantined = True
    lp.close()
    summary["phases"]["lowprec"] = {
        "accuracy_budget": accuracy_budget,
        "probe_delta": {k: round(float(v), 6) for k, v in deltas.items()},
        "served_delta_vs_full": {k: round(v, 6)
                                 for k, v in lp_served.items()},
        "default_bit_equal": default_bit_equal,
        "zero_budget_quarantined": quarantined,
    }
    lowprec_ok = (default_bit_equal and quarantined
                  and all(d <= accuracy_budget for d in deltas.values()))

    summary["failed"] = not (serve_ok and evict_ok and aot_ok
                             and lowprec_ok)
    summary["phase_ok"] = {"serve": serve_ok, "evict": evict_ok,
                           "aot": aot_ok, "lowprec": lowprec_ok}
    return summary


def run_failover_smoke(devices=3, n_models=2, rows=3000, trees=10,
                       features=10, leaves=15, requests=600, threads=6,
                       max_request_rows=60, max_batch_rows=128,
                       kill_after_s=0.2, availability_floor=0.999) -> dict:
    """Kill-one-device-under-load drill (module docstring ``failover``
    phase).  Returns the JSON-ready summary; ``failed`` True when any
    acceptance bar was missed."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.fleet.router import RouterConfig
    from lightgbm_tpu.resilience.faults import ChaosRegistry
    from lightgbm_tpu.serving.loadgen import fire_fleet_requests

    if devices < 2:
        raise ValueError("failover drill needs --devices >= 2")
    boosters = _train_models(n_models, rows, trees, features, leaves)
    verify = _verify_forests(boosters)
    weights = {f"m{i}": float(n_models - i) for i in range(n_models)}

    chaos = ChaosRegistry()
    pod = lgb.PodFleet(
        devices=devices, chaos=chaos, max_batch_rows=max_batch_rows,
        router=RouterConfig(stale_beat_s=1.0, dead_strikes=2,
                            health_interval_s=0.2))
    # generous deadlines: the drill measures availability under device
    # loss, not queue aging (deadline classes have their own tests)
    for cls in list(pod.deadline_classes):
        pod.deadline_classes[cls] = 60_000.0
    for i, b in enumerate(boosters):
        pod.add_model(f"m{i}", b, weight=weights[f"m{i}"])
    pod.warm()
    victim = pod.topology.replicas["m0"][0]
    lost_before = pod.metrics.counter("fleet_devices_lost_total").value

    import threading
    import time as _time

    def killer():
        _time.sleep(kill_after_s)
        chaos.down_device(victim, "vanish")

    threading.Thread(target=killer, daemon=True).start()
    storm = fire_fleet_requests(pod, weights, requests, threads,
                                max_request_rows, verify=verify,
                                timeout=120)
    # let the health sweep finish declaring/draining the victim even if
    # the storm outran it
    deadline = _time.monotonic() + 10.0
    while _time.monotonic() < deadline and \
            pod.metrics.counter("fleet_devices_lost_total").value \
            <= lost_before:
        _time.sleep(0.1)
    _time.sleep(0.3)        # drain thread: replan + recovery gauge
    recovered = pod.metrics.gauge("fleet_recovered_one_tick").value
    live = pod.live_devices()
    replicas = ({n: list(ids)
                 for n, ids in pod.topology.replicas.items()}
                if pod.topology else {})
    summary = {
        "devices": devices,
        "victim_device": victim,
        "requests": storm["requests"],
        "requests_planned": storm["requests_planned"],
        "outcomes": storm["outcomes"],
        "availability": storm["availability"],
        "mismatches": storm["mismatches"],
        "failures": storm["failures"][:5],
        "errors": storm["errors"],
        "wall_seconds": round(storm["wall_seconds"], 3),
        "devices_lost": pod.metrics.counter(
            "fleet_devices_lost_total").value - lost_before,
        "recovered_within_one_tick": bool(recovered),
        "live_devices": live,
        "replicas_after": replicas,
        "hedges": sum(
            pod.metrics.counter("fleet_hedges_total",
                                labels={"model": n}).value
            for n in weights),
    }
    pod.close(drain=False, timeout=2.0)
    summary["failed"] = not (
        storm["failed"] == 0 and not storm["errors"]
        and storm["mismatches"] == 0
        and (storm["availability"] or 0.0) >= availability_floor
        and summary["devices_lost"] == 1
        and summary["recovered_within_one_tick"]
        and victim not in live
        and all(len(ids) >= 1 for ids in replicas.values()))
    return summary


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", type=int, default=3)
    ap.add_argument("--requests", type=int, default=240)
    ap.add_argument("--threads", type=int, default=6)
    ap.add_argument("--rows", type=int, default=3000,
                    help="training rows per synthetic booster")
    ap.add_argument("--trees", type=int, default=10)
    ap.add_argument("--features", type=int, default=10)
    ap.add_argument("--max-request-rows", type=int, default=200)
    ap.add_argument("--max-batch-rows", type=int, default=256)
    ap.add_argument("--accuracy-budget", type=float, default=0.5)
    ap.add_argument("--aot-dir", default=None,
                    help="AOT store dir (default: a temp dir)")
    ap.add_argument("--devices", type=int, default=1,
                    help=">= 2 adds the kill-one-device failover phase "
                         "(a replicated PodFleet under chaos)")
    args = ap.parse_args()

    print(f"[fleet_smoke] {args.models} models, {args.requests} requests "
          f"from {args.threads} threads", flush=True)
    summary = run_smoke(
        n_models=args.models, rows=args.rows, trees=args.trees,
        features=args.features, requests=args.requests,
        threads=args.threads, max_request_rows=args.max_request_rows,
        max_batch_rows=args.max_batch_rows,
        accuracy_budget=args.accuracy_budget, aot_dir=args.aot_dir)
    if args.devices >= 2:
        print(f"[fleet_smoke] failover drill over {args.devices} "
              "simulated devices", flush=True)
        fo = run_failover_smoke(
            devices=args.devices, n_models=min(args.models, 2),
            rows=args.rows, trees=args.trees, features=args.features,
            requests=args.requests, threads=args.threads,
            max_batch_rows=args.max_batch_rows)
        summary["phases"]["failover"] = fo
        summary["phase_ok"]["failover"] = not fo["failed"]
        summary["failed"] = summary["failed"] or fo["failed"]
    print(json.dumps(summary, indent=1, sort_keys=True))
    return 1 if summary["failed"] else 0


if __name__ == "__main__":
    sys.exit(main())
