"""Compile-hang bisect for the rounds-grower training program (one-process
TPU session, single-tenant doctrine).

Round-5 evidence: the 13b30f3-era program (exact rounds, unfused gathers,
no small-round branch) compiled on the chip in 40 s; the current default
program (relaxed growth + small-round lax.cond + fused u32 gather) blocked
the remote compile service for >25 min.  This script inits once, then
tries variants from smallest program to full default, each compile in a
worker thread with a patience cap — if a compile hangs, the thread is
abandoned (the service may still accept the next program; if it queues,
later attempts just time out too and the session exits with what's
banked).

Variants (env gates read at trace time):
  v_exact_nosmall_nopack  ~ proven 13b30f3 program
  v_exact_nosmall_pack    + fused u32 gather
  v_fast_nosmall_pack     + relaxed growth
  v_fast_small_pack       full current default (adds the small-round cond)

Usage: python tools/tpu_bisect.py out.json [n_rows]
"""
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.utils.platform import _cache_dir  # noqa: E402

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

OUT = sys.argv[1] if len(sys.argv) > 1 else os.path.join(REPO, "tpu_bisect.json")
NROWS = int(sys.argv[2]) if len(sys.argv) > 2 else 1_000_000
PATIENCE = float(os.environ.get("BISECT_PATIENCE", 480))
T0 = time.time()
DATA = {"started_utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
        "n_rows": NROWS, "stages": []}


def bank(stage, **kw):
    kw["stage"] = stage
    kw["t_elapsed"] = round(time.time() - T0, 1)
    DATA["stages"].append(kw)
    tmp = OUT + ".tmp"
    # manual tmp+os.replace below; stdlib-only probe must stay
    # importable before jax/package init
    with open(tmp, "w") as f:  # tpulint: disable=atomic-write
        json.dump(DATA, f, indent=1, default=str)
    os.replace(tmp, OUT)
    print(f"[bisect] {stage}: {json.dumps(kw, default=str)[:400]}", flush=True)


# crosses bench.COMPILE_VARIANT_ENVS (the single-source env ladder) with
# the growth mode; ordered smallest program -> full default
def _variants():
    import bench
    envs = list(reversed(bench.COMPILE_VARIANT_ENVS))   # smallest first
    out = []
    for growth in ("rounds", "fast"):
        for i, env in enumerate(envs):
            if growth == "rounds" and i == len(envs) - 1:
                continue   # exact + full default ~ covered by fast runs
            full = {"LGBM_TPU_SMALL_ROUNDS": "1", "LGBM_TPU_PACK": "1"}
            full.update(env)
            out.append((f"v_{growth}_{i}", full, growth))
    return out


def main():
    t = time.time()
    try:
        import jax
        devs = jax.devices()
        import jax.numpy as jnp
        jnp.ones((8, 8)).sum().block_until_ready()
    except Exception as e:
        bank("init", error=str(e)[-600:])
        return 3
    d = devs[0]
    bank("init", seconds=round(time.time() - t, 1), platform=d.platform,
         kind=getattr(d, "device_kind", ""))
    if d.platform == "cpu":
        bank("abort", reason="cpu backend")
        return 3

    import numpy as np

    import bench
    import lightgbm_tpu as lgb

    X, y = bench.make_higgs_like(NROWS, bench.F)

    for name, env, growth in _variants():
        os.environ.update(env)
        params = {"objective": "binary", "num_leaves": 255,
                  "learning_rate": 0.1, "max_bin": 63, "metric": "None",
                  "verbosity": -1, "tpu_tree_growth": growth}
        result = {}
        done = threading.Event()

        def attempt(params=params, result=result, done=done):
            try:
                ds = lgb.Dataset(X, label=y, params=params)
                ds.construct()
                bst = lgb.Booster(params=params, train_set=ds)
                t0 = time.perf_counter()
                bst.update()
                bench.dsync(bst.boosting.train_score)
                result["compile_s"] = round(time.perf_counter() - t0, 1)
                t0 = time.perf_counter()
                for _ in range(10):
                    bst.update()
                bench.dsync(bst.boosting.train_score)
                result["sec_per_tree"] = round(
                    (time.perf_counter() - t0) / 10, 4)
            except Exception as e:
                result["error"] = str(e)[-600:]
            finally:
                done.set()

        th = threading.Thread(target=attempt, daemon=True)
        th.start()
        if not done.wait(PATIENCE):
            bank(name, hung=True, patience_s=PATIENCE)
            # abandoned thread keeps its RPC; try the next program anyway
            continue
        bank(name, **result)
        # first healthy variant is enough signal; keep going only if it
        # failed so the table shows where the wall is
        if "sec_per_tree" in result and os.environ.get(
                "BISECT_ALL") != "1":
            break

    bank("done", total_seconds=round(time.time() - T0, 1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
