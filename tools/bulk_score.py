#!/usr/bin/env python
"""Bulk offline scoring smoke + bench: blockstore -> scores, with a
crash-resume drill.

Builds a synthetic float32 feature BlockStore (streamed to disk in
chunks — the matrix never lives in RAM whole), trains a small booster,
and drives ``data/score.BulkScorer`` through it twice:

1. **full run** into sink A — the throughput number
   (``bulk_rows_per_sec_per_device``) plus predicted-vs-measured peaks
   on both memories and the AOT program source ("aot" on the second
   ever run of a digest, the compile-free resume story);
2. **crash drill** into sink B — score only the first third of the
   blocks (``max_blocks``, the clean stand-in for a SIGKILL between
   manifest commits), then resume with a FRESH scorer; the resumed run
   must skip exactly the banked blocks, and every block file in sink B
   must be byte-identical to sink A's (``cmp``-level equality of the
   score bytes — the resume acceptance bar).

Off-accelerator the row count is capped (interpret-mode fused kernels
and a single host core make 10M rows pointless); the accelerator bench
worker runs the real >= 10M-row shape via ``BENCH_BULK_ROWS``.

The LAST stdout line is a single JSON object so bench.py's worker can
bank it as a stage (``stage: bulk_score``; ``BENCH_SKIP_BULK_SCORE=1``
skips the stage).

Usage:
    JAX_PLATFORMS=cpu python tools/bulk_score.py \
        [--rows 10000000] [--features 12] [--block-rows 65536] \
        [--leaves 31] [--rounds 12] [--keep DIR]
"""

import argparse
import filecmp
import json
import os
import shutil
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CPU_ROWS_CAP = 200_000


def _build_feature_store(path, rows, features, block_rows, seed=0):
    """Stream a synthetic [rows, F] float32 matrix into a BlockStore in
    block-sized chunks — bounded RSS regardless of ``rows``."""
    from lightgbm_tpu.data.blockstore import BlockStore

    rng = np.random.RandomState(seed)
    st = BlockStore.create(path, rows, features, np.float32, block_rows)
    done = 0
    while done < rows:
        r = min(block_rows, rows - done)
        chunk = rng.randn(r, features).astype(np.float32)
        chunk[:, 0] = rng.randint(0, 8, size=r)        # categorical
        chunk[rng.rand(r) < 0.1, 2] = np.nan           # missing routing
        st.append_rows(chunk)
        done += r
    return st.finalize()


def _train_booster(features, leaves, rounds, seed=0, train_rows=4000):
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(seed)
    X = rng.randn(train_rows, features).astype(np.float32).astype(np.float64)
    X[:, 0] = rng.randint(0, 8, size=train_rows)
    y = (X[:, 1] + X[:, 3] * X[:, 4] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "verbosity": -1, "num_leaves": leaves},
        lgb.Dataset(X, label=y, categorical_feature=[0]),
        num_boost_round=rounds, verbose_eval=False)
    return bst._forest(0, len(bst.models) // bst.num_tree_per_iteration)


def _sink_files(path):
    return sorted(n for n in os.listdir(path) if n.endswith(".bin"))


def run_bulk(rows=10_000_000, features=12, block_rows=65_536, leaves=31,
             rounds=12, workdir=None) -> dict:
    from lightgbm_tpu.data.score import BulkScorer, ScoreSink
    from lightgbm_tpu.fleet.aot import AOTStore, aot_dir_from_env
    from lightgbm_tpu.ops.histogram import on_accelerator
    from lightgbm_tpu.predict import DeviceForest
    from lightgbm_tpu.serving.registry import forest_digest

    accel = on_accelerator()
    if not accel:
        rows = min(int(rows), CPU_ROWS_CAP)
    rows = max(int(rows), 1)
    block_rows = max(min(int(block_rows), rows), 1)

    own_tmp = workdir is None
    root = workdir or tempfile.mkdtemp(prefix="lgbm_tpu_bulk_")
    os.makedirs(root, exist_ok=True)
    try:
        store = _build_feature_store(
            os.path.join(root, "features"), rows, features, block_rows)
        forest = _train_booster(features, leaves, rounds)
        dev = DeviceForest(forest)
        digest = forest_digest(forest)
        aot_dir = aot_dir_from_env()
        aot_store = AOTStore(aot_dir) if aot_dir else None

        def scorer(sink):
            return BulkScorer(dev, store, os.path.join(root, sink),
                              aot_store=aot_store, digest=digest)

        # ---- full run: the throughput number --------------------------
        stats = scorer("sink_a").run()
        nb = int(store.num_blocks)

        # ---- crash drill: partial run, then resume with a new scorer --
        cut = max(nb // 3, 1)
        partial = scorer("sink_b").run(max_blocks=cut)
        resumed = scorer("sink_b").run()
        sink_b = ScoreSink.open_or_create(
            os.path.join(root, "sink_b"), rows, 1, block_rows, nb, digest)

        files_a = _sink_files(os.path.join(root, "sink_a"))
        files_b = _sink_files(os.path.join(root, "sink_b"))
        byte_identical = files_a == files_b and all(
            filecmp.cmp(os.path.join(root, "sink_a", n),
                        os.path.join(root, "sink_b", n), shallow=False)
            for n in files_a)
        resume_ok = (byte_identical and sink_b.complete
                     and partial["blocks_scored"] == cut
                     and resumed["skipped_blocks"] == cut
                     and resumed["blocks_scored"] == nb - cut)
        if not resume_ok:
            raise RuntimeError(
                "bulk-score crash-resume FAILED: "
                f"byte_identical={byte_identical} "
                f"complete={sink_b.complete} partial={partial} "
                f"resumed={{'skipped': {resumed['skipped_blocks']}, "
                f"'scored': {resumed['blocks_scored']}}}")

        stats.update({
            "accelerator": accel,
            "features": int(features),
            "block_rows": int(block_rows),
            "resume_ok": True,
            "resume_cut_blocks": cut,
            "resume_skipped_blocks": int(resumed["skipped_blocks"]),
            "resume_byte_identical": byte_identical,
            "aot_store": bool(aot_store),
        })
        return stats
    finally:
        if own_tmp:
            shutil.rmtree(root, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--features", type=int, default=12)
    ap.add_argument("--block-rows", type=int, default=65_536)
    ap.add_argument("--leaves", type=int, default=31)
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="work under DIR and keep it (default: temp dir, "
                         "removed)")
    args = ap.parse_args()
    out = run_bulk(args.rows, args.features, args.block_rows, args.leaves,
                   args.rounds, workdir=args.keep)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
