"""tpulint CLI shim — the implementation lives in ``tools/lint/cli.py``
(this file is shadowed by the ``tools.lint`` package for imports, so it
stays a pure filesystem entry point; ``python -m tools.lint`` is the
import-world spelling of the same command).  Usage, output contract and
exit codes: ``python tools/lint.py --help`` / docs/LINTING.md.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
