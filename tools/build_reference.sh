#!/bin/sh
# Build the reference LightGBM (/root/reference) out-of-source and stage the
# Python package with the fresh lib at /tmp/refpkg for tests/test_parity.py.
#
# The reference CMakeLists pins EXECUTABLE/LIBRARY_OUTPUT_PATH to its own
# (read-only-by-policy) source dir (CMakeLists.txt:199-200), so the binaries
# land there during `make` and are immediately moved out.
set -e
BUILD=${1:-/tmp/lgb_build}
PKG=${2:-/tmp/refpkg}
mkdir -p "$BUILD"
cd "$BUILD"
cmake /root/reference -DCMAKE_BUILD_TYPE=Release > cmake.log 2>&1
make -j"$(nproc)" > make.log 2>&1
for f in lightgbm lib_lightgbm.so; do
    [ -f "/root/reference/$f" ] && mv "/root/reference/$f" "$BUILD/$f"
done
mkdir -p "$PKG"
cp -r /root/reference/python-package/lightgbm "$PKG/"
cp "$BUILD/lib_lightgbm.so" "$PKG/lightgbm/"
echo "reference staged: $PKG/lightgbm (CLI: $BUILD/lightgbm)"
