#!/bin/sh
# Build the reference LightGBM out-of-tree and stage the Python package with
# the fresh lib at /tmp/refpkg for tests/test_parity.py.
#
# The reference CMakeLists pins EXECUTABLE/LIBRARY_OUTPUT_PATH to its own
# source dir with a plain SET() (CMakeLists.txt:199-200) which cannot be
# overridden from the command line, and /root/reference is read-only by
# policy — so the source tree is first copied to a scratch dir and built
# there (~2 min).
set -e
SRC=${3:-/tmp/refsrc}
BUILD=${1:-/tmp/lgb_build}
PKG=${2:-/tmp/refpkg}
if [ ! -f "$SRC/.copy_complete" ]; then
    # stage into a temp dir and rename so an interrupted copy can never
    # leave a half-populated cache that later runs mistake for complete
    rm -rf "$SRC" "$SRC.tmp"
    mkdir -p "$SRC.tmp"
    cp -r /root/reference/CMakeLists.txt /root/reference/src \
          /root/reference/include /root/reference/compute \
          /root/reference/python-package /root/reference/VERSION.txt \
          "$SRC.tmp/"
    touch "$SRC.tmp/.copy_complete"
    mv "$SRC.tmp" "$SRC"
fi
mkdir -p "$BUILD"
cd "$BUILD"
cmake "$SRC" -DCMAKE_BUILD_TYPE=Release > cmake.log 2>&1
make -j"$(nproc)" > make.log 2>&1
mkdir -p "$PKG"
cp -r "$SRC/python-package/lightgbm" "$PKG/"
cp "$SRC/lib_lightgbm.so" "$PKG/lightgbm/"
echo "reference staged: $PKG/lightgbm (CLI: $SRC/lightgbm)"
