"""Model-axis sweep micro-bench: aggregate boosting throughput vs B.

One booster's macro-chunk program cannot fill the MXU at small-data
shapes; the batched multi-booster plane (lightgbm_tpu/multi/) stacks B
boosters along a vmapped lane axis of ONE program over ONE shared binned
matrix.  This probe measures exactly that claim: the SAME chunk body is
compiled solo (B=1) and vmapped at B in {2, 4, 8} over heterogeneous
per-lane inputs (learning rates, bagging masks), and the table reports
per-dispatch latency, aggregate boosting iterations/sec and the
compiler-measured MFU per batch width (obs/devprof.measure_program), next
to the planner's lane-chunk verdict (ops.planner.plan_model_batch).

Acceptance (enforced on accelerator backends only — a CPU host has no
idle MXU to fill, so there the table is informational): B=8 aggregate
iters/sec >= 4x B=1.  A missed bar raises, so failed sweep runs are
never journaled (bench.py run_stage contract).

Usage: python tools/sweep_probe.py [--rows N] [--features F] [--reps R]
Prints one JSON object; bench.py wires this as the journaled ``sweep``
stage (BENCH_SKIP_SWEEP=1 skips).
"""

import argparse
import json
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

BATCH_WIDTHS = (1, 2, 4, 8)


def run_probe(rows=200_000, features=28, max_bin=63, leaves=31,
              chunk=8, reps=3, widths=BATCH_WIDTHS) -> dict:
    import jax
    import jax.numpy as jnp

    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.macro import chunk_host_inputs, make_chunk_fn
    from lightgbm_tpu.obs.devprof import measure_program
    from lightgbm_tpu.ops.histogram import on_accelerator
    from lightgbm_tpu.ops.planner import plan_model_batch

    rng = np.random.RandomState(0)
    n, F = int(rows), int(features)
    X = rng.randn(n, F)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.randn(n) > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": int(max_bin)},
                     free_raw_data=False)
    ds.construct()

    device = None
    try:
        device = jax.devices()[0]
    except Exception:
        pass

    widths = tuple(sorted(int(w) for w in widths))
    out = {"rows": n, "features": F, "max_bin": int(max_bin),
           "leaves": int(leaves), "chunk": int(chunk),
           "batch_widths": list(widths)}
    c = int(chunk)
    for B in widths:
        # heterogeneous lanes: per-lane lr + bagging keep the dispatch
        # honest (identical lanes would let XLA CSE the whole batch)
        boosters = [lgb.Booster(
            {"objective": "binary", "num_leaves": int(leaves),
             "max_bin": int(max_bin), "verbosity": -1,
             "deterministic": True,
             "learning_rate": 0.05 + 0.02 * i,
             "bagging_fraction": 0.9 - 0.05 * (i % 4),
             "bagging_freq": 1, "bagging_seed": 7 + i},
            train_set=ds) for i in range(B)]
        bs = [b.boosting for b in boosters]
        for b in bs:
            b.boost_from_average()
        xs_l = [chunk_host_inputs(b, c)[0] for b in bs]
        xs_B = jax.tree_util.tree_map(lambda *a: jnp.stack(a), *xs_l)
        score_B = jnp.stack([b.train_score for b in bs])
        cu_B = jnp.stack([b._cegb_state[0] for b in bs])
        cr_B = jnp.stack([b._cegb_state[1] for b in bs])
        gc, hc = bs[0]._macro_const_grads()
        # measurement twin of multi/batch.py's program, WITHOUT score
        # donation: measure_program re-invokes with the same buffers
        fn_B = jax.jit(jax.vmap(
            make_chunk_fn(bs[0]),
            in_axes=(None, 0, 0, 0, None, 0, None, None, None, None)))
        args = (bs[0].binned, score_B, cu_B, cr_B, np.int32(c), xs_B,
                bs[0]._macro_ctx["label"], bs[0]._macro_ctx["weight"],
                gc, hc)
        m = measure_program(fn_B, args, reps=reps, device=device)
        sec = m["seconds_per_call"]
        out[f"B{B}"] = {
            "seconds_per_dispatch": sec,
            "iters_per_sec": (B * c) / sec if sec > 0 else 0.0,
            "mfu_measured": m.get("mfu"),
            "flops": m.get("flops"),
            "bytes_accessed": m.get("bytes_accessed"),
        }

    cfg = bs[0].grower_cfg
    out["model_batch_plan"] = plan_model_batch(
        b_total=max(widths), rows=bs[0].num_data, features=F,
        num_bins=bs[0].num_bins, num_leaves=int(leaves),
        stacked=False, method=cfg.hist_method,
        round_width=cfg.round_width, tile_rows=cfg.tile_rows).summary()

    b1 = out[f"B{min(widths)}"]["iters_per_sec"]
    bmax = out[f"B{max(widths)}"]["iters_per_sec"]
    out["aggregate_speedup_vs_b1"] = (bmax / b1) if b1 > 0 else 0.0
    out["accel"] = bool(on_accelerator())
    if out["accel"] and 8 in widths and 1 in widths:
        speedup8 = out["B8"]["iters_per_sec"] / out["B1"]["iters_per_sec"]
        if speedup8 < 4.0:
            raise RuntimeError(
                "sweep probe: B=8 aggregate throughput "
                f"{speedup8:.2f}x B=1 — below the 4x acceptance bar; "
                "the model axis is not filling the chip")
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--max-bin", type=int, default=63)
    ap.add_argument("--leaves", type=int, default=31)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument("--reps", type=int, default=3)
    a = ap.parse_args()
    print(json.dumps(run_probe(rows=a.rows, features=a.features,
                               max_bin=a.max_bin, leaves=a.leaves,
                               chunk=a.chunk, reps=a.reps), indent=2))


if __name__ == "__main__":
    main()
