#!/usr/bin/env python
"""Co-residency smoke: loadgen traffic AND continual refresh on the SAME
device set, behind the shared residency ledger — the CLI twin of
tests/test_coresident.py and the bench ``coresident`` stage (bench.py
imports ``run_smoke``).  Stdout ends with one JSON summary object.

Phases (each banks its own sub-dict in the summary):

* ``train``       — train the deployed model, stand up a chaos-armed
  ``PodFleet`` (a scheduled ``device.delay`` window inflates batch
  latency mid-run — the contention shape brownout must catch), lease the
  serving residency out of the ledger.
* ``coresidency`` — drive threaded loadgen traffic through the fleet
  while the ``coresident.Scheduler`` runs refresh rounds on the same
  devices: brownout guards watch every replica's windowed p99 at a
  ceiling well BELOW the serving SLO, the chaos delay window forces at
  least one throttle, and the refreshed model hot-swaps in.

Acceptance bars (``failed`` true when any is missed):
zero non-typed traffic failures; overall request p99 within the serving
SLO; ``model_age_seconds`` drops across the refresh; the brownout
throttle counter moved (training yielded to serving at least once).

Usage:
    JAX_PLATFORMS=cpu python tools/coresident_smoke.py \
        [--rows 4000] [--trees 8] [--refresh-trees 6] [--requests 120]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_data(rng, rows, features):
    X = rng.randn(rows, features).astype(np.float32).astype(np.float64)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    return X, y


def run_smoke(rows=4000, trees=8, refresh_trees=6, features=10,
              leaves=15, requests=120, threads=4, max_request_rows=64,
              slo_ms=2000.0, brownout_ms=30.0, delay_s=0.12,
              directory=None) -> dict:
    """Run both phases; returns the JSON-ready summary dict."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.coresident import CoresidentConfig, Scheduler
    from lightgbm_tpu.fleet import PodFleet
    from lightgbm_tpu.obs.flight import global_flight
    from lightgbm_tpu.obs.watchdog import global_watchdog
    from lightgbm_tpu.ops.planner import ResidencyLedger
    from lightgbm_tpu.resilience.faults import ChaosRegistry, FaultSpec
    from lightgbm_tpu.serving.errors import DeadlineExceeded, QueueFull

    own_tmp = None
    if directory is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="lgbt_coresident_")
        directory = own_tmp.name
    # the chaos delay window DELIBERATELY breaches a brownout guard, so
    # every run dumps a rising-edge bundle — keep it out of the cwd
    prev_flight_dir = global_flight._out_dir
    global_flight._out_dir = directory

    summary = {"rows": rows, "trees": trees, "phases": {}}
    rng = np.random.RandomState(0)
    params = {"objective": "binary", "verbosity": -1,
              "num_leaves": leaves}

    # ----------------------------------------------------------- train
    X, y = _make_data(rng, rows, features)
    base_ds = lgb.Dataset(X, label=y, free_raw_data=False)
    deployed = lgb.train(params, base_ds, trees, verbose_eval=False)

    # a mid-run latency-inflation window on every device: batches 4..23
    # each stall delay_s before SUCCEEDING — contention, not failure
    chaos = ChaosRegistry([
        FaultSpec(site="device", kind="delay", at=i, arg=delay_s)
        for i in range(4, 24)])
    fleet = PodFleet(devices=2, chaos=chaos, max_batch_rows=256)
    fleet.add_model("live", deployed)
    fleet.warm()
    global_watchdog.watch_freshness("live")
    global_watchdog.mark_fresh("live")

    ledger = ResidencyLedger(limit_bytes=1 << 30)
    cfg = CoresidentConfig(brownout_p99_ms=brownout_ms,
                           throttle_delay_s=0.01, recovery_s=0.3,
                           escalate_s=30.0,   # throttle-only smoke
                           poll_interval_s=0.02)
    sched = Scheduler(fleet=fleet, ledger=ledger, config=cfg,
                      workdir=os.path.join(directory, "work"))
    serving_lease = sched.lease_serving_residency()
    guards = sched.guard_fleet()
    summary["phases"]["train"] = {
        "iterations": deployed.current_iteration(),
        "devices": fleet.live_devices(),
        "guards": guards,
        "serving_lease_bytes": (serving_lease.nbytes
                                if serving_lease else 0),
        "ledger": ledger.summary(),
    }

    # ----------------------------------------------------- coresidency
    lat_ms: list = []
    typed: list = []
    untyped: list = []
    stop = threading.Event()

    def worker(tidx):
        r = np.random.RandomState(1000 + tidx)
        per = requests // threads
        for _ in range(per):
            m = int(r.randint(1, max_request_rows + 1))
            Xr = r.randn(m, features).astype(np.float32).astype(np.float64)
            t0 = time.perf_counter()
            try:
                fleet.predict("live", Xr, timeout=120)
                lat_ms.append((time.perf_counter() - t0) * 1e3)
            except (QueueFull, DeadlineExceeded) as e:
                typed.append(type(e).__name__)
            except Exception as e:  # noqa: BLE001 — the bar counts these
                untyped.append(repr(e)[:200])
            if stop.is_set():
                break
            time.sleep(0.002)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    time.sleep(0.3)       # let traffic (and the delay window) ramp first
    age_before = global_watchdog.model_age_s("live")

    Xf, yf = _make_data(rng, rows // 2, features)
    fresh = lgb.Dataset(Xf, label=yf, free_raw_data=False)
    t0 = time.perf_counter()
    booster, stats = sched.refresh("live", fresh, params, refresh_trees,
                                   init_model=deployed)
    refresh_s = time.perf_counter() - t0
    age_after = global_watchdog.model_age_s("live")
    for t in ts:
        t.join(timeout=120)
    stop.set()

    # served output must be the refreshed booster, bit-identical
    probe = X[:128]
    served = fleet.predict("live", probe, timeout=120)
    ref = booster.predict(probe, raw_score=True)
    p99 = (float(np.percentile(np.array(lat_ms), 99))
           if lat_ms else None)
    sstats = sched.stats()
    summary["phases"]["coresidency"] = {
        "requests_ok": len(lat_ms),
        "typed_failures": len(typed),
        "untyped_failures": untyped,
        "p99_ms": round(p99, 2) if p99 is not None else None,
        "slo_ms": slo_ms,
        "throttles": sstats["throttles"],
        "pauses": sstats["pauses"],
        "scheduler_state": sstats["state"],
        "chunk_cap": stats["chunk_cap"],
        "refresh_seconds": round(refresh_s, 3),
        "refreshed_iterations": booster.current_iteration(),
        "served_bit_equal_refreshed": bool(np.array_equal(served, ref)),
        "model_age_before_s": (round(age_before, 3)
                               if age_before is not None else None),
        "model_age_after_s": (round(age_after, 3)
                              if age_after is not None else None),
    }

    sched.close()
    if serving_lease is not None:
        ledger.release(serving_lease)
    fleet.close()
    global_watchdog.unwatch("live")
    summary["phases"]["coresidency"]["flight_dumps"] = sorted(
        d for d in os.listdir(directory) if d.startswith("flight_"))
    global_flight._out_dir = prev_flight_dir
    if own_tmp is not None:
        own_tmp.cleanup()

    phase_ok = {
        "no_untyped_failures": not untyped and len(lat_ms) > 0,
        "p99_within_slo": p99 is not None and p99 <= slo_ms,
        "model_age_dropped": (age_before is not None
                              and age_after is not None
                              and age_after < age_before),
        "throttled": sstats["throttles"] > 0,
        "swap_bit_equal": summary["phases"]["coresidency"]
        ["served_bit_equal_refreshed"],
    }
    summary["phase_ok"] = phase_ok
    summary["failed"] = not all(phase_ok.values())
    return summary


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4000)
    ap.add_argument("--trees", type=int, default=8)
    ap.add_argument("--refresh-trees", type=int, default=6)
    ap.add_argument("--features", type=int, default=10)
    ap.add_argument("--requests", type=int, default=120)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--max-request-rows", type=int, default=64)
    ap.add_argument("--slo-ms", type=float, default=2000.0)
    ap.add_argument("--brownout-ms", type=float, default=30.0)
    ap.add_argument("--dir", default=None,
                    help="work dir (default: a temp dir)")
    args = ap.parse_args()

    print(f"[coresident_smoke] {args.rows} rows, {args.trees}+"
          f"{args.refresh_trees} trees, {args.requests} requests on a "
          "shared device set", flush=True)
    summary = run_smoke(
        rows=args.rows, trees=args.trees,
        refresh_trees=args.refresh_trees, features=args.features,
        requests=args.requests, threads=args.threads,
        max_request_rows=args.max_request_rows, slo_ms=args.slo_ms,
        brownout_ms=args.brownout_ms, directory=args.dir)
    print(json.dumps(summary, indent=1, sort_keys=True))
    return 1 if summary["failed"] else 0


if __name__ == "__main__":
    sys.exit(main())
