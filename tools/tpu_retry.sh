#!/bin/sh
# Keep attempting the single-process TPU measurement session until the
# tunnel yields a backend (wedge cycles block ~25 min then UNAVAILABLE).
# Success = the banked JSON contains the "done" stage (the process exits 0
# even when individual stages bank errors, so the exit code alone is not a
# success signal).
cd /root/repo
i=0
while [ $i -lt ${TPU_RETRY_MAX:-12} ]; do
    i=$((i+1))
    out=/root/repo/tpu_measure_r5_att$i.json
    echo "[tpu_retry] attempt $i $(date -u +%H:%M:%S)"
    python tools/tpu_measure.py "$out"
    rc=$?
    echo "[tpu_retry] attempt $i exited rc=$rc"
    if grep -q '"stage": "done"' "$out" 2>/dev/null; then
        echo "[tpu_retry] attempt $i banked a complete session; stopping"
        break
    fi
    sleep ${TPU_RETRY_SLEEP:-90}
done
