#!/bin/sh
# Keep attempting the single-process TPU measurement session until the
# tunnel yields a backend (wedge cycles block ~25 min then UNAVAILABLE).
cd /root/repo
i=0
while [ $i -lt 12 ]; do
    i=$((i+1))
    echo "[tpu_retry] attempt $i $(date -u +%H:%M:%S)"
    python tools/tpu_measure.py /root/repo/tpu_measure_r5_att$i.json
    rc=$?
    echo "[tpu_retry] attempt $i exited rc=$rc"
    if [ $rc -eq 0 ]; then break; fi
    sleep 90
done
