#!/bin/bash
# Run the REFERENCE package's own python test suite against lightgbm_tpu
# via a module shim (import lightgbm -> lightgbm_tpu).
#
# Status on this image (2026-07-30): test_basic.py 7 passed, 3 failed —
# every failure is the modern-sklearn API break in the OLD tests
# (load_breast_cancer(True) positional / load_boston removed), not a
# package gap.  test_engine.py / test_sklearn.py cannot even import on
# modern sklearn (load_boston).  Re-run after any API-surface change.
set -e
cd "$(dirname "$0")/.."
SHIM_DIR=$(mktemp -d)
cat > "$SHIM_DIR/refshim.py" <<EOF
import sys
sys.path.insert(0, "$(pwd)")
from lightgbm_tpu.utils.platform import force_cpu_inprocess
force_cpu_inprocess(1)
import lightgbm_tpu
sys.modules["lightgbm"] = lightgbm_tpu
EOF
PYTHONPATH="$SHIM_DIR" python -m pytest -p refshim \
    /root/reference/tests/python_package_test/test_basic.py \
    -q -o cache_dir="$SHIM_DIR/.pc" "$@"
