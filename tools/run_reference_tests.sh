#!/bin/bash
# Run the REFERENCE package's own python test suite against lightgbm_tpu
# via a module shim (import lightgbm -> lightgbm_tpu).
#
# Status on this image (2026-07-31, round 5):
#   test_basic.py   7 passed; 3 failures are modern-sklearn API breaks in
#                   the OLD tests (load_breast_cancer(True) positional)
#   test_engine.py  ~45/50 passing.  Remaining failures and why:
#     - data-substitution: sklearn removed load_boston, so the shim below
#       builds a synthetic stand-in; tests asserting exact iteration
#       counts / thresholds measured on REAL boston can miss marginally
#       (test_early_stopping_for_only_first_metric,
#        test_get_split_value_histogram, test_mape_dart)
#     - test_auc_mu: asserts 2-class multiclass AUC trajectory == binary
#       AUC trajectory exactly; ours agree to ~4e-5 (rank-equivalence of
#       softmax-2 vs sigmoid training differs at float level)
#   test_sklearn.py  25/29 passing (estimator-check shim below).  The 4
#   remaining failures, each justified:
#     - test_dart / test_first_metric_only: thresholds / early-stop
#       iteration counts hardcoded from REAL boston; on the synthetic
#       stand-in the REFERENCE ITSELF scores R2 0.32-0.67 vs the asserted
#       0.8 (verified against the locally built reference lib; our dart
#       averages the same quality over seeds)
#     - test_inf_handle: the reference diverges to l2=inf on 1e30-scale
#       labels x 1e10 weights (double-score overflow artifact, asserted
#       as the expected output); we fit the weighted mean exactly at f32
#       resolution and report l2=0 — a deliberate, saner deviation
#     - test_sklearn_integration: runs MODERN sklearn's full check suite
#       (which the reference's own wrapper predates and would fail far
#       earlier).  We pass tags/clone/NotFittedError/validation checks;
#       the first remaining check (all-zero sample_weight must raise)
#       CONTRADICTS reference semantics asserted by test_nan_handle
#       (trains with all-zero weights, expects nan metrics), so it is not
#       satisfiable while staying reference-faithful.
#   test_plotting.py 3/5 passing; the 2 failures call graph.render(),
#       which needs the graphviz `dot` binary this image doesn't ship
#       (the reference package fails identically here).
#
# Re-run after any API-surface change.
set -e
cd "$(dirname "$0")/.."
SHIM_DIR=$(mktemp -d)
cat > "$SHIM_DIR/refshim.py" <<EOF
import sys
sys.path.insert(0, "$(pwd)")
from lightgbm_tpu.utils.platform import force_cpu_inprocess
force_cpu_inprocess(1)
import lightgbm_tpu
sys.modules["lightgbm"] = lightgbm_tpu

# modern-sklearn compatibility for the OLD reference tests
import numpy as _np
import sklearn.datasets as _skd

try:
    _has_boston = hasattr(_skd, "load_boston")
except Exception:          # sklearn raises from __getattr__
    _has_boston = False
if not _has_boston:
    def load_boston(return_X_y=False):
        rng = _np.random.RandomState(42)
        X = rng.rand(506, 13) * 10.0
        w = rng.randn(13) * 0.5
        # centered signal: y in the real-boston range (~5..50, mean ~22)
        y = (X - 5.0) @ w + rng.randn(506) * 0.5 + 22.0
        if return_X_y:
            return X, y
        class _B:  # noqa: N801
            data, target = X, y
        return _B
    _skd.load_boston = load_boston

_OLD_SIGS = {
    "load_breast_cancer": ("return_X_y",),
    "load_iris": ("return_X_y",),
    "load_wine": ("return_X_y",),
    "load_linnerud": ("return_X_y",),
    "load_digits": ("n_class", "return_X_y"),
}

def _positional_ok(orig, argnames):
    def f(*a, **k):
        for name, val in zip(argnames, a):
            k[name] = val
        return orig(**k)
    return f

for _n, _sig in _OLD_SIGS.items():
    if hasattr(_skd, _n):
        setattr(_skd, _n, _positional_ok(getattr(_skd, _n), _sig))

# sklearn >= 1.x renamed the estimator-check internals the OLD
# test_sklearn.py imports: _yield_all_checks(name, est) became
# _yield_all_checks(est, legacy) yielding single-arg checks.  Adapt both
# directions so "for check in _yield_all_checks(name, est): check(name,
# est)" keeps working and check.__name__ still names the check.
# (NOTE: this file is written through an unquoted heredoc - no backticks
# or dollar signs in comments.)
import sklearn.utils.estimator_checks as _est_checks
import inspect as _inspect

_sig = None
try:
    _sig = _inspect.signature(_est_checks._yield_all_checks)
except AttributeError:
    pass
if _sig is None or "legacy" in _sig.parameters:
    _modern_yield = getattr(_est_checks, "_yield_all_checks", None)

    class _CheckAdapter:
        def __init__(self, chk):
            inner = getattr(chk, "func", chk)
            self.__name__ = getattr(inner, "__name__", "check")
            self._chk = chk
            # decide the calling convention UP FRONT from the signature
            # (a try/except TypeError retry would mask genuine TypeErrors
            # raised by the estimator code under test)
            try:
                n_free = len(_inspect.signature(chk).parameters)
            except (TypeError, ValueError):
                n_free = 1
            self._wants_name = n_free >= 2
        def __call__(self, name, est):
            from unittest import SkipTest as _ST
            try:
                if self._wants_name:
                    return self._chk(name, est)
                return self._chk(est)
            except _ST:
                # the OLD test forwards SkipTest to warnings.warn but never
                # imports warnings (latent bug: old checks never skipped);
                # treat an environment-skip as a no-op here instead
                return None

    def _yield_all_checks(name, estimator):
        if _modern_yield is None:
            return
        for chk in _modern_yield(estimator, legacy=True):
            yield _CheckAdapter(chk)

    _est_checks._yield_all_checks = _yield_all_checks
if not hasattr(_est_checks, "SkipTest"):
    from sklearn.exceptions import SkipTestWarning as _stw  # noqa: F401
    from unittest import SkipTest as _SkipTest
    _est_checks.SkipTest = _SkipTest

# old sklearn accepted an estimator CLASS here; modern clone() requires an
# instance
_orig_cpdc = _est_checks.check_parameters_default_constructible

def check_parameters_default_constructible(name, estimator):
    if isinstance(estimator, type):
        estimator = estimator()
    return _orig_cpdc(name, estimator)

_est_checks.check_parameters_default_constructible = (
    check_parameters_default_constructible)
EOF
FILES="${REF_SUITE:-test_basic.py test_engine.py test_sklearn.py test_plotting.py}"
PATHS=""
for f in $FILES; do
    PATHS="$PATHS /root/reference/tests/python_package_test/$f"
done
PYTHONPATH="$SHIM_DIR" python -m pytest -p refshim \
    $PATHS -q -o cache_dir="$SHIM_DIR/.pc" "$@"
