#!/bin/bash
# Run the REFERENCE package's own python test suite against lightgbm_tpu
# via a module shim (import lightgbm -> lightgbm_tpu).
#
# Status on this image (2026-07-30, end of round 4):
#   test_basic.py   7 passed; 3 failures are modern-sklearn API breaks in
#                   the OLD tests (load_breast_cancer(True) positional)
#   test_engine.py  ~45/50 passing.  Remaining failures and why:
#     - data-substitution: sklearn removed load_boston, so the shim below
#       builds a synthetic stand-in; tests asserting exact iteration
#       counts / thresholds measured on REAL boston can miss marginally
#       (test_early_stopping_for_only_first_metric,
#        test_get_split_value_histogram, test_mape_dart)
#     - test_auc_mu: asserts 2-class multiclass AUC trajectory == binary
#       AUC trajectory exactly; ours agree to ~4e-5 (rank-equivalence of
#       softmax-2 vs sigmoid training differs at float level)
#   test_sklearn.py / test_plotting.py cannot even import on modern
#   sklearn (from sklearn.datasets import load_boston at module top).
#
# Re-run after any API-surface change.
set -e
cd "$(dirname "$0")/.."
SHIM_DIR=$(mktemp -d)
cat > "$SHIM_DIR/refshim.py" <<EOF
import sys
sys.path.insert(0, "$(pwd)")
from lightgbm_tpu.utils.platform import force_cpu_inprocess
force_cpu_inprocess(1)
import lightgbm_tpu
sys.modules["lightgbm"] = lightgbm_tpu

# modern-sklearn compatibility for the OLD reference tests
import numpy as _np
import sklearn.datasets as _skd

try:
    _has_boston = hasattr(_skd, "load_boston")
except Exception:          # sklearn raises from __getattr__
    _has_boston = False
if not _has_boston:
    def load_boston(return_X_y=False):
        rng = _np.random.RandomState(42)
        X = rng.rand(506, 13) * 10.0
        w = rng.randn(13) * 0.5
        # centered signal: y in the real-boston range (~5..50, mean ~22)
        y = (X - 5.0) @ w + rng.randn(506) * 0.5 + 22.0
        if return_X_y:
            return X, y
        class _B:  # noqa: N801
            data, target = X, y
        return _B
    _skd.load_boston = load_boston

_OLD_SIGS = {
    "load_breast_cancer": ("return_X_y",),
    "load_iris": ("return_X_y",),
    "load_wine": ("return_X_y",),
    "load_linnerud": ("return_X_y",),
    "load_digits": ("n_class", "return_X_y"),
}

def _positional_ok(orig, argnames):
    def f(*a, **k):
        for name, val in zip(argnames, a):
            k[name] = val
        return orig(**k)
    return f

for _n, _sig in _OLD_SIGS.items():
    if hasattr(_skd, _n):
        setattr(_skd, _n, _positional_ok(getattr(_skd, _n), _sig))
EOF
PYTHONPATH="$SHIM_DIR" python -m pytest -p refshim \
    /root/reference/tests/python_package_test/test_basic.py \
    /root/reference/tests/python_package_test/test_engine.py \
    -q -o cache_dir="$SHIM_DIR/.pc" "$@"
