#!/usr/bin/env python
"""Inference-kernel micro-bench: while vs fori vs fused traversal.

The training kernel war has hist_probe; this is the predict path's
probe.  It trains a small synthetic booster (categorical feature + NaN
column, so the routing recipe is fully exercised), stands up one
``DeviceForest`` per traversal variant, and reports:

- **structural parity**: fori and fused leaf indices bit-identical to
  the while_loop baseline on a mixed batch (zeros / NaN / +-huge rows
  included) — the invariant every other number rests on;
- **serving parity**: the elected forest's ``predict_raw`` bit-equal to
  ``Booster.predict(raw_score=True)`` (the serving acceptance bar);
- **measured utilization** per variant via
  ``obs/devprof.predict_utilization_table`` (compiler-counted
  FLOPs/bytes + wall sec/call -> sec/Mrow, MFU, HBM GB/s);
- **election**: what ``ops/planner.plan_predict`` picks analytically,
  what it picks after the measured timings are banked into the
  autotune store's ``"p-..."`` family (cold vs warm, hit/miss/flip
  counters for bench_diff's election-quality gate);
- ``predict_sec_per_mrow`` (the elected variant) and
  ``speedup_vs_while`` — on accelerators at >= 1M rows the probe FAILS
  (raises) below 3x, the ISSUE 19 acceptance bar; off-accelerator the
  numbers are interpret-mode noise, so rows are capped and only parity
  is enforced.

The LAST stdout line is a single JSON object so bench.py's worker can
bank it as a stage (``stage: predict_probe``;
``BENCH_SKIP_PREDICT_PROBE=1`` skips the stage).

Usage:
    JAX_PLATFORMS=cpu python tools/predict_probe.py \
        [--rows 1000000] [--features 12] [--leaves 31] [--rounds 20] \
        [--reps 3]
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# off-accelerator the fused arm runs in Pallas interpret mode — minutes
# per Mrow, and the timings mean nothing; cap the probe shape there
CPU_ROWS_CAP = 50_000


def _train_booster(rows, features, leaves, rounds, seed=0):
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(seed)
    X = rng.randn(rows, features).astype(np.float32).astype(np.float64)
    X[:, 0] = rng.randint(0, 8, size=rows)          # categorical
    X[rng.rand(rows) < 0.1, 2] = np.nan             # missing routing
    y = (X[:, 1] + X[:, 3] * X[:, 4] > 0).astype(float)
    bst = lgb.train(
        {"objective": "binary", "verbosity": -1, "num_leaves": leaves},
        lgb.Dataset(X, label=y, categorical_feature=[0]),
        num_boost_round=rounds, verbose_eval=False)
    n_iter = len(bst.models) // bst.num_tree_per_iteration
    return bst, bst._forest(0, n_iter), X


def parity_check(forest, X, variants=("while", "fori", "fused")) -> dict:
    """Bit-identical leaf indices across traversal variants on a batch
    salted with the routing edge cases (zeros, NaN rows, +-huge)."""
    import jax.numpy as jnp

    from lightgbm_tpu.predict import DeviceForest

    Xs = np.array(X[:512], np.float64)
    Xs[0, :] = 0.0
    Xs[1, :] = np.nan
    Xs[2, :] = -1e30
    Xs[3, :] = 1e30
    ref = None
    out = {}
    for v in variants:
        dev = DeviceForest(forest, variant=v)
        leaves = np.asarray(dev._leaves_jit(
            jnp.asarray(np.asarray(Xs, np.float32))))
        if ref is None:
            ref = leaves
            out[v] = {"baseline": True}
        else:
            out[v] = {"bit_equal_to_while": bool(np.array_equal(ref, leaves))}
    out["ok"] = all(d.get("bit_equal_to_while", True) for d in out.values()
                    if isinstance(d, dict))
    return out


def autotune_probe(table, rows, features, num_trees, num_class,
                   precision="f32") -> dict:
    """Bank the measured per-variant timings into the planner's
    ``"p-..."`` autotune family and run the election cold and warm —
    the predict twin of hist_probe's --autotune column."""
    from lightgbm_tpu.ops import planner as P

    out = {"enabled": P.autotune_enabled(), "store_dir": P.autotune_dir()}
    if not (P.autotune_enabled() and P.autotune_dir()):
        out["skipped"] = ("no autotune store configured: set "
                          "LGBM_TPU_AUTOTUNE_DIR or LGBM_TPU_COMPILE_CACHE")
        return out
    sec = {v: table[v]["seconds_per_call"] for v in ("while", "fori", "fused")
           if isinstance(table.get(v), dict) and "seconds_per_call" in table[v]}
    if len(sec) < 2:
        out["skipped"] = "fewer than two variants produced timings"
        return out
    P.autotune_counters(reset=True)

    def plan():
        return P.plan_predict(
            num_trees=num_trees, nodes_dim=1, leaves_dim=1,
            features=features, rows=rows, num_class=num_class,
            precision=precision)

    cold = plan()
    for v, s in sec.items():
        P.record_predict_timing(rows, features, num_trees, num_class,
                                precision, v, s)
    warm = plan()
    counters = P.autotune_counters()
    out.update({
        "shape_bucket": warm.autotune_key,
        "cold_variant": cold.variant,
        "cold_elected_by": cold.elected_by,
        "warm_variant": warm.variant,
        "warm_elected_by": warm.elected_by,
        "winner": min(sec, key=sec.get),
        "seconds_per_call": sec,
        "autotune_hits": counters["hits"],
        "autotune_misses": counters["misses"],
        "autotune_flips": counters["flips"],
    })
    return out


def run_probe(rows=1_000_000, features=12, leaves=31, rounds=20,
              reps=3, train_rows=4000) -> dict:
    import jax

    from lightgbm_tpu.obs.devprof import predict_utilization_table
    from lightgbm_tpu.ops.histogram import on_accelerator
    from lightgbm_tpu.predict import DeviceForest

    accel = on_accelerator()
    if not accel:
        rows = min(int(rows), CPU_ROWS_CAP)

    bst, forest, X = _train_booster(train_rows, features, leaves, rounds)
    out = {
        "rows": int(rows), "features": int(features),
        "num_trees": int(forest.num_trees),
        "platform": jax.devices()[0].platform,
        "accelerator": accel,
    }

    # ---- parity first: timings of wrong kernels are worthless ---------
    out["parity"] = parity_check(forest, X)
    if not out["parity"]["ok"]:
        raise RuntimeError(
            f"traversal variant parity FAILED: {out['parity']}")

    # ---- serving bit-parity vs the booster's own raw predict ----------
    dev = DeviceForest(forest)            # planner-elected variant
    out["elected_variant"] = dev.variant
    out["tile_rows"] = dev.tile_rows
    out["chunk_rows"] = dev.chunk_rows
    # predict_raw_padded is the serving entry point (registry programs);
    # predict_raw is the f32 device-accumulation fast path and does NOT
    # carry the bit-parity contract
    raw = dev.predict_raw_padded(X)[0]
    ref = bst.predict(X, raw_score=True)
    out["serving_bit_equal"] = bool(np.array_equal(raw, ref))
    if not out["serving_bit_equal"]:
        raise RuntimeError(
            "elected traversal variant changed Booster.predict("
            "raw_score=True) output — serving parity broken")

    # ---- measured utilization per variant -----------------------------
    table = predict_utilization_table(dev, rows=rows, reps=reps)
    out["utilization"] = table
    mrow = max(rows / 1e6, 1e-9)
    sec_per_mrow = {v: table[v]["seconds_per_call"] / mrow
                    for v in ("while", "fori", "fused")
                    if isinstance(table.get(v), dict)
                    and "seconds_per_call" in table[v]}
    out["sec_per_mrow"] = sec_per_mrow
    elected = dev.variant if dev.variant in sec_per_mrow else "fori"
    if elected in sec_per_mrow and "while" in sec_per_mrow:
        out["predict_sec_per_mrow"] = sec_per_mrow[elected]
        out["speedup_vs_while"] = round(
            sec_per_mrow["while"] / max(sec_per_mrow[elected], 1e-12), 3)
        if accel and rows >= 1_000_000 and out["speedup_vs_while"] < 3.0:
            raise RuntimeError(
                f"elected kernel '{elected}' is only "
                f"{out['speedup_vs_while']}x faster than while_loop at "
                f"{rows} rows — below the 3x acceptance bar")

    # ---- autotune family: banked timings steer the next election ------
    out["autotune"] = autotune_probe(
        table, rows, int(np.asarray(forest.split_feature).max(initial=0)) + 1,
        int(forest.num_trees), 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--features", type=int, default=12)
    ap.add_argument("--leaves", type=int, default=31)
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()
    out = run_probe(args.rows, args.features, args.leaves, args.rounds,
                    args.reps)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
