# Makes tools/ importable so `tools.lint` (the tpulint package) and
# `tools.gen_parameters_doc` resolve from the repo root.  The scripts in
# this directory remain directly runnable (`python tools/<script>.py`).
