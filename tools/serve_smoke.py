#!/usr/bin/env python
"""Serving smoke: train a tiny booster, stand up the in-process server,
fire mixed-shape requests from several threads, print the metrics JSON.

The CLI twin of tests/test_serving.py::test_serving_stress — for eyeballs
and CI logs rather than asserts.  The LAST stdout line is a single JSON
object: throughput, latency percentiles (from the histogram buckets) and
the full serving metrics snapshot (schema: docs/SERVING.md).

Usage:
    JAX_PLATFORMS=cpu python tools/serve_smoke.py \
        [--requests 1000] [--threads 8] [--rows 2000] \
        [--max-batch-rows 512] [--backend device|host] [--model model.txt]

Without --model a 12-round binary booster is trained on synthetic
float32-precise data, and every response is verified bit-equal to
StackedForest.predict_raw (the serving acceptance bar).
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=1000)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--rows", type=int, default=2000,
                    help="training rows for the synthetic booster")
    ap.add_argument("--features", type=int, default=10)
    ap.add_argument("--max-request-rows", type=int, default=700)
    ap.add_argument("--max-batch-rows", type=int, default=512)
    ap.add_argument("--batch-window-ms", type=float, default=2.0)
    ap.add_argument("--backend", default="device",
                    choices=["device", "host"])
    ap.add_argument("--model", default=None,
                    help="model file to serve (skips training + verify)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="run the N-model fleet smoke instead "
                         "(tools/fleet_smoke.py pass-through); 0 = this "
                         "single-model smoke")
    args = ap.parse_args()

    if args.fleet:
        import json as _json

        from fleet_smoke import run_smoke
        summary = run_smoke(n_models=args.fleet, requests=args.requests,
                            threads=args.threads, features=args.features,
                            max_request_rows=min(args.max_request_rows,
                                                 args.max_batch_rows),
                            max_batch_rows=args.max_batch_rows)
        print(_json.dumps(summary, indent=1, sort_keys=True))
        return 1 if summary["failed"] else 0

    import lightgbm_tpu as lgb

    f = args.features
    verify_forest = None
    if args.model:
        booster = lgb.Booster(model_file=args.model)
        f = booster.num_features()
    else:
        rng = np.random.RandomState(0)
        X = rng.randn(args.rows, f).astype(np.float32).astype(np.float64)
        y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
        booster = lgb.train(
            {"objective": "binary", "verbosity": -1, "num_leaves": 31},
            lgb.Dataset(X, label=y), num_boost_round=12, verbose_eval=False)
        n_iter = len(booster.models) // booster.num_tree_per_iteration
        verify_forest = booster._forest(0, n_iter)

    from lightgbm_tpu.serving.loadgen import fire_requests

    server = booster.serve(max_batch_rows=args.max_batch_rows,
                           batch_window_ms=args.batch_window_ms,
                           backend=args.backend)
    print(f"[serve_smoke] firing "
          f"{args.requests // args.threads * args.threads} requests "
          f"from {args.threads} threads (backend={args.backend})",
          flush=True)
    storm = fire_requests(server, args.requests, args.threads,
                          args.max_request_rows, f,
                          verify_forest=verify_forest, timeout=120)
    metrics = server.metrics_dict()
    server.close()

    wall = storm["wall_seconds"]
    failed = bool(storm["mismatches"] or storm["errors"]
                  or storm["requests"] != storm["requests_planned"])
    result = {
        "requests": storm["requests"],
        "requests_planned": storm["requests_planned"],
        "rows": storm["rows"],
        "wall_seconds": round(wall, 3),
        "requests_per_second": round(storm["requests"] / wall, 1),
        "rows_per_second": round(storm["rows"] / wall, 1),
        "bit_equal_verified": (None if verify_forest is None
                               else not failed),
        "mismatches": len(storm["mismatches"]),
        "worker_errors": storm["errors"],
        "metrics": metrics,
    }
    print(json.dumps(result, indent=1, sort_keys=True))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
