#!/usr/bin/env python
"""Chaos smoke: train under a scripted fault schedule and assert recovery.

The CLI twin of tests/test_chaos.py — for eyeballs and CI logs.  Three
phases, each asserting its acceptance bar, with a single JSON summary as
the LAST stdout line (exit 0 only when every phase holds):

1. **collective** — ``distributed_bin_mappers`` over a fake K-rank mesh
   with the ``--schedule`` faults applied to the allgather seam and
   ``resilient_allgather`` wrapping it: every rank must either complete
   with mappers identical to the fault-free run, or (for dead-transport
   schedules) abort with CollectiveError on every rank inside the
   deadline.  It must never hang and never bin from a corrupted payload.
2. **checkpoint** — train with bundle snapshots while the ``fs.*`` part
   of the schedule fires through the chaos:// file system; then resume
   from the surviving bundles and assert the final model is
   BYTE-IDENTICAL to an uninterrupted run.
3. **quarantine** — hot-swap a NaN-poisoned model into a server and
   assert it is rejected by the probe batch.

Schedule syntax (docs/RESILIENCE.md), e.g.::

    python tools/chaos_smoke.py \
        --schedule "allgather.bitflip@0:rank=1,allgather.drop@3:rank=2,fs.partial@4" \
        --world 4 --rounds 12 --snapshot-freq 2 --seed 0
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_SCHEDULE = ("allgather.bitflip@0:rank=1,allgather.truncate@4:rank=2,"
                    "allgather.drop@2:rank=0,fs.partial@4")


def phase_collective(args, summary):
    from lightgbm_tpu.parallel.dist_data import (distributed_bin_mappers,
                                                 make_fake_allgather)
    from lightgbm_tpu.resilience import (ChaosRegistry, CollectiveError,
                                         ResilienceConfig)
    rng = np.random.RandomState(args.seed)
    X = rng.rand(args.rows_per_rank * args.world, 6)
    bounds = np.linspace(0, len(X), args.world + 1).astype(int)
    cfg = ResilienceConfig(deadline_s=args.deadline, max_retries=8,
                           base_backoff_s=0.01, jitter_seed=args.seed)

    def run(chaos):
        fake = make_fake_allgather(args.world, timeout=2.0)
        out, errs = [None] * args.world, [None] * args.world

        def r(k):
            ag = fake(k)
            if chaos is not None:
                ag = chaos.wrap_allgather(ag, k)
            try:
                out[k] = distributed_bin_mappers(
                    X[bounds[k]:bounds[k + 1]], params={}, rank=k,
                    world=args.world, allgather_bytes=ag, resilience=cfg)
            except Exception as e:  # noqa: BLE001
                errs[k] = e
        ts = [threading.Thread(target=r, args=(k,))
              for k in range(args.world)]
        [t.start() for t in ts]
        deadline = time.monotonic() + args.deadline + 60
        for t in ts:
            t.join(max(1.0, deadline - time.monotonic()))
        assert not any(t.is_alive() for t in ts), "HANG: a rank never returned"
        return out, errs

    clean, errs = run(None)
    assert not any(errs), f"fault-free run failed: {errs}"
    chaos = ChaosRegistry(args.schedule, seed=args.seed)
    t0 = time.monotonic()
    out, errs = run(chaos)
    elapsed = time.monotonic() - t0
    if any(errs):
        assert all(isinstance(e, CollectiveError) for e in errs), \
            f"INCONSISTENT abort: {errs}"
        assert elapsed < args.deadline + 30, "abort not deadline-bounded"
        summary["collective"] = {"outcome": "consistent_abort",
                                 "elapsed_s": round(elapsed, 2)}
    else:
        for k in range(args.world):
            for m, n in zip(out[k][0], clean[0][0]):
                assert m.num_bin == n.num_bin and np.array_equal(
                    m.bin_upper_bound, n.bin_upper_bound), \
                    f"rank {k} binned from a corrupted payload"
        summary["collective"] = {"outcome": "recovered",
                                 "faults_fired": chaos.log,
                                 "elapsed_s": round(elapsed, 2)}


def phase_checkpoint(args, summary, workdir):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.resilience import ChaosRegistry
    rng = np.random.RandomState(args.seed)
    X = rng.rand(600, 8)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float32)
    P = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "bagging_fraction": 0.8, "bagging_freq": 1, "min_data_in_leaf": 5}

    full = lgb.train(P, lgb.Dataset(X, label=y), args.rounds,
                     verbose_eval=False)
    full.save_model(f"{workdir}/full.txt")

    chaos = ChaosRegistry(args.schedule, seed=args.seed)
    chaos.install_filesystem("chaos")
    died_at = max(args.snapshot_freq, args.rounds * 2 // 3)
    try:
        lgb.train(P, lgb.Dataset(X, label=y), died_at, verbose_eval=False,
                  snapshot_freq=args.snapshot_freq,
                  snapshot_out=f"chaos://{workdir}/m.txt")
    except OSError as e:
        # an injected ENOSPC/transient killed the run mid-snapshot —
        # exactly the crash being simulated; resume from what survived
        summary.setdefault("checkpoint_notes", []).append(
            f"train died on injected fault: {e}")
    finally:
        chaos.uninstall_filesystem()

    res = lgb.train(P, lgb.Dataset(X, label=y), args.rounds,
                    verbose_eval=False,
                    resume_from=f"{workdir}/m.txt.ckpt")
    res.save_model(f"{workdir}/res.txt")
    a = open(f"{workdir}/full.txt", "rb").read()
    b = open(f"{workdir}/res.txt", "rb").read()
    assert a == b, "resumed model is NOT byte-identical to uninterrupted run"
    summary["checkpoint"] = {"outcome": "bit_identical_resume",
                             "fs_faults_fired": [f for f in chaos.log
                                                 if f.startswith("fs")],
                             "model_bytes": len(a)}


def phase_quarantine(args, summary):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import SwapQuarantined
    rng = np.random.RandomState(args.seed)
    X = rng.rand(400, 6)
    y = (X[:, 0] > 0.5).astype(np.float32)
    P = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "min_data_in_leaf": 5}
    good = lgb.train(P, lgb.Dataset(X, label=y), 4, verbose_eval=False)
    bad = lgb.train(P, lgb.Dataset(X, label=y), 4, verbose_eval=False)
    bad.boosting.models[0].leaf_value[:] = np.nan
    srv = good.serve(backend="host")
    try:
        srv.predict(X[:8])
        gen = srv.metrics.gauge("model_generation").value
        try:
            srv.swap_model(bad)
            raise AssertionError("poisoned swap was PROMOTED")
        except SwapQuarantined:
            pass
        assert srv.metrics.gauge("model_generation").value == gen
        summary["quarantine"] = {
            "outcome": "rejected_at_probe",
            "swap_quarantines":
                srv.metrics.counter("swap_quarantines").value}
    finally:
        srv.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--schedule", default=DEFAULT_SCHEDULE,
                    help="fault schedule (docs/RESILIENCE.md syntax)")
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--snapshot-freq", type=int, default=2)
    ap.add_argument("--rows-per-rank", type=int, default=500)
    ap.add_argument("--deadline", type=float, default=20.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    args = ap.parse_args()

    from lightgbm_tpu.utils.platform import force_cpu_inprocess
    force_cpu_inprocess(1)

    import tempfile
    summary = {"schedule": args.schedule, "ok": False}
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory() as td:
        workdir = args.workdir or td
        phase_collective(args, summary)
        phase_checkpoint(args, summary, workdir)
        phase_quarantine(args, summary)
    summary["ok"] = True
    summary["elapsed_s"] = round(time.monotonic() - t0, 2)
    print(json.dumps(summary, indent=1))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except AssertionError as e:
        print(json.dumps({"ok": False, "assertion": str(e)}))
        sys.exit(1)
