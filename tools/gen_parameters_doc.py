"""Generate docs/Parameters.rst from the Config dataclass + alias table.

reference: helpers/parameter_generator.py generates config_auto.cpp AND
docs/Parameters.rst from structured comments in config.h so the alias map
and the user docs can never drift from the source of truth.  Here the
source of truth is the ``Config`` dataclass and ``_ALIASES`` dict in
``lightgbm_tpu/config.py``; this script derives the docs (and the
section structure from the ``# section`` comments) from them.

Run:  python tools/gen_parameters_doc.py          # rewrite docs/Parameters.rst
      python tools/gen_parameters_doc.py --check  # exit 1 if docs are stale
                                                  # (tests/test_api_surface.py
                                                  # runs this in CI)
"""
import dataclasses
import io
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.config import _ALIASES, Config  # noqa: E402

OUT = os.path.join(REPO, "docs", "Parameters.rst")


def _sections():
    """(field name -> section title) from the explicit ``# section: <name>``
    sentinels that structure the dataclass body — explicit, so an ordinary
    short comment can never silently spawn a garbage doc section."""
    src = open(os.path.join(REPO, "lightgbm_tpu", "config.py")).read()
    body = src.split("class Config:", 1)[1]
    section = "Core Parameters"
    out = {}
    for line in body.splitlines():
        m = re.match(r"\s*#\s*section:\s*(.+?)\s*$", line)
        if m:
            section = m.group(1).strip().title() + " Parameters"
            continue
        f = re.match(r"\s{4}(\w+)\s*:\s*\w", line)
        if f:
            out[f.group(1)] = section
    return out


def generate() -> str:
    fields = dataclasses.fields(Config)
    sec_of = _sections()
    aliases_of = {}
    for alias, canon in _ALIASES.items():
        if alias != canon:
            aliases_of.setdefault(canon, []).append(alias)

    buf = io.StringIO()
    w = buf.write
    w("Parameters\n==========\n\n")
    w("Generated from ``lightgbm_tpu/config.py`` by "
      "``tools/gen_parameters_doc.py`` — do not edit by hand.\n"
      "The reference analogue is ``docs/Parameters.rst`` generated from "
      "``config.h`` by ``helpers/parameter_generator.py``.\n\n")
    current = None
    for f in fields:
        sec = sec_of.get(f.name, "Other Parameters")
        if sec != current:
            w(f"\n{sec}\n{'-' * len(sec)}\n\n")
            current = sec
        default = f.default
        if default is dataclasses.MISSING:
            default = (f.default_factory()
                       if f.default_factory is not dataclasses.MISSING
                       else "")
        typename = getattr(f.type, "__name__", str(f.type))
        w(f"- ``{f.name}``: {typename}, default ``{default!r}``")
        al = aliases_of.get(f.name)
        if al:
            w(f", aliases: {', '.join('``%s``' % a for a in sorted(al))}")
        w("\n")
    return buf.getvalue()


def main():
    out_path = OUT
    if "--out" in sys.argv:
        i = sys.argv.index("--out")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
            print("--out requires a path argument", file=sys.stderr)
            return 2
        out_path = sys.argv[i + 1]
    text = generate()
    if "--check" in sys.argv:
        on_disk = open(out_path).read() if os.path.exists(out_path) else ""
        # name the missing fields FIRST: "stale" alone sends people
        # diffing; a missing config key (the usual drift: a field added
        # without regenerating) should fail by name
        missing = [f.name for f in dataclasses.fields(Config)
                   if f"``{f.name}``" not in on_disk]
        if missing:
            print(f"{out_path} is missing Config fields: "
                  f"{', '.join(missing)}; regenerate with "
                  "python tools/gen_parameters_doc.py", file=sys.stderr)
            return 1
        if on_disk != text:
            print(f"{out_path} is stale: regenerate with "
                  "python tools/gen_parameters_doc.py", file=sys.stderr)
            return 1
        print(f"{out_path} is current")
        return 0
    with open(out_path, "w") as fh:
        fh.write(text)
    print(f"wrote {out_path} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
