"""Generate docs/Parameters.rst from the Config dataclass + alias table.

Thin shim: the implementation lives in ``tools/lint/params_doc.py`` so
tpulint's ``docs-sync`` rule and this standalone entrypoint share ONE
generator/checker (the reference analogue is
helpers/parameter_generator.py generating Parameters.rst from config.h).
CLI contract unchanged:

Run:  python tools/gen_parameters_doc.py          # rewrite docs/Parameters.rst
      python tools/gen_parameters_doc.py --check  # exit 1 if docs are stale
                                                  # (tests/test_api_surface.py
                                                  # runs this in CI)
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.lint import params_doc  # noqa: E402


def main():
    out_path = params_doc.OUT
    if "--out" in sys.argv:
        i = sys.argv.index("--out")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("--"):
            print("--out requires a path argument", file=sys.stderr)
            return 2
        out_path = sys.argv[i + 1]
    if "--check" in sys.argv:
        code, messages = params_doc.check(out_path)
        for m in messages:
            print(m, file=sys.stderr if code else sys.stdout)
        return code
    text = params_doc.generate()
    from lightgbm_tpu.utils.file_io import write_atomic
    write_atomic(out_path, text)
    print(f"wrote {out_path} ({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
