#!/usr/bin/env python
"""Dispatch-overhead micro-bench: per-iteration dispatch cost vs. fused
macro-step throughput (boosting/macro.py).

Measures, on the live backend:

- ``dispatch_ms``: the fixed cost of launching a trivial jitted program
  (the floor every per-iteration training round pays from Python);
- ``per_iter``: iters/sec training one jitted program per boosting round
  (``LGBM_TPU_CHUNK=0`` legacy path semantics, via ``update_chunk(1)``
  so the compiled loop body is identical and only the DISPATCH COUNT
  differs);
- ``fused[c]``: iters/sec with ``update_chunk(c)`` for each chunk size
  in the ladder — same trees, 1/c as many dispatches.

The LAST stdout line is a single JSON object so bench.py's worker can
bank it as a stage (``stage: dispatch_probe``); the probe-backed test in
tests/test_macro.py is registered under the ``perf`` pytest marker.

Usage:
    JAX_PLATFORMS=cpu python tools/dispatch_probe.py \
        [--rows 100000] [--features 28] [--leaves 63] [--iters 24] \
        [--chunks 8,16,32]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_dispatch_ms(reps: int = 50) -> float:
    """Fixed per-program dispatch cost: a trivial donated jitted program
    on a tiny buffer, timed end-to-end including the host round-trip."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros((8,), jnp.float32)
    f(x).block_until_ready()            # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        f(x).block_until_ready()
    return (time.perf_counter() - t0) / reps * 1e3


def run_probe(rows=100_000, features=28, leaves=63, iters=24,
              chunks=(8, 16, 32), max_bin=63) -> dict:
    import jax

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(0)
    X = rng.randn(rows, features).astype(np.float32).astype(np.float64)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": leaves,
              "max_bin": max_bin, "learning_rate": 0.1, "verbosity": -1}
    train_set = lgb.Dataset(X, label=y, params=params, free_raw_data=False)
    train_set.construct()
    del X

    def sync(b):
        jax.block_until_ready(b.boosting.train_score)

    out = {
        "rows": rows, "features": features, "leaves": leaves,
        "iters_per_mode": iters,
        "platform": jax.devices()[0].platform,
        "dispatch_ms": round(measure_dispatch_ms(), 3),
    }

    # per-iteration path: one dispatch per boosting round (same compiled
    # loop body as the fused path — only the dispatch count differs)
    booster = lgb.Booster(params=params, train_set=train_set)
    booster.update()                    # compile outside the clock
    sync(booster)
    t0 = time.perf_counter()
    for _ in range(iters):
        booster.update()
    sync(booster)
    per_iter_s = time.perf_counter() - t0
    out["per_iter"] = {"iters_per_sec": round(iters / per_iter_s, 2),
                       "ms_per_iter": round(per_iter_s / iters * 1e3, 2)}

    # fused macro-steps: whole chunks only, so exactly one program shape
    # compiles (outside the clock) and the timed loop is pure dispatch+run
    out["fused"] = {}
    for c in chunks:
        c = min(c, iters)
        n_chunks = max(iters // c, 1)
        fused_iters = n_chunks * c
        booster = lgb.Booster(params=params, train_set=train_set)
        booster.update_chunk(c)                # compile outside the clock
        sync(booster)
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            booster.update_chunk(c)
        sync(booster)
        fused_s = time.perf_counter() - t0
        ms_per_iter = fused_s / fused_iters * 1e3
        out["fused"][str(c)] = {
            "iters": fused_iters,
            "iters_per_sec": round(fused_iters / fused_s, 2),
            "ms_per_iter": round(ms_per_iter, 2),
            "speedup_vs_per_iter": round(
                (per_iter_s / iters * 1e3) / ms_per_iter, 3),
        }
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--features", type=int, default=28)
    ap.add_argument("--leaves", type=int, default=63)
    ap.add_argument("--max-bin", type=int, default=63)
    ap.add_argument("--iters", type=int, default=24)
    ap.add_argument("--chunks", default="8,16,32")
    args = ap.parse_args()
    chunks = tuple(int(c) for c in args.chunks.split(",") if c)
    out = run_probe(args.rows, args.features, args.leaves, args.iters,
                    chunks, args.max_bin)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
