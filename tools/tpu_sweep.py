"""Grower-parameter sweep on the live chip: one process, shared dataset.

Times tpu_tree_growth x tpu_round_width at 1M x 28 (HIGGS shape) plus
chained-primitive costs, banking results per stage (single-tenant tunnel
doctrine, docs/PERFORMANCE.md).

Run ALONE:  python tools/tpu_sweep.py out.json
"""
import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.utils.platform import _cache_dir  # noqa: E402

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir())
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.2")

OUT = sys.argv[1] if len(sys.argv) > 1 else os.path.join(REPO, "tpu_sweep.json")
T0 = time.time()
DATA = {"started_utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
        "stages": []}
N = int(os.environ.get("SWEEP_ROWS", 1_000_000))
TREES = int(os.environ.get("SWEEP_TREES", 12))


def bank(stage, **kw):
    kw["stage"] = stage
    kw["t_elapsed"] = round(time.time() - T0, 1)
    DATA["stages"].append(kw)
    tmp = OUT + ".tmp"
    # manual tmp+os.replace below; stdlib-only probe must stay
    # importable before jax/package init
    with open(tmp, "w") as f:  # tpulint: disable=atomic-write
        json.dump(DATA, f, indent=1, default=str)
    os.replace(tmp, OUT)
    print(f"[sweep] {stage}: {json.dumps(kw, default=str)[:400]}", flush=True)


def main():
    t = time.time()
    try:
        import jax
        d = jax.devices()[0]
        import jax.numpy as jnp
        jnp.ones((8, 8)).sum().block_until_ready()
    except Exception as e:
        bank("init", error=str(e)[-600:])
        return 3
    bank("init", seconds=round(time.time() - t, 1), platform=d.platform)
    if d.platform == "cpu":
        bank("abort", reason="cpu backend")
        return 3

    import numpy as np

    import bench
    import lightgbm_tpu as lgb
    from bench import dsync

    X, y = bench.make_higgs_like(N, 28)
    base = {"objective": "binary", "num_leaves": 255, "learning_rate": 0.1,
            "max_bin": 63, "metric": "None", "verbosity": -1}
    ds = lgb.Dataset(X, label=y, params=base)
    t1 = time.time()
    ds.construct()
    bank("binning", seconds=round(time.time() - t1, 1))
    del X

    configs = [
        ("strict_128", {"tpu_tree_growth": "rounds", "tpu_round_width": 128}),
        ("fast_128", {"tpu_tree_growth": "fast", "tpu_round_width": 128}),
        ("fast_64", {"tpu_tree_growth": "fast", "tpu_round_width": 64}),
        ("fast_32", {"tpu_tree_growth": "fast", "tpu_round_width": 32}),
        ("strict_64", {"tpu_tree_growth": "rounds", "tpu_round_width": 64}),
    ]
    for name, extra in configs:
        if os.environ.get(f"SWEEP_SKIP_{name.upper()}") == "1":
            bank(name, skipped=True)
            continue
        try:
            params = dict(base, **extra)
            bst = lgb.Booster(params=params, train_set=ds)
            t1 = time.perf_counter()
            bst.update()
            dsync(bst.boosting.train_score)
            compile_s = time.perf_counter() - t1
            t1 = time.perf_counter()
            for _ in range(TREES - 1):
                bst.update()
            dsync(bst.boosting.train_score)
            spt = (time.perf_counter() - t1) / max(TREES - 1, 1)
            auc = bench.holdout_auc(bst, 28)
            bank(name, sec_per_tree=round(spt, 4),
                 compile_seconds=round(compile_s, 1),
                 holdout_auc=round(float(auc), 5))
        except Exception as e:
            bank(name, error=str(e)[-400:], tb=traceback.format_exc()[-800:])

    # chained primitives at half-HIGGS scale: pipeline reps, one sync
    import jax
    import jax.numpy as jnp
    from jax import lax

    def chain(name, fn, x, reps=10):
        try:
            t1 = time.perf_counter()
            y = fn(x)
            dsync(y)
            compile_s = time.perf_counter() - t1
            t1 = time.perf_counter()
            y = x
            for _ in range(reps):
                y = fn(y)
            dsync(y)
            total = time.perf_counter() - t1
            bank(name, ms=round((total - 0.075) / reps * 1e3, 2),
                 compile_s=round(compile_s, 1))
        except Exception as e:
            bank(name, error=str(e)[-300:])

    rng = np.random.RandomState(0)
    m = 5_500_000
    keys = jnp.asarray(rng.randint(0, 129, m).astype(np.int32))
    chain("sort_kv_5p5m",
          jax.jit(lambda k: lax.sort(
              (k, jnp.arange(m, dtype=jnp.int32)), is_stable=True,
              num_keys=1)[1] % 129), keys)
    mat = jnp.asarray(rng.randint(0, 63, (m, 28)).astype(np.uint8))
    perm = jnp.asarray(rng.permutation(m).astype(np.int32))
    chain("gather_rows_5p5m",
          jax.jit(lambda p: (jnp.take(mat, p, axis=0).sum(axis=1)
                             .astype(jnp.int32) + p) % m), perm)
    bank("done", total_seconds=round(time.time() - T0, 1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
